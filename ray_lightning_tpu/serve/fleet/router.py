"""The fleet front door: :class:`FleetServer` — N serve replicas behind
one ``submit/generate/drain/shutdown`` surface.

One :class:`~ray_lightning_tpu.serve.server.Server` is exactly one SPMD
fleet; heavy traffic needs many.  ``FleetServer(module, replicas=N)``
holds N independent replicas (each an unmodified ``Server`` placed
through the existing cluster backends) and adds the three fleet
behaviors on the driver:

- **Routing** — least-loaded by (active slots, queue depth), with
  tenant stickiness as the tiebreak inside ``sticky_slack``: a tenant's
  requests keep landing on the replica that already holds its prefix
  pages (serve/fleet/pages.py KV affinity), but never at the price of
  real load imbalance.  With ``fleet={"prefix_fed": True}`` the
  router-resident prefix directory (serve/fleet/federation.py) goes
  first: the replica MEASURED to hold the longest matching prefix wins
  inside the same slack, and a prefix held only on another replica is
  pulled over the KV-ship plane before admission — shared prompts
  prefill once per fleet, not once per replica.  Per-tenant quotas are
  enforced FLEET-WIDE on
  dispatched in-flight requests (the per-replica schedulers run
  unquoted); a tenant at quota parks in the fleet queue without
  head-of-line-blocking other tenants.

- **Failover** — a replica whose serve pump dies has already failed its
  admitted requests (cause + per-rank flight-recorder dumps in
  ``Server.failure_report``); the router re-dispatches every
  queued-but-unprefilled request to survivors (safe: nothing was
  computed, generation is deterministic) and fails only the truly lost
  in-flight ones with a :class:`FleetReplicaLost` that links the flight
  paths.  The fleet then grows a replacement back toward
  ``min_replicas``.

- **Autoscaling** — the pump feeds queue-depth / TTFT-p99 signals (the
  trace plane's numbers) to the :class:`~ray_lightning_tpu.serve.fleet.
  autoscale.Autoscaler`; grow spawns a replica in the background
  (PR 7's grow-to-continue headroom, serve-side), shrink drains one
  gracefully — withdrawn queued requests complete elsewhere, in-flight
  ones finish locally, then the replica shuts down (the serve analog of
  shrink-to-continue).  Decisions, cooldowns and per-event actuation
  seconds land on ``/status`` and as ``rlt_fleet_*`` gauges/counters.

::

    fleet = FleetServer(module, replicas=2, num_workers=1,
                        platform="cpu", fleet={"max_replicas": 4},
                        telemetry={"metrics_port": 0}).start()
    req = fleet.submit(prompt_tokens, tenant="alice")
    tokens = req.result(timeout=60)
    fleet.shutdown()
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Optional

import numpy as np

from ray_lightning_tpu.serve.fleet.autoscale import Autoscaler
from ray_lightning_tpu.serve.fleet.config import FleetConfig
from ray_lightning_tpu.serve.fleet.pages import PageConfig, _prefix_hash
from ray_lightning_tpu.serve.fleet.replica import FleetReplica
from ray_lightning_tpu.telemetry import metrics as _metrics

_log = logging.getLogger(__name__)


def pick_replica(rows: "list[dict]", sticky_rid: Optional[int] = None,
                 sticky_slack: int = 1,
                 pool: Optional[str] = None,
                 spill: bool = False,
                 affinity: "Optional[dict]" = None) -> Optional[int]:
    """Routing policy (pure — fleet/selfcheck.py drives it directly).

    ``rows``: one ``{"rid", "active", "queued", "slots"[, "role"]}``
    per routable replica.  Least-loaded wins: fewest active slots, then
    shortest queue, then lowest id (deterministic).  The tenant's
    sticky replica overrides the winner only while its load is within
    ``sticky_slack`` of the winner on BOTH axes — KV affinity must
    never hide a hot replica.

    ``affinity`` (prefix federation): ``{rid: matched_prefix_tokens}``
    from the fleet directory — the replica already holding the LONGEST
    matching prefix beats least-loaded (and beats stickiness: measured
    pages outrank a routing habit), under the SAME slack discipline:
    a prefix hit never justifies routing onto a hot replica, because
    past the slack the pages can be fetched instead (the federation's
    whole point).

    ``pool`` restricts routing to one disaggregation role ("prefill" /
    "decode"); when NO row carries that role the filter falls back to
    every row — a role pool that emptied (shrink, failover) degrades
    to pooled routing instead of stranding requests.

    ``spill`` (pooled decode-pool traffic only): when the pool's best
    replica is saturated (every slot live AND a queue behind it), a
    fully-idle replica OUTSIDE the pool joins the candidates — after a
    prefill burst drains, the dedicated prefill replica absorbs pooled
    work instead of idling while the decode pool grinds its backlog.
    """
    if not rows:
        return None
    if pool is not None:
        pooled = [r for r in rows if r.get("role", "pooled") == pool]
        if pooled:
            if spill:
                best = min(pooled, key=lambda r: (
                    r["active"], r["queued"], r["rid"]))
                if best["active"] >= best["slots"] and best["queued"]:
                    pooled = pooled + [
                        r for r in rows if r not in pooled
                        and r["active"] == 0 and r["queued"] == 0]
            rows = pooled
    best = min(rows, key=lambda r: (r["active"], r["queued"], r["rid"]))
    if affinity:
        near = [r for r in rows
                if affinity.get(r["rid"], 0) > 0
                and r["active"] <= best["active"] + sticky_slack
                and r["queued"] <= best["queued"] + sticky_slack]
        if near:
            return max(near, key=lambda r: (affinity[r["rid"]],
                                            -r["rid"]))["rid"]
    if sticky_rid is not None and sticky_rid != best["rid"]:
        for r in rows:
            if r["rid"] == sticky_rid \
                    and r["active"] <= best["active"] + sticky_slack \
                    and r["queued"] <= best["queued"] + sticky_slack:
                return r["rid"]
    return best["rid"]


class FleetReplicaLost(RuntimeError):
    """An in-flight request died with its replica; carries the links to
    the per-rank flight-recorder dumps (the failover report)."""

    def __init__(self, message: str, flight_paths: Optional[dict] = None):
        super().__init__(message)
        self.flight_paths = dict(flight_paths or {})


class FleetRequest:
    """Driver-side handle on one fleet request.  Mirrors
    :class:`~ray_lightning_tpu.serve.scheduler.ServeRequest`'s surface
    (``done()`` / ``result(timeout)``) but survives replica failover:
    the inner per-replica request may be replaced any number of times
    before the fleet-level outcome settles."""

    def __init__(self, fid: int, prompt: np.ndarray, tenant: str,
                 max_new_tokens: Optional[int]):
        self.id = fid
        self.prompt = prompt
        self.tenant = tenant
        self.max_new_tokens = max_new_tokens
        #: current per-replica request (None while parked in the fleet
        #: queue) and the replica it was dispatched to
        self.inner = None
        self.replica: Optional[int] = None
        self.requeues = 0
        self.t_submit = time.monotonic()
        self.t_done: Optional[float] = None
        #: fleet-level TTFT: submit-at-the-front-door to first token,
        #: fleet queueing included (the autoscaler's grow signal)
        self.ttft_s: Optional[float] = None
        self.tpot_s: Optional[float] = None
        self.error: Optional[BaseException] = None
        self._tokens: Optional[np.ndarray] = None
        #: disaggregation state (router-owned): ``{"stage": "prefill"}``
        #: while the prefill leg runs, then ``{"stage": "decode",
        #: "head": [t1], "shipped": bool}`` on the decode leg; the head
        #: tokens prepend to the decode leg's stream at completion
        self._disagg: Optional[dict] = None
        self._event = threading.Event()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"fleet request {self.id} not complete after {timeout}s")
        if self.error is not None:
            raise self.error
        return self._tokens


class FleetServer:
    """Front-door router over N serve replicas with signal-driven
    autoscaling (module docstring)."""

    def __init__(
        self,
        module,
        *,
        replicas: Optional[int] = None,
        fleet: Any = None,
        autoscale: bool = True,
        tenant_quotas: "dict[str, int] | int | None" = None,
        paged: Any = True,
        telemetry: Any = None,
        default_root_dir: Optional[str] = None,
        replica_factory: Optional[Callable[[int], Any]] = None,
        **server_kwargs,
    ):
        from ray_lightning_tpu.telemetry import TelemetryConfig
        cfg = FleetConfig.resolve(fleet)
        initial = int(replicas) if replicas is not None \
            else cfg.min_replicas
        if initial < 1:
            raise ValueError("replicas must be >= 1")
        if not autoscale:
            cfg = dataclasses.replace(cfg, min_replicas=initial,
                                      max_replicas=initial)
        else:
            if initial > cfg.max_replicas:
                cfg = dataclasses.replace(cfg, max_replicas=initial)
            if initial < cfg.min_replicas:
                cfg = dataclasses.replace(cfg, min_replicas=initial)
        self.cfg = cfg
        self.initial_replicas = initial
        self.module = module
        self.paged = PageConfig.resolve(paged)
        self._default_quota: Optional[int] = (
            int(tenant_quotas) if isinstance(tenant_quotas, int) else None)
        self._quotas: dict[str, int] = (
            dict(tenant_quotas) if isinstance(tenant_quotas, dict) else {})
        self.telemetry = TelemetryConfig.resolve(telemetry)
        self.default_root_dir = default_root_dir or os.path.join(
            os.getcwd(), "rlt_fleet")
        server_kwargs.pop("tenant_quotas", None)   # fleet-enforced
        self._server_kwargs = server_kwargs
        self._factory = replica_factory or self._default_factory
        self.autoscaler = Autoscaler(cfg)
        self._replicas: dict[int, FleetReplica] = {}
        self._rid = 0
        self._pending: deque[FleetRequest] = deque()
        self._inflight: dict[int, FleetRequest] = {}
        self._tenant_inflight: dict[str, int] = {}
        self._sticky: dict[str, int] = {}
        self._fid = 0
        self._lock = threading.RLock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._pump: Optional[threading.Thread] = None
        self._scale_threads: list[threading.Thread] = []
        self._agg = None
        self._metrics_server = None
        self._last_tick = 0.0
        self._ttfts: deque[float] = deque(maxlen=128)
        self._draining = False
        self._started = False
        #: failover log: replica, cause, flight paths, requeued/failed
        self.failovers: list[dict] = []
        #: prefix-reuse counters folded in from removed replicas, so a
        #: shrink doesn't erase the fleet's reuse evidence
        self._retired_pages = {"prefill_tokens_requested": 0,
                               "prefill_tokens_computed": 0,
                               "prefix_hits": 0, "reused_prefills": 0,
                               "remote_imports": 0,
                               "federated_tokens_reused": 0}
        #: finalized goodput docs of removed replicas (same rationale:
        #: a shrink must not erase the fleet's wall-clock attribution)
        self._retired_goodput: list = []
        self.completed = 0
        self.failed = 0
        self.requeued = 0
        #: KV-ship channel (disaggregated decode): the router is both
        #: ends' driver, so one Mailbox IS the peer channel — puts can
        #: be chaos-dropped (arm_kvship_drop) and takes retry/backoff
        #: per RLT_PEER_RETRIES exactly like the worker↔worker plane
        from ray_lightning_tpu.cluster.peer import Mailbox
        self._kvship_mailbox = Mailbox()
        self._kvship_drop = 0
        self._kvship_seconds = 0.0
        #: ships run OFF the router pump (a ship is two worker RPCs
        #: plus the codec; inline it would stall every other request's
        #: dispatch — the exact TTFT the disaggregation exists to win)
        self._kvship_pool = None
        self.kvship = {"codec": cfg.kvship_codec, "ships": 0,
                       "bytes_wire": 0, "bytes_raw": 0, "retries": 0,
                       "failovers": 0, "skipped": 0}
        #: prefix federation (serve/fleet/federation.py): the router-
        #: resident directory every replica's PagedKV advertises donor
        #: retentions to; a directory hit for a prefix the admitting
        #: replica lacks pulls the pages over the SAME kvship plane
        #: (shared counters, reason="federation" on the metrics)
        self.directory = None
        if cfg.prefix_fed and self.paged.enabled:
            from ray_lightning_tpu.serve.fleet.federation import \
                PrefixDirectory
            self.directory = PrefixDirectory(
                self.paged.page_size, ttl_s=cfg.prefix_fed_ttl_s)
        self._kvfed_seconds = 0.0
        #: in-flight federated fetches, keyed (target rid, prefix hash)
        #: — the capacity gate AND the dedupe (N queued requests with
        #: one shared prefix must not fetch it N times)
        self._fed_inflight: set = set()
        self.federation = {"codec": cfg.kvship_codec, "hits": 0,
                           "fetches": 0, "ships": 0, "bytes_wire": 0,
                           "bytes_raw": 0, "retries": 0, "failovers": 0,
                           "skipped": 0}
        # chaos: an RLT_FAULT peerdrop spec arms the router's kvship
        # mailbox exactly like it arms the worker↔worker peer channel
        # (elastic/faults.py) — serve workers never install the
        # training-side FaultInjector, so the spec is unambiguous here
        raw_fault = os.environ.get("RLT_FAULT", "").strip()
        if raw_fault and "peerdrop" in raw_fault \
                and (cfg.roles or self.directory is not None):
            from ray_lightning_tpu.elastic.faults import parse_faults
            for spec in parse_faults(raw_fault):
                if spec.kind == "peerdrop":
                    self._kvship_drop += spec.count

    # -- construction ------------------------------------------------------

    def _default_factory(self, rid: int):
        """An unmodified :class:`Server` per replica: same module, same
        config, its own worker actors via the cluster backends.  The
        fleet's env knobs (RLT_FLEET*, RLT_SERVE_PAGED*) round-trip
        into every replica's worker actors."""
        import dataclasses as _dc

        from ray_lightning_tpu.serve.server import Server
        kw = dict(self._server_kwargs)
        worker_env = {**self.cfg.worker_env(),
                      **kw.pop("worker_env", {})}
        if self.cfg.role_for(rid) == "prefill" \
                and "max_prefills_per_step" not in kw:
            # a dedicated prefill replica never interleaves decode
            # tails, so it batches admissions to its slot count — the
            # admission-throughput half of the disaggregation win (a
            # pooled replica admitting this greedily would stall its
            # live decodes' TPOT every step)
            kw["max_prefills_per_step"] = kw.get("max_batch_slots", 8)
        # replicas carry their own aggregator (heartbeats + flight
        # recorder for THEIR workers) but never the driver metrics
        # registry or HTTP endpoint — those are fleet-level singletons
        rep_telemetry = None
        if self.telemetry.enabled:
            rep_telemetry = _dc.replace(self.telemetry, metrics=False,
                                        metrics_port=None)
        return Server(
            self.module,
            tenant_quotas=None,
            telemetry=rep_telemetry,
            paged=self.paged,
            # roles or federation configured → every replica can
            # ship/receive KV pages (the per-bucket import programs
            # are cheap and a failback-to-pooled replica may still
            # receive a ship or a federated fetch)
            kvship=(bool(self.cfg.roles) or self.directory is not None)
            and self.paged.enabled,
            default_root_dir=os.path.join(self.default_root_dir,
                                          f"replica_{rid}"),
            worker_env=worker_env,
            **kw)

    def _new_replica(self) -> FleetReplica:
        with self._lock:
            rid = self._rid
            self._rid += 1
            rep = FleetReplica(rid, self._factory(rid),
                               role=self.cfg.role_for(rid))
            self._replicas[rid] = rep
        if self.directory is not None:
            pages = getattr(rep.server.scheduler, "pages", None)
            if pages is not None:
                pages.bind_federation(rid, self.directory)
        return rep

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "FleetServer":
        """Spawn the initial replicas (concurrently — each is its own
        actor fleet), start the router pump.  Blocking; returns self."""
        if self._started:
            return self
        self._start_telemetry()
        reps = [self._new_replica() for _ in range(self.initial_replicas)]
        errors: list[BaseException] = []

        def boot(rep):
            try:
                rep.start()
            except BaseException as e:   # noqa: BLE001 - surfaced below
                errors.append(e)

        threads = [threading.Thread(target=boot, args=(rep,),
                                    name=f"rlt-fleet-boot-{rep.id}",
                                    daemon=True) for rep in reps]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            for rep in reps:
                try:
                    rep.shutdown(graceful=False)
                except Exception:
                    pass
            self._stop_telemetry()
            raise errors[0]
        self._started = True
        self._pump = threading.Thread(target=self._pump_loop, daemon=True,
                                      name="rlt-fleet-router")
        self._pump.start()
        _log.info("fleet ready: %d replica(s), autoscale [%d, %d]",
                  len(reps), self.cfg.min_replicas, self.cfg.max_replicas)
        return self

    def _start_telemetry(self) -> None:
        cfg = self.telemetry
        if not (cfg.enabled and cfg.metrics):
            return
        from ray_lightning_tpu import telemetry
        from ray_lightning_tpu.telemetry import exporter as _exporter
        agg = telemetry.TelemetryAggregator(
            cfg.resolve_dir(self.default_root_dir),
            heartbeat_timeout=cfg.heartbeat_timeout,
            hard_timeout=cfg.hard_timeout,
            flight_capacity=cfg.flight_capacity,
            incident_cfg=cfg.resolved_incident(),
            run_kind="serve")
        self._agg = agg
        # ONE driver registry for the whole fleet: the router's
        # rlt_fleet_* gauges/counters and every replica scheduler's
        # rlt_serve_* instruments flush into the same exposition
        telemetry.enable_metrics(rank=-1, sink=agg.ingest_metrics,
                                 interval=cfg.metrics_interval)
        self._metrics_server = _exporter.start_metrics_server(
            agg, cfg, status_extra=self.status)

    def _stop_telemetry(self) -> None:
        if self._agg is None:
            return
        from ray_lightning_tpu import telemetry
        telemetry.flush_metrics()
        telemetry.disable_metrics()
        if self._metrics_server is not None:
            self._metrics_server.stop()
            self._metrics_server = None
        self._agg.export()
        self._agg = None

    @property
    def metrics_url(self) -> Optional[str]:
        return self._metrics_server.url \
            if self._metrics_server is not None else None

    # -- request surface ---------------------------------------------------

    def submit(self, prompt, tenant: str = "default",
               max_new_tokens: Optional[int] = None) -> FleetRequest:
        """Enqueue a prompt at the front door; the router dispatches it
        to the best replica (possibly after a failover or a grow)."""
        if not self._started:
            raise RuntimeError("FleetServer.start() first")
        if self._draining:
            raise RuntimeError("fleet is draining; no new requests")
        prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        if len(prompt) == 0:
            raise ValueError("empty prompt")
        with self._lock:
            fr = FleetRequest(self._fid, prompt, tenant, max_new_tokens)
            self._fid += 1
            self._pending.append(fr)
        self._wake.set()
        return fr

    def generate(self, prompt, tenant: str = "default",
                 max_new_tokens: Optional[int] = None,
                 timeout: Optional[float] = 300.0) -> np.ndarray:
        """Blocking submit-and-wait."""
        return self.submit(prompt, tenant=tenant,
                           max_new_tokens=max_new_tokens).result(timeout)

    # -- the router pump ---------------------------------------------------

    def _pump_loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(0.01)
            self._wake.clear()
            try:
                self._poll_completions()
                self._scan_failures()
                self._dispatch_pending()
                self._tick_autoscaler()
            except Exception:
                _log.error("fleet router pump error", exc_info=True)
                time.sleep(0.05)

    def _routable(self) -> "list[FleetReplica]":
        return [r for r in self._replicas.values() if r.routable]

    def _quota_of(self, tenant: str) -> Optional[int]:
        return self._quotas.get(tenant, self._default_quota)

    def _poll_completions(self) -> None:
        with self._lock:
            inflight = list(self._inflight.values())
        for fr in inflight:
            inner = fr.inner
            if inner is None or not inner.done():
                continue
            if inner.error is None:
                stage = (fr._disagg.get("stage")
                         if fr._disagg is not None else None)
                if stage == "prefill":
                    # hand the ship to the kvship pool; the pump keeps
                    # dispatching while the pages travel
                    fr._disagg["stage"] = "shipping"
                    self._kvship_executor().submit(
                        self._advance_disagg_task, fr)
                elif stage == "shipping":
                    continue     # leg 1 done, ship in flight
                else:
                    self._finish_ok(fr)
            else:
                rep = self._replicas.get(fr.replica)
                if rep is not None and rep.failed:
                    continue   # the failover scan routes this one
                self._finish_failed(fr, inner.error)

    def _finish_ok(self, fr: FleetRequest) -> None:
        inner = fr.inner
        toks = list(inner.generated)
        if fr._disagg is not None:
            # the prefill leg's token(s) lead the decode leg's stream
            toks = list(fr._disagg.get("head", ())) + toks
        fr._tokens = np.asarray(toks, dtype=np.int32)
        fr.t_done = time.monotonic()
        if fr.ttft_s is None and inner.t_first is not None:
            # disaggregated requests stamped TTFT at the prefill leg
            fr.ttft_s = inner.t_first - fr.t_submit
            self._ttfts.append(fr.ttft_s)
        fr.tpot_s = inner.tpot_s
        with self._lock:
            self._inflight.pop(fr.id, None)
            self._tenant_inflight[fr.tenant] = max(
                0, self._tenant_inflight.get(fr.tenant, 1) - 1)
            self.completed += 1
        fr._event.set()
        self._count("rlt_fleet_requests_total", 1, status="ok",
                    tenant=fr.tenant)

    def _finish_failed(self, fr: FleetRequest,
                       error: BaseException) -> None:
        fr.error = error
        fr.t_done = time.monotonic()
        with self._lock:
            self._inflight.pop(fr.id, None)
            self._tenant_inflight[fr.tenant] = max(
                0, self._tenant_inflight.get(fr.tenant, 1) - 1)
            self.failed += 1
        fr._event.set()
        self._count("rlt_fleet_requests_total", 1, status="failed",
                    tenant=fr.tenant)

    def _requeue(self, fr: FleetRequest) -> None:
        with self._lock:
            self._inflight.pop(fr.id, None)
            self._tenant_inflight[fr.tenant] = max(
                0, self._tenant_inflight.get(fr.tenant, 1) - 1)
            fr.inner = None
            fr.replica = None
            fr._disagg = None    # a redispatch restarts from scratch
            fr.requeues += 1
            self._pending.appendleft(fr)
            self.requeued += 1
        self._count("rlt_fleet_requests_total", 1, status="requeued",
                    tenant=fr.tenant)

    def _scan_failures(self) -> None:
        for rep in list(self._replicas.values()):
            if rep.failed and rep.state != "dead":
                self._handle_failover(rep)

    def _handle_failover(self, rep: FleetReplica) -> None:
        """A replica's serve pump died mid-serve.  Its scheduler has
        already failed every admitted request (with flight dumps);
        queued-but-unprefilled ones are re-dispatched to survivors —
        nothing was computed for them, and greedy generation is
        deterministic, so a replay is the same answer."""
        rep.mark_dead()
        error = rep.server._error
        report = getattr(rep.server, "failure_report", None) or {}
        flight_paths = report.get("flight_paths", {})
        requeued = failed = 0
        with self._lock:
            mine = [fr for fr in self._inflight.values()
                    if fr.replica == rep.id]
        for fr in mine:
            inner = fr.inner
            if inner is not None and inner.t_admit is None:
                self._requeue(fr)
                requeued += 1
            else:
                lost = FleetReplicaLost(
                    f"replica {rep.id} lost request in flight: "
                    f"{error!r} (flight dumps: "
                    f"{sorted(flight_paths.values())})",
                    flight_paths=flight_paths)
                lost.__cause__ = error
                self._finish_failed(fr, lost)
                failed += 1
        event = {"replica": rep.id, "cause": repr(error),
                 "flight_paths": dict(flight_paths),
                 "requeued": requeued, "failed": failed,
                 "at": time.time()}
        self.failovers.append(event)
        self._count("rlt_fleet_failover_total", 1)
        _log.error("fleet failover: replica %d dead (%r); %d requeued, "
                   "%d lost; flight dumps: %s", rep.id, error, requeued,
                   failed, sorted(flight_paths.values()))
        self._reap_async(rep)
        with self._lock:
            capacity = sum(1 for r in self._replicas.values()
                           if r.state in ("starting", "serving"))
        if capacity < self.cfg.min_replicas:
            self._spawn_async("failover replacement", autoscaled=False)
        self._wake.set()

    def _fold_pages(self, rep: FleetReplica) -> None:
        """Preserve a departing replica's prefix-reuse counters (and
        drop its directory advertisements — a dead donor must stop
        attracting fetches)."""
        if self.directory is not None:
            self.directory.invalidate_replica(rep.id)
        pages = getattr(rep.server.scheduler, "pages", None)
        if pages is None:
            return
        st = pages.stats()
        with self._lock:
            for key in self._retired_pages:
                self._retired_pages[key] += st.get(key, 0)

    def _fold_goodput(self, rep: FleetReplica) -> None:
        """Preserve a departing replica's goodput partition (the pump
        finalized its ledger during shutdown)."""
        try:
            doc = rep.server.goodput()
        except Exception:
            doc = None
        if doc:
            with self._lock:
                self._retired_goodput.append(doc)

    def _reap_async(self, rep: FleetReplica) -> None:
        def reap():
            try:
                rep.shutdown(graceful=False)
            except Exception:
                pass
            self._fold_pages(rep)
            self._fold_goodput(rep)
            with self._lock:
                self._replicas.pop(rep.id, None)
        t = threading.Thread(target=reap, daemon=True,
                             name=f"rlt-fleet-reap-{rep.id}")
        t.start()
        self._scale_threads.append(t)

    def _dispatch_pending(self) -> None:
        with self._lock:
            if not self._pending:
                return
            routable = self._routable()
            if not routable:
                return
            rows = {rep.id: rep.load_row() for rep in routable}
            reps = {rep.id: rep for rep in routable}
            for fr in list(self._pending):
                quota = self._quota_of(fr.tenant)
                if quota is not None and \
                        self._tenant_inflight.get(fr.tenant, 0) >= quota:
                    continue   # tenant at fleet-wide quota; others pass
                disagg = self._disagg_eligible(fr, reps)
                # prefix-affinity routing: the directory knows which
                # replica already holds the longest matching prefix —
                # land there when its load allows, fetch otherwise
                aff = None
                if self.directory is not None \
                        and len(fr.prompt) >= self.paged.page_size:
                    aff = self.directory.affinity(fr.prompt)
                if disagg:
                    # disaggregated: the prefill pool computes the
                    # prompt (ONE token), its KV pages ship, a decode
                    # replica finishes the request (_advance_disagg)
                    rid = pick_replica(list(rows.values()),
                                       None, 0, pool="prefill",
                                       affinity=aff)
                else:
                    # with roles configured, pooled traffic routes to
                    # the DECODE pool: a full request parked on a
                    # prefill replica would hold one of its slots for
                    # a whole decode tail, stalling every disagg
                    # admission behind it (pick_replica fails back to
                    # all rows when the pool empties)
                    rid = pick_replica(list(rows.values()),
                                       self._sticky.get(fr.tenant),
                                       self.cfg.sticky_slack,
                                       pool="decode" if self.cfg.roles
                                       else None,
                                       spill=bool(self.cfg.roles),
                                       affinity=aff)
                if rid is None:
                    break
                rep = reps[rid]
                fetch = self._plan_fed_fetch(fr, rid, aff)
                if fetch is not None:
                    # a replica OTHER than the routed one holds a
                    # longer prefix: pull the pages first (off-pump,
                    # capacity-gated), then submit — the admission
                    # lands on freshly-installed donor rows and
                    # prefills only the suffix
                    self._pending.remove(fr)
                    self._inflight[fr.id] = fr
                    self._tenant_inflight[fr.tenant] = \
                        self._tenant_inflight.get(fr.tenant, 0) + 1
                    if not disagg:
                        self._sticky[fr.tenant] = rid
                    self._fed_inflight.add(fetch[3])
                    self._kvship_executor().submit(
                        self._fed_fetch_task, fr, fetch, rid, disagg)
                    rows[rid]["queued"] += 1
                    continue
                try:
                    if disagg:
                        # piggyback the KV export only when the decode
                        # pool could actually adopt it right now — a
                        # doomed export still costs the prefill lane a
                        # device fetch per admission (ship_kv=False
                        # legs fall back to the donor-match export,
                        # opportunistically)
                        ship = any(
                            r.role == "decode"
                            and hasattr(r.server, "can_adopt_kv")
                            and r.server.can_adopt_kv()
                            for r in reps.values())
                        inner = rep.server.submit(
                            fr.prompt, tenant=fr.tenant,
                            max_new_tokens=1, ship_kv=ship)
                    else:
                        inner = rep.server.submit(
                            fr.prompt, tenant=fr.tenant,
                            max_new_tokens=fr.max_new_tokens)
                except Exception:
                    # replica refused (failed/draining between probe
                    # and submit); the failure scan sorts it out
                    rows.pop(rid, None)
                    reps.pop(rid, None)
                    if not rows:
                        break
                    continue
                self._pending.remove(fr)
                fr.inner = inner
                fr.replica = rid
                fr._disagg = {"stage": "prefill"} if disagg else None
                self._inflight[fr.id] = fr
                self._tenant_inflight[fr.tenant] = \
                    self._tenant_inflight.get(fr.tenant, 0) + 1
                if not disagg:
                    self._sticky[fr.tenant] = rid
                rows[rid]["queued"] += 1   # count our own dispatches

    # -- disaggregated decode (prefill pool → KV ship → decode pool) -------

    def _disagg_eligible(self, fr: FleetRequest,
                         reps: "dict[int, FleetReplica]") -> bool:
        """Disaggregate this request?  Needs BOTH dedicated pools
        routable (a pool that emptied fails back to pooled routing),
        shippable replicas (paging + kv_import programs on both ends),
        a prompt long enough to own at least one whole page, room in
        the buckets for the decode leg's prompt+first-token resubmit,
        and more than one token wanted (a 1-token request IS its
        prefill leg)."""
        prefills = [r for r in reps.values() if r.role == "prefill"]
        decodes = [r for r in reps.values() if r.role == "decode"]
        if not prefills or not decodes:
            return False
        if not all(r.server.can_ship_kv() for r in prefills + decodes):
            return False
        if fr.max_new_tokens is not None and fr.max_new_tokens <= 1:
            return False
        buckets = prefills[0].server.buckets
        if len(fr.prompt) + 1 > max(buckets):
            return False
        return len(fr.prompt) >= self.paged.page_size

    def _kvship_executor(self):
        with self._lock:
            if self._kvship_pool is None:
                from concurrent.futures import ThreadPoolExecutor
                self._kvship_pool = ThreadPoolExecutor(
                    max_workers=2, thread_name_prefix="rlt-kvship")
            return self._kvship_pool

    def _advance_disagg_task(self, fr: FleetRequest) -> None:
        """Pool-thread wrapper: a ship/advance that dies for any reason
        requeues the request (a redispatch restarts it from scratch,
        pooled if the pools vanished meanwhile)."""
        try:
            self._advance_disagg(fr)
        except Exception:
            _log.error("disagg advance failed; requeueing fleet "
                       "request %d", fr.id, exc_info=True)
            self._requeue(fr)
        finally:
            self._wake.set()

    def _advance_disagg(self, fr: FleetRequest) -> None:
        """The prefill leg finished (one token): ship its KV pages to
        a decode replica and submit the decode leg there.  A ship that
        times out (chaos drop, dead peer) fails over PER-REQUEST: the
        decode replica simply prefills the prompt itself (pooled mode)
        — deterministic greedy makes the answer identical, only the
        prefill compute is paid twice."""
        leg1 = fr.inner
        t1 = int(leg1.generated[-1])
        if fr.ttft_s is None and leg1.t_first is not None:
            # fleet TTFT = the PREFILL leg's first token — the number
            # the disaggregation bench compares against pooled serving
            fr.ttft_s = leg1.t_first - fr.t_submit
            self._ttfts.append(fr.ttft_s)
        want = fr.max_new_tokens
        if want is None:
            # pin the effective budget so leg-2 doesn't re-apply the
            # full per-replica default on top of the prefill token
            src0 = self._replicas.get(fr.replica)
            want = src0.server.scheduler.default_max_new_tokens \
                if src0 is not None else 32
        hit_eos = (leg1.eos_token is not None
                   and t1 == leg1.eos_token)
        if hit_eos or (want is not None and want <= 1):
            fr._disagg = {"stage": "done", "head": []}
            self._finish_ok(fr)
            return
        with self._lock:
            routable = {rep.id: rep for rep in self._routable()}
        rows = [rep.load_row() for rep in routable.values()]
        rid = pick_replica(rows, None, 0, pool="decode")
        rep = routable.get(rid) if rid is not None else None
        if rep is None:
            self._requeue(fr)       # decode pool AND failback empty
            return
        src = self._replicas.get(fr.replica)
        shipped = False
        if src is not None:
            shipped = self._ship_kv(
                src, rep, fr.prompt, fr.id,
                req_id=getattr(fr.inner, "id", None)) == "ok"
        prompt2 = np.concatenate(
            [fr.prompt, np.asarray([t1], dtype=np.int32)])
        remaining = None if want is None else want - 1
        try:
            inner2 = rep.server.submit(prompt2, tenant=fr.tenant,
                                       max_new_tokens=remaining)
        except Exception:
            self._requeue(fr)
            return
        with self._lock:
            fr.inner = inner2
            fr.replica = rep.id
            fr._disagg = {"stage": "decode", "head": [t1],
                          "shipped": shipped}

    def _ship_kv(self, src: FleetReplica, dst: FleetReplica,
                 prompt: np.ndarray, fid: int,
                 req_id: Optional[int] = None,
                 reason: str = "disagg") -> str:
        """One KV-page ship over the peer channel: export the donor
        rows from ``src``, codec-compress them onto the mailbox, take
        with retry/backoff (RLT_PEER_RETRIES), decode and install on
        ``dst``.  Returns a status string — ``"ok"`` (installed),
        ``"stale"`` (the donor vanished between lookup and export:
        federation invalidates the directory entry), ``"busy"`` (no
        adoptable slot on ``dst``), ``"timeout"`` (wire chaos / dead
        peer), ``"error"``.  Anything but ``"ok"`` means the consumer
        prefills for itself (per-request local failover); bookkeeping
        lands in ``self.kvship`` for the disagg push path or
        ``self.federation`` for the pull path, and wall-clock in the
        matching goodput bucket (kv_ship vs kv_fed)."""
        from ray_lightning_tpu.cluster.peer import PeerTimeout, \
            _retry_policy
        from ray_lightning_tpu.comm.quant import dequantize_blob, \
            quantize_blob
        t0 = time.monotonic()
        codec = self.cfg.kvship_codec
        stats = self.kvship if reason == "disagg" else self.federation
        try:
            # a disagg leg-1 prefill piggybacked its rows into the
            # prefill replica's kv outbox (claimed by req_id) — no
            # worker round-trip; federation pulls fall through to the
            # pin-under-lock donor-match export
            exported = src.server.export_kv(prompt, req_id=req_id)
            if exported is None:
                stats["skipped"] += 1
                return "stale"
            if hasattr(dst.server, "can_adopt_kv") \
                    and not dst.server.can_adopt_kv():
                # every destination slot is live: the install would
                # fail after paying quantize + mailbox + a worker
                # round-trip — skip up front and let the consumer
                # prefill for itself (same fallback, none of the cost)
                stats["skipped"] += 1
                return "busy"
            k_rows, v_rows, matched = exported
            kp, ks = quantize_blob(k_rows, codec)
            vp, vs = quantize_blob(v_rows, codec)
            payload = {
                "k": (np.asarray(kp), None if ks is None
                      else np.asarray(ks)),
                "v": (np.asarray(vp), None if vs is None
                      else np.asarray(vs)),
                "shape": tuple(k_rows.shape), "codec": codec,
                "tokens": np.asarray(prompt[:matched], dtype=np.int32),
            }
            wire = sum(a.nbytes for pair in (payload["k"], payload["v"])
                       for a in pair if a is not None)
            raw = 2 * int(np.prod(k_rows.shape)) * 4   # fp32 baseline
            tag = ("kvship", reason, int(fid))
            with self._lock:
                drop = self._kvship_drop > 0
                if drop:
                    self._kvship_drop -= 1
            if not drop:
                self._kvship_mailbox.put(tag, payload)
            else:
                _log.warning("kvship chaos: dropping ship for fleet "
                             "request %d", fid)
            try:
                got = self._kvship_mailbox.take(
                    tag, timeout=self._kvship_timeout(),
                    who=f"decode replica {dst.id}",
                    src=f"prefill replica {src.id}")
            except PeerTimeout as e:
                retries, _ = _retry_policy()
                stats["retries"] += retries
                stats["failovers"] += 1
                self._count("rlt_kvship_retries_total", max(1, retries),
                            reason=reason)
                self._count("rlt_kvship_failovers_total", 1,
                            reason=reason)
                if self._agg is not None:
                    # correlation event: the flight-dump / incident
                    # timeline names the failover cause next to the
                    # latency it explains
                    self._agg.note_event(
                        "kvship_failover", request=int(fid),
                        src=src.id, dst=dst.id, reason=reason,
                        cause=repr(e))
                _log.warning("kvship failover for fleet request %d: %s",
                             fid, e)
                return "timeout"
            k2 = dequantize_blob(got["k"][0], got["k"][1],
                                 got["codec"], got["shape"])
            v2 = dequantize_blob(got["v"][0], got["v"][1],
                                 got["codec"], got["shape"])
            if not dst.server.import_kv(got["tokens"],
                                        np.asarray(k2),
                                        np.asarray(v2)):
                stats["skipped"] += 1
                return "busy"
            stats["ships"] += 1
            stats["bytes_wire"] += wire
            stats["bytes_raw"] += raw
            self._count("rlt_kvship_ships_total", 1, codec=codec,
                        reason=reason)
            # wire vs raw as separate label series: the live fp8
            # compression ratio is wire/raw straight off /metrics
            self._count("rlt_kvship_bytes_total", wire, codec=codec,
                        reason=reason, kind="wire")
            self._count("rlt_kvship_bytes_total", raw, codec=codec,
                        reason=reason, kind="raw")
            return "ok"
        except Exception:
            _log.warning("kvship failed; consumer prefills locally",
                         exc_info=True)
            stats["failovers"] += 1
            self._count("rlt_kvship_failovers_total", 1, reason=reason)
            return "error"
        finally:
            dt = time.monotonic() - t0
            with self._lock:
                if reason == "disagg":
                    self._kvship_seconds += dt
                else:
                    self._kvfed_seconds += dt

    # -- prefix federation (directory hit → pull over the kvship plane) ----

    def _plan_fed_fetch(self, fr: FleetRequest, rid: int,
                        aff: "Optional[dict]"):
        """Should this admission pull its prefix from another replica
        before submitting?  Called under ``self._lock`` from the
        dispatch loop.  Returns ``(donor_rid, donor_slot, matched,
        inflight_key)`` or ``None`` (= submit normally and prefill
        locally).  A plan commits only when the donor beats what the
        routed replica already holds, both ends can ship, and the
        fetch fits the ``prefix_fed_fetches`` capacity gate — a hit
        past the gate degrades to local prefill, never queues behind
        the wire."""
        if self.directory is None or not aff:
            return None
        dst = self._replicas.get(rid)
        if dst is None or not dst.server.can_ship_kv():
            return None
        hit = self.directory.lookup(fr.prompt, exclude_rid=rid)
        if hit is None:
            return None
        drid, dslot, matched = hit
        if matched <= aff.get(rid, 0):
            return None   # the routed replica already holds as much
        # draining donors still export fine (their pages outlive the
        # withdraw); dead/folded ones already left the directory
        src = self._replicas.get(drid)
        if src is None or src.failed or not src.server.can_ship_kv():
            return None
        key = (rid, _prefix_hash(
            np.asarray(fr.prompt[:matched], dtype=np.int32)))
        if key in self._fed_inflight \
                or len(self._fed_inflight) >= self.cfg.prefix_fed_fetches:
            self.federation["skipped"] += 1
            return None
        self.federation["hits"] += 1
        return drid, dslot, matched, key

    def _fed_fetch_task(self, fr: FleetRequest, fetch, rid: int,
                        disagg: bool) -> None:
        """Pool-thread leg of a federated fetch: ship the donor pages
        onto the routed replica, then submit the request there — its
        prefill lands on the freshly-installed rows and computes only
        the suffix (the scheduler's ``prefill_reused`` path).  ANY
        ship outcome still submits: a failed pull degrades to local
        prefill on the same replica (token-exact either way, only the
        prefill compute differs), and a donor found gone heals the
        stale directory entry."""
        drid, dslot, matched, key = fetch
        try:
            self.federation["fetches"] += 1
            src = self._replicas.get(drid)
            dst = self._replicas.get(rid)
            status = "error"
            if src is not None and dst is not None:
                status = self._ship_kv(src, dst, fr.prompt, fr.id,
                                       reason="federation")
            if status in ("stale", "error") \
                    and self.directory is not None:
                # the donor vanished between lookup and export (the
                # eviction race) — heal the entry so the next lookup
                # doesn't chase it; "busy"/"timeout" keep it: the
                # donor is alive, only this fetch lost
                self.directory.invalidate(drid, dslot)
        except Exception:
            _log.warning("federated fetch failed; request %d prefills "
                         "locally", fr.id, exc_info=True)
        finally:
            with self._lock:
                self._fed_inflight.discard(key)
        rep = self._replicas.get(rid)
        if rep is None or rep.failed:
            self._requeue(fr)
            self._wake.set()
            return
        try:
            if disagg:
                ship = any(
                    r.role == "decode"
                    and hasattr(r.server, "can_adopt_kv")
                    and r.server.can_adopt_kv()
                    for r in self._replicas.values()
                    if not r.failed)
                inner = rep.server.submit(
                    fr.prompt, tenant=fr.tenant,
                    max_new_tokens=1, ship_kv=ship)
            else:
                inner = rep.server.submit(
                    fr.prompt, tenant=fr.tenant,
                    max_new_tokens=fr.max_new_tokens)
        except Exception:
            self._requeue(fr)
            self._wake.set()
            return
        with self._lock:
            fr.inner = inner
            fr.replica = rid
            fr._disagg = {"stage": "prefill"} if disagg else None
        self._wake.set()

    @staticmethod
    def _kvship_timeout() -> float:
        try:
            return float(os.environ.get("RLT_KVSHIP_TIMEOUT_S", "0.2")
                         or 0.2)
        except ValueError:
            return 0.2

    def arm_kvship_drop(self, count: int = 1) -> None:
        """Chaos hook (the serve analog of the elastic plane's
        ``peerdrop`` fault): drop the next ``count`` KV-page ships on
        the channel, forcing the retry → timeout → per-request
        pooled-failover path the chaos test pins."""
        with self._lock:
            self._kvship_drop += int(count)

    # -- autoscaling -------------------------------------------------------

    def signals(self) -> dict:
        """The autoscaler's inputs — the same queue-depth and TTFT
        numbers the trace plane exports per tenant, aggregated
        fleet-wide."""
        with self._lock:
            reps = [r for r in self._replicas.values()
                    if r.state in ("starting", "serving")]
            routable = [r for r in reps if r.routable]
            queued = len(self._pending) + sum(r.queued for r in routable)
            active = sum(r.active for r in routable)
            slots = sum(r.slots for r in routable)
            ttfts = list(self._ttfts)
        ttft_p99 = (float(np.percentile(np.asarray(ttfts), 99)) * 1e3
                    if ttfts else None)
        return {"replicas": len(reps), "queued": queued,
                "active": active, "slots_total": max(1, slots),
                "ttft_p99_ms": ttft_p99}

    def _tick_autoscaler(self) -> None:
        now = time.monotonic()
        if now - self._last_tick < self.cfg.tick_interval_s:
            return
        self._last_tick = now
        sig = self.signals()
        self._gauge("rlt_fleet_replicas_total", sig["replicas"])
        self._gauge("rlt_fleet_queue_depth_total", sig["queued"])
        self._gauge("rlt_fleet_active_slots_total", sig["active"])
        if self._agg is not None:
            # incident plane: fleetwide TTFT/queue detectors tick on
            # the same signals the autoscaler reads
            ttft_ms = sig.get("ttft_p99_ms")
            self._agg.note_serve_signals(
                queue_depth=sig["queued"],
                ttft_p99_s=(ttft_ms / 1e3
                            if ttft_ms is not None else None))
        if self._draining:
            return
        decision = self.autoscaler.tick(sig)
        if decision is None:
            return
        if self._agg is not None:
            # correlation event: an autoscale actuation right before a
            # latency anomaly is a named cause (autoscale-thrash rule)
            self._agg.note_event("autoscale",
                                 action=decision["action"],
                                 reason=decision.get("reason"))
        if decision["action"] == "grow":
            self._spawn_async(decision["reason"], autoscaled=True)
        else:
            self._shrink_async(decision["reason"])

    def _spawn_async(self, reason: str, autoscaled: bool) -> None:
        def grow():
            t0 = time.monotonic()
            rep = self._new_replica()
            ok = True
            try:
                rep.start()
                _log.info("fleet grow: replica %d serving (%s)",
                          rep.id, reason)
            except Exception:
                ok = False
                _log.error("fleet grow failed", exc_info=True)
                with self._lock:
                    self._replicas.pop(rep.id, None)
            seconds = time.monotonic() - t0
            if autoscaled:
                self.autoscaler.note_actuated(seconds, ok)
            self._count("rlt_fleet_grow_total", 1,
                        outcome="ok" if ok else "error")
            self._count("rlt_fleet_scale_seconds_total", seconds,
                        action="grow")
            self._wake.set()
        t = threading.Thread(target=grow, daemon=True,
                             name="rlt-fleet-grow")
        t.start()
        self._scale_threads.append(t)

    def _shrink_async(self, reason: str) -> None:
        with self._lock:
            routable = self._routable()
            if len(routable) <= self.cfg.min_replicas:
                self.autoscaler.note_actuated(0.0, False)
                return
            # least-loaded first; ties drain the NEWEST replica — the
            # oldest holds the warmest prefix-donor population
            rep = min(routable,
                      key=lambda r: (r.active + r.queued, -r.id))
            rep.mark_draining()

        def shrink():
            t0 = time.monotonic()
            ok = True
            # withdraw the not-yet-admitted requests; they complete on
            # a surviving replica (nothing computed for them yet)
            withdrawn = rep.server.scheduler.withdraw_queued()
            withdrawn_ids = {id(r) for r in withdrawn}
            with self._lock:
                mine = [fr for fr in self._inflight.values()
                        if fr.replica == rep.id and fr.inner is not None
                        and id(fr.inner) in withdrawn_ids]
            for fr in mine:
                self._requeue(fr)
            self._wake.set()
            deadline = time.monotonic() + 300
            while not rep.idle():
                if rep.failed or time.monotonic() > deadline:
                    ok = False
                    break
                time.sleep(0.02)
            if ok:
                try:
                    rep.shutdown(graceful=True)
                except Exception:
                    ok = False
                    _log.warning("fleet shrink: replica %d shutdown "
                                 "failed", rep.id, exc_info=True)
                self._fold_pages(rep)
                self._fold_goodput(rep)
                with self._lock:
                    self._replicas.pop(rep.id, None)
                _log.info("fleet shrink: replica %d drained and "
                          "stopped (%s)", rep.id, reason)
            seconds = time.monotonic() - t0
            self.autoscaler.note_actuated(seconds, ok)
            self._count("rlt_fleet_shrink_total", 1,
                        outcome="ok" if ok else "error")
            self._count("rlt_fleet_scale_seconds_total", seconds,
                        action="shrink")
            self._wake.set()
        t = threading.Thread(target=shrink, daemon=True,
                             name=f"rlt-fleet-shrink-{rep.id}")
        t.start()
        self._scale_threads.append(t)

    # -- drain / shutdown --------------------------------------------------

    def drain(self, timeout: Optional[float] = 300.0) -> None:
        """Stop admitting; wait for every pending and in-flight request
        to settle (completed, failed, or failed over and completed)."""
        self._draining = True
        self._wake.set()
        deadline = time.monotonic() + (timeout or 0)
        while True:
            with self._lock:
                if not self._pending and not self._inflight:
                    return
            if timeout is not None and time.monotonic() > deadline:
                raise TimeoutError(f"fleet drain incomplete after "
                                   f"{timeout}s")
            self._wake.set()
            time.sleep(0.02)

    def shutdown(self, graceful: bool = True) -> None:
        """Drain (when graceful), stop the router, tear down every
        replica and the fleet telemetry."""
        if graceful and self._started:
            try:
                self.drain()
            except TimeoutError:
                _log.warning("fleet drain timed out; shutting down "
                             "anyway")
        self._stop.set()
        self._wake.set()
        if self._pump is not None and self._pump.is_alive():
            self._pump.join(10)
        if self._kvship_pool is not None:
            self._kvship_pool.shutdown(wait=False, cancel_futures=True)
        for t in self._scale_threads:
            t.join(30)
        reps = list(self._replicas.values())

        def down(rep):
            try:
                rep.shutdown(graceful=graceful)
            except Exception:
                _log.warning("replica %d shutdown failed", rep.id,
                             exc_info=True)

        threads = [threading.Thread(target=down, args=(rep,), daemon=True)
                   for rep in reps]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        self._replicas.clear()
        self._stop_telemetry()
        self._started = False

    def __enter__(self) -> "FleetServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown(graceful=exc[0] is None)

    # -- evidence ----------------------------------------------------------

    def status(self) -> dict:
        """The fleet block of ``/status`` (exporter ``status_extra``)
        and the bench's evidence surface."""
        with self._lock:
            replicas = {str(rid): rep.status()
                        for rid, rep in sorted(self._replicas.items())}
            pending = len(self._pending)
            inflight = len(self._inflight)
            sticky = dict(self._sticky)
        pages = self.pages_stats()
        doc = {
            "fleet": {
                "replicas": replicas,
                "pending": pending,
                "inflight": inflight,
                "completed": self.completed,
                "failed": self.failed,
                "requeued": self.requeued,
                "sticky": sticky,
                "autoscale": self.autoscaler.stats(),
                "failovers": [dict(e) for e in self.failovers],
                "bounds": {"min": self.cfg.min_replicas,
                           "max": self.cfg.max_replicas},
            }
        }
        if self.cfg.roles:
            # disaggregated-decode evidence: wire bytes by codec, the
            # compression ratio vs the fp32 baseline, and the chaos
            # counters (retries / per-request failovers)
            kv = dict(self.kvship)
            kv["roles"] = list(self.cfg.roles)
            kv["compression_ratio"] = round(
                kv["bytes_raw"] / kv["bytes_wire"], 4) \
                if kv["bytes_wire"] else None
            doc["fleet"]["kvship"] = kv
        if self.directory is not None:
            # prefix-federation evidence: directory occupancy +
            # hit/miss/invalidation counts, the pull-path wire
            # counters, and the live compression ratio
            fed = dict(self.federation)
            fed["compression_ratio"] = round(
                fed["bytes_raw"] / fed["bytes_wire"], 4) \
                if fed["bytes_wire"] else None
            fed["directory"] = self.directory.stats()
            doc["fleet"]["federation"] = fed
        if pages:
            doc["fleet"]["pages"] = pages
        gp = self.goodput_stats()
        if gp:
            doc["fleet"]["goodput"] = gp
        return doc

    def goodput_stats(self) -> Optional[dict]:
        """Fleet goodput: every replica pump's wall-clock partition
        (live peeks for serving replicas, finalized docs for retired
        ones) aggregated, with the autoscaler's actuation seconds as
        an extra ``autoscale`` bucket.  Actuation runs on router
        threads — never inside a replica pump — so adding it to both
        the wall and its bucket keeps ``sum(buckets) == run_wall``
        true on the aggregate by construction."""
        from ray_lightning_tpu.telemetry import goodput as _goodput
        with self._lock:
            reps = list(self._replicas.values())
            docs = list(self._retired_goodput)
        for rep in reps:
            try:
                doc = rep.server.goodput()
            except Exception:
                doc = None
            if doc:
                docs.append(doc)
        if not docs:
            return None
        actuation = sum(float(e.get("seconds") or 0.0)
                        for e in self.autoscaler.stats().get("events", ()))
        extra = {"autoscale": actuation}
        if self._kvship_seconds:
            # KV shipping runs on the router thread between the two
            # legs — it's wall the replicas never see, attributed here
            extra["kv_ship"] = self._kvship_seconds
        if self._kvfed_seconds:
            # federated pulls are a DISTINCT bucket from disagg ships:
            # wire seconds spent avoiding prefill, not prefill seconds
            extra["kv_fed"] = self._kvfed_seconds
        return _goodput.aggregate(docs, extra_buckets=extra)

    def pages_stats(self) -> Optional[dict]:
        """Fleet-aggregated prefix-reuse numbers (sums the replicas'
        PagedKV stats; ratio recomputed over the sums)."""
        if not self.paged.enabled:
            return None
        with self._lock:
            reps = list(self._replicas.values())
            retired = dict(self._retired_pages)
        requested = retired["prefill_tokens_requested"]
        computed = retired["prefill_tokens_computed"]
        hits = retired["prefix_hits"]
        reused = retired["reused_prefills"]
        remote = retired["remote_imports"]
        fed_reused = retired["federated_tokens_reused"]
        for rep in reps:
            pages = getattr(rep.server.scheduler, "pages", None)
            if pages is None:
                continue
            st = pages.stats()
            requested += st["prefill_tokens_requested"]
            computed += st["prefill_tokens_computed"]
            hits += st["prefix_hits"]
            reused += st["reused_prefills"]
            remote += st.get("remote_imports", 0)
            fed_reused += st.get("federated_tokens_reused", 0)
        out = {
            "page_size": self.paged.page_size,
            "prefill_tokens_requested": requested,
            "prefill_tokens_computed": computed,
            "prefix_hits": hits,
            "reused_prefills": reused,
            "prefix_reuse_ratio": round(1.0 - computed / requested, 4)
            if requested else 0.0,
        }
        if self.directory is not None:
            # the federation's OWN contribution: prefill tokens the
            # fleet skipped because the pages were pulled from another
            # replica (a strict subset of the overall reuse ratio)
            out["remote_imports"] = remote
            out["federated_tokens_reused"] = fed_reused
            out["federated_reuse_ratio"] = round(
                fed_reused / requested, 4) if requested else 0.0
        return out

    def stats(self) -> dict:
        return {**self.status(),
                "signals": self.signals()}

    # -- metrics plumbing (no-ops when the metrics plane is off) -----------

    @staticmethod
    def _count(name: str, value: float, **labels: Any) -> None:
        reg = _metrics.get_registry()
        if reg is not None:
            reg.counter(name).inc(value, **labels)

    @staticmethod
    def _gauge(name: str, value: float) -> None:
        reg = _metrics.get_registry()
        if reg is not None:
            reg.gauge(name).set(value)


__all__ = ["FleetServer", "FleetRequest", "FleetReplicaLost",
           "pick_replica"]
