"""Fleet-level prefix-cache federation: the router-resident directory
that turns any replica's donor pages into a hit for every OTHER
replica ("prefill once per fleet", ROADMAP item 1's last leg).

Per-replica prefix reuse (pages.py PrefixIndex) only hits when routing
happens to land a prompt on the replica that already holds its prefix —
tenant stickiness makes that likely, nothing makes it true.  And a
disaggregated fleet's prefill pool re-prefills prefixes the decode pool
already adopted.  :class:`PrefixDirectory` closes both gaps:

- **Advertise** — a replica's :class:`~ray_lightning_tpu.serve.fleet.
  pages.PagedKV` advertises every donor RETENTION here
  (``bind_federation`` installs the hook): page-aligned prefix hashes →
  (replica, slot, page count, liveness stamp).  Only retained donors
  advertise, never live slots — a donor is pinnable for the export leg
  (pages.py ``pin``), so its rows cannot be overwritten between the
  directory hit and the worker fetch; a live slot's rows could be.

- **Invalidate** — donor eviction (LRU pressure, slot reuse,
  ``drop_all``) drops the entry; replica death/shrink drops the whole
  replica (router ``_fold_pages``); a fetch that finds the donor gone
  anyway (the lookup→fetch race) heals the stale entry itself.

- **Lookup** — longest page-aligned matching prefix across the fleet,
  with the SAME exact-token verification as the local index: the
  directory stores the registered tokens, so a hash collision can
  never route a fetch, and the donor side re-verifies against its own
  index before exporting a single row.  Entries older than ``ttl_s``
  are treated as dead (liveness: a wedged replica's advertisements age
  out instead of attracting doomed fetches forever).

The directory is pure bookkeeping — the actual page movement rides the
PR 19 KV-ship plane (export → codec → mailbox → import) unchanged, now
pull-driven (router fetches on a directory hit) as well as push-driven
(disagg prefill→decode ships).  Size is bounded by construction: one
entry per retained (replica, slot) donor, replaced on re-registration —
``pages()`` can never exceed the fleet's retained page total
(fleet/selfcheck.py pins the invariant).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

import numpy as np

from ray_lightning_tpu.serve.fleet.pages import _prefix_hash


class PrefixDirectory:
    """Fleet-wide donor registry: page-aligned prefix hash → (replica,
    slot, pages, liveness).  Router-resident; replicas' PagedKV
    instances call in via the ``bind_federation`` hooks.  Thread-safe
    and a leaf lock — no method calls back into a scheduler."""

    def __init__(self, page_size: int, ttl_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        if page_size < 1:
            raise ValueError("page_size must be >= 1")
        if ttl_s <= 0:
            raise ValueError("ttl_s must be > 0")
        self.page_size = int(page_size)
        self.ttl_s = float(ttl_s)
        self._clock = clock
        #: (rid, slot) -> registered prefix tokens (whole pages)
        self._regs: dict = {}
        #: hash(prefix of k pages) -> set of (rid, slot) registering it
        self._by_hash: dict = {}
        #: (rid, slot) -> last advertisement time (liveness)
        self._stamp: dict = {}
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.invalidations = 0

    # -- advertisement -----------------------------------------------------

    def register(self, rid: int, slot: int, tokens) -> int:
        """Advertise ``(rid, slot)`` as a fleet donor for its tokens'
        whole pages (re-registration replaces — one entry per donor,
        which is what bounds the directory by retained pages).
        Returns the registered length in tokens."""
        tokens = np.asarray(tokens, dtype=np.int32).reshape(-1)
        n_pages = len(tokens) // self.page_size
        key = (int(rid), int(slot))
        with self._lock:
            self._drop(key)
            if n_pages == 0:
                return 0
            reg = tokens[:n_pages * self.page_size].copy()
            self._regs[key] = reg
            self._stamp[key] = self._clock()
            for k in range(1, n_pages + 1):
                h = _prefix_hash(reg[:k * self.page_size])
                self._by_hash.setdefault(h, set()).add(key)
            return len(reg)

    def _drop(self, key) -> None:
        reg = self._regs.pop(key, None)
        self._stamp.pop(key, None)
        if reg is None:
            return
        for k in range(1, len(reg) // self.page_size + 1):
            h = _prefix_hash(reg[:k * self.page_size])
            keys = self._by_hash.get(h)
            if keys is not None:
                keys.discard(key)
                if not keys:
                    del self._by_hash[h]

    # -- invalidation ------------------------------------------------------

    def invalidate(self, rid: int, slot: int) -> None:
        """Donor gone (evicted, slot reused, or a fetch found it
        missing): drop its advertisement."""
        with self._lock:
            if (int(rid), int(slot)) in self._regs:
                self.invalidations += 1
            self._drop((int(rid), int(slot)))

    def invalidate_replica(self, rid: int) -> None:
        """Replica gone (failover, shrink, drop_all): every entry it
        advertised is dead."""
        rid = int(rid)
        with self._lock:
            for key in [k for k in self._regs if k[0] == rid]:
                self.invalidations += 1
                self._drop(key)

    # -- lookup ------------------------------------------------------------

    def _live(self, key, now: float) -> bool:
        return now - self._stamp.get(key, -1e18) <= self.ttl_s

    def lookup(self, tokens, exclude_rid: Optional[int] = None
               ) -> "tuple[int, int, int] | None":
        """Longest page-aligned matching prefix fleet-wide:
        ``(rid, slot, matched_tokens)`` or ``None``.  Exact-token
        verified (hash collisions can't route a fetch); expired
        entries are pruned in passing, not returned."""
        tokens = np.asarray(tokens, dtype=np.int32).reshape(-1)
        max_pages = len(tokens) // self.page_size
        now = self._clock()
        with self._lock:
            for k in range(max_pages, 0, -1):
                prefix = tokens[:k * self.page_size]
                keys = self._by_hash.get(_prefix_hash(prefix))
                best = None
                for key in sorted(keys or ()):
                    if exclude_rid is not None and key[0] == exclude_rid:
                        continue
                    if not self._live(key, now):
                        continue
                    reg = self._regs.get(key)
                    if reg is not None and len(reg) >= len(prefix) \
                            and np.array_equal(reg[:len(prefix)], prefix):
                        # freshest stamp wins; sorted() makes ties
                        # deterministic by (rid, slot)
                        if best is None or self._stamp[key] \
                                > self._stamp[best]:
                            best = key
                if best is not None:
                    self.hits += 1
                    return best[0], best[1], len(prefix)
            # prune what aged out so size tracks live donors
            for key in [k for k in self._stamp
                        if not self._live(k, now)]:
                self._drop(key)
            self.misses += 1
            return None

    def affinity(self, tokens) -> "dict[int, int]":
        """Per-replica longest matched prefix (tokens) for the router's
        prefix-affinity routing — which replica already holds how much
        of this prompt."""
        tokens = np.asarray(tokens, dtype=np.int32).reshape(-1)
        max_pages = len(tokens) // self.page_size
        now = self._clock()
        out: dict = {}
        with self._lock:
            for k in range(max_pages, 0, -1):
                prefix = tokens[:k * self.page_size]
                for key in self._by_hash.get(_prefix_hash(prefix), ()):
                    if key[0] in out or not self._live(key, now):
                        continue
                    reg = self._regs.get(key)
                    if reg is not None and len(reg) >= len(prefix) \
                            and np.array_equal(reg[:len(prefix)], prefix):
                        out[key[0]] = len(prefix)
        return out

    # -- evidence ----------------------------------------------------------

    def pages(self) -> int:
        """Total advertised pages — bounded by the fleet's retained
        pages (one replaced-on-reregister entry per donor slot)."""
        with self._lock:
            return sum(len(r) // self.page_size
                       for r in self._regs.values())

    def entries(self) -> int:
        with self._lock:
            return len(self._regs)

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._regs),
                "pages": sum(len(r) // self.page_size
                             for r in self._regs.values()),
                "replicas": len({k[0] for k in self._regs}),
                "hits": self.hits,
                "misses": self.misses,
                "invalidations": self.invalidations,
                "ttl_s": self.ttl_s,
            }


__all__ = ["PrefixDirectory"]
