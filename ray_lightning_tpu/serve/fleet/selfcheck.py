"""Fleet-plane selfcheck for ``format.sh --check`` (CI gate).

Same contract as the serve/comm/elastic selfchecks: cheap,
deterministic, no pytest, no jax backend — validates the invariants
that would otherwise only fail deep inside a live fleet:

1. ``FleetConfig`` / ``PageConfig`` validation + the RLT_FLEET* /
   RLT_SERVE_PAGED* env round-trip (replica actors must inherit the
   fleet config under both cluster backends);
2. page free-list accounting: ``free + allocated == total`` through
   alloc / lazy-growth / donor-retention / eviction;
3. prefix-hash round-trip: longest page-aligned match, exact-token
   verification (a forged hash collision must NOT donate), drop;
4. the autoscaler cooldown state machine: patience debounce, cooldown
   after actuation, min/max bounds, grow-beats-shrink;
5. router policy invariants: least-loaded pick, tenant stickiness
   within slack only, prefix-affinity beating both (inside the same
   slack), and fleet-wide quota conservation under a simulated
   dispatch loop;
6. the federation directory (federation.py): register → lookup →
   invalidate round-trip, liveness expiry with an injected clock,
   hash/exact-token agreement (a forged collision must NOT route a
   fetch), and size bounded by retained pages (re-registration
   replaces);
7. every ``rlt_fleet_*`` metric name is Prometheus-clean (the PR 2
   lint).
"""

from __future__ import annotations

import os


def _check_config_roundtrip() -> None:
    from ray_lightning_tpu.serve.fleet.config import FleetConfig
    from ray_lightning_tpu.serve.fleet.pages import PageConfig

    cfg = FleetConfig(min_replicas=2, max_replicas=5,
                      grow_queue_depth=3.5, grow_ttft_p99_ms=250.0,
                      shrink_occupancy=0.2, patience_ticks=3,
                      cooldown_s=7.5, tick_interval_s=0.25,
                      sticky_slack=2, roles=("prefill", "decode"),
                      kvship_codec="int8", prefix_fed=True,
                      prefix_fed_ttl_s=12.5, prefix_fed_fetches=3)
    saved = {k: os.environ.pop(k) for k in list(os.environ)
             if k.startswith(("RLT_FLEET", "RLT_SERVE_PAGE",
                              "RLT_KVSHIP"))}
    try:
        os.environ.update(cfg.worker_env())
        assert FleetConfig.resolve(None) == cfg, FleetConfig.resolve(None)
        for k in cfg.worker_env():
            del os.environ[k]
        pc = PageConfig(enabled=True, page_size=32)
        os.environ.update(pc.worker_env())
        assert PageConfig.resolve(None) == pc
        for k in pc.worker_env():
            del os.environ[k]
        assert PageConfig.resolve(None) == PageConfig(enabled=False)
        assert not PageConfig(enabled=False).worker_env()
    finally:
        for k in list(os.environ):
            if k.startswith(("RLT_FLEET", "RLT_SERVE_PAGE",
                             "RLT_KVSHIP")):
                del os.environ[k]
        os.environ.update(saved)
    # role cycling: a fleet that outgrows the tuple stays deterministic
    assert [cfg.role_for(i) for i in range(4)] == \
        ["prefill", "decode", "prefill", "decode"]
    assert FleetConfig().role_for(3) == "pooled"
    for bad in (dict(min_replicas=0), dict(max_replicas=0),
                dict(patience_ticks=0), dict(tick_interval_s=0),
                dict(roles=("prefill", "verify")),
                dict(kvship_codec="zstd"),
                dict(prefix_fed_ttl_s=0.0),
                dict(prefix_fed_fetches=0)):
        try:
            FleetConfig(**bad)
        except ValueError:
            pass
        else:
            raise AssertionError(f"expected ValueError for {bad}")
    print("fleet selfcheck: FleetConfig/PageConfig env round-trip OK")


def _check_page_pool() -> None:
    from ray_lightning_tpu.serve.fleet.pages import PagePool

    pool = PagePool(slots=4, max_seq_len=32, page_size=8)
    assert pool.total_pages == 16 and pool.free == 16
    pool.note_written(0, 9)          # 2 pages
    pool.note_written(0, 5)          # never shrinks below high water
    assert pool.held(0) == 2
    pool.note_written(1, 32)         # the whole slot
    pool.check()
    assert pool.free == 16 - 2 - 4
    freed = pool.shrink_to(1, 16)    # donor keeps its 2 prefix pages
    assert freed == 2 and pool.held(1) == 2
    pool.check()
    assert pool.release(1) == 2 and pool.release(1) == 0
    pool.check()
    assert pool.free == 14
    print("fleet selfcheck: page free-list accounting OK")


def _check_prefix_index() -> None:
    import numpy as np

    from ray_lightning_tpu.serve.fleet.pages import (PrefixIndex,
                                                     _prefix_hash)

    idx = PrefixIndex(page_size=4)
    base = np.arange(100, 120, dtype=np.int32)
    assert idx.register(0, base, limit=19) == 16   # whole pages under 19
    hit = idx.lookup(np.concatenate([base[:8], [7, 7, 7, 7]]))
    assert hit == (0, 8), hit
    hit = idx.lookup(base)                          # longest wins
    assert hit == (0, 16), hit
    assert idx.lookup(np.arange(5, dtype=np.int32)) is None
    # forged collision: same bucket, different tokens must NOT donate
    other = base[:4].copy()
    other[0] = 999
    forged = _prefix_hash(other[:4])
    idx._by_hash.setdefault(forged, set()).add(0)
    assert idx.lookup(other) is None, "collision donated"
    del idx._by_hash[forged]
    idx.drop(0)
    assert idx.lookup(base) is None and not idx._by_hash
    print("fleet selfcheck: prefix-hash round-trip + collision "
          "verification OK")


def _check_autoscaler() -> None:
    from ray_lightning_tpu.serve.fleet.autoscale import Autoscaler
    from ray_lightning_tpu.serve.fleet.config import FleetConfig

    clock = [0.0]
    a = Autoscaler(FleetConfig(min_replicas=1, max_replicas=3,
                               grow_queue_depth=2, patience_ticks=2,
                               cooldown_s=5.0, shrink_occupancy=0.5),
                   clock=lambda: clock[0])
    hot = {"replicas": 1, "queued": 10, "active": 4, "slots_total": 4}
    idle = {"replicas": 2, "queued": 0, "active": 0, "slots_total": 8}
    assert a.tick(hot) is None, "patience ignored"
    d = a.tick(hot)
    assert d == {"action": "grow",
                 "reason": d["reason"]} and "queue_depth" in d["reason"]
    assert a.tick(hot) is None, "decided while actuating"
    a.note_actuated(1.5, True)
    assert a.events[-1]["seconds"] == 1.5 and a.events[-1]["ok"]
    clock[0] = 2.0
    for _ in range(4):
        assert a.tick(hot) is None, "cooldown ignored"
    clock[0] = 10.0
    assert a.tick(idle) is None
    d = a.tick(idle)
    assert d is not None and d["action"] == "shrink", d
    a.note_actuated(0.5, True)
    clock[0] = 100.0
    # bounds: no shrink below min, no grow above max
    for _ in range(5):
        assert a.tick({"replicas": 1, "queued": 0, "active": 0,
                       "slots_total": 4}) is None
        assert a.tick({"replicas": 3, "queued": 99, "active": 12,
                       "slots_total": 12}) is None
    st = a.stats()
    assert st["grows"] == 1 and st["shrinks"] == 1
    print("fleet selfcheck: autoscaler patience/cooldown/bounds OK")


def _check_router_policy() -> None:
    from ray_lightning_tpu.serve.fleet.router import pick_replica

    rows = [{"rid": 0, "active": 2, "queued": 0, "slots": 4},
            {"rid": 1, "active": 0, "queued": 3, "slots": 4},
            {"rid": 2, "active": 0, "queued": 1, "slots": 4}]
    assert pick_replica(rows) == 2, "least-loaded violated"
    # sticky wins inside slack...
    assert pick_replica(rows, sticky_rid=1, sticky_slack=2) == 1
    # ...but never past it
    assert pick_replica(rows, sticky_rid=0, sticky_slack=1) == 2
    assert pick_replica([], sticky_rid=0) is None
    # prefix affinity: the replica measured to hold the prefix wins
    # inside the slack (even over stickiness)...
    assert pick_replica(rows, sticky_slack=2, affinity={1: 8}) == 1
    assert pick_replica(rows, sticky_rid=2, sticky_slack=2,
                        affinity={1: 8}) == 1
    # ...longest prefix beats a shorter one...
    assert pick_replica(rows, sticky_slack=2,
                        affinity={1: 16, 2: 8}) == 1
    # ...but never past the slack: pages can be FETCHED instead
    assert pick_replica(rows, sticky_slack=1, affinity={0: 8}) == 2

    # fleet-wide quota conservation under a simulated dispatch loop:
    # 8 requests from one quota-2 tenant over 3 replicas — dispatched
    # in-flight never exceeds the quota, every request eventually runs
    quota, inflight, done, pending = 2, [], 0, list(range(8))
    sticky = None
    while pending or inflight:
        while pending and len(inflight) < quota:
            rid = pick_replica(rows, sticky)
            inflight.append((pending.pop(0), rid))
            sticky = rid
            assert len(inflight) <= quota, "quota violated"
        done += 1
        inflight.pop(0)
    assert done == 8
    print("fleet selfcheck: router least-loaded/sticky/quota OK")


def _check_pool_routing() -> None:
    """Disaggregation pools: ``pool=`` restricts routing to one role;
    an EMPTY pool falls back to every row (a drained/failed role pool
    degrades to pooled routing instead of stranding requests)."""
    from ray_lightning_tpu.serve.fleet.router import pick_replica

    rows = [{"rid": 0, "active": 3, "queued": 0, "slots": 4,
             "role": "prefill"},
            {"rid": 1, "active": 0, "queued": 0, "slots": 4,
             "role": "decode"},
            {"rid": 2, "active": 1, "queued": 0, "slots": 4,
             "role": "prefill"}]
    assert pick_replica(rows, pool="prefill") == 2   # busier 0 loses
    assert pick_replica(rows, pool="decode") == 1
    # decode pool emptied -> failback to pooled (least-loaded overall)
    no_decode = [r for r in rows if r["role"] != "decode"]
    assert pick_replica(no_decode, pool="decode") == 2
    # rows without a role key count as pooled, never as a named pool
    bare = [{"rid": 7, "active": 0, "queued": 0, "slots": 4}]
    assert pick_replica(bare + rows, pool="prefill") == 2
    assert pick_replica(bare, pool="prefill") == 7   # failback again
    print("fleet selfcheck: pool routing + empty-pool failback OK")


def _check_kvship_codecs() -> None:
    """KV wire bytes by codec: fp8/int8 pages must ride the wire at
    >= 3x under the raw (fp32) control leg, and every codec must
    round-trip shape-exact (bit-exact for raw — the ship→resume parity
    leg tests/test_fleet.py pins end-to-end)."""
    import numpy as np

    from ray_lightning_tpu.comm.quant import (dequantize_blob,
                                              quantize_blob)
    rows = (np.arange(2 * 1 * 64 * 2 * 16, dtype=np.float32)
            .reshape(2, 1, 64, 2, 16) / 777.0 - 1.1).astype("bfloat16")
    raw_payload, _ = quantize_blob(rows, "raw")
    raw_bytes = np.asarray(raw_payload).nbytes
    assert raw_bytes == rows.size * 4, "raw control leg must be fp32"
    for codec in ("fp8", "int8"):
        payload, scales = quantize_blob(rows, codec)
        wire = np.asarray(payload).nbytes + (
            np.asarray(scales).nbytes if scales is not None else 0)
        ratio = raw_bytes / wire
        assert ratio >= 3.0, (codec, ratio)
        back = np.asarray(dequantize_blob(payload, scales, codec,
                                          rows.shape))
        assert back.shape == rows.shape, (codec, back.shape)
    back = np.asarray(dequantize_blob(raw_payload, None, "raw",
                                      rows.shape)).astype("bfloat16")
    assert (back == rows).all(), "raw roundtrip not bit-exact"
    print("fleet selfcheck: kvship codec wire-bytes >= 3x + "
          "roundtrip OK")


def _check_federation_directory() -> None:
    """Federation directory invariants: register → lookup →
    invalidate round-trip, liveness expiry (injected clock), forged
    hash collisions never route, size bounded by retained pages."""
    import numpy as np

    from ray_lightning_tpu.serve.fleet.federation import PrefixDirectory
    from ray_lightning_tpu.serve.fleet.pages import _prefix_hash

    clock = [0.0]
    d = PrefixDirectory(page_size=4, ttl_s=10.0, clock=lambda: clock[0])
    base = np.arange(200, 220, dtype=np.int32)
    assert d.register(0, 1, base[:9]) == 8      # whole pages only
    assert d.register(1, 0, base) == 20
    hit = d.lookup(base)
    assert hit == (1, 0, 20), hit               # longest wins
    hit = d.lookup(np.concatenate([base[:8], [5, 5, 5, 5]]),
                   exclude_rid=1)
    assert hit == (0, 1, 8), hit                # exclusion honored
    # hash/exact-token agreement: a forged collision must NOT route
    other = base[:4].copy()
    other[0] = 999
    forged = _prefix_hash(other[:4])
    d._by_hash.setdefault(forged, set()).add((0, 1))
    assert d.lookup(other) is None, "collision routed a fetch"
    d._by_hash.pop(forged, None)
    # affinity mirrors lookup, per replica
    aff = d.affinity(base)
    assert aff == {0: 8, 1: 20}, aff
    # size bounded: re-registration REPLACES (one entry per donor slot)
    d.register(1, 0, base[:12])
    assert d.entries() == 2 and d.pages() == 2 + 3
    # invalidation round-trip
    d.invalidate(0, 1)
    assert d.lookup(base[:8]) == (1, 0, 8)
    d.invalidate_replica(1)
    assert d.lookup(base) is None
    assert d.entries() == 0 and not d._by_hash
    # liveness: entries past ttl_s are dead AND get pruned in passing
    d.register(2, 3, base[:8])
    clock[0] = 11.0
    assert d.lookup(base) is None
    assert d.entries() == 0, "expired entry not pruned"
    st = d.stats()
    assert st["hits"] == 3 and st["invalidations"] == 2, st
    print("fleet selfcheck: federation directory register/lookup/"
          "invalidate + liveness expiry OK")


def _check_metric_names() -> None:
    from ray_lightning_tpu.telemetry.metrics import validate_metric_name
    for name in ("rlt_fleet_replicas_total",
                 "rlt_fleet_queue_depth_total",
                 "rlt_fleet_active_slots_total",
                 "rlt_fleet_requests_total",
                 "rlt_fleet_grow_total", "rlt_fleet_shrink_total",
                 "rlt_fleet_failover_total",
                 "rlt_fleet_scale_seconds_total",
                 "rlt_serve_prefill_tokens_total",
                 "rlt_kvship_ships_total", "rlt_kvship_bytes_total",
                 "rlt_kvship_retries_total",
                 "rlt_kvship_failovers_total"):
        validate_metric_name(name)
    print("fleet selfcheck: metric names Prometheus-clean")


def _main(argv: list) -> int:
    _check_config_roundtrip()
    _check_page_pool()
    _check_prefix_index()
    _check_autoscaler()
    _check_router_policy()
    _check_pool_routing()
    _check_kvship_codecs()
    _check_federation_directory()
    _check_metric_names()
    return 0


if __name__ == "__main__":   # pragma: no cover - exercised via format.sh
    import sys
    sys.exit(_main(sys.argv[1:]))
