"""Fleet serving plane: a front-door router over N serve replicas
(ROADMAP item 2 — "millions of users need many fleets").

Layout:

- ``router.py``    — :class:`FleetServer`: the public front door —
  least-loaded + tenant-sticky routing, fleet-wide quotas, failover
  with flight-recorder-linked reports
- ``replica.py``   — :class:`FleetReplica`: lifecycle + load probes
  around one unmodified :class:`~ray_lightning_tpu.serve.server.Server`
- ``autoscale.py`` — :class:`Autoscaler`: queue-depth / TTFT-p99-driven
  grow & shrink between ``min_replicas``/``max_replicas`` with
  patience + cooldown debouncing
- ``pages.py``     — paged KV accounting + the prefix-hash index behind
  "shared system prompts prefill once per replica"
- ``federation.py`` — :class:`PrefixDirectory`: the router-resident
  fleet-wide donor registry behind "prefill once per FLEET" — replicas
  advertise retained prefixes, admissions on other replicas pull the
  pages over the KV-ship plane instead of re-prefilling
- ``config.py``    — :class:`FleetConfig` (+ the RLT_FLEET* env
  round-trip)
- ``selfcheck.py`` — dependency-light invariants for
  ``format.sh --check``
"""

from ray_lightning_tpu.serve.fleet.autoscale import (  # noqa: F401
    Autoscaler,
)
from ray_lightning_tpu.serve.fleet.config import FleetConfig  # noqa: F401
from ray_lightning_tpu.serve.fleet.federation import (  # noqa: F401
    PrefixDirectory,
)
from ray_lightning_tpu.serve.fleet.pages import (  # noqa: F401
    PageConfig,
    PagedKV,
    PagePool,
    PrefixIndex,
)
from ray_lightning_tpu.serve.fleet.replica import (  # noqa: F401
    FleetReplica,
)
from ray_lightning_tpu.serve.fleet.router import (  # noqa: F401
    FleetReplicaLost,
    FleetRequest,
    FleetServer,
    pick_replica,
)

__all__ = [
    "FleetServer",
    "FleetRequest",
    "FleetReplica",
    "FleetReplicaLost",
    "FleetConfig",
    "Autoscaler",
    "PageConfig",
    "PagedKV",
    "PagePool",
    "PrefixDirectory",
    "PrefixIndex",
    "pick_replica",
]
