"""Signal-driven replica autoscaling: the decision state machine.

The :class:`Autoscaler` is deliberately PURE policy — it consumes a
signals dict (queue depth, active slots, recent TTFT p99: the same
per-tenant numbers the trace plane already exports on /status and
/metrics) and returns grow/shrink decisions; the
:class:`~ray_lightning_tpu.serve.fleet.router.FleetServer` pump is the
actuator (spawn a replica via the cluster backends; drain one via the
serve analog of shrink-to-continue).  Keeping decide separate from
actuate is what makes the cooldown/patience state machine testable
without a fleet (fleet/selfcheck.py drives it with synthetic signals).

State machine:

- each ``tick(signals)`` evaluates the grow and shrink predicates;
- a predicate must hold for ``patience_ticks`` CONSECUTIVE ticks
  before the decision fires (debounce: one bursty tick must not scale);
- after a decision fires, no new decision until the actuator reports
  completion via :meth:`note_actuated` AND ``cooldown_s`` elapses —
  actuation takes seconds (a grow compiles a fleet), and deciding again
  from signals measured mid-actuation would oscillate;
- every decision and its measured actuation seconds land in
  :attr:`events` — surfaced on ``/status`` and in the bench's ``fleet``
  JSON field, and counted as ``rlt_fleet_grow_total`` /
  ``rlt_fleet_shrink_total`` / ``rlt_fleet_scale_seconds_total``.
"""

from __future__ import annotations

import time
from typing import Optional

from ray_lightning_tpu.serve.fleet.config import FleetConfig


class Autoscaler:
    """Grow/shrink decisions between ``min_replicas``/``max_replicas``."""

    def __init__(self, cfg: FleetConfig, clock=time.monotonic):
        self.cfg = cfg
        self._clock = clock
        self._grow_streak = 0
        self._shrink_streak = 0
        #: monotonic time before which no decision may fire
        self._cooldown_until = 0.0
        #: a fired decision not yet note_actuated (blocks new decisions)
        self._in_flight: Optional[dict] = None
        #: decision log: {action, reason, at, seconds, ok}
        self.events: list[dict] = []

    # -- predicates --------------------------------------------------------

    def _grow_reason(self, s: dict) -> Optional[str]:
        replicas = max(1, int(s.get("replicas", 1)))
        if replicas >= self.cfg.max_replicas:
            return None
        queued = float(s.get("queued", 0))
        per_replica = queued / replicas
        if per_replica > self.cfg.grow_queue_depth:
            return (f"queue_depth {queued:.0f} over {replicas} replica(s)"
                    f" > {self.cfg.grow_queue_depth:g}/replica")
        ttft = s.get("ttft_p99_ms")
        if self.cfg.grow_ttft_p99_ms is not None and ttft is not None \
                and float(ttft) > self.cfg.grow_ttft_p99_ms:
            return (f"ttft_p99 {float(ttft):.1f}ms"
                    f" > {self.cfg.grow_ttft_p99_ms:g}ms")
        return None

    def _shrink_reason(self, s: dict) -> Optional[str]:
        replicas = int(s.get("replicas", 1))
        if replicas <= self.cfg.min_replicas:
            return None
        if float(s.get("queued", 0)) > 0:
            return None
        slots = max(1, int(s.get("slots_total", 1)))
        occupancy = float(s.get("active", 0)) / slots
        if occupancy < self.cfg.shrink_occupancy:
            return (f"occupancy {occupancy:.2f}"
                    f" < {self.cfg.shrink_occupancy:g} with empty queue")
        return None

    # -- the tick ----------------------------------------------------------

    def tick(self, signals: dict) -> Optional[dict]:
        """Evaluate one tick; returns ``{"action": "grow"|"shrink",
        "reason": ...}`` when a decision fires, else None."""
        if self._in_flight is not None:
            return None
        now = self._clock()
        grow = self._grow_reason(signals)
        shrink = self._shrink_reason(signals)
        self._grow_streak = self._grow_streak + 1 if grow else 0
        self._shrink_streak = self._shrink_streak + 1 if shrink else 0
        if now < self._cooldown_until:
            return None
        action = reason = None
        # grow wins ties: under-capacity hurts users, over-capacity
        # only hurts the bill
        if grow and self._grow_streak >= self.cfg.patience_ticks:
            action, reason = "grow", grow
        elif shrink and self._shrink_streak >= self.cfg.patience_ticks:
            action, reason = "shrink", shrink
        if action is None:
            return None
        self._grow_streak = self._shrink_streak = 0
        event = {"action": action, "reason": reason,
                 "at": time.time(), "seconds": None, "ok": None}
        self.events.append(event)
        self._in_flight = event
        return {"action": action, "reason": reason}

    def note_actuated(self, seconds: float, ok: bool = True) -> None:
        """The actuator reports the fired decision finished (or failed);
        the cooldown clock starts HERE, not at decide time."""
        if self._in_flight is None:
            return
        self._in_flight["seconds"] = round(float(seconds), 3)
        self._in_flight["ok"] = bool(ok)
        self._in_flight = None
        self._cooldown_until = self._clock() + self.cfg.cooldown_s

    # -- evidence ----------------------------------------------------------

    @property
    def in_cooldown(self) -> bool:
        return self._clock() < self._cooldown_until

    @property
    def actuating(self) -> bool:
        return self._in_flight is not None

    def stats(self) -> dict:
        return {
            "events": [dict(e) for e in self.events],
            "grows": sum(1 for e in self.events if e["action"] == "grow"),
            "shrinks": sum(1 for e in self.events
                           if e["action"] == "shrink"),
            "in_cooldown": self.in_cooldown,
            "actuating": self.actuating,
        }


__all__ = ["Autoscaler"]
