"""One serve replica in a fleet: an unmodified :class:`Server` plus the
router's bookkeeping around it.

A replica is NOT a new execution engine — it wraps one
:class:`~ray_lightning_tpu.serve.server.Server` (itself an SPMD fleet
of worker actors placed through the existing cluster backends) and adds
what the front-door router needs: a lifecycle state machine, cheap load
probes for routing, and the withdraw/failover surface.

States::

    starting ──► serving ──► draining ──► stopped
        │            │
        └────────────┴──────► dead    (mid-serve fleet failure)

``starting`` replicas receive no traffic (the grow actuator flips them
to ``serving`` once ``Server.start()`` returns with warm programs);
``draining`` replicas finish their in-flight requests but receive no
new ones (the serve analog of shrink-to-continue); ``dead`` replicas
had a mid-serve failure — their in-flight requests were failed by the
server pump (cause + flight-recorder dumps in
``server.failure_report``) and the router fails over what it can.
"""

from __future__ import annotations

import threading
import time
from typing import Optional


class FleetReplica:
    """Router-side handle on one serve replica."""

    def __init__(self, rid: int, server, role: str = "pooled"):
        self.id = int(rid)
        self.server = server
        #: disaggregation pool (config.py roles): "prefill" replicas
        #: take admissions, "decode" replicas finish shipped requests,
        #: "pooled" does both (the router fails back to pooled routing
        #: when a dedicated pool empties)
        self.role = str(role)
        self.state = "starting"
        self.created_at = time.time()
        self.started_at: Optional[float] = None
        #: set by the grow/start actuator on failure (distinct from a
        #: mid-serve death, which lands in server.failure_report)
        self.start_error: Optional[BaseException] = None
        self._lock = threading.Lock()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "FleetReplica":
        """Blocking ``Server.start()`` (spawn actors, compile, warm);
        flips to ``serving``.  Run from the grow actuator thread — the
        router pump never blocks on this."""
        try:
            self.server.start()
        except BaseException as e:
            self.start_error = e
            self.state = "dead"
            raise
        self.started_at = time.time()
        self.state = "serving"
        return self

    def mark_draining(self) -> None:
        with self._lock:
            if self.state == "serving":
                self.state = "draining"

    def mark_dead(self) -> None:
        with self._lock:
            self.state = "dead"

    def shutdown(self, graceful: bool = True) -> None:
        try:
            self.server.shutdown(graceful=graceful)
        finally:
            if self.state != "dead":
                self.state = "stopped"

    # -- probes ------------------------------------------------------------

    @property
    def failed(self) -> bool:
        """A mid-serve fleet failure surfaced on this replica's pump."""
        return getattr(self.server, "_error", None) is not None

    @property
    def routable(self) -> bool:
        return self.state == "serving" and not self.failed

    @property
    def active(self) -> int:
        return self.server.scheduler.active_count

    @property
    def queued(self) -> int:
        return self.server.scheduler.queued_count

    @property
    def slots(self) -> int:
        return self.server.scheduler.allocator.slots

    def idle(self) -> bool:
        return self.server.scheduler.idle()

    def load_row(self) -> dict:
        """The routing-policy view of this replica
        (serve/fleet/router.py pick_replica)."""
        return {"rid": self.id, "active": self.active,
                "queued": self.queued, "slots": self.slots,
                "role": self.role}

    def status(self) -> dict:
        sched = self.server.scheduler
        doc = {
            "state": self.state,
            "role": self.role,
            "active": sched.active_count,
            "queued": sched.queued_count,
            "slots": sched.allocator.slots,
            "completed": sched.completed,
            "failed": sched.failed,
        }
        if sched.pages is not None:
            doc["pages"] = sched.pages.stats()
        report = getattr(self.server, "failure_report", None)
        if report is not None:
            doc["failure"] = report
        return doc


__all__ = ["FleetReplica"]
