"""Fleet-plane configuration: replica bounds + autoscaler policy.

``FleetConfig`` is the router's and autoscaler's shared knob set,
resolved like every other plane config (CommPolicy / ElasticConfig /
PageConfig): an explicit object or dict wins, ``None`` reads the
``RLT_FLEET*`` env knobs, and :meth:`worker_env` reproduces the config
via :meth:`resolve` in a worker process — so replica actors inherit the
fleet config under both cluster backends exactly the way ``RLT_COMM*``
and ``RLT_ELASTIC*`` ship (the satellite's round-trip contract, pinned
by fleet/selfcheck.py and tests/test_fleet.py).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Optional


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """How the fleet scales and routes.

    min_replicas / max_replicas: the autoscaler's bounds; the router
        also grows back toward ``min_replicas`` after a failover.
    grow_queue_depth: queued requests PER SERVING REPLICA above which
        the autoscaler votes grow.
    grow_ttft_p99_ms: recent fleet TTFT p99 above which the autoscaler
        votes grow (None = queue signal only).
    shrink_occupancy: live-slot fraction below which (with an empty
        queue) the autoscaler votes shrink.
    patience_ticks: consecutive agreeing ticks before a decision fires
        (debounce — one bursty tick must not scale the fleet).
    cooldown_s: seconds after an action completes before the next may
        fire (grow actuation takes seconds; deciding again from stale
        signals mid-actuation would oscillate).
    tick_interval_s: autoscaler evaluation cadence.
    sticky_slack: tenant stickiness tolerance — the tenant's last
        replica wins routing while its active-slot load is within this
        many slots of the least-loaded replica (KV affinity keeps
        prefix-reuse hits local without defeating load balance).
    roles: per-replica pool assignment for disaggregated serving —
        one of ``"prefill"`` / ``"decode"`` / ``"pooled"`` per replica
        index, cycled when the fleet outgrows the tuple.  Empty =
        every replica pooled (the pre-disaggregation behavior).  With
        both a prefill and a decode pool routable, an admission
        prefills on the prefill pool, its KV pages ship over the peer
        channel (``kvship_codec``), and the decode replica finishes
        the request; either pool emptying fails back to pooled
        routing.
    kvship_codec: wire codec for shipped KV pages (comm/quant.py):
        ``"fp8"`` (default), ``"int8"``, ``"int4"``, ``"bf16"``, or
        ``"raw"`` (the uncompressed fp32 A/B control leg).
    prefix_fed: fleet-level prefix-cache federation
        (serve/fleet/federation.py): replicas advertise retained
        donors to a router-resident directory, and an admission whose
        prefix lives on ANOTHER replica fetches the pages over the
        KV-ship plane instead of re-prefilling — shared prompts
        prefill once per FLEET, not once per replica.  Requires
        paging; off keeps routing and reuse per-replica.
    prefix_fed_ttl_s: directory-entry liveness window — an
        advertisement older than this is treated as dead (a wedged
        replica's donors age out instead of attracting doomed
        fetches).
    prefix_fed_fetches: max concurrent federated fetches (the
        capacity gate): a directory hit past this budget dispatches
        normally and prefills locally rather than queueing behind the
        wire.
    """

    min_replicas: int = 1
    max_replicas: int = 1
    grow_queue_depth: float = 4.0
    grow_ttft_p99_ms: Optional[float] = None
    shrink_occupancy: float = 0.25
    patience_ticks: int = 2
    cooldown_s: float = 10.0
    tick_interval_s: float = 0.5
    sticky_slack: int = 1
    roles: "tuple[str, ...]" = ()
    kvship_codec: str = "fp8"
    prefix_fed: bool = False
    prefix_fed_ttl_s: float = 30.0
    prefix_fed_fetches: int = 2

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError("fleet min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError("fleet max_replicas must be >= min_replicas")
        if self.grow_queue_depth <= 0:
            raise ValueError("fleet grow_queue_depth must be > 0")
        if not (0.0 <= self.shrink_occupancy <= 1.0):
            raise ValueError("fleet shrink_occupancy must be in [0, 1]")
        if self.patience_ticks < 1:
            raise ValueError("fleet patience_ticks must be >= 1")
        if self.cooldown_s < 0:
            raise ValueError("fleet cooldown_s must be >= 0")
        if self.tick_interval_s <= 0:
            raise ValueError("fleet tick_interval_s must be > 0")
        if self.sticky_slack < 0:
            raise ValueError("fleet sticky_slack must be >= 0")
        object.__setattr__(self, "roles", tuple(self.roles))
        for r in self.roles:
            if r not in ("prefill", "decode", "pooled"):
                raise ValueError(
                    f"fleet role {r!r}: must be prefill/decode/pooled")
        from ray_lightning_tpu.comm.quant import CODEC_MODES
        if self.kvship_codec not in CODEC_MODES + ("raw",):
            raise ValueError(
                f"kvship_codec {self.kvship_codec!r}: must be one of "
                f"{CODEC_MODES + ('raw',)}")
        if self.prefix_fed_ttl_s <= 0:
            raise ValueError("fleet prefix_fed_ttl_s must be > 0")
        if self.prefix_fed_fetches < 1:
            raise ValueError("fleet prefix_fed_fetches must be >= 1")

    # -- construction ----------------------------------------------------

    @classmethod
    def resolve(cls, value: Any) -> "FleetConfig":
        if isinstance(value, cls):
            return value
        if isinstance(value, dict):
            return cls(**value)
        if value is not None:
            raise TypeError(f"bad fleet config: {value!r}")
        ttft_raw = os.environ.get("RLT_FLEET_GROW_TTFT_MS", "").strip()
        return cls(
            min_replicas=int(os.environ.get("RLT_FLEET_MIN", "1") or 1),
            max_replicas=int(os.environ.get(
                "RLT_FLEET_MAX",
                os.environ.get("RLT_FLEET_MIN", "1") or "1") or 1),
            grow_queue_depth=float(
                os.environ.get("RLT_FLEET_GROW_QUEUE", "4") or 4),
            grow_ttft_p99_ms=float(ttft_raw) if ttft_raw else None,
            shrink_occupancy=float(
                os.environ.get("RLT_FLEET_SHRINK_OCC", "0.25") or 0.25),
            patience_ticks=int(
                os.environ.get("RLT_FLEET_PATIENCE", "2") or 2),
            cooldown_s=float(
                os.environ.get("RLT_FLEET_COOLDOWN", "10") or 10),
            tick_interval_s=float(
                os.environ.get("RLT_FLEET_TICK", "0.5") or 0.5),
            sticky_slack=int(
                os.environ.get("RLT_FLEET_STICKY_SLACK", "1") or 1),
            roles=tuple(
                r.strip()
                for r in os.environ.get("RLT_FLEET_ROLES", "").split(",")
                if r.strip()),
            kvship_codec=os.environ.get(
                "RLT_KVSHIP_CODEC", "fp8").strip() or "fp8",
            prefix_fed=os.environ.get(
                "RLT_FLEET_PREFIX_FED", "").strip()
            in ("1", "true", "True"),
            prefix_fed_ttl_s=float(os.environ.get(
                "RLT_FLEET_PREFIX_FED_TTL", "30") or 30),
            prefix_fed_fetches=int(os.environ.get(
                "RLT_FLEET_PREFIX_FED_FETCHES", "2") or 2),
        )

    # -- env round-trip --------------------------------------------------

    def worker_env(self) -> dict:
        """Env mapping reproducing this config via :meth:`resolve` in a
        worker process (fleet/selfcheck.py pins the round-trip)."""
        env = {
            "RLT_FLEET_MIN": str(self.min_replicas),
            "RLT_FLEET_MAX": str(self.max_replicas),
            "RLT_FLEET_GROW_QUEUE": repr(self.grow_queue_depth),
            "RLT_FLEET_SHRINK_OCC": repr(self.shrink_occupancy),
            "RLT_FLEET_PATIENCE": str(self.patience_ticks),
            "RLT_FLEET_COOLDOWN": repr(self.cooldown_s),
            "RLT_FLEET_TICK": repr(self.tick_interval_s),
            "RLT_FLEET_STICKY_SLACK": str(self.sticky_slack),
        }
        if self.grow_ttft_p99_ms is not None:
            env["RLT_FLEET_GROW_TTFT_MS"] = repr(self.grow_ttft_p99_ms)
        if self.roles:
            env["RLT_FLEET_ROLES"] = ",".join(self.roles)
        if self.kvship_codec != "fp8":
            env["RLT_KVSHIP_CODEC"] = self.kvship_codec
        if self.prefix_fed:
            env["RLT_FLEET_PREFIX_FED"] = "1"
        if self.prefix_fed_ttl_s != 30.0:
            env["RLT_FLEET_PREFIX_FED_TTL"] = repr(self.prefix_fed_ttl_s)
        if self.prefix_fed_fetches != 2:
            env["RLT_FLEET_PREFIX_FED_FETCHES"] = \
                str(self.prefix_fed_fetches)
        return env

    def role_for(self, index: int) -> str:
        """Pool assignment for replica ``index``: the roles tuple,
        cycled so a fleet that outgrows it keeps a deterministic
        assignment; empty tuple = everything pooled."""
        if not self.roles:
            return "pooled"
        return self.roles[index % len(self.roles)]


__all__ = ["FleetConfig"]
