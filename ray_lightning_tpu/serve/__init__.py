"""Serving plane: AOT-compiled multi-tenant inference with continuous
batching (ROADMAP item 1 — the "millions of users, heavy traffic" leg).

Layout:

- ``buckets.py``   — sequence-length buckets (the static-shape contract)
- ``kvcache.py``   — slot-indexed device KV cache spec + slot free-list
- ``scheduler.py`` — driver request queue: tenant quota, fair share,
  continuous batch formation
- ``engine.py``    — worker engine: per-bucket prefill + one decode
  program, AOT-compiled through the persistent compilation cache
- ``worker.py``    — the persistent serve actor (cluster backends)
- ``server.py``    — the public :class:`Server` endpoint
- ``selfcheck.py`` — dependency-light invariants for ``format.sh --check``
- ``fleet/``       — the fleet plane: :class:`FleetServer` router over
  N replicas, signal-driven autoscaling, paged KV with prefix reuse
"""

from ray_lightning_tpu.serve.buckets import (  # noqa: F401
    DEFAULT_BUCKETS,
    bucket_for,
    pad_to_bucket,
    resolve_buckets,
)
from ray_lightning_tpu.serve.kvcache import (  # noqa: F401
    KVCacheSpec,
    SlotAllocator,
)
from ray_lightning_tpu.serve.scheduler import (  # noqa: F401
    Scheduler,
    ServeRequest,
)
from ray_lightning_tpu.serve.server import Server, ServeSpec  # noqa: F401


def __getattr__(name):
    # the fleet plane imports lazily: Server alone must not pay for it
    if name in ("FleetServer", "FleetConfig", "PageConfig"):
        from ray_lightning_tpu.serve import fleet
        return getattr(fleet, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "Server",
    "ServeSpec",
    "FleetServer",
    "FleetConfig",
    "PageConfig",
    "Scheduler",
    "ServeRequest",
    "KVCacheSpec",
    "SlotAllocator",
    "DEFAULT_BUCKETS",
    "resolve_buckets",
    "bucket_for",
    "pad_to_bucket",
]
