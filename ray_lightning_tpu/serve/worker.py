"""The persistent serve actor: the cluster backends' ``serve`` mode.

Training actors live for ONE blocking ``execute(_worker_run, ...)``
call; a :class:`ServeWorker` instead stays resident — ``setup_serve``
builds the engine once (jax.distributed join, compile-cache activation,
telemetry, AOT warmup), then the driver streams ``serve_step`` calls
for the fleet's whole life.  It extends the generic
:class:`~ray_lightning_tpu.cluster.executor.RLTExecutor`, so the
driver-side rendezvous plumbing (node IP / free port / env vars) is the
same one the fit path uses, under both cluster backends.

Lockstep contract: every worker of a fleet receives the IDENTICAL plan
and dispatches the same SPMD programs in the same order; rank 0 alone
returns the produced tokens (outputs are replicated, the others return
``None`` to keep the RPC thin).

Trace plane (telemetry/tracing.py): the plan carries each request's
trace id (prefill entries) and a slot→trace map (decode), so this
worker's prefill/decode spans carry the ids back over the queue channel
and the driver aggregator reassembles one span tree per request.  The
plan may also carry a ``profile`` control dict — the on-demand
``jax.profiler`` window armed by ``POST /debug/profile``; every rank
captures its own subdir for the window's step count.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Optional

from ray_lightning_tpu.cluster.executor import RLTExecutor
from ray_lightning_tpu.telemetry import span
from ray_lightning_tpu.telemetry.tracing import WorkerProfiler

_log = logging.getLogger(__name__)


class ServeWorker(RLTExecutor):
    """One per TPU host; holds the :class:`ServeEngine` across calls."""

    def __init__(self, env: Optional[dict] = None):
        super().__init__(env)
        self._engine = None
        self._rank = 0
        self._nproc = 1
        self._hb = None
        self._telemetry_cfg = None
        self._profiler: Optional[WorkerProfiler] = None

    # -- lifecycle ---------------------------------------------------------

    def setup_serve(self, payload: tuple, rank: int, queue) -> dict:
        """Join the distributed runtime, enable telemetry, build and
        warm the engine.  Returns setup facts the driver logs."""
        from ray_lightning_tpu.plugins.xla import _configure_worker_jax
        _configure_worker_jax()
        import jax

        spec, weights = payload
        self._rank = rank
        self._nproc = int(os.environ.get("RLT_NUM_PROCESSES", "1"))
        if self._nproc > 1:
            jax.distributed.initialize(
                coordinator_address=os.environ["RLT_COORDINATOR"],
                num_processes=self._nproc,
                process_id=rank,
            )
        self._setup_telemetry(spec, rank, queue)
        from ray_lightning_tpu.compile import cache as compile_cache
        compile_cache.activate(spec.compile_cache)

        from ray_lightning_tpu.serve.engine import ServeEngine
        from ray_lightning_tpu.serve.spec import SpecConfig
        # spec/kvship ride the pickled ServeSpec when the driver set
        # them; otherwise the RLT_SPEC_* / RLT_SERVE_KVSHIP worker env
        # (the fleet's replica-actor round-trip) decides here
        sp = getattr(spec, "spec", None)
        if sp is None:
            sp = SpecConfig.resolve(None)
        kvship = getattr(spec, "kvship", None)
        if kvship is None:
            kvship = os.environ.get(
                "RLT_SERVE_KVSHIP", "").strip() in ("1", "true", "True")
        self._engine = ServeEngine(
            spec.module, spec.strategy, spec.buckets, spec.slots,
            spec.max_seq_len, seed=spec.seed, weights=weights,
            paged=getattr(spec, "paged", None),
            spec=sp, kvship=bool(kvship)).setup()
        return {
            "rank": rank,
            "mesh": dict(self._engine._mesh.shape),
            "buckets": list(self._engine.buckets),
            "slots": self._engine.slots,
            "kv_shape": list(self._engine.kv_spec.shape),
            "stats": self._engine.stats(),
        }

    def _setup_telemetry(self, spec, rank: int, queue) -> None:
        cfg = getattr(spec, "telemetry", None)
        self._telemetry_cfg = cfg
        if cfg is None or not cfg.enabled or queue is None:
            return
        from ray_lightning_tpu import telemetry
        from ray_lightning_tpu.telemetry import heartbeat as hb_mod
        telemetry.enable(
            rank=rank,
            sink=lambda recs, _q=queue, _r=rank: _q.put(
                (_r, telemetry.spans_item(_r, recs))),
            capacity=cfg.capacity, flush_every=cfg.flush_every)
        if cfg.metrics:
            telemetry.enable_metrics(
                rank=rank,
                sink=lambda item, _q=queue, _r=rank: _q.put((_r, item)),
                interval=cfg.metrics_interval)
        if not hb_mod.process_heartbeat_active():
            self._hb = hb_mod.HeartbeatSender(
                lambda item, _q=queue, _r=rank: _q.put((_r, item)),
                rank=rank, interval=cfg.heartbeat_interval).start()

    # -- the serving hot path ----------------------------------------------

    def serve_step(self, plan: dict) -> Optional[dict]:
        """Execute one scheduler plan: one decode over every live slot,
        then the admitting prefills (scheduler.py plan format).

        The order is load-bearing.  The decode program has static shapes,
        so it writes K/V for EVERY slot — slots outside ``decode_slots``
        carry ``tokens=0/positions=0`` and get a dummy write at position
        0.  Decode never reads a same-step prefill's state (a slot
        admitted at step k joins the decode at step k+1), so decode-first
        lets each admitting prefill overwrite its slot's dummy entry;
        prefill-first would let the dummy write clobber the prompt's
        position-0 K/V just after the prefill produced it, corrupting
        every subsequent token (position 0 is always inside the mask).
        Free slots that are NOT admitted this step keep the dummy entry
        harmlessly: their next prefill rewrites the whole prefix
        (kvcache.py invariant)."""
        engine = self._engine
        if engine is None:
            raise RuntimeError("serve_step before setup_serve")
        prof = plan.get("profile")
        if prof is not None:
            # on-demand jax.profiler window riding the plan broadcast
            # (POST /debug/profile, telemetry/tracing.py)
            if self._profiler is None:
                self._profiler = WorkerProfiler(rank=self._rank)
            self._profiler.maybe_start(prof)
        result: dict[str, Any] = {"prefill": {}, "decode": {}}
        decode = plan.get("decode")
        if decode is not None and decode.get("spec"):
            # speculative round: k draft steps then ONE batched target
            # verify; the SCHEDULER decides acceptance from the raw
            # outputs (scheduler._apply_spec), workers stay stateless
            import time as _time
            t0 = _time.monotonic()
            with span("draft", traces=decode.get("traces"),
                      slots=len(decode["slots"])):
                drafts = engine.draft(decode["tokens"],
                                      decode["positions"])
            t1 = _time.monotonic()
            with span("verify", traces=decode.get("traces"),
                      slots=len(decode["slots"])):
                ver = engine.verify(decode["tokens"],
                                    decode["positions"], drafts)
            t2 = _time.monotonic()
            for s in decode["slots"]:
                result["decode"][s] = {
                    "draft": [int(x) for x in drafts[s]],
                    "verify": [int(x) for x in ver[s]]}
            # wall attribution for the goodput ledger (server pump):
            # draft/verify are their own buckets, not "decode"
            result["timing"] = {"draft": t1 - t0, "verify": t2 - t1}
        elif decode is not None:
            # ONE span for the shared decode program, fanned out to
            # every live request's tree via the slot→trace map
            with span("decode", traces=decode.get("traces"),
                      slots=len(decode["slots"])):
                toks = engine.decode(decode["tokens"],
                                     decode["positions"])
            for s in decode["slots"]:
                result["decode"][s] = int(toks[s])
        for p in plan["prefills"]:
            reuse = p.get("reuse")
            with span("prefill", trace=p.get("trace"),
                      bucket=p["bucket"], slot=p["slot"],
                      reused=(reuse or {}).get("matched", 0)):
                if reuse is not None:
                    # prefix-cache hit (serve/fleet/pages.py): copy the
                    # matched donor pages, compute only the suffix
                    result["prefill"][p["slot"]] = engine.prefill_reused(
                        p["slot"], reuse["src"], p["tokens"],
                        p["length"], reuse["matched"])
                else:
                    result["prefill"][p["slot"]] = engine.prefill(
                        p["slot"], p["tokens"], p["length"], p["bucket"])
            if p.get("draft"):
                # prime the draft cache for the admitted prompt (fresh
                # AND reused admissions — the draft cache has no
                # kv_copy plane, it always recomputes the full prefix)
                engine.draft_prefill(p["slot"], p["tokens"],
                                     p["length"], p["bucket"])
            exp = p.get("export_kv")
            if exp is not None:
                # ship-bound prefill (disaggregation leg 1): the donor
                # rows ride back WITH the step result, so the router's
                # KV ship never pays a second worker round-trip nor
                # races this slot's later eviction
                with span("kv_export", slot=p["slot"],
                          bucket=exp["bucket"]):
                    rows = engine.export_kv(p["slot"], exp["bucket"])
                result.setdefault("kv_export", {})[p["slot"]] = rows
        if self._profiler is not None:
            self._profiler.note_step()
        return result if self._rank == 0 else None

    # -- KV-page shipping (fleet disaggregation) ---------------------------

    def serve_export_kv(self, slot: int, bucket: int):
        """Device→host donor rows for the router's KV-ship leg.  Runs
        on every rank (the gather is SPMD-replicated); rank 0 alone
        returns the payload, mirroring ``serve_step``."""
        with span("kv_export", slot=slot, bucket=bucket):
            rows = self._engine.export_kv(slot, bucket)
        return rows if self._rank == 0 else None

    def serve_import_kv(self, slot: int, k_rows, v_rows) -> None:
        """Install shipped donor rows (engine ``kv_import_{b}``) —
        dispatched on every rank to keep the SPMD fleet in lockstep."""
        with span("kv_import", slot=slot,
                  bucket=int(k_rows.shape[2])):
            self._engine.import_kv(slot, k_rows, v_rows)

    # -- evidence / teardown -----------------------------------------------

    def serve_stats(self) -> dict:
        return self._engine.stats() if self._engine is not None else {}

    def teardown_serve(self) -> None:
        """Graceful worker exit: flush telemetry, leave the coordination
        service cleanly (the fit path's teardown discipline,
        plugins/xla.py)."""
        if self._profiler is not None:
            self._profiler.stop()   # close a window the drain truncated
        cfg = self._telemetry_cfg
        if cfg is not None and cfg.enabled:
            from ray_lightning_tpu import telemetry
            telemetry.flush_metrics()
            telemetry.disable_metrics()
            telemetry.flush()
            telemetry.disable()
            if self._hb is not None:
                self._hb.stop()
        if self._nproc > 1:
            import jax
            try:
                jax.distributed.shutdown()
            except RuntimeError:
                pass


__all__ = ["ServeWorker"]
