"""State streams: in-band serialization of parameter/optimizer pytrees.

The reference round-trips trained weights from rank-0 worker to driver as
an in-memory byte stream (torch.save → BytesIO, util.py:71-90) because
PL's temp-file handoff breaks multi-node (rationale at ray_ddp.py:480-486).
Same shape here, but TPU-native: pytrees of ``jax.Array`` are fetched to
host, converted to numpy and serialized with flax's msgpack codec — no
pickle on the hot path, no torch dependency, and the stream is
platform-independent (a stream produced on a TPU pod loads on a CPU-only
driver).
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from flax import serialization


def _to_host(tree: Any) -> Any:
    """Fetch a pytree of (possibly sharded, device-resident) arrays to host
    numpy.  For multi-host global arrays callers must gather addressable
    shards first (see parallel/gather.py)."""

    def _leaf(x):
        if isinstance(x, jax.Array):
            return np.asarray(jax.device_get(x))
        if isinstance(x, (np.ndarray, np.generic, int, float, bool, bytes, str)):
            return x
        return x

    return jax.tree_util.tree_map(_leaf, tree)


def to_state_stream(state: Any) -> bytes:
    """Serialize a pytree of arrays into a byte stream (util.py:71-75 analog)."""
    host_tree = serialization.to_state_dict(_to_host(state))
    return serialization.msgpack_serialize(host_tree)


def load_state_stream(stream: bytes, target: Any | None = None) -> Any:
    """Deserialize a state stream.

    Without ``target``, returns the raw nested-dict-of-numpy form.  With
    ``target`` (a pytree of matching structure), restores into that
    structure via flax's ``from_state_dict`` — the analog of the
    ``map_location`` rehydration in util.py:78-90, except placement is
    deferred to the caller (JAX arrays are placed by the jitted program's
    shardings, not at deserialization time).
    """
    tree = serialization.msgpack_restore(stream)
    if target is None:
        return tree
    return serialization.from_state_dict(target, tree)
