"""Optional-dependency guards.

Mirrors the reference's ``Unavailable`` sentinel + ``TUNE_INSTALLED`` /
``HOROVOD_AVAILABLE`` flag pattern (reference: ray_lightning/util.py:40-44,
ray_lightning/tune.py:13-27, ray_lightning/ray_horovod.py:17-25): a missing
optional dependency is replaced by a class that raises a clear error on
*use*, never on import, so the core framework degrades gracefully.
"""

from __future__ import annotations

import importlib.util


class Unavailable:
    """Placeholder for a class from a dependency that is not installed.

    Raises on instantiation (not on import), matching the reference's
    contract (util.py:40-44).
    """

    _reason = "This class requires a dependency that is not installed."

    def __init__(self, *args, **kwargs):
        raise ImportError(self._reason)

    def __init_subclass__(cls, **kwargs):
        raise ImportError(cls._reason)


def _has(mod: str) -> bool:
    try:
        return importlib.util.find_spec(mod) is not None
    except (ImportError, ModuleNotFoundError, ValueError):
        return False


#: True when a real Ray runtime is importable.  The built-in subprocess
#: actor backend (cluster/local.py) is used otherwise, so unlike the
#: reference — which hard-requires Ray (setup.py:12) — everything here
#: works without it.
RAY_AVAILABLE: bool = _has("ray")

#: torch is only used for interop (datasets / DataLoader collation and
#: torch-tensor batch conversion); the compute path is pure JAX.
TORCH_AVAILABLE: bool = _has("torch")
