"""Platform/env helpers shared by the plugins and driver entry points."""

from __future__ import annotations

import os

_FORCE_FLAG = "xla_force_host_platform_device_count"


def host_device_count_flags(n: int, base_flags: str | None = None) -> str:
    """XLA_FLAGS value with exactly one ``--{_FORCE_FLAG}={n}``.

    Strips any inherited copy of the flag (e.g. from a test harness)
    first, so the virtual-device count is deterministic.
    """
    base = (os.environ.get("XLA_FLAGS", "")
            if base_flags is None else base_flags)
    flags = [f for f in base.split() if _FORCE_FLAG not in f]
    flags.append(f"--{_FORCE_FLAG}={n}")
    return " ".join(flags).strip()
