"""Observability callbacks: throughput measurement + profiler traces.

SURVEY.md §5 (tracing/profiling): the reference's only perf-measurement
code is the sharded example's ``CUDACallback`` (epoch wall time + peak
CUDA memory, examples/ray_ddp_sharded_example.py:16-45), deferring deeper
profiling to external tools.  The TPU-native equivalents here:

- :class:`ThroughputMonitor` — steps/sec, tokens or samples/sec, epoch
  wall time and peak device memory (PJRT ``memory_stats`` replacing
  ``torch.cuda.max_memory_allocated``), logged into
  ``trainer.callback_metrics`` so rank-0's numbers ride the normal
  distributed result relay.
- :class:`JaxProfilerCallback` — captures an XLA/TPU trace for a window
  of training steps via ``jax.profiler`` (view in TensorBoard /
  Perfetto), the analog of the torch profiler the reference defers to.

Both are pure host-side hooks: they never appear inside compiled steps,
and the throughput clock is careful to measure async dispatch correctly
(a step's wall time is only meaningful after forcing a device sync, which
the monitor does once per window, not per step).
"""

from __future__ import annotations

import logging
import os
import time
from typing import Optional

import numpy as np

from ray_lightning_tpu.core.callbacks import Callback
from ray_lightning_tpu import telemetry

_log = logging.getLogger(__name__)


def peak_device_memory_bytes() -> Optional[int]:
    """Peak HBM bytes in use on the first local device, if the PJRT
    backend reports it (TPU does; CPU typically returns nothing)."""
    import jax
    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    return stats.get("peak_bytes_in_use")


class ThroughputMonitor(Callback):
    """Log steps/sec, samples/sec (and tokens/sec for sequence batches),
    per-epoch wall time and peak device memory.

    ``window`` controls how often the device is synced to take a
    measurement — syncing per step would serialize async dispatch and
    slow training, so the monitor forces one sync every ``window`` steps
    and averages over the window.
    """

    @staticmethod
    def _emit(trainer, name: str, value: float) -> None:
        """One emission path: ``callback_metrics`` (rank-0's copy rides
        the distributed result relay) AND a telemetry counter (every
        rank's value lands on the merged driver timeline)."""
        trainer.log_metric(name, value)
        telemetry.counter(name, value)

    def __init__(self, window: int = 50, log_tokens: bool = True):
        self.window = max(1, int(window))
        self.log_tokens = log_tokens
        self._t0: Optional[float] = None
        self._epoch_t0: Optional[float] = None
        self._units = 0
        self._samples = 0
        self._steps = 0           # optimizer steps in the current window
        self._prev_step = 0       # last observed trainer.global_step

    @staticmethod
    def _sync(outputs) -> None:
        """Force completion of the async-dispatched window."""
        import jax
        leaves = [x for x in jax.tree_util.tree_leaves(outputs)
                  if isinstance(x, jax.Array)]
        if leaves:
            jax.block_until_ready(leaves[-1])

    def _reset_window(self, trainer) -> None:
        self._t0 = None
        self._units = 0
        self._samples = 0
        self._steps = 0
        self._prev_step = trainer.global_step

    def on_train_epoch_start(self, trainer, module):
        self._epoch_t0 = time.monotonic()
        self._prev_step = trainer.global_step

    def on_validation_start(self, trainer, module):
        # mid-epoch eval does host+device work outside training; drop the
        # current window so it cannot deflate steps/sec
        self._reset_window(trainer)

    def on_train_batch_end(self, trainer, module, outputs, batch, batch_idx):
        import jax
        # under steps_per_execution>1 this hook fires once per CHUNK with
        # its last batch: count real optimizer steps by global_step delta
        # and scale the sample/token tally by it (uniform batch shapes —
        # the compiled multi-step requires them anyway)
        delta = max(1, trainer.global_step - self._prev_step)
        self._prev_step = trainer.global_step
        self._steps += delta
        leaves = [x for x in jax.tree_util.tree_leaves(batch)
                  if hasattr(x, "shape") and getattr(x, "ndim", 0) >= 1]
        if leaves:
            lead = leaves[0]
            self._samples += int(lead.shape[0]) * delta
            # tokens/sec only for [B, T] integer batches (token ids);
            # float [B, features...] batches are not sequences
            is_tokens = (self.log_tokens and lead.ndim == 2
                         and np.issubdtype(np.asarray(lead).dtype,
                                           np.integer))
            self._units += int(lead.shape[0]) * delta * (
                int(lead.shape[1]) if is_tokens else 1)
        if self._steps < self.window:
            return
        self._sync(outputs)
        now = time.monotonic()
        if self._t0 is not None:
            dt = now - self._t0
            self._emit(trainer, "steps_per_sec", self._steps / dt)
            self._emit(trainer, "samples_per_sec", self._samples / dt)
            if self.log_tokens and self._units != self._samples:
                self._emit(trainer, "tokens_per_sec", self._units / dt)
            # peak HBM per window (not just per epoch): regressions show
            # up at window granularity on the telemetry timeline
            peak = peak_device_memory_bytes()
            if peak:
                self._emit(trainer, "peak_memory_mb", peak / 1e6)
        self._t0 = now
        self._units = 0
        self._samples = 0
        self._steps = 0

    def on_train_epoch_end(self, trainer, module):
        if self._epoch_t0 is not None:
            self._emit(trainer, "epoch_time_s",
                       time.monotonic() - self._epoch_t0)
        peak = peak_device_memory_bytes()
        if peak:
            self._emit(trainer, "peak_memory_mb", peak / 1e6)
        # new window per epoch: the epoch boundary does host work
        self._reset_window(trainer)


class JaxProfilerCallback(Callback):
    """Capture a jax.profiler trace for steps [start_step, start_step +
    num_steps) of training; written under ``log_dir`` (default
    ``<default_root_dir>/profile``) for TensorBoard/Perfetto."""

    needs_batch = False   # windows on global_step; never reads the batch

    def __init__(self, start_step: int = 5, num_steps: int = 5,
                 log_dir: Optional[str] = None):
        self.start_step = int(start_step)
        self.num_steps = max(1, int(num_steps))
        self.log_dir = log_dir
        self._active = False
        self._done = False

    def _dir(self, trainer) -> str:
        return self.log_dir or os.path.join(trainer.default_root_dir,
                                            "profile")

    def on_train_batch_start(self, trainer, module, batch, batch_idx):
        # >= so a resumed run already past start_step still captures its
        # window (global_step restores from the checkpoint)
        if self._active or self._done \
                or trainer.global_step < self.start_step:
            return
        import jax
        path = self._dir(trainer)
        os.makedirs(path, exist_ok=True)
        try:
            jax.profiler.start_trace(path)
            self._active = True
            self._started_at = trainer.global_step
        except Exception as e:  # profiling must never kill training
            _log.warning("profiler trace failed to start: %s", e)

    def on_train_batch_end(self, trainer, module, outputs, batch, batch_idx):
        if self._active and trainer.global_step >= \
                self._started_at + self.num_steps:
            self._stop(outputs)

    def on_train_end(self, trainer, module):
        if self._active:
            self._stop(None)

    def _stop(self, outputs) -> None:
        import jax
        if outputs is not None:
            leaves = [x for x in jax.tree_util.tree_leaves(outputs)
                      if isinstance(x, jax.Array)]
            if leaves:  # make the traced window include real device work
                jax.block_until_ready(leaves[-1])
        try:
            jax.profiler.stop_trace()
        except Exception as e:
            _log.warning("profiler trace failed to stop: %s", e)
        self._active = False
        self._done = True
