"""Deterministic seeding across driver and workers.

Replaces PL's ``seed_everything`` / ``reset_seed`` which the reference
invokes per worker before process-group init (ray_ddp.py:403-405).  The
seed is propagated driver→worker through the ``RLT_GLOBAL_SEED`` env var,
the analog of ``PL_GLOBAL_SEED`` (ray_ddp.py:213-219).
"""

from __future__ import annotations

import os
import random

import numpy as np

SEED_ENV_VAR = "RLT_GLOBAL_SEED"


def seed_everything(seed: int | None = None) -> int:
    """Seed python, numpy and record the seed for JAX PRNG-key derivation.

    JAX has no global RNG: modules derive ``jax.random.key(seed)`` streams
    from the returned value (Trainer does this per fit).  Returns the seed
    so callers can thread it explicitly.
    """
    if seed is None:
        env = os.environ.get(SEED_ENV_VAR)
        seed = int(env) if env is not None else random.randint(0, 2**31 - 1)
    seed = int(seed)
    os.environ[SEED_ENV_VAR] = str(seed)
    random.seed(seed)
    np.random.seed(seed % (2**32))
    return seed


def reset_seed() -> int | None:
    """Re-apply the seed recorded in the env, if any (worker-side)."""
    env = os.environ.get(SEED_ENV_VAR)
    if env is None:
        return None
    return seed_everything(int(env))
