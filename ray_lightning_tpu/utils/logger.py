"""Metrics loggers.

The reference inherits PL's logger stack (metrics files under the
trainer's root dir; rank-zero gating via ``rank_zero_only.rank``,
ray_ddp.py:405).  Here :class:`CSVLogger` is the built-in equivalent of
PL's CSVLogger: one ``metrics.csv`` under ``<root>/logs/``, a row per
logging event, columns unioned across events.  ``Trainer(logger=True)``
(the default) installs it; ``logger=False`` disables; any object with a
``log_metrics(dict, step)`` method slots in as a custom logger.

Rank-zero gating happens in the trainer (only rank 0's logger writes),
so files on a shared FS are written once per run, like the reference's
rank-zero-gated PL loggers.

Distributed caveat for CUSTOM loggers: with actor plugins the trainer is
pickled into the workers, so ``log_metrics`` fires on rank-0's *copy* —
a logger must persist externally (file/DB/service, as CSVLogger does);
in-memory state never returns to the driver (only ``callback_metrics``
does, via the result relay).
"""

from __future__ import annotations

import csv
import os
import tempfile
import uuid


class CSVLogger:
    """Append-only CSV metrics log (PL CSVLogger analog).

    O(1) memory: rows append straight to disk; when the column set grows
    (e.g. the first val_* metrics after an epoch) the existing file is
    read back once and rewritten under the new header, so late-appearing
    metrics still land in one coherent table.
    """

    def __init__(self, save_dir: str, name: str = "logs"):
        self.save_dir = save_dir
        self.name = name
        self._fields: list[str] = ["step"]
        self._started = False
        # Identifies THIS logical run across pickled copies (the trainer
        # is re-pickled into workers per dispatch, so fit→validate uses
        # two copies of this object that must share one file) while
        # distinguishing a genuinely new run pointed at the same root
        # dir, which must truncate rather than append to the stale file.
        self._run_id = uuid.uuid4().hex

    @property
    def log_dir(self) -> str:
        return os.path.join(self.save_dir, self.name)

    @property
    def path(self) -> str:
        return os.path.join(self.log_dir, "metrics.csv")

    @property
    def _runid_path(self) -> str:
        return self.path + ".runid"

    def _sync_with_existing_file(self) -> None:
        """Adopt an existing file's columns and switch to append mode —
        but only when the file belongs to this run (runid sidecar
        matches).  A matching file means this logger is a pickled copy of
        the run's original (plugins/xla.py re-pickles the trainer per
        dispatch, e.g. fit then validate) and must append; a mismatched
        or missing sidecar means the file is a leftover from a previous
        run sharing the root dir and must be truncated, not extended.
        """
        if self._started:
            return
        if os.path.exists(self.path):
            try:
                with open(self._runid_path) as f:
                    owner = f.read().strip()
            except OSError:
                owner = None
            if owner != self._run_id:
                return  # stale file from another run: overwrite on write
            with open(self.path, newline="") as f:
                header = next(csv.reader(f), None)
            if header:
                self._fields.extend(
                    k for k in header if k not in self._fields)
                self._started = True

    def log_metrics(self, metrics: dict, step: int) -> None:
        row = {"step": int(step)}
        for k, v in metrics.items():
            try:
                row[k] = float(v)
            except (TypeError, ValueError):
                continue
        self._sync_with_existing_file()
        new_fields = [k for k in row if k not in self._fields]
        if new_fields:
            self._fields.extend(new_fields)
            # schema grew (rare; e.g. first val_* after an epoch): fold
            # the existing file into the new header.  Steady state is an
            # O(1)-memory append — no rows are retained in memory.
            self._rewrite_with_new_header()
        os.makedirs(self.log_dir, exist_ok=True)
        mode = "a" if self._started else "w"
        if mode == "w":
            # Invariant: a sidecar naming run R exists only while the
            # csv holds R's rows.  Unlink first, write the csv, then
            # write the sidecar atomically — a crash anywhere in the
            # sequence leaves "no owner" (the next writer overwrites),
            # never a sidecar pointing at another run's rows (cross-run
            # mixing) and never a run truncating its own partial file.
            try:
                os.remove(self._runid_path)
            except FileNotFoundError:
                pass
        with open(self.path, mode, newline="") as f:
            writer = csv.DictWriter(f, fieldnames=self._fields, restval="")
            if mode == "w":
                writer.writeheader()
            writer.writerow(row)
        if mode == "w":
            fd, tmp = tempfile.mkstemp(dir=self.log_dir)
            with os.fdopen(fd, "w") as f:
                f.write(self._run_id)
            os.replace(tmp, self._runid_path)
        self._started = True

    def _rewrite_with_new_header(self) -> None:
        """Fold the existing file into the grown header.  Crash-safe:
        the re-headered copy is written to a temp file in the same
        directory and ``os.replace``d over the original, so a crash
        mid-rewrite leaves the old complete file, never a truncated
        ``metrics.csv``."""
        if not self._started or not os.path.exists(self.path):
            return
        with open(self.path, newline="") as f:
            old_rows = list(csv.DictReader(f))
        fd, tmp = tempfile.mkstemp(dir=self.log_dir, suffix=".csv")
        try:
            with os.fdopen(fd, "w", newline="") as f:
                writer = csv.DictWriter(f, fieldnames=self._fields,
                                        restval="")
                writer.writeheader()
                for r in old_rows:
                    writer.writerow(r)
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def finalize(self) -> None:
        """Everything is flushed on write; nothing buffered."""


def resolve_logger(logger, default_root_dir: str):
    """Trainer's ``logger=`` argument → a logger object or None.

    True → CSVLogger under the root dir; False/None → no logging;
    anything with ``log_metrics`` → used as-is.
    """
    if logger is True:
        return CSVLogger(default_root_dir)
    if not logger:
        return None
    if hasattr(logger, "log_metrics"):
        return logger
    raise TypeError(
        f"logger must be True/False or expose log_metrics(dict, step); "
        f"got {type(logger).__name__}")
