"""Per-host TPU chip partitioning — the ``_share_cuda_visible_devices``
analog (reference ray_ddp.py:221-265).

The reference unions each node's GPU ids into ``CUDA_VISIBLE_DEVICES``
so co-located workers can address their devices.  TPU inverts the
problem: libtpu assumes one process owns the whole host unless told
otherwise, so when several actors land on ONE TPU host (splitting a
v4-8 into per-chip workers, say) each process must be scoped to its own
chips via the ``TPU_*`` env family *before* libtpu initializes:

- ``TPU_CHIPS_PER_PROCESS_BOUNDS`` — the 3-D topology slab of chips one
  process owns;
- ``TPU_PROCESS_BOUNDS`` — how many such slabs tile the host;
- ``TPU_VISIBLE_CHIPS`` / ``TPU_VISIBLE_DEVICES`` — which local chip
  indices this process may open;
- ``TPU_PROCESS_ADDRESSES`` + ``TPU_PROCESS_PORT`` +
  ``CLOUD_TPU_TASK_ID`` — the co-located processes' local mesh
  rendezvous.

Impossible splits (a chip count that is not a rectangular sub-slab of
the host) raise instead of silently producing a hung libtpu init.
"""

from __future__ import annotations

from typing import Sequence

#: chip-count → 3-D bounds for the host form factors we know how to
#: tile: 1 chip, a chip pair, a v4/v5p host (2×2), a v2/v3/v5e host
#: (2×4).
_BOUNDS: dict[int, tuple[int, int, int]] = {
    1: (1, 1, 1),
    2: (1, 2, 1),
    4: (2, 2, 1),
    8: (2, 4, 1),
}


def process_bounds(devices_per_worker: int,
                   n_colocated: int) -> tuple[str, str]:
    """(chips_per_process_bounds, process_bounds) strings for
    ``n_colocated`` workers each owning ``devices_per_worker`` chips of
    one host.  The split must exactly tile a known host form factor."""
    host_chips = devices_per_worker * n_colocated
    if devices_per_worker not in _BOUNDS or host_chips not in _BOUNDS:
        raise ValueError(
            f"cannot split a TPU host into {n_colocated} workers x "
            f"{devices_per_worker} chips: {host_chips} chips is not a "
            f"known host form factor {sorted(_BOUNDS)} "
            f"(reference analog: _share_cuda_visible_devices, "
            f"ray_ddp.py:221-265)")
    cpb = _BOUNDS[devices_per_worker]
    host = _BOUNDS[host_chips]
    if any(h % c for h, c in zip(host, cpb)):
        raise ValueError(
            f"{devices_per_worker}-chip slab {cpb} does not tile the "
            f"{host_chips}-chip host {host}")
    pb = tuple(h // c for h, c in zip(host, cpb))
    return ",".join(map(str, cpb)), ",".join(map(str, pb))


def partition_env(
    devices_per_worker: int,
    local_rank: int,
    node_ip: str,
    ports: Sequence[int],
) -> dict[str, str]:
    """Env for ONE co-located worker (``local_rank`` of
    ``len(ports)`` on ``node_ip``; ``ports[i]`` is worker i's local
    rendezvous port)."""
    n = len(ports)
    cpb, pb = process_bounds(devices_per_worker, n)
    lo = local_rank * devices_per_worker
    chips = ",".join(str(c) for c in range(lo, lo + devices_per_worker))
    return {
        "TPU_CHIPS_PER_PROCESS_BOUNDS": cpb,
        "TPU_PROCESS_BOUNDS": pb,
        "TPU_VISIBLE_CHIPS": chips,
        "TPU_VISIBLE_DEVICES": chips,  # older libtpu spelling
        "TPU_PROCESS_ADDRESSES": ",".join(
            f"{node_ip}:{p}" for p in ports),
        "TPU_PROCESS_PORT": str(ports[local_rank]),
        "CLOUD_TPU_TASK_ID": str(local_rank),
    }
