"""Sharded (orbax-backed) checkpointing — no host gather.

The parity checkpoint path (core/trainer.py save_checkpoint) mirrors the
reference: gather the full state to the host, serialize one blob
(reference analog: to_state_stream/torch.save, util.py:71-90).  That is
fine at BoringModel scale and wrong at pod scale — gathering a sharded
1.3B+ train state funnels every shard through one host's memory and one
file.

:class:`ShardedCheckpointer` is the TPU-native alternative (SURVEY.md §5
flags exactly this: "state streams must gather sharded (ZeRO) optimizer
state or write per-host shards"): each process writes only the array
shards it owns (orbax OCDBT format), saves run asynchronously behind the
training step, and restore re-shards directly into the CURRENT mesh —
resuming on a different world size or strategy never materializes the
full state on any single host (the reference's resume-with-fewer-workers
case, test_ddp_sharded.py:119-138, at scales where the gather path
cannot).

Paths may be local or fsspec-style remote (gs://...) — orbax talks to
GCS natively, matching the "pods have no shared local FS" default.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax

from ray_lightning_tpu.telemetry import span


def _manager(directory: str, async_save: bool, max_to_keep: Optional[int]):
    import orbax.checkpoint as ocp
    if "://" not in directory:
        directory = os.path.abspath(directory)
    options = ocp.CheckpointManagerOptions(
        max_to_keep=max_to_keep,
        enable_async_checkpointing=async_save,
    )
    # item names/handlers declared up front: a FRESH manager over an
    # existing directory can then answer item_metadata() (the elastic
    # reshard path reads saved shapes before restoring) and restore a
    # subset of items, instead of failing handler inference
    return ocp.CheckpointManager(
        directory, options=options,
        item_names=("state", "meta"),
        item_handlers={"state": ocp.StandardCheckpointHandler(),
                       "meta": ocp.JsonCheckpointHandler()})


def abstract_like(state: Any, shardings: Any) -> Any:
    """ShapeDtypeStruct pytree carrying the target shardings — the
    restore target that tells orbax where every shard should land."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
        state, shardings)


class ShardedCheckpointer:
    """Per-shard async checkpoint manager over a step-numbered directory.

    Layout: ``<directory>/<step>/{state,meta}`` (orbax OCDBT).  ``state``
    is the TrainState pytree written shard-by-shard; ``meta`` is a small
    JSON dict (epoch, global_step, strategy, ...).
    """

    def __init__(self, directory: str, async_save: bool = True,
                 max_to_keep: Optional[int] = None):
        self.directory = directory
        self.max_to_keep = max_to_keep
        self._mgr = _manager(directory, async_save, max_to_keep)

    # -- save ------------------------------------------------------------

    def save(self, step: int, state: Any, meta: Optional[dict] = None):
        """Write ``state`` under ``step``.  Returns immediately when
        async (the copy out of device memory happens first; the disk
        write proceeds behind the training loop).  Saving a step that
        already exists is a no-op (two cadences — e.g. every-N-steps and
        every-epoch — can land on the same global step)."""
        import orbax.checkpoint as ocp
        if int(step) in self._mgr.all_steps():
            return
        # the span covers only the blocking part of an async save (the
        # device→host copy); the disk write proceeds behind training
        with span("checkpoint", step=int(step), sharded=True):
            self._mgr.save(int(step), args=ocp.args.Composite(
                state=ocp.args.StandardSave(state),
                meta=ocp.args.JsonSave(dict(meta or {}))))

    def wait(self) -> None:
        """Block until in-flight async saves hit disk."""
        with span("checkpoint_wait"):
            self._mgr.wait_until_finished()

    def saving_in_progress(self) -> bool:
        """True while a previous async save is still writing — the
        elastic snapshotter's backpressure probe (elastic/snapshot.py).
        Conservatively False on orbax builds without the query (a save
        then simply blocks inside orbax instead of being skipped)."""
        probe = getattr(self._mgr, "is_saving_in_progress", None)
        if probe is None:
            return False
        try:
            return bool(probe())
        except Exception:
            return False

    # -- restore ---------------------------------------------------------

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self) -> list[int]:
        return sorted(self._mgr.all_steps())

    def saved_state_metadata(self, step: Optional[int] = None):
        """Shapes/dtypes of the SAVED ``state`` tree (a nested dict of
        array metadata, no array data read) — what the elastic reshard
        path compares the restore target against so a topology change
        never restores silently wrong (elastic/reshard.py).  ``None``
        when the manager cannot answer (old orbax, remote quirk)."""
        if step is None:
            step = self.latest_step()
        if step is None:
            return None
        try:
            md = self._mgr.item_metadata(int(step))
            return getattr(md, "state", None)
        except Exception:
            return None

    def restore(self, abstract_state: Any,
                step: Optional[int] = None) -> tuple[Any, dict]:
        """Load ``(state, meta)`` at ``step`` (default: latest), sharded
        per ``abstract_state``'s shardings — which may describe a
        different mesh than the one that saved."""
        import orbax.checkpoint as ocp
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"No checkpoint steps under {self.directory}")
        out = self._mgr.restore(int(step), args=ocp.args.Composite(
            state=ocp.args.StandardRestore(abstract_state),
            meta=ocp.args.JsonRestore()))
        return out.state, dict(out.meta or {})

    def close(self) -> None:
        self._mgr.close()

    # -- detection -------------------------------------------------------

    @staticmethod
    def _dir_entries(path: str) -> "Optional[list[str]]":
        # Detection must degrade to "not a sharded checkpoint" on ANY
        # listing failure: remote fsspec backends (gcsfs etc.) raise
        # non-OSError exceptions, and this runs on every restore.
        try:
            if "://" in path:
                import fsspec
                fs, p = fsspec.core.url_to_fs(path)
                if not fs.isdir(p):
                    return None
                return [os.path.basename(e.rstrip("/")) for e in fs.ls(p)]
            if os.path.isdir(path):
                return os.listdir(path)
        except Exception:
            pass
        return None

    @staticmethod
    def split_step_dir(path: str) -> "tuple[str, Optional[int]]":
        """``.../cks/42`` → ``(.../cks, 42)``; a root dir → ``(path,
        None)``.  Users naturally pass either the manager root or one
        specific step directory."""
        base = os.path.basename(path.rstrip("/"))
        if base.isdigit():
            return path.rstrip("/")[: -len(base)].rstrip("/"), int(base)
        return path, None

    @classmethod
    def is_sharded_checkpoint(cls, path: str) -> bool:
        """True when ``path`` is an orbax checkpoint directory — either
        the step-numbered root or one step inside it (vs the single-file
        msgpack format of Trainer.save_checkpoint)."""
        names = cls._dir_entries(path)
        if names is None:
            return False
        root, step = cls.split_step_dir(path)
        if step is not None:
            # a specific step dir: saved items live directly inside
            return any(n in ("state", "meta", "_CHECKPOINT_METADATA")
                       for n in names)
        return any(n.isdigit() for n in names)
