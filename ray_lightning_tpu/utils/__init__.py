from ray_lightning_tpu.utils.imports import (
    RAY_AVAILABLE,
    TORCH_AVAILABLE,
    Unavailable,
)  # noqa: F401  (Unavailable/TORCH_AVAILABLE: optional-dep gate surface)
from ray_lightning_tpu.utils.seed import seed_everything
from ray_lightning_tpu.utils.states import (
    load_state_stream,
    to_state_stream,
)

__all__ = [
    "RAY_AVAILABLE",
    "TORCH_AVAILABLE",
    "Unavailable",
    "seed_everything",
    "to_state_stream",
    "load_state_stream",
]
