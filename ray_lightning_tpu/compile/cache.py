"""Persistent XLA compilation-cache management.

Every actor, every Ray Tune trial and every fault-recovery restart of
this framework dispatches byte-identical SPMD programs — and, without
this module, re-pays full XLA compilation for each of them.  JAX ships a
persistent compilation cache keyed by the serialized HLO + compile
options; what it does NOT ship is lifecycle management: who picks the
directory, how workers of a cluster run share (or seed) it, how tune
trials point at one cache, and how hits/misses become observable.  That
is this module:

- :class:`CompileCacheConfig` — picklable settings carried on the
  Trainer (like ``TelemetryConfig``), resolved from the ``compile_cache=``
  argument, the ``RLT_COMPILE_CACHE*`` env knobs, or the live builtin
  tune session (tune/runner.py points every trial of an experiment at
  one shared cache under the experiment dir).
- :func:`activate` — enables JAX's persistent cache at a *namespaced*
  subdirectory of the configured root
  (``<root>/jax<version>-<platform>-<device kind>-d<devices>-p<procs>``),
  so entries from a different jax version, device kind or topology can
  never collide with this run's, and a shared root stays safe to point
  heterogeneous jobs at.
- Cache accounting: listeners on JAX's monitoring events count cache
  hits / misses and accumulate real backend-compile seconds; the
  metrics plane (telemetry/metrics.py) exposes them as
  ``rlt_compile_cache_hits_total`` / ``rlt_compile_cache_misses_total``
  / ``rlt_compile_seconds_total``, and bench rounds read
  :func:`status_word` for the JSON line's ``compile_cache`` field.

Nothing here imports jax at module load (worker_main touches sibling
packages before jax exists); jax is imported inside the functions that
need a live backend.
"""

from __future__ import annotations

import logging
import os
import re
import threading
from dataclasses import dataclass, field, replace
from typing import Any, Optional

_log = logging.getLogger(__name__)

#: default cache root when enabled without an explicit directory
DEFAULT_ROOT = os.path.join(
    os.path.expanduser("~"), ".cache", "ray_lightning_tpu", "xla")

#: the user-facing env knobs (README "Compilation cache"; validated by
#: compile/selfcheck.py so docs and code can't drift)
ENV_ENABLE = "RLT_COMPILE_CACHE"            # 0 | 1 | /path/to/root
ENV_DIR = "RLT_COMPILE_CACHE_DIR"           # explicit root directory
ENV_MIN_ENTRY = "RLT_COMPILE_CACHE_MIN_ENTRY_BYTES"
ENV_MIN_COMPILE = "RLT_COMPILE_CACHE_MIN_COMPILE_SECS"
ENV_KNOBS = (ENV_ENABLE, ENV_DIR, ENV_MIN_ENTRY, ENV_MIN_COMPILE)


@dataclass
class CompileCacheConfig:
    """Picklable compile-cache settings carried on the Trainer (the
    trainer ships to workers, so the config rides along for free)."""

    enabled: bool = False
    #: cache ROOT; the topology namespace is appended at activation.
    #: None = :data:`DEFAULT_ROOT`.
    dir: Optional[str] = None
    #: persist entries at least this large (bytes; 0 = everything —
    #: jax's own default of 0 kept, the floor exists for shared NFS
    #: roots where tiny entries cost more in metadata than they save)
    min_entry_bytes: int = 0
    #: persist only compiles at least this slow (seconds; 0 = every
    #: compile — deliberately below jax's 1.0 default so short CPU-test
    #: programs and small eval steps warm-start too; raise it on shared
    #: roots if churn becomes a problem)
    min_compile_secs: float = 0.0

    @classmethod
    def resolve(cls, value: Any) -> "CompileCacheConfig":
        """Trainer's ``compile_cache=`` argument → a config.

        ``None`` defers to the environment and the live builtin tune
        session; ``True``/``False`` force (default root); a string is an
        explicit cache root; a dict supplies field overrides (enabled
        unless it says otherwise).  Precedence for ``None``:
        ``RLT_COMPILE_CACHE=0`` kills everything; an env-provided dir
        wins over the tune session's per-experiment dir (a user pointing
        every job at one root beats per-experiment isolation); bare
        ``RLT_COMPILE_CACHE=1`` enables the default root.
        """
        if isinstance(value, cls):
            return value
        if isinstance(value, bool):
            return cls(enabled=value)._with_env_knobs() if value else cls()
        if isinstance(value, str):
            return cls(enabled=True, dir=value)._with_env_knobs()
        if isinstance(value, dict):
            cfg = dict(value)
            cfg.setdefault("enabled", True)
            return cls(**cfg)
        if value is not None:
            raise TypeError(
                f"compile_cache must be None/bool/str/dict/"
                f"CompileCacheConfig; got {type(value).__name__}")
        enable = os.environ.get(ENV_ENABLE, "").strip()
        if enable == "0":
            return cls()
        env_dir = os.environ.get(ENV_DIR, "").strip() or None
        if enable not in ("", "0", "1") and env_dir is None:
            env_dir = enable          # RLT_COMPILE_CACHE=/path/to/root
        if env_dir is None:
            env_dir = _session_cache_dir()
        if env_dir is None and enable != "1":
            return cls()
        return cls(enabled=True, dir=env_dir)._with_env_knobs()

    def _with_env_knobs(self) -> "CompileCacheConfig":
        out = self
        raw = os.environ.get(ENV_MIN_ENTRY, "").strip()
        if raw:
            try:
                out = replace(out, min_entry_bytes=int(raw))
            except ValueError:
                _log.warning("%s=%r is not an integer; ignored",
                             ENV_MIN_ENTRY, raw)
        raw = os.environ.get(ENV_MIN_COMPILE, "").strip()
        if raw:
            try:
                out = replace(out, min_compile_secs=float(raw))
            except ValueError:
                _log.warning("%s=%r is not a number; ignored",
                             ENV_MIN_COMPILE, raw)
        return out

    @property
    def root(self) -> str:
        return self.dir or DEFAULT_ROOT

    def worker_env(self) -> dict[str, str]:
        """Env replicating this config in a spawned worker — belt and
        braces alongside the pickled trainer (covers worker-side code
        that consults the env before the payload arrives)."""
        if not self.enabled:
            return {}
        return {
            ENV_ENABLE: "1",
            ENV_DIR: self.root,
            ENV_MIN_ENTRY: str(self.min_entry_bytes),
            ENV_MIN_COMPILE: str(self.min_compile_secs),
        }


def _session_cache_dir() -> Optional[str]:
    """Shared per-experiment cache dir of the live builtin tune trial
    (tune/runner.py sets it so all same-shape trials warm-start from
    trial 0's compiles), or None outside a trial."""
    try:
        from ray_lightning_tpu.tune.session import get_compile_cache_dir
        return get_compile_cache_dir()
    except Exception:
        return None


def namespace_dir(root: str) -> str:
    """Topology-namespaced subdirectory of ``root``.

    JAX's cache key already covers the program; the namespace keeps one
    shared root safe across jax versions / device kinds / mesh sizes
    (stale or foreign entries live in sibling dirs, never this one) and
    makes ``du``-level hygiene possible per topology.
    """
    import jax
    dev = jax.devices()[0]
    kind = re.sub(r"[^A-Za-z0-9_.+-]+", "-",
                  str(getattr(dev, "device_kind", dev.platform) or
                      dev.platform))
    name = (f"jax{jax.__version__}-{dev.platform}-{kind}"
            f"-d{jax.device_count()}-p{jax.process_count()}")
    return os.path.join(root, name)


# -- activation -----------------------------------------------------------

_active_dir: Optional[str] = None
_activate_lock = threading.Lock()


def activate(config: CompileCacheConfig) -> Optional[str]:
    """Point JAX's persistent compilation cache at the config's
    namespaced directory (idempotent; re-activating with a different
    root resets jax's cache handle so the switch takes effect — the
    tune runner re-targets one process across experiments this way).
    Returns the active namespaced dir, or None when disabled."""
    global _active_dir
    if config is None or not config.enabled:
        return None
    import jax
    with _activate_lock:
        ns = namespace_dir(config.root)
        os.makedirs(ns, exist_ok=True)
        if _active_dir != ns:
            # unconditionally drop jax's memoized cache state: jax
            # latches "cache unused" at the first compile of a process,
            # so activating AFTER any compile has happened (tests, a
            # warmup jit, a prior experiment) would otherwise be ignored
            _reset_jax_cache()
            jax.config.update("jax_enable_compilation_cache", True)
            jax.config.update("jax_compilation_cache_dir", ns)
            _active_dir = ns
            _log.info("persistent XLA compilation cache at %s", ns)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                          int(config.min_entry_bytes))
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(config.min_compile_secs))
        _install_listeners()
        return ns


def deactivate() -> None:
    """Restore jax's no-persistent-cache default (tests use this so one
    module's cache dir never leaks into the next)."""
    global _active_dir
    with _activate_lock:
        if _active_dir is None:
            return
        import jax
        _reset_jax_cache()
        jax.config.update("jax_compilation_cache_dir", None)
        _active_dir = None


def active_dir() -> Optional[str]:
    return _active_dir


def _reset_jax_cache() -> None:
    """Drop jax's live cache handle so the next compile re-reads the
    (changed) cache-dir config."""
    try:
        from jax._src import compilation_cache as _jcc
        _jcc.reset_cache()
    except Exception:   # pragma: no cover - jax internals moved
        _log.debug("could not reset jax compilation cache", exc_info=True)


# -- accounting -----------------------------------------------------------

@dataclass
class CacheStats:
    """Cumulative compile/cache accounting for this process."""

    hits: int = 0
    requests: int = 0
    backend_compile_secs: float = 0.0
    #: compile seconds a cache hit avoided (as recorded with the entry)
    saved_secs: float = 0.0
    retrieval_secs: float = 0.0
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False)

    @property
    def misses(self) -> int:
        return max(0, self.requests - self.hits)

    def snapshot(self) -> "CacheStats":
        with self._lock:
            return CacheStats(hits=self.hits, requests=self.requests,
                              backend_compile_secs=self.backend_compile_secs,
                              saved_secs=self.saved_secs,
                              retrieval_secs=self.retrieval_secs)


_stats = CacheStats()
_listeners_installed = False

_EV_HIT = "/jax/compilation_cache/cache_hits"
_EV_REQUEST = "/jax/compilation_cache/compile_requests_use_cache"
_EV_COMPILE_SECS = "/jax/core/compile/backend_compile_duration"
_EV_SAVED_SECS = "/jax/compilation_cache/compile_time_saved_sec"
_EV_RETRIEVAL_SECS = "/jax/compilation_cache/cache_retrieval_time_sec"


def _on_event(event: str, **_kw: Any) -> None:
    if event == _EV_HIT:
        with _stats._lock:
            _stats.hits += 1
    elif event == _EV_REQUEST:
        with _stats._lock:
            _stats.requests += 1


def _on_duration(event: str, duration: float, **_kw: Any) -> None:
    if event == _EV_COMPILE_SECS:
        with _stats._lock:
            _stats.backend_compile_secs += duration
    elif event == _EV_SAVED_SECS:
        with _stats._lock:
            _stats.saved_secs += duration
    elif event == _EV_RETRIEVAL_SECS:
        with _stats._lock:
            _stats.retrieval_secs += duration


def _install_listeners() -> None:
    """Register jax monitoring listeners once per process.  Monitoring
    is a private-but-stable jax surface; failure degrades to zeroed
    stats, never to a broken cache."""
    global _listeners_installed
    if _listeners_installed:
        return
    try:
        from jax._src import monitoring
        monitoring.register_event_listener(_on_event)
        monitoring.register_event_duration_secs_listener(_on_duration)
        _listeners_installed = True
    except Exception:   # pragma: no cover - jax internals moved
        _log.warning("jax monitoring unavailable; compile-cache hit/miss "
                     "accounting disabled", exc_info=True)


def stats() -> CacheStats:
    """Consistent snapshot of this process's compile/cache counters."""
    return _stats.snapshot()


def reset_stats() -> None:
    with _stats._lock:
        _stats.hits = 0
        _stats.requests = 0
        _stats.backend_compile_secs = 0.0
        _stats.saved_secs = 0.0
        _stats.retrieval_secs = 0.0


def status_word() -> str:
    """One word for the bench JSON line: ``hit`` (the persistent cache
    served at least one program this process), ``miss`` (active but
    everything compiled fresh), ``off`` (no cache active)."""
    if _active_dir is None:
        return "off"
    s = stats()
    if s.hits > 0:
        return "hit"
    return "miss"


def publish_metrics(registry) -> None:
    """Mirror the cumulative stats into the metrics plane (called from
    ``MetricsRegistry.snapshot`` when this module is loaded)."""
    s = stats()
    registry.gauge("rlt_compile_cache_hits_total").set(s.hits)
    registry.gauge("rlt_compile_cache_misses_total").set(s.misses)
    registry.gauge("rlt_compile_seconds_total").set(
        round(s.backend_compile_secs, 6))


# -- startup overlap bookkeeping ------------------------------------------

def note_first_step(seconds: float) -> None:
    """Record time-to-first-step into the metrics plane (the trainer
    calls this once per fit; bench.py reads the trainer attribute)."""
    from ray_lightning_tpu.telemetry import metrics as _metrics
    reg = _metrics.get_registry()
    if reg is not None:
        reg.gauge("rlt_time_to_first_step_seconds").set(round(seconds, 6))


__all__ = [
    "CompileCacheConfig",
    "DEFAULT_ROOT",
    "ENV_KNOBS",
    "activate",
    "deactivate",
    "active_dir",
    "namespace_dir",
    "stats",
    "reset_stats",
    "status_word",
    "publish_metrics",
    "note_first_step",
    "CacheStats",
]
