"""Compile-plane self-check (format.sh --check / tests).

Validates, without initializing any jax backend, that the compile
plane's user-facing surface is internally consistent: env-knob parsing
round-trips through ``worker_env``, the pack/unpack seeding path
round-trips bytes, and the metric names the plane publishes are
registered in the metrics plane's lint surface (so ``/metrics`` can
never emit an unscrapable compile series).  Exits nonzero on any
violation — same contract as the metrics-name lint it runs beside.
"""

from __future__ import annotations

import os
import tempfile

#: metric names compile/cache.py publishes (publish_metrics +
#: note_first_step); must all be declared in telemetry.metrics
#: CORE_METRICS so the name lint covers them
PUBLISHED_METRICS = (
    "rlt_compile_cache_hits_total",
    "rlt_compile_cache_misses_total",
    "rlt_compile_seconds_total",
    "rlt_time_to_first_step_seconds",
)


def run_selfcheck() -> list[str]:
    """Returns the list of violations (empty = clean)."""
    from ray_lightning_tpu.compile import cache, shipping
    from ray_lightning_tpu.telemetry import metrics as tmetrics

    problems: list[str] = []

    # 1. every published metric is in CORE_METRICS and Prometheus-clean
    for name in PUBLISHED_METRICS:
        if name not in tmetrics.CORE_METRICS:
            problems.append(
                f"compile plane publishes {name!r} but it is missing "
                f"from telemetry.metrics.CORE_METRICS")
        try:
            tmetrics.validate_metric_name(name)
        except ValueError as e:
            problems.append(str(e))

    # 2. env-knob round-trip: a config built from env reproduces itself
    #    through worker_env (what the plugin ships to workers)
    saved = {k: os.environ.get(k) for k in cache.ENV_KNOBS}
    try:
        for k in cache.ENV_KNOBS:
            os.environ.pop(k, None)
        os.environ[cache.ENV_ENABLE] = "1"
        os.environ[cache.ENV_DIR] = "/tmp/rlt-selfcheck-cache"
        os.environ[cache.ENV_MIN_ENTRY] = "1024"
        os.environ[cache.ENV_MIN_COMPILE] = "0.25"
        cfg = cache.CompileCacheConfig.resolve(None)
        if not (cfg.enabled and cfg.root == "/tmp/rlt-selfcheck-cache"
                and cfg.min_entry_bytes == 1024
                and cfg.min_compile_secs == 0.25):
            problems.append(f"env resolution broken: {cfg}")
        env = cfg.worker_env()
        for k in cache.ENV_KNOBS:
            os.environ.pop(k, None)
        os.environ.update(env)
        cfg2 = cache.CompileCacheConfig.resolve(None)
        if cfg2 != cfg:
            problems.append(
                f"worker_env round-trip drifted: {cfg} -> {cfg2}")
        os.environ[cache.ENV_ENABLE] = "0"
        if cache.CompileCacheConfig.resolve(None).enabled:
            problems.append(f"{cache.ENV_ENABLE}=0 failed to disable")
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    # 3. pack/unpack round-trip (the worker seeding path)
    with tempfile.TemporaryDirectory(prefix="rlt_selfcheck_") as d:
        src = os.path.join(d, "src")
        os.makedirs(os.path.join(src, "sub"))
        with open(os.path.join(src, "sub", "entry"), "wb") as f:
            f.write(b"x" * 128)
        blob = shipping.pack_cache_dir(src)
        if blob is None:
            problems.append("pack_cache_dir returned None for a "
                            "populated dir")
        else:
            dst = os.path.join(d, "dst")
            n = shipping.unpack_cache_dir(blob, dst)
            target = os.path.join(dst, "sub", "entry")
            if n != 1 or not os.path.isfile(target) \
                    or os.path.getsize(target) != 128:
                problems.append("pack/unpack round-trip corrupted the "
                                "cache entry")

    return problems


def _main(argv: list[str]) -> int:
    problems = run_selfcheck()
    for p in problems:
        print(f"compile selfcheck: {p}")
    if not problems:
        print("compile selfcheck: env knobs, metric names and cache "
              "seeding consistent")
    return 1 if problems else 0


if __name__ == "__main__":   # pragma: no cover - exercised via format.sh
    import sys
    sys.exit(_main(sys.argv[1:]))
