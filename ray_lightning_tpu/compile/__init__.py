"""Compile plane: persistent XLA compilation cache + AOT precompile.

Makes compilation a cached, overlapped, shared resource instead of a
per-process tax (the biggest framework-controlled wall-clock cost once
steady-state step time sits at raw-JAX parity):

- ``cache.py`` — lifecycle of JAX's persistent compilation cache:
  config/env resolution, topology-namespaced directories, hit/miss and
  compile-seconds accounting surfaced through the metrics plane.
- ``aot.py`` — background lower+compile of the step programs from their
  ``eval_shape`` avals, overlapped with state init, the rendezvous and
  the device-resident dataset upload.
- ``shipping.py`` — cache-dir seeding for cluster backends without a
  shared filesystem.

Wired through ``core/trainer.py`` (activation + AOT submission +
time-to-first-step), ``core/loop_engine.py`` (cached-step programs
submit when their shapes become known), ``plugins/xla.py`` (worker env
+ seeding), and ``tune/runner.py`` (one shared cache per experiment).
"""

from ray_lightning_tpu.compile.cache import (  # noqa: F401
    CacheStats,
    CompileCacheConfig,
    DEFAULT_ROOT,
    activate,
    active_dir,
    deactivate,
    namespace_dir,
    note_first_step,
    publish_metrics,
    reset_stats,
    stats,
    status_word,
)
from ray_lightning_tpu.compile.aot import (  # noqa: F401
    AotPrecompiler,
    global_batch_abstract,
    stack_abstract,
)

__all__ = [
    "CacheStats",
    "CompileCacheConfig",
    "DEFAULT_ROOT",
    "activate",
    "active_dir",
    "deactivate",
    "namespace_dir",
    "note_first_step",
    "publish_metrics",
    "reset_stats",
    "stats",
    "status_word",
    "AotPrecompiler",
    "global_batch_abstract",
    "stack_abstract",
]
