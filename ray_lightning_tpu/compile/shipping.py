"""Cache-dir shipping for backends without a shared filesystem.

The built-in subprocess backend's workers live on the driver's node, so
pointing them at the driver's cache ROOT is already sharing
(``ClusterBackend.shared_filesystem``).  Real Ray workers may land on
other nodes where the driver's cache path is an empty local dir — for
those, the plugin packs the driver's cache root into one blob, ships it
through the object store (once, not per worker), and each worker seeds
its local dir from the blob before its first compile.  Seeding is
strictly additive (existing entries are never overwritten) and capped,
so a huge accumulated cache degrades to partial seeding, not a
multi-GB broadcast.
"""

from __future__ import annotations

import io
import logging
import os
import tarfile
from typing import Optional

_log = logging.getLogger(__name__)

#: don't broadcast more than this much packed cache to workers; newest
#: entries win (they're the ones the restarted/new run most likely needs)
MAX_PACK_BYTES = 256 << 20


def pack_cache_dir(root: str,
                   max_bytes: int = MAX_PACK_BYTES) -> Optional[bytes]:
    """Gzipped tar of ``root``'s cache entries (newest first, stopping
    at ``max_bytes`` of file payload).  None when the dir is missing or
    empty — callers then simply skip seeding."""
    if not root or not os.path.isdir(root):
        return None
    entries: list[tuple[float, str, int]] = []
    for dirpath, _dirs, files in os.walk(root):
        for fn in files:
            path = os.path.join(dirpath, fn)
            try:
                st = os.stat(path)
            except OSError:
                continue
            entries.append((st.st_mtime, path, st.st_size))
    if not entries:
        return None
    entries.sort(reverse=True)          # newest first
    buf = io.BytesIO()
    packed = 0
    skipped = 0
    with tarfile.open(fileobj=buf, mode="w:gz") as tar:
        for _mtime, path, size in entries:
            if packed + size > max_bytes:
                skipped += 1
                continue
            packed += size
            tar.add(path, arcname=os.path.relpath(path, root))
    if skipped:
        _log.warning(
            "compile-cache pack capped at %d bytes: %d older entr%s "
            "not shipped to workers", max_bytes, skipped,
            "y" if skipped == 1 else "ies")
    return buf.getvalue()


def unpack_cache_dir(blob: bytes, root: str) -> int:
    """Seed ``root`` from a :func:`pack_cache_dir` blob.  Existing
    entries are kept (a worker's own newer compiles beat the driver's
    snapshot).  Returns the number of entries written."""
    os.makedirs(root, exist_ok=True)
    written = 0
    with tarfile.open(fileobj=io.BytesIO(blob), mode="r:gz") as tar:
        for member in tar.getmembers():
            if not member.isfile():
                continue
            # refuse path escapes from a hostile/corrupt blob
            dest = os.path.realpath(os.path.join(root, member.name))
            if not dest.startswith(os.path.realpath(root) + os.sep):
                _log.warning("skipping cache entry with unsafe path %r",
                             member.name)
                continue
            if os.path.exists(dest):
                continue
            os.makedirs(os.path.dirname(dest), exist_ok=True)
            src = tar.extractfile(member)
            if src is None:
                continue
            tmp = f"{dest}.{os.getpid()}.tmp"
            with open(tmp, "wb") as f:
                f.write(src.read())
            os.replace(tmp, dest)       # atomic: readers never see partials
            written += 1
    return written


__all__ = ["pack_cache_dir", "unpack_cache_dir", "MAX_PACK_BYTES"]
