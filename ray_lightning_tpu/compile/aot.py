"""AOT precompilation overlapped with fit setup.

The trainer knows every step program's exact input avals the moment
``_build_compiled`` finishes (``jax.eval_shape`` of the init fn gives
the state; the peeked example batch gives the batch), yet without this
module XLA compilation only starts at the FIRST DISPATCH — serialized
after state init, the rendezvous, the sanity check and the
device-resident dataset upload.  :class:`AotPrecompiler` moves it off
the critical path: one background thread runs
``jitted.lower(*abstract_args).compile()`` for each submitted program
while the fit does that other work.

The compiled artifact reaches the first dispatch THROUGH THE
PERSISTENT CACHE, not through memory: jax's ``lower().compile()``
executables are invisible to the jit dispatch path (measured — the
dispatch re-invokes XLA even on the same jit object), but with the
persistent cache active the background compile writes the cache entry
and the dispatch-time compile collapses to a ~ms disk retrieval.
Without an active cache, precompiling would genuinely DOUBLE compile
work (measured +50% on the CPU test suite), so :meth:`resolve`
disables itself unless :func:`compile.cache.active_dir` is set —
AOT overlap is a feature of the cached configuration, by construction.

Failure is always soft: a program whose predicted avals turn out wrong
(exotic loader, mispredicted global batch) logs and falls back to the
normal lazy compile at dispatch — precompilation is an overlap
optimization, never a correctness dependency.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass
from typing import Any, Optional

from ray_lightning_tpu.telemetry import counter as _tcounter

_log = logging.getLogger(__name__)

#: kill switch: RLT_AOT_PRECOMPILE=0 restores compile-at-first-dispatch
ENV_AOT = "RLT_AOT_PRECOMPILE"


class AotPrecompiler:
    """Sequentially compiles submitted programs on one daemon thread.

    One thread, not a pool: concurrent XLA compiles fight over the same
    cores the main thread's init compile is using, and the programs of
    one fit share most of their compilation anyway.  ``barrier()``
    blocks until everything submitted so far is done — the trainer calls
    it right before the first train dispatch so a lazy dispatch-time
    compile never races the background one for the same program.
    """

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self.results: dict[str, Any] = {}   # name -> seconds | exception
        self._queue: list[tuple[str, Any, tuple]] = []
        self._pending = 0
        self._cond = threading.Condition()
        self._thread: Optional[threading.Thread] = None

    @classmethod
    def resolve(cls) -> "AotPrecompiler":
        """Enabled only when the persistent cache is active (module
        docstring: without it, background compiles are pure double
        work) and ``RLT_AOT_PRECOMPILE`` doesn't opt out."""
        from ray_lightning_tpu.compile import cache as _cache
        enabled = (os.environ.get(ENV_AOT, "").strip() != "0"
                   and _cache.active_dir() is not None)
        return cls(enabled=enabled)

    def submit(self, name: str, jitted, abstract_args: tuple) -> None:
        """Queue ``jitted.lower(*abstract_args).compile()`` under
        ``name``.  No-op when disabled."""
        if not self.enabled:
            return
        with self._cond:
            self._queue.append((name, jitted, abstract_args))
            self._pending += 1
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, daemon=True, name="rlt-aot-precompile")
                self._thread.start()

    def _run(self) -> None:
        while True:
            with self._cond:
                if not self._queue:
                    return
                name, jitted, args = self._queue.pop(0)
            t0 = time.monotonic()
            try:
                jitted.lower(*args).compile()
                dt = time.monotonic() - t0
                self.results[name] = dt
                # counter, not span: spans share the recorder's open-span
                # stack with the main thread, and a cross-thread push
                # would corrupt its nesting depth
                _tcounter("precompile_seconds", dt, program=name)
            except Exception as e:   # noqa: BLE001 - soft fallback
                self.results[name] = e
                _log.info(
                    "AOT precompile of %s failed (%s: %s); the program "
                    "will compile lazily at first dispatch", name,
                    type(e).__name__, e)
            finally:
                with self._cond:
                    self._pending -= 1
                    self._cond.notify_all()

    def barrier(self, timeout: Optional[float] = None) -> dict[str, Any]:
        """Wait for every submitted compile; returns the results map.
        Instant once drained (the per-epoch engine calls it every
        epoch; only the first can wait)."""
        with self._cond:
            self._cond.wait_for(lambda: self._pending == 0,
                                timeout=timeout)
        return dict(self.results)

    def succeeded(self, name: str) -> bool:
        return isinstance(self.results.get(name), float)


# -- batched AOT scoring (planner verify stage) ----------------------------

@dataclass
class ScoredCompile:
    """What one AOT candidate compile yields for plan ranking: measured
    compile seconds, the backend's real per-device memory analysis, and
    the audited HLO collective wire bytes (comm/audit.py model)."""

    name: str
    seconds: float = 0.0
    argument_bytes: int = 0
    output_bytes: int = 0
    temp_bytes: int = 0
    alias_bytes: int = 0
    wire_bytes: int = 0
    #: per-link split of wire_bytes (replica-group classification with
    #: the candidate's ici group size) — both 0 for flat candidates
    wire_bytes_dcn: int = 0
    wire_bytes_ici: int = 0
    error: Optional[str] = None

    @property
    def ok(self) -> bool:
        return self.error is None

    @property
    def peak_bytes(self) -> int:
        """Per-device residency of one step dispatch: live arguments +
        outputs + XLA temp workspace, minus the aliased (donated)
        buffers counted on both sides."""
        return max(0, self.argument_bytes + self.output_bytes
                   + self.temp_bytes - self.alias_bytes)

    def to_dict(self) -> dict:
        return {
            "compile_seconds": round(self.seconds, 6),
            "argument_bytes": self.argument_bytes,
            "output_bytes": self.output_bytes,
            "temp_bytes": self.temp_bytes,
            "alias_bytes": self.alias_bytes,
            "peak_bytes": self.peak_bytes,
            "wire_bytes": self.wire_bytes,
            "wire_bytes_dcn": self.wire_bytes_dcn,
            "wire_bytes_ici": self.wire_bytes_ici,
            "error": self.error,
        }


def compile_scored(programs: "list[tuple[str, Any, tuple, int]]",
                   max_workers: int = 4) -> "dict[str, ScoredCompile]":
    """AOT-compile candidate programs concurrently and score each.

    ``programs`` entries are ``(name, jitted, abstract_args,
    axis_size)`` or ``(..., axis_size, ici_size)`` — ``axis_size``
    scales reduce-scatter results back to input bytes in the wire
    audit; a non-zero ``ici_size`` (hierarchical comm candidates)
    additionally splits the audited bytes by link tier over each
    collective's replica groups.  Unlike :class:`AotPrecompiler`
    (one thread — its compiles overlap the main thread's init compile),
    these run BEFORE any other compilation exists, so a small pool is
    pure win; with the persistent cache active every artifact lands on
    disk and the winner's first real dispatch collapses to a cache
    retrieval.  Failure is per-program soft: a candidate whose compile
    raises scores as an error entry instead of sinking the whole plan.
    """
    import concurrent.futures

    from ray_lightning_tpu.comm.audit import (total_wire_bytes,
                                              wire_bytes_by_link)

    def one(entry) -> ScoredCompile:
        name, jitted, args, axis_size = entry[:4]
        ici_size = entry[4] if len(entry) > 4 else 0
        t0 = time.monotonic()
        try:
            compiled = jitted.lower(*args).compile()
        except Exception as e:   # noqa: BLE001 - per-candidate soft fail
            return ScoredCompile(name=name,
                                 seconds=time.monotonic() - t0,
                                 error=f"{type(e).__name__}: {e}")
        out = ScoredCompile(name=name, seconds=time.monotonic() - t0)
        try:
            mem = compiled.memory_analysis()
            out.argument_bytes = int(
                getattr(mem, "argument_size_in_bytes", 0) or 0)
            out.output_bytes = int(
                getattr(mem, "output_size_in_bytes", 0) or 0)
            out.temp_bytes = int(
                getattr(mem, "temp_size_in_bytes", 0) or 0)
            out.alias_bytes = int(
                getattr(mem, "alias_size_in_bytes", 0) or 0)
        except Exception:   # noqa: BLE001 - backend without the API
            _log.debug("memory_analysis unavailable for %s", name,
                       exc_info=True)
        try:
            text = compiled.as_text()
            out.wire_bytes = total_wire_bytes(text, axis_size=axis_size)
            if ici_size > 1:
                link = wire_bytes_by_link(text, ici_size,
                                          axis_size=axis_size)
                out.wire_bytes_dcn = link["dcn"]
                out.wire_bytes_ici = link["ici"]
        except Exception:   # noqa: BLE001 - text dump unavailable
            _log.debug("HLO wire audit unavailable for %s", name,
                       exc_info=True)
        return out

    if not programs:
        return {}
    workers = max(1, min(max_workers, len(programs)))
    with concurrent.futures.ThreadPoolExecutor(
            max_workers=workers,
            thread_name_prefix="rlt-plan-aot") as pool:
        return {s.name: s for s in pool.map(one, programs)}


# -- abstract-aval helpers -------------------------------------------------

def global_batch_abstract(host_batch, process_count: int):
    """Abstract avals of the batch the train step will actually see.

    Single-process: the host (numpy) batch goes straight into the jitted
    step, so its own shapes/dtypes are the avals.  Multi-process: the
    dispatch wraps each leaf in ``make_array_from_process_local_data``,
    whose global array concatenates the per-process shards along dim 0 —
    global leading dim = local × process count (the same arithmetic the
    mesh ``batch_hint`` uses).  Pass the batch AFTER ``_host_cast`` so
    bf16 input casting is reflected in the dtypes.
    """
    import jax
    import numpy as np

    def leaf(x):
        a = np.asarray(x)
        shape = a.shape
        if process_count > 1 and a.ndim > 0:
            shape = (shape[0] * process_count,) + shape[1:]
        return jax.ShapeDtypeStruct(shape, a.dtype)

    return jax.tree_util.tree_map(leaf, host_batch)


def stack_abstract(abstract_batch, k: int):
    """Avals of ``k`` stacked batches (the ``steps_per_execution``
    chunk program's input: one leading scan dimension)."""
    import jax

    return jax.tree_util.tree_map(
        lambda s: jax.ShapeDtypeStruct((k,) + tuple(s.shape), s.dtype),
        abstract_batch)


__all__ = [
    "AotPrecompiler",
    "ENV_AOT",
    "ScoredCompile",
    "compile_scored",
    "global_batch_abstract",
    "stack_abstract",
]
