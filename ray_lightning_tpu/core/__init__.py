from ray_lightning_tpu.core.module import LightningModule, StepContext
from ray_lightning_tpu.core.trainer import Trainer
from ray_lightning_tpu.core.callbacks import Callback, EarlyStopping, ModelCheckpoint
from ray_lightning_tpu.core.data import DataLoader
from ray_lightning_tpu.core.datamodule import LightningDataModule

__all__ = [
    "LightningModule",
    "StepContext",
    "Trainer",
    "Callback",
    "EarlyStopping",
    "ModelCheckpoint",
    "DataLoader",
    "LightningDataModule",
]
