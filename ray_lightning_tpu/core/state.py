"""Train state pytree.

Replaces the implicit (module, optimizer, grad-scaler) object state of the
torch stack with one explicit pytree that flows through the compiled step
— the unit that strategies shard, checkpoints serialize, and the
rank-0→driver state stream round-trips (util.py:71-90 analog).
"""

from __future__ import annotations

from typing import Any

import jax
from flax import struct


class TrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    model_state: Any          # non-trainable collections (batch_stats, ...)
    opt_state: Any
    rng: jax.Array

    @classmethod
    def create(cls, params, model_state, opt_state, rng):
        import jax.numpy as jnp
        return cls(step=jnp.zeros((), jnp.int32), params=params,
                   model_state=model_state, opt_state=opt_state, rng=rng)
