"""Batch sources for the trainer's single epoch engine.

Round 2 grew three divergent epoch loops (streamed, chunked, cached)
that triplicated limit/callback/val-interval semantics and shipped one
real behavioral divergence (the cached loop froze batch membership
across epochs while a shuffling streamed loader re-draws it).  The
engine now has ONE loop (``Trainer._train_epoch``) over a *batch
source*; the dispatch shape (per-batch, k-step chunk, device-resident
gather) is the source's business, the semantics (limits, callbacks,
metrics, val cadence) are the engine's and exist once.

- :class:`StreamSource` — host batches from the loader.  chunk-size-1
  take = the classic streamed loop; full-k takes stack into one
  ``lax.scan`` dispatch (``steps_per_execution``).
- :class:`CachedSource` — the device-resident train set.  Samples are
  uploaded ONCE in dataset order (flat [N, ...]); each epoch the
  loader's own index order drives a device-side *repack* into
  [n_batches, B, ...], so batch membership exactly matches what the
  streamed loop would have assembled — shuffle included (the round-2
  frozen-membership divergence is gone by construction).  Per-step
  dispatches then gather batch i on-device; only integer indices cross
  the host→device link (the tunnel-bandwidth fix, benchmarks/README.md
  config #1).  A trailing partial batch (drop_last=False) cannot ride
  the fixed-shape cache and is assembled host-side and routed through
  the single-step program instead (the np.stack shape crash of the
  round-2 cache is structurally impossible here: samples stack at the
  dataset level, where shapes are uniform by construction).

Reference anchor: this replaces the reference's single hot loop
(ray_ddp.py:472 — PL ``run_stage`` inside each worker) rather than
mirroring it; the chunk/cache shapes exist because a tunneled TPU makes
per-step host work the bottleneck the reference never had.
"""

from __future__ import annotations

import logging
import os
import time
from dataclasses import dataclass
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_lightning_tpu.telemetry import span
from ray_lightning_tpu.telemetry import goodput as _goodput
from ray_lightning_tpu.telemetry import metrics as _metrics
from ray_lightning_tpu.telemetry.anatomy import anatomy_tick
from ray_lightning_tpu.telemetry.tracing import profile_tick

_log = logging.getLogger(__name__)


@dataclass
class Item:
    """One pending training step.

    ``payload`` is a host batch (``kind="host"``) or an int batch index
    into the source's repacked device cache (``kind="cached"``).
    ``device`` carries an in-flight device transfer when the stream
    source prefetched this batch (double-buffering).  ``batch``
    materializes the host-side batch for callbacks; the engine only
    calls it when some callback overrides a per-batch hook
    (``Trainer._any_batch_hook``), so cached epochs under default
    callbacks never pay host collation at all.
    """

    batch_idx: int
    kind: str                      # "host" | "cached"
    payload: Any
    _batch_fn: Callable[[], Any] = None
    device: Any = None

    _materialized: Any = None

    def batch(self):
        if self._materialized is None and self._batch_fn is not None:
            self._materialized = self._batch_fn()
            self._batch_fn = None
        return self._materialized if self._materialized is not None \
            else self.payload


class StreamSource:
    """Host batches straight from the loader (one fresh pass per epoch).

    Per-step dispatch additionally DOUBLE-BUFFERS: the transfer of
    batch k+1 (and k+2) is issued while step k still computes, so the
    host→device copy rides under the compute instead of serializing
    with it (the round-2 streamed path started each batch's transfer
    only at its own dispatch; on the tunneled chip that stacked link
    time on top of step time).  Multi-process runs prefetch the same
    way since round 4: ``jax.make_array_from_process_local_data`` only
    issues this process's (async) per-device puts plus global
    metadata — no collective — so assembling batch k+1's global array
    early is safe as long as every process prefetches in the same
    order, which the shared loader contract already guarantees (pinned
    by tests/test_plugin_distributed.py: the RLT_STREAM_PREFETCH A/B is
    loss-sequence identical across actors, and the divergent-order
    canary shows a contract violation skews identically with prefetch
    on or off — pairing is positional either way); the
    round-3 gate serialized link time with step time on exactly the
    path a real pod feeds with (VERDICT r3 weak #3).  Chunked dispatch
    keeps its own host-side stacking.
    """

    PREFETCH_DEPTH = 2

    def __init__(self, trainer, loader, strategy):
        self._trainer = trainer
        self._strategy = strategy
        self._it = enumerate(loader)
        self._buf: list = []            # pre-pulled items, transfers live
        self._prefetch = (trainer.steps_per_execution == 1
                          and os.environ.get("RLT_STREAM_PREFETCH",
                                             "1") != "0")
        self._fingerprinter = None
        if trainer.world_size > 1:
            # opt-in divergent-loader detection (RLT_DATA_CHECK=1):
            # relay a per-step batch fingerprint to the driver, which
            # cross-checks ranks against the shared-loader contract and
            # raises on divergence (core/datacheck.py)
            from ray_lightning_tpu.core import datacheck
            self._fingerprinter = datacheck.BatchFingerprinter.maybe_create(
                loader, trainer.global_rank, trainer.current_epoch)
        self.exhausted = False

    def _pull(self) -> "Item | None":
        """One acceptable batch from the loader, honoring
        ``limit_train_batches`` (which counts loader POSITIONS, not
        accepted batches — the contract shared by every dispatch path).
        The ``data_wait`` span is the host-side input-pipeline cost per
        batch — when it rivals the step span, the loader is the
        bottleneck."""
        t = self._trainer
        t0 = time.monotonic()
        try:
            with span("data_wait"):
                while not self.exhausted:
                    try:
                        batch_idx, batch = next(self._it)
                    except StopIteration:
                        self.exhausted = True
                        return None
                    if t.limit_train_batches is not None \
                            and batch_idx >= t.limit_train_batches:
                        self.exhausted = True
                        return None
                    if t._batch_ok(batch, self._strategy):
                        if self._fingerprinter is not None:
                            self._fingerprinter.observe(batch_idx, batch)
                        return Item(batch_idx=batch_idx, kind="host",
                                    payload=batch)
            return None
        finally:
            waited = time.monotonic() - t0
            _metrics.on_data_wait(waited)
            _goodput.on_data_wait(waited)

    def _start_transfer(self, item: Item) -> None:
        if item.device is not None:
            return
        t = self._trainer
        host = t._host_cast(item.payload)
        if jax.process_count() > 1:
            # assemble the global array NOW: the per-device puts of this
            # process's shards go out asynchronously under step k
            sh = self._strategy.batch_shardings(t._mesh, host)
            item.device = jax.tree_util.tree_map(
                lambda x, s: jax.make_array_from_process_local_data(s, x),
                host, sh)
        elif t._mesh is not None and t._mesh.devices.size > 1:
            sh = self._strategy.batch_shardings(t._mesh, host)
            item.device = jax.device_put(host, sh)
        else:
            item.device = jax.device_put(host)

    def take(self, n: int) -> list:
        out: list = []
        while len(out) < n:
            item = self._buf.pop(0) if self._buf else self._pull()
            if item is None:
                break
            out.append(item)
        if self._prefetch:
            for it in out:
                self._start_transfer(it)
            while len(self._buf) < self.PREFETCH_DEPTH:
                nxt = self._pull()
                if nxt is None:
                    break
                self._start_transfer(nxt)
                self._buf.append(nxt)
        return out

    def chunkable(self, items: list) -> bool:
        """A chunk stacks host batches — every leaf shape must agree
        (a ragged final batch otherwise crashes the np.stack)."""
        if any(it.kind != "host" for it in items):
            return False
        shapes = [
            tuple(x.shape for x in jax.tree_util.tree_leaves(
                jax.tree_util.tree_map(np.asarray, it.payload)))
            for it in items]
        return all(s == shapes[0] for s in shapes)

    def run_one(self, trainer, item: Item):
        # on-demand profile window (POST /debug/profile → control file,
        # telemetry/tracing.py) + cadence-armed anatomy window
        # (telemetry/anatomy.py): one global check each when disarmed
        profile_tick()
        anatomy_tick()
        if item.device is not None:
            gbatch = item.device
        else:
            gbatch = trainer._put_batch(item.payload, self._strategy)
        trainer.state, metrics = trainer._train_step(trainer.state, gbatch)
        return metrics

    def run_chunk(self, trainer, items: list):
        profile_tick()
        anatomy_tick()
        stacked = jax.tree_util.tree_map(
            lambda *xs: np.stack(xs), *[it.payload for it in items])
        gbatch = trainer._put_batch(stacked, self._strategy, stacked=True)
        trainer.state, metrics = trainer._multi_train_step(
            trainer.state, gbatch)
        return metrics


class CachedSource:
    """Device-resident train set with per-epoch membership-accurate
    repacking (module docstring).  Built once per fit; ``new_epoch``
    refreshes the plan from the loader's index order."""

    def __init__(self, trainer, loader, strategy):
        self._trainer = trainer
        self._loader = loader
        self._strategy = strategy
        self._flat = None              # device pytree [N, ...]
        self._repacked = None          # device pytree [nb, B, ...]
        self._last_perm: Optional[np.ndarray] = None
        self._repack_jit = None
        self._plan: list = []          # epoch's Items
        self._pos = 0
        self._host_memo: Optional[dict] = None
        self._host_memo_perm: Optional[np.ndarray] = None
        self._promise_broken = False   # loader changed order w/o shuffle
        self.exhausted = False

    # -- construction ---------------------------------------------------

    @staticmethod
    def usable(trainer, loader) -> bool:
        """The cache needs the loader's anatomy (dataset + index order +
        collate); foreign loaders fall back to streaming with a note."""
        ok = all(hasattr(loader, a) for a in
                 ("dataset", "_indices", "collate_fn", "batch_size",
                  "drop_last")) \
            and hasattr(loader.dataset, "__len__") \
            and hasattr(loader.dataset, "__getitem__") \
            and len(loader.dataset) > 0 and loader.batch_size > 0
        if not ok:
            _log.warning(
                "cache_train_dataset needs a ray_lightning_tpu DataLoader "
                "over an indexable dataset; got %r — streaming instead.",
                type(loader).__name__)
        return ok

    def _gather_host(self, sample_ids) -> Any:
        """Host batch of the given sample ids (zero-copy view for
        contiguous ids over an ArrayDataset — the no-shuffle hot case,
        where this runs per batch for callback arguments; vectorized
        gather otherwise; per-sample collate for foreign datasets)."""
        from ray_lightning_tpu.core.data import ArrayDataset
        ds = self._loader.dataset
        ids = np.asarray(sample_ids)
        if isinstance(ds, ArrayDataset):
            if len(ids) and np.array_equal(
                    ids, np.arange(ids[0], ids[0] + len(ids))):
                return ds[slice(int(ids[0]), int(ids[0]) + len(ids))]
            return ds[ids]
        return self._loader.collate_fn([ds[int(i)] for i in ids])

    @property
    def _n_shards(self) -> int:
        return max(1, getattr(self._loader, "num_shards", 1))

    def build(self) -> bool:
        """Upload all samples (dataset order) to device; False = unusable
        (caller streams instead; nothing has been consumed from the
        loader — the cache reads the DATASET, not the iterator).

        Multi-process (the loader is a per-process shard clone): the
        flat cache is ONE global array whose dim-0 sharding follows the
        batch sharding — each process materializes only the sample rows
        its devices own (``make_array_from_callback``), and the
        per-epoch repack is a global SPMD gather whose all-to-all moves
        samples wherever the epoch's membership needs them.  This is
        what lets a shuffling loader re-draw CROSS-PROCESS batch
        membership with the dataset resident on device — the round-2
        cache simply refused to run distributed."""
        t = self._trainer
        loader = self._loader
        n = len(loader.dataset)
        global_batch = loader.batch_size * self._n_shards
        self._global_batch = global_batch
        # kick the cached-step AOT compiles NOW (compile/aot.py): the
        # repacked shape is fully predictable from dataset/batch sizes,
        # and the upload below is exactly the work the compile should
        # hide under.  The engine barriers before the first dispatch.
        self._submit_precompiles(n)

        def repack(flat_dev, perm):
            nb = perm.shape[0] // global_batch
            g = jax.tree_util.tree_map(
                lambda f: jnp.take(f, perm, axis=0), flat_dev)
            return jax.tree_util.tree_map(
                lambda x: x.reshape((nb, global_batch) + x.shape[1:]), g)

        if self._n_shards > 1:
            dp = self._strategy.data_parallel_size(t._mesh)
            if n % dp:
                _log.warning(
                    "cache_train_dataset: dataset size %d does not "
                    "divide across %d data shards; streaming instead.",
                    n, dp)
                return False
            # materialize per-leaf global arrays: the callback hands jax
            # exactly the row range each local device owns.  Row chunks
            # are memoized by range — jax asks once per (leaf, local
            # device shard) and the gather/cast work should happen once
            # per distinct range, not leaves × shards times.
            sample = t._host_cast(self._gather_host(np.arange(1)))
            shardings = self._strategy.batch_shardings(t._mesh, sample)
            leaves, treedef = jax.tree_util.tree_flatten(sample)
            shard_leaves = jax.tree_util.tree_leaves(shardings)
            chunk_memo: dict = {}

            def rows_chunk(start, stop):
                got = chunk_memo.get((start, stop))
                if got is None:
                    got = chunk_memo[(start, stop)] = \
                        jax.tree_util.tree_leaves(t._host_cast(
                            self._gather_host(np.arange(start, stop))))
                return got

            out_leaves = []
            for li, (leaf0, sh) in enumerate(zip(leaves, shard_leaves)):
                shape = (n,) + leaf0.shape[1:]

                def cb(idx, li=li):
                    start = idx[0].start or 0
                    stop = idx[0].stop if idx[0].stop is not None else n
                    piece = rows_chunk(start, stop)[li]
                    # apply any trailing-dim index components verbatim
                    return piece[(slice(None),) + tuple(idx[1:])]

                out_leaves.append(jax.make_array_from_callback(
                    shape, sh, cb))
            self._flat = jax.tree_util.tree_unflatten(treedef, out_leaves)
            chunk_memo.clear()
        else:
            flat = t._host_cast(self._gather_host(np.arange(n)))
            leaves = jax.tree_util.tree_leaves(flat)
            if not leaves or any(x.shape[0] != n for x in leaves):
                _log.warning(
                    "cache_train_dataset: collated dataset is not "
                    "[N, ...]-shaped; streaming instead.")
                return False
            shardings = self._flat_shardings(flat, n)
            self._flat = jax.device_put(flat, shardings) \
                if shardings is not None else jax.device_put(flat)
        jax.block_until_ready(self._flat)

        kw = {}
        if t._stacked_batch_shardings is not None:
            kw["out_shardings"] = t._stacked_batch_shardings
        self._repack_jit = jax.jit(repack, **kw)
        return True

    def _submit_precompiles(self, n: int) -> None:
        """Background-compile the cached single/multi-step programs from
        predicted avals.  The batch count replicates ``_epoch_plan``'s
        arithmetic WITHOUT calling ``_indices()`` (an extra shuffle draw
        would shift every later epoch's order); a loader whose index
        count diverges from ``len(dataset)`` just wastes one background
        compile and falls back to lazy.  Best-effort by construction."""
        t = self._trainer
        pre = getattr(t, "_precompiler", None)
        if pre is None or not pre.enabled \
                or t._cached_single_step is None:
            return
        try:
            B = self._loader.batch_size
            P = self._n_shards
            per_rank = n if P == 1 else (n + (-n) % P) // P
            nb = per_rank // B
            if t.limit_train_batches is not None:
                nb = min(nb, int(t.limit_train_batches))
            if nb <= 0:
                return
            sample = t._host_cast(self._gather_host(np.arange(1)))
            ds_abs = jax.tree_util.tree_map(
                lambda s: jax.ShapeDtypeStruct(
                    (nb, self._global_batch) + np.asarray(s).shape[1:],
                    np.asarray(s).dtype),
                sample)
            idx_dtype = np.dtype(np.int32)
            pre.submit("cached_single", t._cached_single_step,
                       (t._abstract_state, ds_abs,
                        jax.ShapeDtypeStruct((), idx_dtype)))
            if t.steps_per_execution > 1 and t._cached_multi_step is not None:
                pre.submit(
                    "cached_multi", t._cached_multi_step,
                    (t._abstract_state, ds_abs,
                     jax.ShapeDtypeStruct((t.steps_per_execution,),
                                          idx_dtype)))
        except Exception:   # noqa: BLE001 - overlap only, never fatal
            _log.debug("cached-step precompile skipped", exc_info=True)

    def _flat_shardings(self, flat, n):
        t = self._trainer
        if t._mesh is None or t._mesh.devices.size <= 1:
            return None
        from jax.sharding import NamedSharding, PartitionSpec as P
        dp = self._strategy.data_parallel_size(t._mesh)
        if dp > 1 and n % dp == 0:
            return self._strategy.batch_shardings(t._mesh, flat)
        # N does not divide: replicate the flat copy (one-time cost;
        # the per-step repacked arrays stay sharded)
        return jax.tree_util.tree_map(
            lambda _: NamedSharding(t._mesh, P()), flat)

    # -- per-epoch plan --------------------------------------------------

    def _epoch_indices(self) -> np.ndarray:
        return np.asarray(self._loader._indices())

    def _epoch_plan(self):
        """(perm, local_ids, nb, tail_local): the epoch's global repack
        permutation, the per-batch LOCAL sample ids (this process's
        portion — callback arguments and the host tail match what the
        streamed loop would feed this rank), the full-batch count, and
        the local tail ids.

        Multi-process: every rank reconstructs the full (unsharded)
        index order and re-derives each rank's strided shard exactly as
        DataLoader.shard does, so all ranks compute the SAME global perm
        and execute the same repack program in lockstep.  Row order
        within a global batch groups ranks contiguously — a mean loss is
        order-invariant, and each rank's callbacks see its own rows.
        """
        loader = self._loader
        B = loader.batch_size
        P = self._n_shards
        if P == 1:
            idx = self._epoch_indices()
            nb = len(idx) // B
            local = [idx[j * B:(j + 1) * B] for j in range(nb)]
            perm_src = idx
            tail = idx[nb * B:]
            return perm_src[:nb * B], local, nb, tail
        full = np.asarray(loader.shard(1, 0)._indices())
        pad = (-len(full)) % P
        if pad:
            full = np.concatenate([full, full[:pad]])
        per_rank = [full[r::P] for r in range(P)]
        nb = len(per_rank[0]) // B
        rank = getattr(loader, "shard_index", 0)
        local = [per_rank[rank][j * B:(j + 1) * B] for j in range(nb)]
        perm = np.concatenate([
            np.concatenate([pr[j * B:(j + 1) * B] for pr in per_rank])
            for j in range(nb)]) if nb else np.zeros((0,), np.int64)
        tail = per_rank[rank][nb * B:]
        return perm, local, nb, tail

    def new_epoch(self) -> "CachedSource":
        t = self._trainer
        loader = self._loader
        B = loader.batch_size
        perm, local_ids, nb, tail = self._epoch_plan()
        if t.limit_train_batches is not None and \
                nb > t.limit_train_batches:
            nb = t.limit_train_batches
            perm = perm[:nb * self._global_batch]
            local_ids = local_ids[:nb]
        perm = perm.astype(np.int32)
        if self._last_perm is None or not np.array_equal(
                perm, self._last_perm):
            if self._flat is None:
                # the flat upload was dropped (shuffle=False promised a
                # stable index order) yet this epoch's perm CHANGED — a
                # loader whose _indices() varies without advertising
                # shuffle=True.  Re-upload from the dataset once, then
                # treat the loader as shuffling (keep the flat copy
                # resident) so the O(dataset) re-upload doesn't repeat
                # every order-changing epoch.
                _log.warning(
                    "cache_train_dataset: loader %s changed its epoch "
                    "index order despite shuffle=False; re-uploading the "
                    "flat device cache once and keeping it resident (set "
                    "shuffle=True to declare this upfront).",
                    type(loader).__name__)
                self._promise_broken = True
                if not self.build():   # pragma: no cover — build
                    raise RuntimeError(  # succeeded once already
                        "cache_train_dataset: flat cache re-upload failed")
            with span("repack", epoch=t.current_epoch):
                self._repacked = self._repack_jit(self._flat, perm)
            self._last_perm = perm
            if not getattr(loader, "shuffle", False) \
                    and not self._promise_broken:
                # membership claims to be fixed for the rest of the fit:
                # drop the flat upload instead of pinning a second full
                # dataset copy in device memory all fit long (eagerly —
                # keeping it through epoch 1 would regress peak HBM; the
                # warning path above covers loaders that break the
                # promise)
                self._flat = None
        # host-batch memo for callback arguments: valid while membership
        # (perm) is unchanged, so no-shuffle epochs collate each batch
        # at most once per fit instead of once per epoch
        if self._host_memo is None or not np.array_equal(
                perm, self._host_memo_perm):
            self._host_memo = {}
            self._host_memo_perm = perm

        def batch_of(sample_ids):
            return t._host_cast(self._gather_host(sample_ids))

        def memo_batch(j, sample_ids):
            got = self._host_memo.get(j)
            if got is None:
                got = self._host_memo[j] = batch_of(sample_ids)
            return got

        self._plan = [
            Item(batch_idx=j, kind="cached", payload=j,
                 _batch_fn=(lambda j=j, s=local_ids[j]:
                            memo_batch(j, s)))
            for j in range(nb)]
        under_limit = (t.limit_train_batches is None
                       or nb < t.limit_train_batches)
        if len(tail) and not loader.drop_last and under_limit:
            tail_batch = batch_of(tail)
            if t._batch_ok(tail_batch, self._strategy):
                self._plan.append(Item(batch_idx=nb, kind="host",
                                       payload=tail_batch))
        self._pos = 0
        self.exhausted = False
        return self

    # -- engine surface --------------------------------------------------

    def take(self, n: int) -> list:
        out = self._plan[self._pos:self._pos + n]
        self._pos += len(out)
        if self._pos >= len(self._plan):
            self.exhausted = True
        return out

    def chunkable(self, items: list) -> bool:
        return all(it.kind == "cached" for it in items)

    def run_one(self, trainer, item: Item):
        profile_tick()
        anatomy_tick()
        if item.kind == "host":
            gbatch = trainer._put_batch(item.payload, self._strategy)
            trainer.state, metrics = trainer._train_step(
                trainer.state, gbatch)
            return metrics
        trainer.state, metrics = trainer._cached_single_step(
            trainer.state, self._repacked, np.int32(item.payload))
        return metrics

    def run_chunk(self, trainer, items: list):
        profile_tick()
        anatomy_tick()
        idxs = np.asarray([it.payload for it in items], dtype=np.int32)
        trainer.state, metrics = trainer._cached_multi_step(
            trainer.state, self._repacked, idxs)
        return metrics
