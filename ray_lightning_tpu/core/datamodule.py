"""LightningDataModule analog (used by the reference's Tune example,
examples/ray_ddp_tune.py with pl_bolts MNISTDataModule)."""

from __future__ import annotations


class LightningDataModule:
    """Groups dataloaders + data lifecycle hooks, separable from the model."""

    def __init__(self):
        self.trainer = None
        self._prepared = False
        self._setup_stages: set[str] = set()

    def prepare_data(self) -> None:
        """One-time, per-node data materialization (download etc.)."""

    def setup(self, stage: str) -> None:
        """Per-process setup (splits, transforms)."""

    def train_dataloader(self):
        return None

    def val_dataloader(self):
        return None

    def test_dataloader(self):
        return None

    def predict_dataloader(self):
        return None

    # -- lifecycle bookkeeping (idempotent, like PL) -----------------------

    def _call_prepare_data(self) -> None:
        if not self._prepared:
            self.prepare_data()
            self._prepared = True

    def _call_setup(self, stage: str) -> None:
        if stage not in self._setup_stages:
            self.setup(stage)
            self._setup_stages.add(stage)

    def __getstate__(self):
        state = self.__dict__.copy()
        state["trainer"] = None
        return state
