"""Data loading: numpy-first batches with per-process sharding.

The reference relies on torch ``DataLoader`` + ``DistributedSampler``
wired per stage by PL using ``distributed_sampler_kwargs``
(ray_ddp.py:536-540).  On TPU the equivalent concern is *global-batch
assembly*: each host process loads its shard of the global batch and the
loop forms a global ``jax.Array`` over the mesh from process-local data.
This loader therefore owns sharding directly (``shard(num_shards, index)``)
instead of going through a sampler object.

Datasets can be: a tuple/dict of arrays (fast vectorized path), any
object with ``__len__`` + ``__getitem__`` (covers torch Datasets without
importing torch), or an arbitrary iterable (no sharding/shuffle support).
Batches are numpy pytrees; the training loop device-puts them with the
strategy's batch sharding.
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, Sequence

import numpy as np


def _to_numpy(x: Any) -> Any:
    """Convert torch tensors / jax arrays / lists to numpy without importing
    torch unconditionally."""
    if isinstance(x, np.ndarray):
        return x
    if hasattr(x, "detach") and hasattr(x, "cpu"):  # torch.Tensor duck-type
        return x.detach().cpu().numpy()
    if hasattr(x, "__array__"):
        return np.asarray(x)
    return x


def default_collate(samples: Sequence[Any]) -> Any:
    """Stack a list of samples (arrays / tuples / dicts) into a batch."""
    first = samples[0]
    if isinstance(first, (tuple, list)):
        return type(first)(
            default_collate([s[i] for s in samples]) for i in range(len(first)))
    if isinstance(first, dict):
        return {k: default_collate([s[k] for s in samples]) for k in first}
    arrs = [_to_numpy(s) for s in samples]
    if np.isscalar(arrs[0]) or (isinstance(arrs[0], np.ndarray)
                                and arrs[0].ndim == 0):
        return np.asarray(arrs)
    return np.stack(arrs)


class ArrayDataset:
    """Dataset over a pytree (tuple/dict) of equal-length arrays."""

    def __init__(self, *arrays: Any, **named: Any):
        if arrays and named:
            raise ValueError("Pass either positional or named arrays.")
        self._tree = named if named else (
            arrays[0] if len(arrays) == 1 and isinstance(arrays[0], dict)
            else tuple(arrays))
        leaves = (list(self._tree.values())
                  if isinstance(self._tree, dict) else list(self._tree))
        if not leaves:
            raise ValueError("Empty dataset.")
        self._leaves = [_to_numpy(a) for a in leaves]
        self._len = len(self._leaves[0])
        for a in self._leaves:
            if len(a) != self._len:
                raise ValueError("All arrays must share the leading dim.")

    def __len__(self) -> int:
        return self._len

    def _rebuild(self, leaves):
        if isinstance(self._tree, dict):
            return dict(zip(self._tree.keys(), leaves))
        if isinstance(self._tree, tuple) and len(leaves) == 1:
            return leaves[0]
        return tuple(leaves)

    def __getitem__(self, idx):
        return self._rebuild([a[idx] for a in self._leaves])

    def take(self, indices: np.ndarray):
        """Vectorized gather of a batch of indices."""
        return self._rebuild([a[indices] for a in self._leaves])


class DataLoader:
    """Minimal, shardable batch loader producing numpy pytrees."""

    def __init__(
        self,
        dataset: Any,
        batch_size: int = 1,
        shuffle: bool = False,
        drop_last: bool = False,
        seed: int = 0,
        collate_fn: Callable | None = None,
        num_shards: int = 1,
        shard_index: int = 0,
        prefetch: bool = True,
    ):
        self.dataset = dataset
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        self.collate_fn = collate_fn or default_collate
        self.num_shards = num_shards
        self.shard_index = shard_index
        self.prefetch = prefetch
        self._epoch = 0
        if not hasattr(dataset, "__len__"):
            if shuffle or num_shards > 1:
                raise ValueError(
                    "Iterable datasets support neither shuffle nor sharding.")

    # -- distributed-sampler analog ---------------------------------------

    def shard(self, num_shards: int, shard_index: int) -> "DataLoader":
        """Return a copy of this loader restricted to one process's shard
        (``DistributedSampler`` analog, ray_ddp.py:536-540)."""
        clone = DataLoader(
            self.dataset,
            batch_size=self.batch_size,
            shuffle=self.shuffle,
            drop_last=self.drop_last,
            seed=self.seed,
            collate_fn=self.collate_fn,
            num_shards=num_shards,
            shard_index=shard_index,
            prefetch=self.prefetch,
        )
        clone._epoch = self._epoch
        return clone

    def set_epoch(self, epoch: int) -> None:
        """Reshuffle deterministically per epoch (DistributedSampler parity)."""
        self._epoch = int(epoch)

    # -- iteration ---------------------------------------------------------

    def _indices(self) -> np.ndarray:
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self._epoch)
            idx = rng.permutation(n)
        else:
            idx = np.arange(n)
        if self.num_shards > 1:
            # Pad so every shard sees the same number of samples (matching
            # DistributedSampler's wrap-around), then stride.
            pad = (-len(idx)) % self.num_shards
            if pad:
                idx = np.concatenate([idx, idx[:pad]])
            idx = idx[self.shard_index::self.num_shards]
        return idx

    def __len__(self) -> int:
        if not hasattr(self.dataset, "__len__"):
            raise TypeError("Iterable dataset has no length.")
        n = len(self._indices())
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def __iter__(self) -> Iterator[Any]:
        if not hasattr(self.dataset, "__len__"):
            yield from self.dataset
            return
        idx = self._indices()
        fast = isinstance(self.dataset, ArrayDataset)
        if fast and self.prefetch:
            native_iter = self._native_iter(idx)
            if native_iter is not None:
                yield from native_iter
                return
        n_full = len(idx) // self.batch_size
        end = n_full * self.batch_size if self.drop_last else len(idx)
        for start in range(0, end, self.batch_size):
            batch_idx = idx[start:start + self.batch_size]
            if len(batch_idx) == 0:
                break
            if fast:
                yield self.dataset.take(batch_idx)
            else:
                yield self.collate_fn([self.dataset[int(i)]
                                       for i in batch_idx])

    def _native_iter(self, idx: np.ndarray) -> Iterator[Any] | None:
        """Batch iteration through the C++ prefetch runtime
        (ray_lightning_tpu.native): background batch assembly with a
        threaded row-gather, overlapping host work with device compute.

        Identical semantics to the Python path: same order (the index
        order is computed here and handed over) and caller-owned batch
        arrays (the prefetcher transfers buffer ownership per batch, so
        retained batches are never overwritten).  Returns None (→ Python
        fallback) when the native library or dataset layout is
        unsupported.
        """
        from ray_lightning_tpu import native
        if not native.native_available():
            return None
        ds = self.dataset
        leaves = ds._leaves
        # contiguity gate: ascontiguousarray inside the prefetcher would
        # silently deep-copy the dataset every epoch otherwise
        if not all(isinstance(a, np.ndarray) and a.dtype != object
                   and a.flags.c_contiguous for a in leaves):
            return None
        if self.drop_last:
            idx = idx[:(len(idx) // self.batch_size) * self.batch_size]
        if len(idx) == 0:
            return None

        def gen():
            pf = native.NativePrefetcher(leaves, self.batch_size)
            try:
                for bufs in pf.iter_epoch(idx):
                    yield ds._rebuild(bufs)
            finally:
                pf.close()
        return gen()
