"""Callbacks: base class + ModelCheckpoint + EarlyStopping.

The reference leans on PL's callback system (TuneReportCallback subclasses
TuneCallback, tune.py:59-134; EarlyStopping exercised in
tests/test_ddp.py:287-306; ModelCheckpoint best_model_path propagated at
ray_ddp.py:378-380).  PL itself is not a dependency here, so the framework
carries its own equivalents with the same semantics.  All callback hooks
run host-side between compiled steps — they never appear inside traces.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np


class Callback:
    """Base callback; hooks mirror the PL names the reference relies on."""

    #: Set False when the callback's per-batch hooks never read their
    #: ``batch`` argument: the engine then skips host-collating cached
    #: batches for it and passes ``batch=None`` (per-step host work is
    #: exactly what the device-resident cache exists to remove).  Leave
    #: True (the safe default) for any callback that looks at the batch.
    needs_batch = True

    def setup(self, trainer, module, stage: str) -> None: ...
    def teardown(self, trainer, module, stage: str) -> None: ...
    def on_fit_start(self, trainer, module) -> None: ...
    def on_fit_end(self, trainer, module) -> None: ...
    def on_sanity_check_start(self, trainer, module) -> None: ...
    def on_sanity_check_end(self, trainer, module) -> None: ...
    def on_train_start(self, trainer, module) -> None: ...
    def on_train_end(self, trainer, module) -> None: ...
    def on_train_epoch_start(self, trainer, module) -> None: ...
    def on_train_epoch_end(self, trainer, module) -> None: ...
    def on_train_batch_start(self, trainer, module, batch, batch_idx) -> None: ...
    def on_train_batch_end(self, trainer, module, outputs, batch,
                           batch_idx) -> None: ...
    def on_validation_start(self, trainer, module) -> None: ...
    def on_validation_end(self, trainer, module) -> None: ...
    def on_validation_epoch_start(self, trainer, module) -> None: ...
    def on_validation_epoch_end(self, trainer, module) -> None: ...
    def on_validation_batch_end(self, trainer, module, outputs, batch,
                                batch_idx) -> None: ...
    def on_test_start(self, trainer, module) -> None: ...
    def on_test_end(self, trainer, module) -> None: ...
    def on_test_epoch_end(self, trainer, module) -> None: ...
    def on_predict_start(self, trainer, module) -> None: ...
    def on_predict_end(self, trainer, module) -> None: ...
    def on_exception(self, trainer, module, err: BaseException) -> None: ...
    def on_save_checkpoint(self, trainer, module, checkpoint: dict) -> None: ...
    def on_load_checkpoint(self, trainer, module, checkpoint: dict) -> None: ...
    def state_dict(self) -> dict:
        return {}
    def load_state_dict(self, state: dict) -> None: ...


_MODE_OPS = {"min": (np.less, np.inf), "max": (np.greater, -np.inf)}


class _Monitor:
    """Shared monitored-metric machinery for checkpoint/early-stop."""

    def __init__(self, monitor: Optional[str], mode: str, min_delta: float = 0.0):
        if mode not in _MODE_OPS:
            raise ValueError(f"mode must be 'min' or 'max', got {mode!r}")
        self.monitor = monitor
        self.mode = mode
        self.min_delta = abs(min_delta)
        self.op, self.worst = _MODE_OPS[mode]
        self.best = self.worst

    def current(self, trainer) -> Optional[float]:
        if self.monitor is None:
            return None
        val = trainer.callback_metrics.get(self.monitor)
        return None if val is None else float(val)

    def improved(self, value: float) -> bool:
        delta = -self.min_delta if self.mode == "min" else self.min_delta
        return bool(self.op(value, self.best + delta)) or self.best == self.worst


class ModelCheckpoint(Callback):
    """Save checkpoints, track the best one (``best_model_path`` parity —
    the reference ships this path rank-0 → driver, ray_ddp.py:475-480)."""

    def __init__(
        self,
        dirpath: Optional[str] = None,
        filename: str = "epoch={epoch}-step={step}",
        monitor: Optional[str] = None,
        mode: str = "min",
        save_top_k: int = 1,
        save_last: bool = False,
        every_n_epochs: int = 1,
    ):
        self.dirpath = dirpath
        self.filename = filename
        self.monitor = monitor
        self.mode = mode
        self.save_top_k = save_top_k
        self.save_last = save_last
        self.every_n_epochs = max(1, every_n_epochs)
        self.best_model_path: str = ""
        self.best_model_score: Optional[float] = None
        self.last_model_path: str = ""
        self._saved: list[tuple[float, str]] = []  # (score, path), best first
        self._mon = _Monitor(monitor, mode)

    def setup(self, trainer, module, stage: str) -> None:
        if self.dirpath is None:
            self.dirpath = os.path.join(trainer.default_root_dir, "checkpoints")

    def _format_name(self, trainer) -> str:
        name = self.filename.format(
            epoch=trainer.current_epoch, step=trainer.global_step,
            **{k: v for k, v in trainer.callback_metrics.items()
               if isinstance(v, (int, float))})
        return name + ".ckpt"

    def _save(self, trainer, path: str) -> None:
        # save_checkpoint is collective (all processes gather, rank 0
        # writes) — every process must enter it, so no rank gate here.
        trainer.save_checkpoint(path)

    def on_validation_end(self, trainer, module) -> None:
        if not trainer.sanity_checking:
            self._maybe_save(trainer)

    def on_train_epoch_end(self, trainer, module) -> None:
        # Only save here when there was no validation this epoch.
        if trainer.num_val_batches == 0:
            self._maybe_save(trainer)

    def _maybe_save(self, trainer) -> None:
        if self.save_top_k == 0:
            return
        if (trainer.current_epoch + 1) % self.every_n_epochs != 0:
            return
        path = os.path.join(self.dirpath, self._format_name(trainer))
        score = self._mon.current(trainer)
        if self.monitor is None:
            self._save(trainer, path)
            self.best_model_path = path
        else:
            if score is None:
                return
            self._saved.append((score, path))
            reverse = self.mode == "max"
            self._saved.sort(key=lambda t: t[0], reverse=reverse)
            if self.save_top_k > 0 and len(self._saved) > self.save_top_k:
                _, evict = self._saved.pop()
                if evict == path:
                    self._record_last(trainer)
                    return  # not in top-k; skip writing
                if trainer.is_global_zero and os.path.exists(evict):
                    os.remove(evict)
            self._save(trainer, path)
            self.best_model_score, self.best_model_path = self._saved[0]
        self._record_last(trainer)

    def _record_last(self, trainer) -> None:
        if self.save_last:
            last = os.path.join(self.dirpath, "last.ckpt")
            self._save(trainer, last)
            self.last_model_path = last

    def state_dict(self) -> dict:
        return {
            "best_model_path": self.best_model_path,
            "best_model_score": self.best_model_score,
            "saved": list(self._saved),
        }

    def load_state_dict(self, state: dict) -> None:
        self.best_model_path = state.get("best_model_path", "")
        self.best_model_score = state.get("best_model_score")
        self._saved = [tuple(t) for t in state.get("saved", [])]


class ShardedCheckpoint(Callback):
    """Periodic sharded (orbax) checkpointing — the pod-scale complement
    to :class:`ModelCheckpoint`.

    Saves the live TrainState shard-by-shard and asynchronously
    (utils/checkpoint.py): every process writes only what it owns, the
    disk write overlaps subsequent training steps, and nothing is
    gathered to one host.  Resume by pointing
    ``Trainer(resume_from_checkpoint=...)`` at the directory.
    """

    def __init__(self, dirpath: Optional[str] = None,
                 every_n_train_steps: int = 0, every_n_epochs: int = 1,
                 max_to_keep: Optional[int] = None):
        self.dirpath = dirpath
        self.every_n_train_steps = every_n_train_steps
        self.every_n_epochs = every_n_epochs
        self.max_to_keep = max_to_keep

    def setup(self, trainer, module, stage: str) -> None:
        if self.dirpath is None:
            self.dirpath = os.path.join(trainer.default_root_dir,
                                        "sharded_checkpoints")

    def _save(self, trainer) -> None:
        trainer.save_sharded_checkpoint(self.dirpath,
                                        max_to_keep=self.max_to_keep)

    needs_batch = False    # step-cadence only; never reads the batch

    def on_train_batch_end(self, trainer, module, outputs, batch,
                           batch_idx) -> None:
        n = self.every_n_train_steps
        if n and trainer.global_step and trainer.global_step % n == 0:
            self._save(trainer)

    def on_train_epoch_end(self, trainer, module) -> None:
        n = self.every_n_epochs
        if n and (trainer.current_epoch + 1) % n == 0:
            self._save(trainer)

    # no on_train_end wait needed: the trainer's fit finalization waits
    # on and closes every sharded checkpointer it opened
    # (trainer._close_sharded_checkpointers)


class EarlyStopping(Callback):
    """Stop training when a monitored metric stops improving
    (exercised by the reference at tests/test_ddp.py:287-306)."""

    def __init__(
        self,
        monitor: str = "val_loss",
        min_delta: float = 0.0,
        patience: int = 3,
        mode: str = "min",
        check_on_train_epoch_end: bool = False,
    ):
        self.monitor = monitor
        self.patience = patience
        self.wait_count = 0
        self.stopped_epoch = 0
        self.check_on_train_epoch_end = check_on_train_epoch_end
        self._mon = _Monitor(monitor, mode, min_delta)

    def _run_check(self, trainer) -> None:
        value = self._mon.current(trainer)
        if value is None:
            return
        if self._mon.improved(value):
            self._mon.best = value
            self.wait_count = 0
        else:
            self.wait_count += 1
            if self.wait_count >= self.patience:
                self.stopped_epoch = trainer.current_epoch
                trainer.should_stop = True

    def on_validation_end(self, trainer, module) -> None:
        if not trainer.sanity_checking and not self.check_on_train_epoch_end:
            self._run_check(trainer)

    def on_train_epoch_end(self, trainer, module) -> None:
        if self.check_on_train_epoch_end or trainer.num_val_batches == 0:
            self._run_check(trainer)

    def state_dict(self) -> dict:
        return {
            "best": self._mon.best,
            "wait_count": self.wait_count,
            "stopped_epoch": self.stopped_epoch,
        }

    def load_state_dict(self, state: dict) -> None:
        self._mon.best = state.get("best", self._mon.worst)
        self.wait_count = state.get("wait_count", 0)
        self.stopped_epoch = state.get("stopped_epoch", 0)
