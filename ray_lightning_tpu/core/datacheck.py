"""Opt-in divergent-loader detection (``RLT_DATA_CHECK=1``).

The multi-process data contract (core/loop_engine.py StreamSource): every
process derives its batch order from the SAME loader state; only the
shard stride differs.  A loader that violates it — e.g. a per-rank seed,
or an order-mutating subclass — trains on silently skewed batch pairings
(rank A's step k meets rank B's step n-1-k) without any crash.  The
canary in tests/test_plugin_distributed.py used to merely *document*
that skew; with this module the framework *detects* it:

- **worker side** (:class:`BatchFingerprinter`, created per epoch by the
  stream source when enabled): for each consumed batch, a cheap crc32
  fingerprint of the actual batch bytes AND of the batch the contract
  says this rank should be consuming (reconstructed from the shared base
  order exactly the way ``DataLoader.shard`` strides it — the same
  re-derivation the cached source uses).  Both ride the worker→driver
  queue as marked items.
- **driver side** (:class:`DataCheckValidator`, installed by the
  distributed plugin): raises when any rank's actual fingerprint
  diverges from its contract fingerprint, or when two ranks disagree on
  the base-order fingerprint for the same epoch (a per-rank-seeded
  shuffle).  The raise happens in the driver's poll loop
  (util.process_results), naming rank, epoch and step.

Cost: one extra dataset gather + two crc32 per step, only when the env
knob is set — a debugging/CI tool, not an always-on tax.
"""

from __future__ import annotations

import logging
import os
import threading
import zlib
from typing import Any, Optional

import numpy as np

_log = logging.getLogger(__name__)

ENV_DATA_CHECK = "RLT_DATA_CHECK"
DATA_CHECK_KEY = "__rlt_data_check__"


def enabled() -> bool:
    return os.environ.get(ENV_DATA_CHECK, "").strip() == "1"


def tree_fingerprint(batch: Any) -> int:
    """crc32 over every leaf's bytes + shape/dtype (order-sensitive:
    positional skew MUST change the value)."""
    import jax
    crc = 0
    for leaf in jax.tree_util.tree_leaves(batch):
        a = np.ascontiguousarray(np.asarray(leaf))
        crc = zlib.crc32(repr((a.shape, str(a.dtype))).encode(), crc)
        crc = zlib.crc32(a.tobytes(), crc)
    return crc


class BatchFingerprinter:
    """Worker-side fingerprint relay for one epoch of one loader."""

    def __init__(self, loader, rank: int, epoch: int, send):
        self._loader = loader
        self._rank = rank
        self._epoch = epoch
        self._send = send
        # the shared base order, re-derived the way every honest shard's
        # _indices() strides it (DataLoader._indices / CachedSource
        # _epoch_plan do the same reconstruction)
        base = np.asarray(loader.shard(1, 0)._indices())
        self._base_fp = zlib.crc32(
            np.ascontiguousarray(base, np.int64).tobytes())
        P = max(1, getattr(loader, "num_shards", 1))
        pad = (-len(base)) % P
        if pad:
            base = np.concatenate([base, base[:pad]])
        self._expected_ids = base[getattr(loader, "shard_index", 0)::P]

    @classmethod
    def maybe_create(cls, loader, rank: int,
                     epoch: int) -> "Optional[BatchFingerprinter]":
        """None unless the knob is set, a worker session queue exists,
        and the loader exposes the needed anatomy (same surface the
        cached source requires)."""
        if not enabled():
            return None
        try:
            from ray_lightning_tpu.session import get_session
            session = get_session()
        except ValueError:
            return None
        ok = all(hasattr(loader, a) for a in
                 ("shard", "_indices", "dataset", "collate_fn",
                  "batch_size")) \
            and hasattr(loader.dataset, "__len__") \
            and hasattr(loader.dataset, "__getitem__")
        if not ok:
            _log.warning("%s=1 needs a ray_lightning_tpu DataLoader over "
                         "an indexable dataset; got %r — data check "
                         "skipped.", ENV_DATA_CHECK, type(loader).__name__)
            return None
        return cls(loader, rank, epoch, session.put_queue)

    def _expected_batch(self, batch_idx: int):
        """The batch the contract says this rank consumes at loader
        position ``batch_idx`` (mirrors DataLoader.__iter__'s gather)."""
        from ray_lightning_tpu.core.data import ArrayDataset
        B = self._loader.batch_size
        ids = self._expected_ids[batch_idx * B:(batch_idx + 1) * B]
        if len(ids) == 0:
            return None
        ds = self._loader.dataset
        if isinstance(ds, ArrayDataset):
            return ds.take(np.asarray(ids))
        return self._loader.collate_fn([ds[int(i)] for i in ids])

    def observe(self, batch_idx: int, batch: Any) -> None:
        """Fingerprint one consumed batch and relay the check item."""
        try:
            expected = self._expected_batch(batch_idx)
            item = {
                DATA_CHECK_KEY: 1,
                "rank": self._rank,
                "epoch": self._epoch,
                "step": batch_idx,
                "fp": tree_fingerprint(batch),
                "expected_fp": (tree_fingerprint(expected)
                                if expected is not None else None),
                "base_fp": self._base_fp,
            }
            self._send(item)
        except Exception:    # the check must never kill a training step
            _log.warning("data-check fingerprint failed", exc_info=True)


class DataCheckValidator:
    """Driver-side cross-rank validation of relayed fingerprints."""

    def __init__(self):
        self._lock = threading.Lock()
        self._base: dict[int, dict[int, int]] = {}   # epoch -> rank -> fp
        self._failure: Optional[str] = None
        self.checked = 0

    def maybe_ingest(self, item: Any) -> bool:
        if not (isinstance(item, dict) and item.get(DATA_CHECK_KEY)):
            return False
        rank = item.get("rank", -1)
        epoch = item.get("epoch", 0)
        step = item.get("step", -1)
        with self._lock:
            self.checked += 1
            if item.get("expected_fp") is not None \
                    and item["fp"] != item["expected_fp"] \
                    and self._failure is None:
                self._failure = (
                    f"divergent data order detected: rank {rank} consumed "
                    f"a batch at epoch {epoch} step {step} that does not "
                    f"match the shared loader order (actual fingerprint "
                    f"{item['fp']:#x} != contract {item['expected_fp']:#x})"
                    f" — every process must derive its order from the "
                    f"same loader state (core/loop_engine.py contract)")
            ranks = self._base.setdefault(epoch, {})
            ranks[rank] = item["base_fp"]
            if len(set(ranks.values())) > 1 and self._failure is None:
                self._failure = (
                    f"divergent base order detected at epoch {epoch}: "
                    f"ranks disagree on the pre-shard index order "
                    f"({ {r: hex(f) for r, f in ranks.items()} }) — "
                    f"per-rank seeds/shuffles violate the shared-loader "
                    f"contract")
        return True

    def verify(self) -> None:
        """Raise on any recorded divergence (called from the driver's
        poll loop, util.process_results)."""
        if self._failure is not None:
            raise RuntimeError(self._failure)


_validator: Optional[DataCheckValidator] = None


def set_active_validator(v: Optional[DataCheckValidator]) -> None:
    global _validator
    _validator = v


def get_active_validator() -> Optional[DataCheckValidator]:
    return _validator


__all__ = [
    "ENV_DATA_CHECK",
    "DATA_CHECK_KEY",
    "enabled",
    "tree_fingerprint",
    "BatchFingerprinter",
    "DataCheckValidator",
    "set_active_validator",
    "get_active_validator",
]
