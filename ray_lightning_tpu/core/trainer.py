"""The Trainer: ``pl.Trainer`` capability analog, re-designed TPU-first.

Structure of a run (compare SURVEY.md §3.1 call stack):

  driver:  Trainer.fit(module)
    └─ plugin.run(...)            — LocalPlugin executes in-process;
                                    RayXlaPlugin ships (trainer, module,
                                    datamodule) to actor workers and
                                    round-trips results (plugins/)
  worker:  trainer._run_stage(...)
    ├─ strategy.build_mesh()      — Mesh over all chips of all hosts
    ├─ jit(init_fn, out_shardings=state_shardings)   — params born sharded
    ├─ jit(train_step, donate_argnums=0)             — ONE compiled SPMD
    │                                                   program; gradient
    │                                                   sync is a sharding
    │                                                   consequence
    └─ host loop: batches → global arrays → compiled step; callbacks and
       checkpointing run host-side between steps.

The host loop never inspects device values except at logging/validation
boundaries (JAX async dispatch keeps the device pipeline full — the
explicit host-transfer-point discipline flagged in SURVEY.md §7).
"""

from __future__ import annotations

import logging
import os
import tempfile
import time
import warnings
from typing import Any, Optional

import fsspec
import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import serialization

from ray_lightning_tpu.compile import (
    AotPrecompiler,
    CompileCacheConfig,
    global_batch_abstract,
    stack_abstract,
)
from ray_lightning_tpu.compile import cache as compile_cache
from ray_lightning_tpu.core.callbacks import Callback, ModelCheckpoint
from ray_lightning_tpu.core.state import TrainState
from ray_lightning_tpu.core.steps import (
    build_eval_step,
    build_init_fn,
    build_predict_step,
    build_train_step,
)
from ray_lightning_tpu.parallel.gather import fetch_tree
from ray_lightning_tpu.parallel.mesh import set_current_mesh
from ray_lightning_tpu.parallel.strategy import resolve_strategy
from ray_lightning_tpu.telemetry import TelemetryConfig, span
from ray_lightning_tpu.telemetry import metrics as _metrics
from ray_lightning_tpu.utils.seed import reset_seed, seed_everything

_log = logging.getLogger(__name__)

_RUNTIME_FIELDS = (
    "state", "_mesh", "_train_step", "_eval_steps", "_predict_step",
    "_state_shardings", "_abstract_state", "_tx", "_init_fn", "_init_rng",
    "_multi_train_step", "_stacked_batch_shardings",
    "_cache_source", "_cached_multi_step", "_cached_single_step",
    "_precompiler", "_abstract_batch", "_grad_sync", "_snapshotter",
    "_redundancy",
)

# every spelling (PL 1.x and 2.x) that means "half-precision inputs";
# on TPU they all resolve to bfloat16 (no loss-scaling machinery)
_BF16_PRECISIONS = ("bf16", "bf16-mixed", "bf16-true",
                    "16", "16-mixed", "16-true")
_FP32_PRECISIONS = ("32", "32-true", "64")


class Trainer:
    """Drives fit / validate / test / predict for a LightningModule."""

    def __init__(
        self,
        max_epochs: Optional[int] = None,
        max_steps: int = -1,
        callbacks: Optional[list[Callback]] = None,
        plugins: Optional[list] = None,
        strategy: Any = None,
        default_root_dir: Optional[str] = None,
        enable_checkpointing: bool = True,
        limit_train_batches: Optional[int] = None,
        limit_val_batches: Optional[int] = None,
        limit_test_batches: Optional[int] = None,
        limit_predict_batches: Optional[int] = None,
        check_val_every_n_epoch: int = 1,
        val_check_interval: Optional[int] = None,
        log_every_n_steps: int = 50,
        num_sanity_val_steps: int = 2,
        accumulate_grad_batches: int = 1,
        steps_per_execution: int = 1,
        cache_train_dataset: bool = False,
        gradient_clip_val: Optional[float] = None,
        precision: str = "32",
        seed: Optional[int] = None,
        resume_from_checkpoint: Optional[str] = None,
        use_distributed_sampler: bool = True,
        enable_progress_bar: bool = False,   # accepted for API parity
        logger: Any = True,                  # accepted for API parity
        telemetry: Any = None,
        compile_cache: Any = None,
        comm_policy: Any = None,
        elastic: Any = None,
        plan: Any = None,
    ):
        if max_epochs is None and (max_steps is None or max_steps < 0):
            max_epochs = 1000
        self.max_epochs = max_epochs
        self.max_steps = max_steps if max_steps is not None else -1
        self.callbacks: list[Callback] = list(callbacks or [])
        self.default_root_dir = default_root_dir or os.path.join(
            os.getcwd(), "rlt_logs")
        self.enable_checkpointing = enable_checkpointing
        self.limit_train_batches = limit_train_batches
        self.limit_val_batches = limit_val_batches
        self.limit_test_batches = limit_test_batches
        self.limit_predict_batches = limit_predict_batches
        self.check_val_every_n_epoch = max(1, check_val_every_n_epoch)
        self.val_check_interval = val_check_interval
        self.log_every_n_steps = max(1, log_every_n_steps)
        self.num_sanity_val_steps = num_sanity_val_steps
        self.accumulate_grad_batches = max(1, accumulate_grad_batches)
        # opt-in multi-step dispatch: fold k optimizer steps into ONE
        # compiled program (lax.scan over stacked batches), cutting host
        # dispatches k× — decisive for small models where per-step
        # dispatch latency dominates compute (BASELINE config #1).
        # Batch-granular callbacks coarsen to once per chunk.
        self.steps_per_execution = max(1, int(steps_per_execution))
        # opt-in device-resident train set: samples upload ONCE (flat,
        # dataset order), each epoch a device-side repack follows the
        # loader's own index order (shuffle-accurate membership), and
        # steps gather their batch on-device — removing the per-step
        # host→device batch transfer entirely (the measured bottleneck
        # for small models on tunneled TPUs: ~28 MB/s link vs
        # microsecond compute).  See core/loop_engine.py CachedSource.
        # Works single- and multi-process (the flat cache becomes one
        # global sharded array); combine with steps_per_execution>1.
        self.cache_train_dataset = bool(cache_train_dataset)
        self.gradient_clip_val = gradient_clip_val
        self.precision = str(precision)
        if self.precision not in _BF16_PRECISIONS + _FP32_PRECISIONS:
            raise ValueError(
                f"Unknown precision {precision!r}; use one of "
                f"{_BF16_PRECISIONS + _FP32_PRECISIONS}")
        self.seed = seed
        self.resume_from_checkpoint = resume_from_checkpoint
        self.use_distributed_sampler = use_distributed_sampler
        # run telemetry (telemetry/): per-rank spans + heartbeats stream
        # to the driver, which exports trace.json / telemetry.jsonl.
        # None defers to RLT_TELEMETRY; the config pickles to workers
        # with the trainer.
        self.telemetry = TelemetryConfig.resolve(telemetry)
        #: exported artifact paths, set by the execution plugin after a
        #: telemetry-enabled run ({"trace": ..., "jsonl": ..., "summary"})
        self._telemetry_paths: Optional[dict] = None
        # persistent XLA compilation cache (compile/): None defers to
        # the RLT_COMPILE_CACHE* env knobs and — inside a builtin tune
        # trial — the experiment's shared cache dir.  Resolved HERE (the
        # trainer is constructed inside the trial thread / on the
        # driver) so the pickled config carries the tune session's dir
        # into actor workers that have no session of their own.
        self.compile_cache = CompileCacheConfig.resolve(compile_cache)
        # compressed gradient collectives (comm/): blockwise-quantized
        # cross-replica reductions with error feedback.  None defers to
        # the RLT_COMM* env knobs; "none" (the default) keeps the train
        # step bit-identical to a policy-less build.  The frozen policy
        # pickles driver→worker with the trainer.
        from ray_lightning_tpu.comm import CommPolicy
        self.comm_policy = CommPolicy.resolve(comm_policy)
        # elastic plane (elastic/): async snapshots + shrink-to-continue
        # fault tolerance.  None defers to the RLT_ELASTIC* env knobs;
        # off (the default) keeps every path below inert.  The frozen
        # config pickles driver→worker with the trainer.
        from ray_lightning_tpu.elastic import ElasticConfig
        self.elastic = ElasticConfig.resolve(elastic)
        # planner plane (plan/): cost-model-driven auto-parallelism
        # behind Trainer(strategy="auto").  None defers to the RLT_PLAN*
        # env knobs; the frozen config pickles driver→worker with the
        # trainer so every rank plans from identical inputs.
        from ray_lightning_tpu.plan import PlanConfig
        self.plan = PlanConfig.resolve(plan)
        from ray_lightning_tpu.utils.logger import resolve_logger
        self.logger = resolve_logger(logger, self.default_root_dir)

        # execution plugin (LocalPlugin unless a distributed one is given)
        from ray_lightning_tpu.plugins.base import LocalPlugin
        dist = [p for p in (plugins or []) if hasattr(p, "run")]
        if len(dist) > 1:
            raise ValueError("At most one execution plugin is supported.")
        self.plugin = dist[0] if dist else LocalPlugin()
        if strategy is not None:
            # explicit Trainer(strategy=...) overrides the plugin default
            self.plugin.strategy = resolve_strategy(strategy)

        if enable_checkpointing and not any(
                isinstance(c, ModelCheckpoint) for c in self.callbacks):
            self.callbacks.append(ModelCheckpoint())

        # run state
        self.lightning_module = None
        self.datamodule = None
        self.current_epoch = 0
        self.global_step = 0
        self.should_stop = False
        self.sanity_checking = False
        self.num_val_batches = 0
        self.callback_metrics: dict[str, float] = {}
        self.logged_metrics: dict[str, float] = {}
        self.state: Optional[TrainState] = None
        self._world = {"world_size": 1, "global_rank": 0, "local_rank": 0,
                       "node_rank": 0}
        self._cache_bytes_hint = None
        self._mesh = None
        #: seconds from stage entry to the first completed train step
        #: (compile + init + upload startup cost; bench.py reports it)
        self.time_to_first_step: Optional[float] = None
        self._stage_t0: Optional[float] = None
        self._precompiler: Optional[AotPrecompiler] = None
        self._epoch_metric_acc: dict[str, list] = {}
        self._warned_skip = False
        self._stage = None
        self._sharded_checkpointers: dict = {}
        self._snapshotter = None
        #: shrink-to-continue bookkeeping, set by the elastic driver on
        #: the driver trainer (rides the pickle to workers — the loader
        #: rescale reads it) and summarized into _elastic_report
        self._elastic_state: Optional[dict] = None
        self._elastic_report: Optional[dict] = None
        self._elastic_worker_stats: Optional[dict] = None
        #: in-memory reconstruct-and-continue package built by the
        #: elastic driver from harvested parity escrows — RIDES the
        #: pickle to the shrunken fleet (unlike the runtime fields
        #: below), where _init_state restores it instead of a snapshot
        self._elastic_recovery: Optional[dict] = None
        #: worker-side parity manager (elastic/redundancy.py), rebuilt
        #: per stage like the snapshotter
        self._redundancy = None
        #: sharded-checkpoint restores executed by THIS process during
        #: the stage — the zero-replay proof reads it (a parity
        #: recovery must show 0)
        self._snapshot_restores = 0
        self._warned_rescale = False
        #: the planner's machine-readable verdict (PlanReport dict) when
        #: strategy="auto" ran; rank-0's copy rides the worker result
        #: package back to the driver (plugins/xla.py)
        self._plan_report: Optional[dict] = None
        #: the winning plan's donation decision, consulted by
        #: _should_donate between the RLT_DONATE force and the heuristic
        self._plan_donate: Optional[bool] = None
        #: goodput plane (telemetry/goodput.py): this rank's finalized
        #: ledger doc, and the driver-side fleet aggregate the bench
        #: harness reads (plugins set it in their teardown)
        self._goodput_local: Optional[dict] = None
        self._goodput_report: Optional[dict] = None

    # ------------------------------------------------------------------
    # pickling across the driver→worker boundary (ray_ddp.py:164-172
    # analog: drop live handles / compiled functions / device arrays)
    # ------------------------------------------------------------------

    def __getstate__(self):
        state = self.__dict__.copy()
        for f in _RUNTIME_FIELDS:
            state[f] = None
        state["lightning_module"] = None
        state["datamodule"] = None
        state["_sharded_checkpointers"] = {}  # live orbax managers
        return state

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------

    def fit(self, module, datamodule=None, ckpt_path: Optional[str] = None):
        ckpt_path = ckpt_path or self.resume_from_checkpoint
        return self.plugin.run(self, module, datamodule, "fit", ckpt_path)

    def validate(self, module, datamodule=None,
                 ckpt_path: Optional[str] = None):
        return self.plugin.run(self, module, datamodule, "validate", ckpt_path)

    def test(self, module, datamodule=None, ckpt_path: Optional[str] = None):
        return self.plugin.run(self, module, datamodule, "test", ckpt_path)

    def predict(self, module, datamodule=None,
                ckpt_path: Optional[str] = None):
        return self.plugin.run(self, module, datamodule, "predict", ckpt_path)

    # -- world info -----------------------------------------------------

    @property
    def world_size(self) -> int:
        return self._world["world_size"]

    @property
    def global_rank(self) -> int:
        return self._world["global_rank"]

    @property
    def local_rank(self) -> int:
        return self._world["local_rank"]

    @property
    def node_rank(self) -> int:
        return self._world["node_rank"]

    @property
    def is_global_zero(self) -> bool:
        return self.global_rank == 0

    @property
    def strategy(self):
        return self.plugin.strategy

    @property
    def checkpoint_callback(self) -> Optional[ModelCheckpoint]:
        for c in self.callbacks:
            if isinstance(c, ModelCheckpoint):
                return c
        return None

    @property
    def early_stopping_callback(self):
        from ray_lightning_tpu.core.callbacks import EarlyStopping
        for c in self.callbacks:
            if isinstance(c, EarlyStopping):
                return c
        return None

    # ------------------------------------------------------------------
    # stage execution (runs in-process locally, or inside each worker)
    # ------------------------------------------------------------------

    def _run_stage(self, module, datamodule, stage: str,
                   ckpt_path: Optional[str] = None):
        self._stage = stage
        self._stage_t0 = time.monotonic()
        self.time_to_first_step = None
        self.lightning_module = module
        module.trainer = self
        self.datamodule = datamodule
        if datamodule is not None:
            datamodule.trainer = self

        if self.seed is not None:
            seed_everything(self.seed)
        else:
            reset_seed()

        self._world = {
            "world_size": jax.process_count(),
            "global_rank": jax.process_index(),
            "local_rank": 0,
            "node_rank": jax.process_index(),
        }

        # deterministic fault injection (elastic/faults.py): RLT_FAULT
        # in this process's env arms kill/wedge/slow-rank-k-at-step-s
        # for chaos tests and benches
        from ray_lightning_tpu.elastic.faults import (FaultInjector,
                                                      maybe_injector_from_env)
        if not any(isinstance(c, FaultInjector) for c in self.callbacks):
            injector = maybe_injector_from_env()
            if injector is not None:
                self.callbacks.append(injector)
        # elastic snapshotting (elastic/snapshot.py): cadence-driven
        # async sharded saves off the critical path, fit only
        self._snapshotter = None
        if stage == "fit" and self.elastic.enabled \
                and self.elastic.snapshot_every_n_steps > 0:
            from ray_lightning_tpu.elastic.snapshot import Snapshotter
            self._snapshotter = Snapshotter(self, self.elastic)
        # parity redundancy (elastic/redundancy.py): cadence-driven
        # optimizer-shard parity over the worker↔worker peer channel,
        # enabling zero-replay recovery on a single-rank loss
        self._redundancy = None
        self._snapshot_restores = 0
        if stage == "fit" and self.elastic.enabled \
                and self.elastic.redundancy > 0:
            self._redundancy = self._build_redundancy()

        # persistent XLA compilation cache: activated before the first
        # jit so every program of this stage (init, train, eval) is a
        # disk hit when a previous process — an earlier tune trial, a
        # pre-restart worker, yesterday's run — compiled it (compile/)
        compile_cache.activate(self.compile_cache)

        # data lifecycle (reference: prepare_data per worker, ray_ddp.py:446)
        if datamodule is not None:
            datamodule._call_prepare_data()
            datamodule._call_setup(stage)
        module.prepare_data()
        module.setup(stage)
        module.setup_model()

        strategy = self.plugin.strategy
        if strategy is None:
            strategy = resolve_strategy(None)
            self.plugin.strategy = strategy

        loaders = self._build_loaders(stage)
        first_loader = loaders.get(
            {"fit": "train", "validate": "val", "test": "test",
             "predict": "predict"}[stage])
        if first_loader is None:
            raise ValueError(f"No dataloader available for stage {stage!r}")

        example_batch, replacement = _peek_first_batch(first_loader)
        if replacement is not first_loader:
            key = {"fit": "train", "validate": "val", "test": "test",
                   "predict": "predict"}[stage]
            loaders[key] = replacement
        leaves = jax.tree_util.tree_leaves(example_batch)
        batch_hint = (leaves[0].shape[0] * jax.process_count()
                      if leaves and getattr(leaves[0], "ndim", 0) > 0
                      else None)
        if getattr(strategy, "name", "") == "auto":
            # planner plane (plan/): everything the cost model needs —
            # module, example batch, topology — is known exactly here,
            # one line before the mesh would be built
            strategy = self._resolve_auto_strategy(
                module, example_batch, batch_hint, strategy, stage)
            self.plugin.strategy = strategy
        if getattr(strategy, "name", "") == "mpmd":
            # MPMD plane (mpmd/): no SPMD mesh or monolithic train step
            # exists — the engine builds per-stage programs and runs
            # the driver-side schedule.  Fit only; evaluate with a
            # non-mpmd strategy (without a stage axis the model is the
            # same sequential math).
            if stage != "fit":
                raise ValueError(
                    f"strategy='mpmd' supports fit only (got "
                    f"{stage!r}); run {stage} under 'ddp' — the model "
                    f"math is identical without a stage split")
            from ray_lightning_tpu.mpmd.engine import run_mpmd_fit
            return run_mpmd_fit(self, module, loaders, example_batch)
        self._mesh = strategy.build_mesh(self.plugin.local_devices(),
                                         batch_hint=batch_hint)
        set_current_mesh(self._mesh)  # for mesh-aware ops (ring attention)
        # goodput plane (telemetry/goodput.py): one ledger per fit run,
        # backdated to the stage clock so the partition covers every
        # second of stage wall (compile and init included).  The plugin
        # armed the plane (or didn't); start_run is a no-op when off.
        self._goodput_ledger = None
        if stage == "fit":
            from ray_lightning_tpu.telemetry import goodput as _goodput
            self._goodput_ledger = _goodput.start_run("fit")
            if self._goodput_ledger is not None:
                self._goodput_ledger._t0 = self._stage_t0
                self._goodput_ledger.devices = int(self._mesh.devices.size)
                tfl = self.telemetry.resolved_goodput_tflops()
                self._goodput_ledger.device_tflops = (
                    float(tfl) if tfl is not None
                    else float(self.plan.device_tflops))
        self._cache_bytes_hint = (
            _cache_bytes_estimate(loaders.get("train"), example_batch)
            if stage == "fit" and self.cache_train_dataset else 0)
        # "compile" covers trace construction + jit setup; the first
        # "step" span additionally contains the XLA compile of the train
        # program (jax compiles lazily at first dispatch)
        t_compile = time.monotonic()
        with span("compile"):
            self._build_compiled(module, example_batch, strategy)
        _metrics.on_compile()
        if self._goodput_ledger is not None:
            self._goodput_ledger.add("compile",
                                     time.monotonic() - t_compile)
            self._goodput_ledger.set_flops_per_step(
                self._price_flops_per_step(module))
        if _metrics.metrics_enabled():
            # the gradient/param collectives XLA compiles into the step
            # from the strategy's shardings have no host call site; the
            # strategy declares their per-step byte cost so the metrics
            # plane can charge it per executed step.  An active comm
            # plane shrinks the declared bytes to the compressed wire
            # payload, so rlt_collective_* and bench JSON see the savings
            from ray_lightning_tpu.comm.audit import declared_dcn_bytes
            op_bytes = strategy.step_collective_bytes(
                self._mesh, self._abstract_state, comm=self._grad_sync)
            if self._redundancy is not None:
                # the parity tick's amortized wire cost is a declared
                # per-step collective like the gradient traffic — the
                # redundancy overhead is a scrapeable series, not a
                # hidden tax (elastic/redundancy.py)
                from ray_lightning_tpu.elastic.redundancy import (
                    declared_parity_bytes)
                pb = declared_parity_bytes(
                    self._abstract_state.opt_state,
                    self._state_shardings.opt_state,
                    self.elastic.redundancy,
                    self.elastic.redundancy_every_n_steps)
                if pb:
                    op_bytes = {**op_bytes, "parity_update": pb}
            _metrics.note_step_collectives(
                op_bytes,
                dcn_bytes=declared_dcn_bytes(op_bytes,
                                             jax.process_count() > 1))
        t_init = time.monotonic()
        with span("init"):
            self._init_state(module, example_batch, strategy, ckpt_path)
        if self._goodput_ledger is not None:
            self._goodput_ledger.add("init", time.monotonic() - t_init)

        for cb in self.callbacks:
            cb.setup(self, module, stage)
        try:
            if stage == "fit":
                result = self._fit_loop(module, loaders)
            elif stage in ("validate", "test"):
                result = self._run_eval_stage(module, stage, loaders)
            else:
                result = self._predict_loop(module, loaders)
        except BaseException as e:
            for cb in self.callbacks:
                cb.on_exception(self, module, e)
            raise
        finally:
            set_current_mesh(None)
            for cb in self.callbacks:
                cb.teardown(self, module, stage)
            self._close_goodput_ledger()
        return result

    def _close_goodput_ledger(self) -> None:
        """Finalize this stage's goodput ledger: fold the snapshotter's
        off-loop costs in, attach the latest measured anatomy window as
        the useful bucket's sub-split, close the partition against the
        stage wall, and keep the doc (``_goodput_local``) for the rank-0
        result package + the telemetry sink."""
        ledger = getattr(self, "_goodput_ledger", None)
        if ledger is None:
            return
        self._goodput_ledger = None
        if self._snapshotter is not None:
            stats = self._snapshotter.stats
            ledger.add("snapshot", stats.get("save_seconds", 0.0))
            ledger.add("snapshot_stall", stats.get("stall_seconds", 0.0))
            try:
                from ray_lightning_tpu import telemetry as _telemetry
                agg = _telemetry.get_active()
                if agg is not None and stats.get("snapshots"):
                    # incident-plane correlation events: a snapshot (and
                    # any stall it exposed on the step path) is a named
                    # cause candidate, not background noise
                    agg.note_event("snapshot",
                                   saves=int(stats.get("snapshots", 0)),
                                   seconds=round(
                                       stats.get("save_seconds", 0.0), 6))
                    if stats.get("stall_seconds", 0.0) > 0:
                        agg.note_event("snapshot_stall",
                                       seconds=round(
                                           stats["stall_seconds"], 6))
            except Exception:
                pass
        try:
            from ray_lightning_tpu.telemetry import anatomy as _anatomy
            ctl = _anatomy.get_anatomy_controller()
            if ctl is not None and ctl.last:
                ledger.set_anatomy(ctl.last)
        except Exception:   # anatomy must never break the partition
            pass
        from ray_lightning_tpu.telemetry import goodput as _goodput
        self._goodput_local = _goodput.finish_run()

    def _attach_observed_divergence(self, agg) -> None:
        """Close the planner's loop against the run's measurements:
        when a plan report exists and anatomy windows landed, attach
        the MEASURED per-step wall + exposed comm next to the winner's
        modeled ``comm_seconds`` (the ``observed`` field of
        plan/report.py) so model-vs-reality divergence is a number.
        No re-ranking happens here — the next plan still starts from
        the model; this only makes the model's error visible."""
        report = getattr(self, "_plan_report", None)
        if not report:
            return
        try:
            anatomy = agg.anatomy_stats()
        except Exception:
            return
        per_rank = (anatomy or {}).get("per_rank") or {}
        walls = [a.get("wall_s", 0.0) for a in per_rank.values()]
        exposed = [a.get("exposed_s", 0.0) for a in per_rank.values()]
        if not walls or max(walls) <= 0:
            return
        winner = next((e for e in report.get("candidates", ())
                       if e.get("status") == "winner"), None)
        modeled_comm = ((winner or {}).get("modeled") or {}) \
            .get("comm_seconds")
        # fleet step = the slowest rank's measured wall (SPMD lockstep)
        step_wall = max(walls)
        exposed_comm = max(exposed)
        observed = {
            "step_wall_s": round(step_wall, 6),
            "exposed_comm_s": round(exposed_comm, 6),
            "modeled_comm_s": (round(float(modeled_comm), 6)
                               if modeled_comm is not None else None),
            "ratio": (round(exposed_comm / float(modeled_comm), 3)
                      if modeled_comm else None),
        }
        report["observed"] = observed
        try:
            # live calibration (ROADMAP 5(a) leg): persist the measured
            # vs modeled comm ratio so the NEXT plan under
            # RLT_PLAN_CALIBRATE=live ranks with corrected bandwidths
            from ray_lightning_tpu.comm.calibrate import (
                save_live_calibration)
            save_live_calibration(step_wall, exposed_comm, modeled_comm)
        except Exception:
            pass
        try:
            # divergence past the band = the plan's model no longer
            # describes this run: a replan-recommended incident verdict
            # (telemetry/incident.py note_divergence)
            agg.incidents.note_divergence(observed)
        except Exception:
            pass

    # -- data -----------------------------------------------------------

    def _get_loader(self, name: str):
        src = None
        if self.datamodule is not None:
            src = getattr(self.datamodule, f"{name}_dataloader")()
        if src is None:
            src = getattr(self.lightning_module, f"{name}_dataloader")()
        if src is not None:
            src = self._elastic_rescale_loader(src, name)
        if src is not None and self.use_distributed_sampler \
                and self.world_size > 1 and hasattr(src, "shard"):
            src = src.shard(self.world_size, self.global_rank)
        return src

    def _build_redundancy(self):
        """Worker-side parity manager for this stage, or None when the
        topology cannot support it (single process, no peer-name map —
        a local in-process fit has no worker↔worker channel)."""
        from ray_lightning_tpu.elastic import redundancy as _red
        world = self.world_size
        if world < 2:
            _log.debug("elastic redundancy: single-process run, "
                       "parity disabled (snapshot replay only)")
            return None
        names = os.environ.get("RLT_PEER_NAMES", "").strip()
        peer_names = [n for n in names.split(",") if n]
        if len(peer_names) != world:
            _log.warning(
                "elastic redundancy: no rank→actor-name map for %d "
                "ranks (RLT_PEER_NAMES=%r); parity disabled, snapshot "
                "replay only", world, names)
            return None
        transport = _red.PeerParityTransport(
            peer_names, self.global_rank, _red.parity_timeout_s())
        _log.info(
            "elastic redundancy: parity over %d neighbor shard(s) "
            "every %d step(s) on %d ranks", self.elastic.redundancy,
            self.elastic.redundancy_every_n_steps, world)
        return _red.RedundancyManager(self, self.elastic,
                                      self.global_rank, world, transport)

    def _elastic_rescale_loader(self, src, name: str):
        """After a shrink-to-continue restart the fleet has fewer
        workers than the run started with; preserve the GLOBAL batch
        (world × per-worker batch — the quantity the optimization
        trajectory depends on) by scaling each survivor's loader batch
        by ``initial_workers / current_workers``.  This is the batch
        half of the resume-with-fewer-workers redistribution the
        checkpoint re-shard does for state (:meth:`_restore_sharded`).
        No-op outside an elastic restart."""
        es = getattr(self, "_elastic_state", None)
        if not es or not self.elastic.enabled \
                or not self.elastic.preserve_global_batch:
            return src
        initial = es.get("initial_workers") or 0
        current = self.world_size
        if initial <= 0 or initial == current:
            return src
        bs = getattr(src, "batch_size", None)
        if bs is None or not hasattr(src, "shard"):
            if not self._warned_rescale:
                self._warned_rescale = True
                _log.warning(
                    "elastic: cannot rescale %s loader %r (no "
                    "batch_size); global batch shrinks %d -> %d "
                    "workers' worth", name, type(src).__name__,
                    initial, current)
            return src
        total = int(bs) * initial
        if total % current:
            if not self._warned_rescale:
                self._warned_rescale = True
                _log.warning(
                    "elastic: global batch %d does not divide across "
                    "%d surviving workers; keeping per-worker batch "
                    "%d", total, current, bs)
            return src
        import copy
        clone = copy.copy(src)
        clone.batch_size = total // current
        _log.info(
            "elastic: %s loader batch %d -> %d on each of %d "
            "survivors (global batch %d preserved from the %d-worker "
            "topology)", name, bs, clone.batch_size, current, total,
            initial)
        return clone

    def _build_loaders(self, stage: str) -> dict:
        if stage == "fit":
            return {"train": self._get_loader("train"),
                    "val": self._get_loader("val")}
        if stage == "validate":
            return {"val": self._get_loader("val")}
        if stage == "test":
            return {"test": self._get_loader("test")}
        return {"predict": self._get_loader("predict")}

    # -- auto-parallelism (plan/) ----------------------------------------

    def _resolve_auto_strategy(self, module, example_batch, batch_hint,
                               auto, stage: str):
        """Run the planner and apply its winning plan: the concrete
        strategy is returned; the comm policy, donation decision and
        microbatch land on the trainer directly (they are trainer
        concerns the strategy object cannot carry).  The full
        :class:`PlanReport` dict lands on ``_plan_report`` and the
        ``rlt_plan_*`` gauges.  Planning scores the TRAIN step, so
        eval/predict-only stages fall back to DDP with a log line
        instead of paying candidate compiles they would never use."""
        from ray_lightning_tpu.comm import CommPolicy
        if stage != "fit":
            _log.info("strategy='auto' plans the train step; %s stage "
                      "falls back to ddp", stage)
            return resolve_strategy("ddp")
        from ray_lightning_tpu.plan import Planner
        cfg = auto.plan if getattr(auto, "plan", None) is not None \
            else self.plan
        planner = Planner(cfg)
        # a user-set accumulate_grad_batches pins the microbatch
        # dimension; the default (1) lets the config's options explore
        mb = (self.accumulate_grad_batches,) \
            if self.accumulate_grad_batches > 1 else None
        with span("plan"):
            report = planner.plan(
                module, self._host_cast(example_batch),
                devices=self.plugin.local_devices(),
                batch_hint=batch_hint,
                base_comm_policy=self.comm_policy,
                microbatch_options=mb,
                tx_factory=lambda gs: self._configure_tx(module, gs))
        self._plan_report = d = report.to_dict()
        winner = report.winner_candidate
        if winner.comm:
            self.comm_policy = report.winner_policy
        else:
            self.comm_policy = CommPolicy()
        self._plan_donate = bool(winner.donate)
        self.accumulate_grad_batches = int(winner.microbatch)
        remat_pick = getattr(winner, "remat", "")
        if remat_pick:
            # apply the winning remat policy to the REAL module (the
            # planner verified candidates on copy.copy clones, so the
            # user's module still carries its default); resets the
            # materialized model so _build_compiled traces the pick
            spec = module.configure_remat()
            if spec is not None and remat_pick != spec.default:
                spec.apply(remat_pick)
                module.setup_model()   # apply() dropped the stale wrap
                _log.info("plan: remat policy %r applied (module "
                          "default was %r)", remat_pick, spec.default)
        _log.info("plan: %s", report.summary())
        try:
            from ray_lightning_tpu import telemetry as _telemetry
            agg = _telemetry.get_active()
            if agg is not None:
                # incident-plane correlation event: a (re-)plan is a
                # step-time discontinuity with a name
                agg.note_event("plan", winner=d.get("winner"),
                               seconds=round(d.get("plan_seconds", 0.0),
                                             6))
        except Exception:
            pass
        reg = _metrics.get_registry()
        if reg is not None:
            reg.gauge("rlt_plan_candidates_total").set(d["enumerated"])
            reg.gauge("rlt_plan_pruned_total").set(d["pruned"])
            reg.gauge("rlt_plan_rejected_total").set(d["rejected"])
            reg.gauge("rlt_plan_compiled_total").set(d["compiled"])
            reg.gauge("rlt_plan_seconds").set(round(d["plan_seconds"], 6))
        return winner.build_strategy()

    # -- compilation -----------------------------------------------------

    def _configure_tx(self, module, grad_sync=None):
        tx = module.configure_optimizers()
        if isinstance(tx, dict):
            tx = tx["optimizer"]
        if self.gradient_clip_val:
            tx = optax.chain(
                optax.clip_by_global_norm(self.gradient_clip_val), tx)
        if grad_sync is not None:
            # outermost wrap: the optimizer state becomes a CommState
            # carrying the error-feedback residual (comm/collectives.py)
            tx = grad_sync.wrap_tx(tx)
        return tx

    # HBM per chip for device kinds whose runtime reports no
    # memory_stats (the axon tunnel returns None); donation falls back
    # to ON for unknown kinds, so a missing entry is safe, not wrong
    _HBM_BY_KIND = {
        "TPU v4": 32 << 30,
        "TPU v5 lite": 16 << 30,
        "TPU v5e": 16 << 30,
        "TPU v5": 95 << 30,      # v5p
        "TPU v5p": 95 << 30,
        "TPU v6 lite": 32 << 30,
        "TPU v6e": 32 << 30,
    }

    def _device_memory_budget(self) -> "int | None":
        dev = self._mesh.devices.flat[0]
        try:
            stats = dev.memory_stats()
            if stats and "bytes_limit" in stats:
                return int(stats["bytes_limit"])
        except Exception:
            pass
        if getattr(dev, "platform", None) == "tpu":
            return self._HBM_BY_KIND.get(getattr(dev, "device_kind", ""))
        return None

    def _should_donate(self, abstract, shardings) -> bool:
        """Donate the TrainState into the step only when memory needs it.

        Donation (in-place state update) halves peak state residency —
        what lets the large configs fit their budgets — but it
        CONSTRAINS XLA's scheduling: the round-5 A/B measured the
        identical gpt2-small program at 51.08 ms/step donated vs
        49.35 ms un-donated on v5e, and BERT at 91.59 vs 90.24.  The
        win does NOT extend up the size axis: gpt2-moe-8e (state
        ~3.6 GB, ~22% of v5e HBM) measured 81.85 un-donated vs 80.08
        donated — so auto skips donation only for SMALL states (the
        measured win region: state ≤ ~10% of the budget, the
        ``_donation_cutoff`` factors put the v5e cut at ~1.9 GB,
        between BERT's win and MoE's loss), and donates whenever the
        budget is unknown (virtual CPU meshes, profiler-less backends).

        NOTE the relationship to the memory-fit audits
        (tests/test_memory_fit.py): the donated-program audits compile
        with ``donate_argnums=0`` EXPLICITLY and are valid whatever
        this heuristic picks; the SKIP region is audited separately —
        the un-donated 1.3B ZeRO-1 program (the config this heuristic
        actually skips on v4-64, state ~2.85 GB/device at data=64) is
        budget-checked against v4's 32 GB with its extra un-aliased
        state copy accounted
        (test_undonated_zero1_budget_in_v4_skip_region and the direct
        memory_analysis audit test_undonated_zero1_compile_audit, both
        tier-1).  The per-config donation decisions are
        additionally pinned in
        tests/test_trainer_local.py::test_donation_decision_table, so a
        change to either side must show up against that table, not
        silently diverge.  ``RLT_DONATE=1``/``0`` forces either way.
        """
        env = os.environ.get("RLT_DONATE", "").strip()
        if env in ("0", "1"):
            return env == "1"
        if env:
            warnings.warn(
                f"RLT_DONATE={env!r} is neither '0' nor '1'; using the "
                "auto heuristic")
        if self._plan_donate is not None:
            # strategy="auto": the planner already decided donation per
            # candidate (same cutoff logic, budget-checked and — for the
            # top-k — verified against the compiled memory_analysis);
            # RLT_DONATE above still force-overrides either way
            return self._plan_donate
        limit = self._device_memory_budget()
        if limit is None:
            return True
        if self.cache_train_dataset:
            # the device-resident dataset cache shares the budget; debit
            # a conservative (un-sharded) estimate, and donate outright
            # when the cache size cannot be bounded up front
            hint = self._cache_bytes_hint
            if hint is None:
                return True
            limit -= hint
        state_bytes = 0
        leaves = jax.tree_util.tree_leaves(abstract)
        shs = jax.tree_util.tree_leaves(
            shardings, is_leaf=lambda x: hasattr(x, "spec"))
        if len(shs) != len(leaves):
            return True     # unrecognized shardings tree: stay safe
        for aval, sh in zip(leaves, shs):
            shape = sh.shard_shape(aval.shape) \
                if hasattr(sh, "shard_shape") else aval.shape
            state_bytes += int(np.prod(shape, dtype=np.int64)) \
                * aval.dtype.itemsize
        return self._donation_cutoff(state_bytes, limit)

    @staticmethod
    def _donation_cutoff(state_bytes: int, limit: int) -> bool:
        """The auto decision given per-device state bytes and the HBM
        budget: un-donated peak carries old+new state (2x) on top of the
        activations/grads the donated program also needs; the 0.3
        ceiling both keeps the skip far from any OOM edge and encodes
        the MEASURED win boundary (small states win, ~22%-of-HBM states
        lose — see the _should_donate docstring).  Pinned per config in
        tests/test_trainer_local.py::test_donation_decision_table."""
        return not (2.5 * state_bytes < 0.3 * limit)

    def _build_compiled(self, module, example_batch, strategy):
        # comm plane: resolve the policy against this strategy/mesh —
        # None (the overwhelmingly common case) keeps every jit below
        # identical to a policy-less build
        self._grad_sync = strategy.grad_transform(self._mesh,
                                                  self.comm_policy)
        if self._grad_sync is not None:
            _log.info("comm plane active: compressed gradient "
                      "collectives %s (error_feedback=%s, "
                      "param_gather=%s, bucket_bytes=%d)",
                      self._grad_sync.describe(),
                      self._grad_sync.error_feedback,
                      self.comm_policy.param_gather,
                      self.comm_policy.bucket_bytes)
        self._tx = self._configure_tx(module, self._grad_sync)
        self._init_fn = build_init_fn(module, self._tx)
        rng = jax.random.PRNGKey(
            int(os.environ.get("RLT_GLOBAL_SEED", "0")) if self.seed is None
            else self.seed)
        self._init_rng = rng
        abstract = jax.eval_shape(self._init_fn, rng, example_batch)
        self._abstract_state = abstract
        shardings = strategy.state_shardings(self._mesh, abstract)
        if self._grad_sync is not None:
            # the error-feedback residual's [world, ...] stacked dim
            # shards on the compressed axes, not per the strategy's
            # generic opt_spec walk
            shardings = shardings.replace(
                opt_state=self._grad_sync.fix_opt_shardings(
                    shardings.opt_state, abstract.opt_state))
        self._state_shardings = shardings
        # Batch placement rides the jit call (in_shardings) instead of an
        # explicit per-step device_put: a numpy batch is transferred and
        # sharded as part of async dispatch.  (Per-array device_put with a
        # NamedSharding is a blocking slow-path transfer per leaf —
        # measured 30x slower on remote TPU tunnels — so on single-device
        # meshes the batch stays unconstrained and takes the fast default
        # transfer path.)
        donate = self._should_donate(abstract, shardings)
        dkw = {"donate_argnums": 0} if donate else {}
        jit_kwargs = dict(out_shardings=(shardings, None), **dkw)
        batch_sh = None
        if self._mesh.devices.size > 1:
            batch_sh = strategy.batch_shardings(self._mesh, example_batch)
            jit_kwargs["in_shardings"] = (shardings, batch_sh)
        step_fn = build_train_step(module, self._tx,
                                   self.accumulate_grad_batches,
                                   grad_sync=self._grad_sync)
        #: un-jitted step for the goodput plane's default FLOP pricing
        #: (tracing only — never dispatched)
        self._pricing_step_fn = step_fn
        self._train_step = jax.jit(step_fn, **jit_kwargs)
        self._multi_train_step = None
        self._stacked_batch_shardings = None
        self._cache_source = None
        self._cache_disabled = False
        self._cached_multi_step = None
        self._cached_single_step = None
        want_stacked = self.steps_per_execution > 1 or self.cache_train_dataset
        if want_stacked and batch_sh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            self._stacked_batch_shardings = jax.tree_util.tree_map(
                lambda s: NamedSharding(
                    self._mesh,
                    PartitionSpec(*((None,) + tuple(s.spec)))),
                batch_sh)
        if self.steps_per_execution > 1:
            def multi_step(state, batches):
                # k steps as one XLA program; metrics stack to [k, ...]
                return jax.lax.scan(step_fn, state, batches)

            mkw = dict(out_shardings=(shardings, None), **dkw)
            if self._stacked_batch_shardings is not None:
                mkw["in_shardings"] = (shardings,
                                       self._stacked_batch_shardings)
            self._multi_train_step = jax.jit(multi_step, **mkw)
        if self.cache_train_dataset:
            # multi-process included: the cache is a global array (one
            # shard per host's devices) and these programs are ordinary
            # SPMD — every process dispatches them in lockstep exactly
            # like the streamed train step (core/loop_engine.py
            # CachedSource.build for the global-assembly details)
            def gather(dataset, i):
                return jax.tree_util.tree_map(
                    lambda d: jax.lax.dynamic_index_in_dim(
                        d, i, 0, keepdims=False), dataset)

            def cached_multi(state, dataset, idxs):
                return jax.lax.scan(
                    lambda s, i: step_fn(s, gather(dataset, i)),
                    state, idxs)

            def cached_single(state, dataset, i):
                return step_fn(state, gather(dataset, i))

            ckw = dict(out_shardings=(shardings, None), **dkw)
            if self._stacked_batch_shardings is not None:
                ckw["in_shardings"] = (
                    shardings, self._stacked_batch_shardings, None)
            self._cached_multi_step = jax.jit(cached_multi, **ckw)
            self._cached_single_step = jax.jit(cached_single, **ckw)
        self._eval_steps = {
            s: _ShardedStepCache(build_eval_step(module, s), self, strategy)
            for s in ("validate", "test")}
        self._predict_step = _ShardedStepCache(build_predict_step(module),
                                               self, strategy)
        self._submit_precompiles(example_batch)

    def _price_flops_per_step(self, module) -> "Optional[float]":
        """FLOPs one optimizer step executes, for measured MFU: the
        module's ``flops_per_step()`` hook when it answers, else the
        default pricing — count every ``dot_general`` in the train-step
        jaxpr (forward + backward + update) over the abstract state and
        global abstract batch, the same dot-counting machinery the
        remat planner prices policies with (core/remat.py).  None when
        neither source can answer; MFU is then simply absent — never
        fabricated."""
        try:
            flops = module.flops_per_step()
        except Exception:
            _log.debug("goodput: flops_per_step() hook raised; falling "
                       "back to jaxpr pricing", exc_info=True)
            flops = None
        if flops is not None:
            return float(flops)
        step_fn = getattr(self, "_pricing_step_fn", None)
        abstract_batch = getattr(self, "_abstract_batch", None)
        if step_fn is None or abstract_batch is None:
            return None
        try:
            from ray_lightning_tpu.core.remat import step_dot_flops
            return float(step_dot_flops(step_fn, self._abstract_state,
                                        abstract_batch))
        except Exception:
            _log.debug("goodput: default train-step FLOP pricing "
                       "failed; MFU unavailable", exc_info=True)
            return None

    def _submit_precompiles(self, example_batch) -> None:
        """AOT-compile the step programs in the background (compile/):
        their input avals are fully known here — abstract state from
        ``eval_shape``, abstract batch from the peeked example — so XLA
        compilation starts NOW and hides under state init, the
        rendezvous, the sanity check and the dataset upload instead of
        serializing at first dispatch.  The compiled artifact reaches
        dispatch through the persistent cache (the background compile
        writes the entry; the first dispatch's compile collapses to a
        disk retrieval), which is why the precompiler only engages when
        the cache is active (compile/aot.py).  The engine's
        ``barrier()`` before the first train dispatch keeps a lazy
        compile from racing a background one; everything here is
        best-effort (a mispredicted aval logs and falls back to lazy)."""
        self._precompiler = AotPrecompiler.resolve()
        ab = global_batch_abstract(self._host_cast(example_batch),
                                   jax.process_count())
        self._abstract_batch = ab
        if self._stage != "fit":
            # eval/predict stages never dispatch the train programs;
            # compiling them in the background would be pure waste (the
            # lazy _ShardedStepCache path still benefits from the
            # persistent cache across runs)
            return
        self._precompiler.submit("train_step", self._train_step,
                                 (self._abstract_state, ab))
        if self._multi_train_step is not None:
            self._precompiler.submit(
                "multi_step", self._multi_train_step,
                (self._abstract_state,
                 stack_abstract(ab, self.steps_per_execution)))
        # cached-dataset programs submit from CachedSource.build once the
        # repacked shape is known (core/loop_engine.py).  The validate
        # step precompiles only when no sanity check will compile it on
        # the main thread first anyway — and against the TRAIN batch
        # structure, the common case (same dataset shapes); a divergent
        # val structure just wastes one background compile.
        if self.num_sanity_val_steps == 0:
            try:
                ev = self._eval_steps["validate"].jitted_for(ab)
                self._precompiler.submit("eval_step", ev,
                                         (self._abstract_state, ab))
            except Exception:       # noqa: BLE001 - overlap only
                _log.debug("eval-step precompile skipped", exc_info=True)

    def _put_batch(self, batch, strategy, stacked: bool = False):
        """Host numpy batch → step input.  Multi-process: each process
        contributes its local shard (``make_array_from_process_local_data``)
        to a global array — the TPU-native equivalent of DistributedSampler
        feeding per-rank DDP replicas.  Single-process: numpy passes
        straight into the jitted step, whose ``in_shardings`` shard it
        during dispatch.

        ``Trainer(precision="bf16")`` casts floating batch leaves to
        bfloat16 here (halving host→device transfer); parameter/compute
        dtypes belong to the model config (e.g. ``GPTConfig.dtype``) —
        on TPU there is no loss-scaling AMP machinery to port, bf16 runs
        natively on the MXU (reference precision flow: PL AMP +
        ShardedGradScaler, ray_ddp_sharded.py:26-29).
        """
        batch = self._host_cast(batch)
        if jax.process_count() > 1:
            shardings = (self._stacked_batch_shardings if stacked
                         else strategy.batch_shardings(self._mesh, batch))
            return jax.tree_util.tree_map(
                lambda x, s: jax.make_array_from_process_local_data(s, x),
                batch, shardings)
        return batch

    def _host_cast(self, batch):
        """numpy-ify a host batch, casting floats to bf16 under
        ``precision="bf16"`` (halves host→device transfer)."""
        cast_bf16 = self.precision in _BF16_PRECISIONS

        def to_host(x):
            a = np.asarray(x)
            if cast_bf16 and np.issubdtype(a.dtype, np.floating):
                a = a.astype(jnp.bfloat16)
            return a

        return jax.tree_util.tree_map(to_host, batch)

    def _batch_ok(self, batch, strategy) -> bool:
        """Leading dim must divide over data shards (XLA static shapes)."""
        dp = strategy.data_parallel_size(self._mesh) // max(
            1, jax.process_count())
        leaves = jax.tree_util.tree_leaves(batch)
        sizes = {l.shape[0] for l in leaves if getattr(l, "ndim", 0) > 0}
        ok = all(s % max(1, dp) == 0 for s in sizes)
        if not ok and not self._warned_skip:
            _log.warning(
                "Skipping batch whose size %s does not divide across %d "
                "data shards; use drop_last or a divisible batch size.",
                sizes, dp)
            self._warned_skip = True
        return ok

    # -- state init / restore -------------------------------------------

    def _init_state(self, module, example_batch, strategy, ckpt_path):
        gbatch = self._put_batch(example_batch, strategy)
        init_jit = jax.jit(self._init_fn,
                           out_shardings=self._state_shardings)
        self.state = init_jit(self._init_rng, gbatch)

        trained = getattr(module, "_trained_variables", None)
        recovery = getattr(self, "_elastic_recovery", None)
        if recovery:
            # zero-replay path (elastic/redundancy.py): the driver
            # reconstructed the dead rank's shard from parity escrows;
            # restore the in-memory package at its escrowed step — the
            # snapshot directory (and ckpt_path) is deliberately NOT
            # read, which the rlt_snapshot_restore_total counter proves
            from ray_lightning_tpu.elastic.redundancy import (
                apply_recovery)
            apply_recovery(self, recovery, module)
            self._elastic_recovery = None   # one-shot, worker copy
        elif ckpt_path:
            self._restore_checkpoint(ckpt_path, module)
        elif trained is not None:
            # Reuse weights from a previous fit with this module (the
            # reference keeps trained weights on the model object after
            # post_dispatch loads them, ray_ddp.py:375-377).
            restored = serialization.from_state_dict(
                {"params": fetch_tree(self.state.params),
                 "model_state": fetch_tree(self.state.model_state)},
                trained)
            self.state = self.state.replace(
                params=jax.device_put(restored["params"],
                                      self._state_shardings.params),
                model_state=jax.device_put(
                    restored["model_state"],
                    self._state_shardings.model_state))

    # -- fit loop --------------------------------------------------------

    def _fit_loop(self, module, loaders):
        train_loader, val_loader = loaders["train"], loaders.get("val")
        strategy = self.plugin.strategy
        self.num_val_batches = self._loader_len(val_loader,
                                                self.limit_val_batches)

        for cb in self.callbacks:
            cb.on_fit_start(self, module)
        module.on_fit_start()

        if val_loader is not None and self.num_sanity_val_steps > 0 \
                and self.num_val_batches > 0:
            self._sanity_check(module, val_loader)

        for cb in self.callbacks:
            cb.on_train_start(self, module)
        module.on_train_start()

        start_epoch = self.current_epoch
        epoch = start_epoch
        ran_epoch = False
        try:
            for epoch in range(start_epoch, self.max_epochs or 10**9):
                if self.should_stop or self._max_steps_reached():
                    break  # e.g. resumed from a checkpoint at max_steps
                ran_epoch = True
                self.current_epoch = epoch
                if hasattr(train_loader, "set_epoch"):
                    train_loader.set_epoch(epoch)
                self._epoch_metric_acc = {}
                for cb in self.callbacks:
                    cb.on_train_epoch_start(self, module)
                module.on_train_epoch_start()

                self._train_epoch(module, train_loader, val_loader, strategy)

                self._flush_epoch_metrics()
                module.on_train_epoch_end()
                for cb in self.callbacks:
                    cb.on_train_epoch_end(self, module)

                if val_loader is not None and self.num_val_batches > 0 \
                        and (epoch + 1) % self.check_val_every_n_epoch == 0:
                    self._eval_loop(module, "validate", val_loader,
                                    self.limit_val_batches)
                if self.should_stop or self._max_steps_reached():
                    break
        finally:
            if ran_epoch:
                self.current_epoch = min(
                    epoch + 1, self.max_epochs or epoch + 1) \
                    if not self.should_stop else epoch
            # else: zero epochs ran (resumed at max_steps) — the restored
            # epoch counter must not drift upward per save/resume cycle
            try:
                module.on_train_end()
                for cb in self.callbacks:
                    cb.on_train_end(self, module)
                module.on_fit_end()
                for cb in self.callbacks:
                    cb.on_fit_end(self, module)
            finally:
                # in-flight async sharded saves must become durable (and
                # their orbax worker threads released) unconditionally —
                # even when the fit is unwinding on an exception or a
                # user hook raises during the unwind; _finalize_fit only
                # runs on the happy path
                self._close_sharded_checkpointers()
        return self._finalize_fit(module)

    def _max_steps_reached(self) -> bool:
        return self.max_steps is not None and self.max_steps >= 0 \
            and self.global_step >= self.max_steps

    def _allowed_chunk(self) -> int:
        """How many steps the next chunk may run without crossing a
        host-decision boundary (max_steps, val_check_interval).  Shared
        by the chunked and cached epoch loops."""
        allowed = self.steps_per_execution
        if self.max_steps is not None and self.max_steps >= 0:
            allowed = min(allowed, self.max_steps - self.global_step)
        if self.val_check_interval:
            allowed = min(
                allowed,
                self.val_check_interval
                - self.global_step % self.val_check_interval)
        return allowed

    def _publish_if_crossed(self, before: int, last_metrics) -> None:
        """Publish when the chunk crossed a log_every_n_steps boundary
        (``last_metrics`` = the chunk's final-step scalars)."""
        if before // self.log_every_n_steps \
                != self.global_step // self.log_every_n_steps:
            self._publish_metrics(last_metrics)

    def _train_source(self, train_loader, strategy):
        """Pick this epoch's batch source (core/loop_engine.py): the
        device-resident cache when enabled and buildable, the streamed
        loader otherwise.  The cache is built once per fit and refreshed
        per epoch from the loader's own index order."""
        from ray_lightning_tpu.core.loop_engine import (
            CachedSource, StreamSource)
        if self._cached_single_step is not None \
                and not self._cache_disabled:
            if self._cache_source is None \
                    and CachedSource.usable(self, train_loader):
                src = CachedSource(self, train_loader, strategy)
                if src.build():
                    self._cache_source = src
            if self._cache_source is None:
                # unusable with THIS loader: remember, so the build is
                # not re-attempted (and the loader not re-read) per epoch
                self._cache_disabled = True
            else:
                return self._cache_source.new_epoch()
        return StreamSource(self, train_loader, strategy)

    def _train_epoch(self, module, train_loader, val_loader, strategy):
        """THE training loop — one engine for every dispatch shape.

        The source decides how batches reach the device (streamed host
        batches, k-step stacked chunks, device-resident gathers); this
        loop owns the semantics exactly once: stop conditions, chunk
        boundaries (``_allowed_chunk`` keeps a chunk from crossing
        max_steps / val_check_interval), ``limit_train_batches``
        position counting (inside the sources' ``take``), callback
        cadence (per batch when dispatching singly, per chunk when k
        ride one dispatch) and the val-interval check after every
        dispatch.  Replaces the round-2 trio of divergent loops.
        """
        source = self._train_source(train_loader, strategy)
        if self._precompiler is not None:
            # close the overlap window: everything submitted (train /
            # chunk / cached-step programs) must land in the executable
            # caches before the first dispatch, or a lazy compile on
            # this thread would race the background one for the same
            # program.  Instant from epoch 2 on (nothing pending).
            self._precompiler.barrier()
        k = self.steps_per_execution
        while not (self.should_stop or self._max_steps_reached()):
            allowed = self._allowed_chunk()
            if allowed <= 0:
                break
            pending = source.take(allowed)
            if not pending:
                if source.exhausted:
                    break
                continue
            if len(pending) == k and k > 1 and source.chunkable(pending):
                self._engine_chunk(module, source, pending)
                self._maybe_interval_val(module, val_loader)
            else:
                for item in pending:
                    self._engine_one(module, source, item)
                    self._maybe_interval_val(module, val_loader)
                    if self.should_stop or self._max_steps_reached():
                        break

    def _maybe_interval_val(self, module, val_loader) -> None:
        if self.val_check_interval \
                and self.global_step % self.val_check_interval == 0 \
                and val_loader is not None and self.num_val_batches > 0:
            self._eval_loop(module, "validate", val_loader,
                            self.limit_val_batches)

    def _batch_hook_plan(self) -> tuple:
        """(invoke, materialize): does any callback override a per-batch
        hook, and does any overriding one actually read ``batch``
        (``Callback.needs_batch``)?  When nothing overrides, the engine
        skips the hook calls; when overriders all declare
        ``needs_batch = False`` at or below the class that defines the
        overriding hook, they are invoked with ``batch=None`` —
        either way cached (especially shuffled) epochs never pay host
        collation for arguments nobody reads (the whole point of the
        cached path is removing per-step host work).  Detection goes
        through ``__func__`` so instance-assigned hooks
        (``cb.on_train_batch_end = fn``) count as overrides too.
        Recomputed per engine call (a few attribute reads on a short
        list) so callbacks added or hook-assigned MID-epoch are honored
        exactly as they were before the skip existed.
        """
        def overrides(cb, name):
            fn = getattr(cb, name, None)
            return getattr(fn, "__func__", fn) is not getattr(Callback, name)

        def hook_needs_batch(cb, name):
            # ``needs_batch`` counts only when declared at or below (as
            # derived as) the definition of the overriding hook.  A user
            # subclass of a needs_batch=False callback that overrides a
            # batch hook without restating the flag gets the
            # conservative default (True) — its new hook body may well
            # read the batch the base class promised to ignore.
            # getattr, not vars(): __slots__ callbacks have no __dict__
            inst = getattr(cb, "__dict__", {})
            if "needs_batch" in inst:
                return inst["needs_batch"]         # instance: most derived
            if name in inst:                       # instance-assigned hook
                return True                        # outranks any class flag
            mro = type(cb).__mro__
            hook_at = next(
                (i for i, k in enumerate(mro) if name in vars(k)), len(mro))
            for k in mro[:hook_at + 1]:
                if "needs_batch" in vars(k):
                    return vars(k)["needs_batch"]
            return True

        invoke = materialize = False
        for cb in self.callbacks:
            for name in ("on_train_batch_start", "on_train_batch_end"):
                if overrides(cb, name):
                    invoke = True
                    if hook_needs_batch(cb, name):
                        materialize = True
        return invoke, materialize

    def _engine_one(self, module, source, item) -> None:
        invoke, want_batch = self._batch_hook_plan()
        if invoke:
            batch = item.batch() if want_batch else None
            for cb in self.callbacks:
                cb.on_train_batch_start(self, module, batch, item.batch_idx)
        t0 = time.monotonic()
        with span("step", step=self.global_step):
            metrics = source.run_one(self, item)
        self.global_step += 1
        step_s = time.monotonic() - t0
        _metrics.on_step(step_s, step=self.global_step)
        if self._goodput_ledger is not None:
            self._goodput_ledger.note_step(step_s)
        if self._redundancy is not None:
            # parity BEFORE the snapshot: a rank that dies inside the
            # save (snapkill) has already escrowed this step
            self._redundancy.maybe_tick()
        if self._snapshotter is not None:
            self._snapshotter.maybe_snapshot()
        self._note_first_step(metrics)
        self._accumulate_metrics(metrics)
        if self.global_step % self.log_every_n_steps == 0:
            self._publish_metrics(metrics)
        if invoke:
            for cb in self.callbacks:
                cb.on_train_batch_end(self, module, metrics, batch,
                                      item.batch_idx)

    def _engine_chunk(self, module, source, items) -> None:
        """k steps in ONE dispatch; batch-granular callbacks coarsen to
        once per chunk (starts for every batch, one end with the chunk's
        stacked metrics and its last batch)."""
        invoke, want_batch = self._batch_hook_plan()
        if invoke:
            for it in items:
                for cb in self.callbacks:
                    cb.on_train_batch_start(
                        self, module, it.batch() if want_batch else None,
                        it.batch_idx)
        before = self.global_step
        # k steps ride one span; the aggregator normalizes per-step time
        # by the "k" attribute when computing percentiles
        t0 = time.monotonic()
        with span("step", step=before, k=len(items)):
            metrics = source.run_chunk(self, items)
        self.global_step += len(items)
        step_s = time.monotonic() - t0
        _metrics.on_step(step_s, k=len(items), step=self.global_step)
        if self._goodput_ledger is not None:
            self._goodput_ledger.note_step(step_s, k=len(items))
        if self._redundancy is not None:
            # chunked dispatch coarsens the parity cadence to chunk
            # boundaries, exactly like the snapshot cadence below
            self._redundancy.maybe_tick()
        if self._snapshotter is not None:
            # chunked dispatch coarsens the snapshot cadence to chunk
            # boundaries, like the batch-granular callbacks do
            self._snapshotter.maybe_snapshot()
        self._note_first_step(metrics)
        self._accumulate_metrics(metrics)
        self._publish_if_crossed(before, jax.tree_util.tree_map(
            lambda a: a[-1], metrics))
        if invoke:
            for cb in self.callbacks:
                cb.on_train_batch_end(
                    self, module, metrics,
                    items[-1].batch() if want_batch else None,
                    items[-1].batch_idx)

    def _note_first_step(self, metrics) -> None:
        """Record time-to-first-step once per stage: the startup cost
        (compile + init + rendezvous + upload) the compile plane exists
        to shrink.  Blocks on the first step's metrics so the number
        covers execution, not just async dispatch — one sync, once."""
        if self.time_to_first_step is not None or self._stage_t0 is None:
            return
        jax.block_until_ready(metrics)
        self.time_to_first_step = time.monotonic() - self._stage_t0
        compile_cache.note_first_step(self.time_to_first_step)

    # -- metrics ---------------------------------------------------------

    def _accumulate_metrics(self, metrics: dict) -> None:
        for k, v in metrics.items():
            self._epoch_metric_acc.setdefault(k, []).append(v)

    def _publish_metrics(self, metrics: dict) -> None:
        for k, v in metrics.items():
            val = float(jax.device_get(v))
            self.callback_metrics[k] = val
            self.logged_metrics[k] = val
        if self.logger is not None and self.is_global_zero and metrics:
            self.logger.log_metrics(
                {k: self.logged_metrics[k] for k in metrics},
                self.global_step)

    def _flush_epoch_metrics(self) -> None:
        flushed = {}
        for k, vals in self._epoch_metric_acc.items():
            # entries are scalars (per-step) or [k] vectors (per-chunk,
            # steps_per_execution>1); flatten to one per-step series
            arr = np.concatenate([
                np.atleast_1d(np.asarray(v, dtype=np.float64))
                for v in jax.device_get(vals)])
            self.callback_metrics[k] = flushed[k] = float(arr.mean())
            self.logged_metrics[k] = float(arr[-1])
        self._epoch_metric_acc = {}
        if self.logger is not None and self.is_global_zero and flushed:
            # _epoch suffix: step-level rows already carry the bare names
            # at this same step; suffixing disambiguates mean-over-epoch
            # from last-step values (PL's convention)
            self.logger.log_metrics(
                {f"{k}_epoch": v for k, v in flushed.items()},
                self.global_step)

    def log_metric(self, name: str, value) -> None:
        """Record a host-side scalar into ``callback_metrics`` (public
        entry point for callbacks; with distributed plugins rank-0's
        metrics ride the normal result relay back to the driver)."""
        self.callback_metrics[name] = float(np.asarray(value))

    # internal alias kept for module-side logging paths
    _log_host_metric = log_metric

    # -- evaluation ------------------------------------------------------

    def _loader_len(self, loader, limit) -> int:
        if loader is None:
            return 0
        if limit == 0:
            return 0
        try:
            n = len(loader)
        except TypeError:
            n = 10**9
        return min(n, limit) if limit is not None else n

    def _sanity_check(self, module, val_loader):
        self.sanity_checking = True
        for cb in self.callbacks:
            cb.on_sanity_check_start(self, module)
        self._eval_loop(module, "validate", val_loader,
                        self.num_sanity_val_steps)
        for cb in self.callbacks:
            cb.on_sanity_check_end(self, module)
        self.sanity_checking = False

    def _eval_loop(self, module, stage: str, loader, limit) -> dict:
        strategy = self.plugin.strategy
        step = self._eval_steps[stage]
        if stage == "validate":
            for cb in self.callbacks:
                cb.on_validation_start(self, module)
            for cb in self.callbacks:
                cb.on_validation_epoch_start(self, module)
            module.on_validation_epoch_start()
        else:
            for cb in self.callbacks:
                cb.on_test_start(self, module)

        acc: list[tuple[dict, int]] = []
        with span("eval", stage=stage):
            for batch_idx, batch in enumerate(loader):
                if limit is not None and batch_idx >= limit:
                    break
                if not self._batch_ok(batch, strategy):
                    continue
                gbatch = self._put_batch(batch, strategy)
                logged = step(self.state, gbatch)
                leaves = jax.tree_util.tree_leaves(batch)
                bsz = leaves[0].shape[0] if leaves and getattr(
                    leaves[0], "ndim", 0) > 0 else 1
                acc.append((logged, bsz))
                if stage == "validate":
                    for cb in self.callbacks:
                        cb.on_validation_batch_end(self, module, logged,
                                                   batch, batch_idx)

        means: dict[str, float] = {}
        if acc:
            keys = acc[0][0].keys()
            total = sum(b for _, b in acc)
            for k in keys:
                vals = np.asarray(
                    jax.device_get([d[k] for d, _ in acc]), dtype=np.float64)
                weights = np.asarray([b for _, b in acc], dtype=np.float64)
                means[k] = float((vals * weights).sum() / max(total, 1))
        if not self.sanity_checking:
            self.callback_metrics.update(means)
            self.logged_metrics.update(means)
            if self.logger is not None and self.is_global_zero and means:
                self.logger.log_metrics(means, self.global_step)

        if stage == "validate":
            module.on_validation_epoch_end()
            for cb in self.callbacks:
                cb.on_validation_epoch_end(self, module)
            for cb in self.callbacks:
                cb.on_validation_end(self, module)
        else:
            for cb in self.callbacks:
                cb.on_test_epoch_end(self, module)
            for cb in self.callbacks:
                cb.on_test_end(self, module)
        return means

    def _run_eval_stage(self, module, stage, loaders):
        loader = loaders["val" if stage == "validate" else "test"]
        limit = (self.limit_val_batches if stage == "validate"
                 else self.limit_test_batches)
        means = self._eval_loop(module, stage, loader, limit)
        return [means]

    def _predict_loop(self, module, loaders):
        strategy = self.plugin.strategy
        loader = loaders["predict"]
        for cb in self.callbacks:
            cb.on_predict_start(self, module)
        outputs = []
        for batch_idx, batch in enumerate(loader):
            if self.limit_predict_batches is not None \
                    and batch_idx >= self.limit_predict_batches:
                break
            if not self._batch_ok(batch, strategy):
                continue
            gbatch = self._put_batch(batch, strategy)
            out = self._predict_step(self.state, gbatch)
            fetched = fetch_tree(out)   # all-gathered: the GLOBAL batch
            if jax.process_count() > 1:
                fetched = _deinterleave_global_batch(
                    fetched, jax.process_count())
            outputs.append(fetched)
        outputs = self._trim_predict_padding(outputs, loader)
        for cb in self.callbacks:
            cb.on_predict_end(self, module)
        return outputs

    @staticmethod
    def _trim_predict_padding(outputs, loader):
        """Drop trailing wrap-around rows added by strided sharding
        (DataLoader._indices pads so every shard is equal length)."""
        if not outputs or getattr(loader, "num_shards", 1) <= 1:
            return outputs
        ds = getattr(loader, "dataset", None)
        if ds is None or not hasattr(ds, "__len__"):
            return outputs
        def rows(o):
            leaves = [l for l in jax.tree_util.tree_leaves(o)
                      if getattr(l, "ndim", 0) > 0]
            return leaves[0].shape[0] if leaves else None

        counts = [rows(o) for o in outputs]
        if any(c is None for c in counts):
            return outputs   # scalar outputs: nothing to trim
        excess = sum(counts) - len(ds)
        if excess <= 0:
            return outputs
        keep = counts[-1] - excess
        if keep <= 0:
            return outputs[:-1]
        outputs[-1] = jax.tree_util.tree_map(
            lambda a: a[:keep] if getattr(a, "ndim", 0) > 0 else a,
            outputs[-1])
        return outputs

    # -- finalization / results round-trip -------------------------------

    def _finalize_fit(self, module):
        self._flush_epoch_metrics()
        trained = {"params": fetch_tree(self.state.params),
                   "model_state": fetch_tree(self.state.model_state)}
        module._trained_variables = trained
        return {"callback_metrics": dict(self.callback_metrics)}

    # ------------------------------------------------------------------
    # checkpointing
    # ------------------------------------------------------------------

    def dump_checkpoint(self) -> dict:
        """Assemble the full checkpoint dict.  Collective: every process
        participates in the state gather (reference analog:
        ``trainer.checkpoint_connector.dump_checkpoint()``, consumed by the
        Tune checkpoint relay, tune.py:172)."""
        module = self.lightning_module
        ckpt = {
            "epoch": int(self.current_epoch),
            "global_step": int(self.global_step),
            "state": serialization.to_state_dict(fetch_tree(self.state)),
            "hparams": _sanitize(dict(module.hparams)) if module else {},
            "callbacks": {type(cb).__name__: _sanitize(cb.state_dict())
                          for cb in self.callbacks},
            "world_size": int(self.world_size),
            "strategy": self.plugin.strategy.name
            if self.plugin.strategy else "none",
        }
        if module is not None:
            module.on_save_checkpoint(ckpt)
        for cb in self.callbacks:
            cb.on_save_checkpoint(self, module, ckpt)
        return ckpt

    @staticmethod
    def serialize_checkpoint(ckpt: dict) -> bytes:
        return serialization.msgpack_serialize(ckpt)

    def save_checkpoint(self, filepath: str) -> None:
        """Collective: every process participates in the gather; only
        global-zero writes (fsspec so GCS paths work on pods —
        SURVEY.md §7 best-path/locality hazard)."""
        with span("checkpoint", step=self.global_step):
            ckpt = self.dump_checkpoint()
            if self.is_global_zero:
                payload = self.serialize_checkpoint(ckpt)
                dirname = os.path.dirname(filepath)
                if dirname and "://" not in filepath:
                    os.makedirs(dirname, exist_ok=True)
                # atomic-ish local write; remote filesystems via fsspec
                if "://" in filepath:
                    with fsspec.open(filepath, "wb") as f:
                        f.write(payload)
                else:
                    fd, tmp = tempfile.mkstemp(dir=dirname or ".")
                    with os.fdopen(fd, "wb") as f:
                        f.write(payload)
                    os.replace(tmp, filepath)

    def _sharded_checkpointer(self, directory: str,
                              max_to_keep: Optional[int] = None):
        """The live orbax manager for ``directory`` (created on first
        use, cached per fit — the elastic snapshotter probes it for
        backpressure before each save)."""
        from ray_lightning_tpu.utils.checkpoint import ShardedCheckpointer
        ckpt = self._sharded_checkpointers.get(directory)
        if ckpt is not None and ckpt.max_to_keep != max_to_keep:
            # retention changed (or two callbacks share the dirpath with
            # conflicting settings): recreate so the new policy applies
            # instead of silently keeping the first one.
            ckpt.wait()
            ckpt.close()
            ckpt = None
        if ckpt is None:
            ckpt = ShardedCheckpointer(directory, max_to_keep=max_to_keep)
            self._sharded_checkpointers[directory] = ckpt
        return ckpt

    def save_sharded_checkpoint(self, directory: str,
                                step: Optional[int] = None,
                                max_to_keep: Optional[int] = None) -> None:
        """Sharded (orbax) save: every process writes only its own array
        shards, asynchronously — no host gather, unlike
        :meth:`save_checkpoint` (utils/checkpoint.py rationale).  All
        processes must call this (collective)."""
        ckpt = self._sharded_checkpointer(directory, max_to_keep)
        module = self.lightning_module
        meta = {
            "epoch": int(self.current_epoch),
            "global_step": int(self.global_step),
            "world_size": int(self.world_size),
            "strategy": self.plugin.strategy.name
            if self.plugin.strategy else "none",
            "hparams": _sanitize(dict(module.hparams)) if module else {},
            "callbacks": {type(cb).__name__: _sanitize(cb.state_dict())
                          for cb in self.callbacks},
        }
        from ray_lightning_tpu.comm.collectives import CommState
        if isinstance(self.state.opt_state, CommState):
            res = jax.tree_util.tree_leaves(self.state.opt_state.residual)
            if res:
                # the error-feedback residual's stacked world size — the
                # reshard restore re-buckets this axis N→M on a topology
                # change (elastic/reshard.py; recorded for forensics,
                # the restore itself reads orbax metadata)
                meta["comm_world"] = int(res[0].shape[0])
        ckpt.save(step if step is not None else int(self.global_step),
                  self.state, meta)

    def wait_for_checkpoints(self) -> None:
        """Block until in-flight async sharded saves are durable."""
        for ckpt in self._sharded_checkpointers.values():
            ckpt.wait()

    def elastic_stats(self) -> Optional[dict]:
        """Elastic-plane numbers for THIS process: snapshot counters
        (snapshots / skipped / save_seconds / stall_seconds) plus the
        shrink bookkeeping the driver stamped on the trainer.  Rank-0's
        copy rides the worker result package back to the driver, which
        folds it into ``trainer._elastic_report``."""
        out: dict = {}
        if self._snapshotter is not None:
            out.update(self._snapshotter.stats)
        if self._redundancy is not None:
            out.update(self._redundancy.stats)
        if self._snapshot_restores:
            out["snapshot_restores"] = self._snapshot_restores
        if self._elastic_state:
            out.update(self._elastic_state)
        return out or None

    def _close_sharded_checkpointers(self) -> None:
        """Wait + release orbax managers (their async worker threads
        outlive the fit otherwise).  A later save simply re-opens."""
        for ckpt in self._sharded_checkpointers.values():
            try:
                ckpt.wait()
                ckpt.close()
            except Exception:  # closing must never mask fit results
                _log.warning("sharded checkpointer close failed",
                             exc_info=True)
        self._sharded_checkpointers = {}

    @staticmethod
    def load_checkpoint_dict(filepath: str) -> dict:
        with fsspec.open(filepath, "rb") as f:
            return serialization.msgpack_restore(f.read())

    def _restore_checkpoint(self, filepath: str, module) -> None:
        from ray_lightning_tpu.utils.checkpoint import ShardedCheckpointer
        if ShardedCheckpointer.is_sharded_checkpoint(filepath):
            self._restore_sharded(filepath, module)
            return
        ckpt = self.load_checkpoint_dict(filepath)
        # Re-shard on load: checkpoints always hold the full (gathered)
        # state, so resuming with a different world size / strategy just
        # re-distributes (covers the reference's resume-with-fewer-workers
        # case, test_ddp_sharded.py:119-138).
        restored = serialization.from_state_dict(
            fetch_tree(self.state), ckpt["state"])
        self.state = jax.device_put(restored, self._state_shardings)
        self.current_epoch = int(ckpt.get("epoch", 0))
        self.global_step = int(ckpt.get("global_step", 0))
        cb_states = ckpt.get("callbacks", {})
        for cb in self.callbacks:
            st = cb_states.get(type(cb).__name__)
            if st:
                cb.load_state_dict(st)
        if module is not None:
            module.on_load_checkpoint(ckpt)
        for cb in self.callbacks:
            cb.on_load_checkpoint(self, module, ckpt)

    def _restore_sharded(self, directory: str, module) -> None:
        """Restore from an orbax directory (root → latest step; a
        specific step dir works too), re-sharding straight into the
        CURRENT mesh — the full state never materializes on one host
        (utils/checkpoint.py).  The topology may differ from the one
        that saved (N→M hosts, strategy swap): global shapes are
        topology-independent except the comm plane's ``[world, ...]``
        error-feedback residual, which elastic/reshard.py re-buckets
        instead of blindly reloading.  Consequently the
        ``on_load_checkpoint`` hooks receive the checkpoint *metadata*
        (same top-level keys as :meth:`dump_checkpoint` minus
        ``state``) — see LightningModule.on_load_checkpoint."""
        from ray_lightning_tpu.elastic.reshard import restore_resharded
        from ray_lightning_tpu.utils.checkpoint import ShardedCheckpointer
        root, step = ShardedCheckpointer.split_step_dir(directory)
        ckpt = ShardedCheckpointer(root)
        try:
            state, meta = restore_resharded(
                ckpt, self.state, self._state_shardings, step=step)
        finally:
            ckpt.close()
        # the replay counter the zero-replay acceptance reads: a parity
        # recovery must finish the fit with this still at 0
        self._snapshot_restores += 1
        reg = _metrics.get_registry()
        if reg is not None:
            reg.counter("rlt_snapshot_restore_total").inc()
        self.state = state
        self.current_epoch = int(meta.get("epoch", 0))
        self.global_step = int(meta.get("global_step", 0))
        cb_states = meta.get("callbacks", {})
        for cb in self.callbacks:
            st = cb_states.get(type(cb).__name__)
            if st:
                cb.load_state_dict(st)
        if module is not None:
            module.on_load_checkpoint(meta)
        for cb in self.callbacks:
            cb.on_load_checkpoint(self, module, meta)

    # elapsed-time helper used by examples/benchmarks
    @staticmethod
    def _now() -> float:
        return time.monotonic()


def _deinterleave_global_batch(tree, w: int):
    """Global fetched batch rows are process-major ([shard0; shard1; …]);
    strided sharding means shard r holds dataset indices r, r+W, … — so
    dataset order is the (position, shard) transpose."""
    def fix(a):
        if getattr(a, "ndim", 0) == 0 or a.shape[0] % w:
            return a
        lb = a.shape[0] // w
        return a.reshape((w, lb) + a.shape[1:]).swapaxes(0, 1).reshape(
            (w * lb,) + a.shape[1:])
    return jax.tree_util.tree_map(fix, tree)


class _ShardedStepCache:
    """Lazily jit a (state, batch) step per batch *structure* with the
    strategy's ``in_shardings``.

    Eval/predict loaders may yield a different batch pytree than the
    train loader the trainer compiled against (e.g. ``(x, y)`` vs ``x``),
    so the jit — whose ``in_shardings`` must match the arg structure — is
    built on first use per structure and cached."""

    def __init__(self, fn, trainer, strategy):
        self._fn = fn
        self._trainer = trainer
        self._strategy = strategy
        self._cache: dict = {}

    def jitted_for(self, batch):
        """The jitted step for this batch *structure* (built on first
        use, cached).  ``batch`` may be concrete or a tree of
        ``ShapeDtypeStruct`` — the key and the shardings only read
        treedef + ndim, which lets the AOT precompiler warm the SAME
        jit object the eval loop later dispatches through."""
        leaves, treedef = jax.tree_util.tree_flatten(batch)
        key = (treedef, tuple(getattr(l, "ndim", 0) for l in leaves))
        jitted = self._cache.get(key)
        if jitted is None:
            if self._trainer._mesh.devices.size > 1:
                batch_sh = self._strategy.batch_shardings(
                    self._trainer._mesh, batch)
                jitted = jax.jit(
                    self._fn,
                    in_shardings=(self._trainer._state_shardings, batch_sh))
            else:
                jitted = jax.jit(self._fn)
            self._cache[key] = jitted
        return jitted

    def __call__(self, state, batch):
        return self.jitted_for(batch)(state, batch)


def _cache_bytes_estimate(loader, example_batch) -> "int | None":
    """Upper-bound bytes of the device-resident train cache (per batch ×
    batch count), for the donation heuristic's budget debit.  None when
    the loader has no length (the same loaders the cache itself refuses,
    core/loop_engine.py) — the caller then donates, the safe default.

    ``limit_train_batches`` deliberately does NOT shrink the debit:
    ``CachedSource.build()`` uploads the FULL dataset regardless of the
    limit (the limit trims the epoch plan, not the flat cache).  And a
    shuffling loader keeps that flat upload resident for the whole fit
    *alongside* each epoch's repacked view, so its debit doubles
    (shuffle=False drops the flat copy right after the first repack —
    the steady-state residency the budget protects is single there).
    (Advisor r5 medium: the old limit-capped single-copy estimate let
    donation skip with far less real headroom than computed.)
    """
    try:
        n = len(loader)
    except TypeError:
        return None
    batch_bytes = sum(
        int(getattr(leaf, "nbytes", 0) or np.asarray(leaf).nbytes)
        for leaf in jax.tree_util.tree_leaves(example_batch))
    total = n * batch_bytes
    if getattr(loader, "shuffle", False):
        total *= 2
    return total


def _peek_first_batch(loader):
    """Grab one batch for shape inference without losing it.

    Re-iterable loaders (anything with ``__len__``) are returned as-is;
    one-shot iterables are wrapped so the peeked batch is replayed at the
    start of the (single) pass.
    """
    it = iter(loader)
    first = next(it)
    if hasattr(loader, "__len__"):
        return first, loader
    return first, _ChainedLoader(first, it)


class _ChainedLoader:
    def __init__(self, first, rest_iter):
        self._first = first
        self._rest = rest_iter
        self._consumed = False

    def __iter__(self):
        if self._consumed:
            return iter(())  # one-shot source: second pass is empty
        self._consumed = True
        import itertools
        return itertools.chain([self._first], self._rest)


def _sanitize(obj):
    """Make a nested structure msgpack-serializable (tuples→lists, numpy
    scalars→python, drop non-serializable leaves)."""
    if isinstance(obj, dict):
        return {str(k): _sanitize(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_sanitize(v) for v in obj]
    if isinstance(obj, (np.generic,)):
        return obj.item()
    if isinstance(obj, (str, bytes, int, float, bool, type(None),
                        np.ndarray)):
        return obj
    if isinstance(obj, jax.Array):
        return np.asarray(jax.device_get(obj))
    return repr(obj)
