"""Remat (rematerialization) as a first-class, *model-generic* lever.

Until PR 12 the remat-policy ladder lived inside ``models/gpt.py`` as a
GPT-private config knob (``GPTConfig.remat_policy``) plus an env
override — the planner could not see it, pipeline/MPMD models only had
a boolean, and BERT had nothing.  This module is the shared machinery
behind the ``LightningModule.configure_remat()`` hook:

- :func:`policy_object` — the canonical name → ``jax.checkpoint``
  policy mapping (``off | full | dots | dots_no_batch`` plus the
  ``checkpoint_name``-based MoE save lists), WITHOUT the
  ``RLT_REMAT_POLICY`` env consultation (that stays a model-build
  concern, models/gpt.py ``_remat_policy``);
- :class:`RematSpec` — what a module declares to the planner: its
  policy ladder, its current default, an ``apply`` to reconfigure the
  module in place, and a ``probe`` that prices one policy from avals;
- the probe primitives: :func:`saved_activation_bytes` (the
  eval_shape-exact bytes of every *computed* residual the policy saves
  — ``jax.ad_checkpoint``'s own ``saved_residuals`` over abstract
  args, argument-sourced residuals excluded because params/input
  residency is already accounted elsewhere) and
  :func:`grad_dot_flops` (matmul FLOPs of the backward jaxpr, counted
  by walking ``dot_general`` eqns recursively — the difference vs the
  un-remat'd baseline is exactly the matmul work the policy recomputes).

Everything here is pure tracing: no compiles, deterministic for fixed
avals — which is what lets plan/cost.py fold these numbers into the
planner's ranking keys without breaking the fleet-wide
agree-without-a-collective contract (plan/planner.py).
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import numpy as np

#: the generic policy ladder every remat-capable model family supports,
#: ordered from no-recompute to max-recompute (models append their
#: checkpoint_name-based extras, e.g. GPT's MoE save lists)
POLICY_LADDER = ("off", "dots", "dots_no_batch", "full")

#: checkpoint_name-based MoE save lists (ops/moe.py checkpoint_name
#: call sites); generic here so any routed-FFN family can reuse them
MOE_POLICIES = ("dots_moe_act", "dots_moe")


def policy_object(name: str):
    """``jax.checkpoint`` policy for a canonical ladder name.

    ``"full"`` maps to ``None`` (jax's default: nothing saveable — the
    max-recompute end); ``"off"`` maps to ``everything_saveable``,
    though callers normally skip the remat wrap entirely for "off"
    (:func:`RematSpec` consumers and models/gpt.py both do).  Raises
    naming the options, mirroring the old gpt-local mapping.
    """
    cp = jax.checkpoint_policies
    policies = {
        "full": None,
        "dots": cp.dots_saveable,
        "dots_no_batch": cp.dots_with_no_batch_dims_saveable,
        # dots + the named MoE intermediates (ops/moe.py
        # checkpoint_name): between dots and off — saving them keeps
        # the expert backward's dgrad fusions off the recompute chains
        # without round-tripping EVERY intermediate the way "off" does
        "dots_moe_act": cp.save_from_both_policies(
            cp.dots_saveable, cp.save_only_these_names("moe_hact")),
        "dots_moe": cp.save_from_both_policies(
            cp.dots_saveable,
            cp.save_only_these_names("moe_hact", "moe_dispatch",
                                     "moe_combine")),
        "off": cp.everything_saveable,
    }
    if name not in policies:
        raise ValueError(
            f"remat_policy {name!r}; options: {sorted(policies)}")
    return policies[name]


@dataclasses.dataclass(frozen=True)
class RematProbe:
    """One policy's modeled cost ingredients at the probe batch size
    (plan/cost.py rescales linearly to the candidate's per-device
    batch — every quantity here is linear in the leading batch dim)."""

    saved_bytes: int        #: computed-residual bytes across ALL blocks
    recompute_flops: int    #: extra backward matmul FLOPs vs no-remat
    n_blocks: int           #: remat region count (per-region overhead)
    batch: int              #: probe leading batch dim (rescale anchor)


@dataclasses.dataclass(frozen=True)
class RematSpec:
    """What ``configure_remat()`` returns: the module's remat surface.

    ``apply(policy)`` reconfigures the module the spec was created from
    IN PLACE (resets any materialized model) — the planner applies it to
    ``copy.copy`` clones for candidate compiles and to the real module
    once a winner is picked (core/trainer.py); ``probe(policy, batch)``
    prices a policy from the example batch's avals alone.
    """

    policies: tuple           #: supported policy names, ladder-ordered
    default: str              #: the module's current effective policy
    apply: Callable           #: (policy: str) -> None, in place
    probe: Callable           #: (policy: str, batch) -> RematProbe


# -- probe primitives ------------------------------------------------------

def _aval_bytes(aval) -> int:
    return int(np.prod(aval.shape, dtype=np.int64) or 1) \
        * aval.dtype.itemsize


def saved_activation_bytes(fn, *args) -> int:
    """Bytes of the residuals ``jax.grad(fn)`` would save that are
    COMPUTED inside ``fn`` (argument-sourced residuals — params, the
    block input — excluded: their residency is charged as state/batch
    elsewhere in the cost model).  ``args`` may be ShapeDtypeStructs;
    this only traces."""
    try:
        from jax.ad_checkpoint import saved_residuals
    except ImportError:   # this jax ships it under _src only
        from jax._src.ad_checkpoint import saved_residuals
    return sum(_aval_bytes(aval) for aval, src in saved_residuals(
        fn, *args) if "argument" not in src)


def _dot_flops_of_jaxpr(jaxpr) -> int:
    """2·M·N·K·batch summed over every ``dot_general`` in ``jaxpr``,
    recursing into sub-jaxprs (pjit / remat / scan / custom-vjp
    bodies)."""
    total = 0
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "dot_general":
            (lc, rc), (lb, _rb) = eqn.params["dimension_numbers"]
            a, b = eqn.invars[0].aval, eqn.invars[1].aval
            batch = int(np.prod([a.shape[i] for i in lb],
                                dtype=np.int64) or 1)
            k = int(np.prod([a.shape[i] for i in lc],
                            dtype=np.int64) or 1)
            m = int(np.prod([a.shape[i] for i in range(a.ndim)
                             if i not in lc and i not in lb],
                            dtype=np.int64) or 1)
            n = int(np.prod([b.shape[i] for i in range(b.ndim)
                             if i not in rc and i not in _rb],
                            dtype=np.int64) or 1)
            total += 2 * batch * m * n * k
        for v in eqn.params.values():
            sub = getattr(v, "jaxpr", None)   # ClosedJaxpr
            if sub is not None and hasattr(sub, "eqns"):
                total += _dot_flops_of_jaxpr(sub)
            elif hasattr(v, "eqns"):          # bare Jaxpr
                total += _dot_flops_of_jaxpr(v)
            elif isinstance(v, (list, tuple)):
                for w in v:                   # e.g. cond branches
                    ws = getattr(w, "jaxpr", w)
                    if hasattr(ws, "eqns"):
                        total += _dot_flops_of_jaxpr(ws)
    return total


def grad_dot_flops(fn, *args) -> int:
    """Matmul FLOPs of ``fn``'s full backward (grads wrt every arg —
    the training shape: a block's backward produces both param grads
    and the activation grad flowing upstream).  Pure tracing; the
    POLICY-minus-BASELINE difference of this number is the recompute
    work a checkpoint policy adds."""
    import jax.numpy as jnp

    def scalar(*a):
        out = fn(*a)
        leaves = jax.tree_util.tree_leaves(out)
        return sum(leaf.astype(jnp.float32).sum() for leaf in leaves)

    g = jax.grad(scalar, argnums=tuple(range(len(args))))
    return _dot_flops_of_jaxpr(jax.make_jaxpr(g)(*args).jaxpr)


def step_dot_flops(fn, *args) -> int:
    """Matmul FLOPs of ``fn``'s OWN jaxpr — for programs that already
    contain their backward (a built train step: forward + grad +
    optimizer update), where :func:`grad_dot_flops` would differentiate
    a second time.  The goodput plane's default ``flops_per_step``
    pricing (telemetry/goodput.py measured MFU)."""
    return _dot_flops_of_jaxpr(jax.make_jaxpr(fn)(*args).jaxpr)


def block_cost(fn, base_fn, *args, base_flops=None) -> "tuple[int, int]":
    """(saved computed-residual bytes of ``fn``, extra backward matmul
    FLOPs of ``fn`` vs the un-remat'd ``base_fn``).  Pass
    ``base_flops`` (one :func:`grad_dot_flops` of ``base_fn``) when
    pricing several policies of the same block to avoid re-tracing the
    baseline per policy."""
    if base_flops is None:
        base_flops = grad_dot_flops(base_fn, *args)
    saved = saved_activation_bytes(fn, *args)
    extra = max(0, grad_dot_flops(fn, *args) - base_flops) \
        if fn is not base_fn else 0
    return saved, extra


__all__ = [
    "MOE_POLICIES",
    "POLICY_LADDER",
    "RematProbe",
    "RematSpec",
    "block_cost",
    "grad_dot_flops",
    "policy_object",
    "saved_activation_bytes",
    "step_dot_flops",
]
