"""Compiled step builders.

Each stage (train / eval / predict / init) is one pure function, jitted
once with the strategy's shardings.  This replaces the reference's hot
loop — PL's ``trainer.run_stage()`` driving torch autograd + DDP hooks
inside each worker (ray_ddp.py:472) — with XLA-compiled SPMD programs:
gradient sync is not an op we call, it is a sharding consequence the
compiler lowers to ICI collectives.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax

from ray_lightning_tpu.core.module import StepContext
from ray_lightning_tpu.core.state import TrainState


def build_init_fn(module, tx) -> Callable:
    """(rng, example_batch) -> TrainState with freshly initialized params."""

    def init_fn(rng, batch):
        init_rng, state_rng = jax.random.split(rng)
        variables = dict(module.init_params(init_rng, batch))
        params = variables.pop("params")
        model_state = variables
        # opt init sees the full-precision init values: an fp32_master tx
        # snapshots its master copy *before* any residency downcast
        opt_state = tx.init(params)
        pd = getattr(module, "param_dtype", None)
        if pd is not None:
            params = jax.tree_util.tree_map(
                lambda p: p.astype(pd)
                if jnp.issubdtype(p.dtype, jnp.floating) else p, params)
        return TrainState.create(params, model_state, opt_state, state_rng)

    return init_fn


def _split_loss(out) -> tuple[jax.Array, dict]:
    if isinstance(out, dict):
        if "loss" not in out:
            raise ValueError("training_step dict output must contain 'loss'")
        extra = {k: v for k, v in out.items() if k != "loss"}
        return out["loss"], extra
    return out, {}


def build_train_step(module, tx,
                     accumulate_grad_batches: int = 1,
                     grad_sync=None) -> Callable:
    """(state, batch) -> (state', metrics).

    With ``accumulate_grad_batches=k`` the batch's leading dim is split
    into k microbatches folded with ``lax.scan`` (static trip count —
    XLA-friendly control flow, no data-dependent Python), gradients are
    averaged, and one optimizer step is applied.

    ``grad_sync`` (a ``comm.GradSync``, default ``None``) routes the
    gradient sync through the comm plane's compressed collectives: the
    gradient computation runs per-device under ``shard_map`` (params
    replicated, batch sharded on the data axes), local grads reduce via
    quantized reduce-scatter + all-gather with the error-feedback
    residual carried in the optimizer state, and the tiny scalars
    (loss / logged / float model-state) pmean at fp32.  The policy can
    further split the reduction across link tiers (``hierarchy`` —
    fp32 inside the ICI group, codec only across DCN) and coalesce
    leaves into overlap-schedulable buckets (``bucket_bytes`` —
    ``GradSync.sync_step`` routes).  With ``None`` the step is
    byte-identical to the pre-comm-plane build: gradient sync stays
    the partitioner's implicit fp32 all-reduce.
    """

    def grads_of(params, model_state, rng, batch):
        def loss_fn(p):
            ctx = StepContext(module, p, model_state, rng, training=True)
            loss, extra = _split_loss(module.training_step(ctx, batch))
            return loss, (ctx.model_state, {**ctx.logged, **extra})
        (loss, (new_ms, logged)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        return loss, new_ms, logged, grads

    def compute_grads(params, model_state, step_rng, batch):
        """Single or k-microbatch-accumulated gradients.  Identical math
        in global view (grad_sync None) and per-device view (inside the
        shard_map region, where ``batch`` is the local shard)."""
        if accumulate_grad_batches <= 1:
            return grads_of(params, model_state, step_rng, batch)
        k = accumulate_grad_batches

        def to_micro(x):
            if getattr(x, "ndim", 0) == 0:
                return x
            if x.shape[0] % k:
                raise ValueError(
                    f"Batch size {x.shape[0]} must be divisible by "
                    f"accumulate_grad_batches={k}")
            return x.reshape((k, x.shape[0] // k) + x.shape[1:])

        micro = jax.tree_util.tree_map(to_micro, batch)

        def body(carry, mb):
            ms, acc = carry
            rng_i = (jax.random.fold_in(step_rng, acc["_i"])
                     if step_rng is not None else None)
            loss, ms, logged, grads = grads_of(params, ms, rng_i, mb)
            acc_g = jax.tree_util.tree_map(jnp.add, acc["g"], grads)
            return (ms, {"g": acc_g, "_i": acc["_i"] + 1}), (loss, logged)

        # accumulate in fp32 regardless of param residency dtype: k
        # bf16 additions would lose low bits the optimizer needs
        zero_g = jax.tree_util.tree_map(
            lambda p: jnp.zeros(
                p.shape,
                jnp.float32 if jnp.issubdtype(p.dtype, jnp.floating)
                else p.dtype),
            params)
        (new_ms, acc), (losses, logged_seq) = jax.lax.scan(
            body, (model_state, {"g": zero_g, "_i": jnp.zeros(
                (), jnp.int32)}), micro)
        grads = jax.tree_util.tree_map(lambda g: g / k, acc["g"])
        loss = losses.mean()
        logged = jax.tree_util.tree_map(lambda x: x.mean(), logged_seq)
        return loss, new_ms, logged, grads

    def synced_grads(state: TrainState, step_rng, batch):
        """Compressed-sync path: local grads + explicit quantized
        reduction under shard_map (comm plane module docstring)."""
        from jax.sharding import PartitionSpec as P

        from ray_lightning_tpu.parallel.mesh import shard_map_compat

        residual = grad_sync.residual_of(state.opt_state)
        comm_key = None
        if grad_sync.policy.stochastic_rounding:
            # derived, never consumed: state.rng advances exactly as in
            # the uncompressed step (the uses_rng contract holds)
            comm_key = jax.random.fold_in(state.rng, state.step)

        def local_fn(params, model_state, step_rng, comm_key, batch,
                     residual):
            if step_rng is not None:
                # decorrelate dropout/rng streams across data shards (in
                # global view one stream spans the global batch; here
                # each shard draws its own)
                step_rng = jax.random.fold_in(step_rng,
                                              grad_sync.axis_index())
            loss, new_ms, logged, grads = compute_grads(
                params, model_state, step_rng, batch)
            if comm_key is not None:
                comm_key = jax.random.fold_in(comm_key,
                                              grad_sync.axis_index())
            grads, new_residual = grad_sync.sync_step(grads, residual,
                                                      rng=comm_key)
            loss, logged, new_ms = grad_sync.pmean((loss, logged, new_ms))
            return loss, new_ms, logged, grads, new_residual

        batch_specs = jax.tree_util.tree_map(
            lambda x: grad_sync.batch_spec(getattr(x, "ndim", 0)), batch)
        res_specs = grad_sync.residual_specs(residual)
        mapped = shard_map_compat(
            local_fn, grad_sync.mesh,
            in_specs=(P(), P(), P(), P(), batch_specs, res_specs),
            out_specs=(P(), P(), P(), P(), res_specs))
        return mapped(state.params, state.model_state, step_rng,
                      comm_key, batch, residual)

    def step_fn(state: TrainState, batch: Any):
        if getattr(module, "uses_rng", True):
            new_rng, step_rng = jax.random.split(state.rng)
            step_rng = jax.random.fold_in(step_rng, state.step)
        else:
            # module declared itself deterministic: the per-step
            # split/fold is pure scalar-core work the compiled step can
            # drop — measurable on microsecond-scale models (the MNIST
            # MLP's device step is ~2/3 rng bookkeeping)
            new_rng, step_rng = state.rng, None

        new_residual = None
        if grad_sync is None:
            loss, new_ms, logged, grads = compute_grads(
                state.params, state.model_state, step_rng, batch)
        else:
            loss, new_ms, logged, grads, new_residual = synced_grads(
                state, step_rng, batch)

        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        if grad_sync is not None:
            new_opt = grad_sync.with_residual(new_opt, new_residual)
        new_params = optax.apply_updates(state.params, updates)
        if grad_sync is not None:
            new_params = grad_sync.regather_params(new_params)
        metrics = {"loss": loss, **logged}
        new_state = state.replace(
            step=state.step + 1, params=new_params, model_state=new_ms,
            opt_state=new_opt, rng=new_rng)
        return new_state, metrics

    return step_fn


def build_prefill_step(module, bucket_len: int, model=None,
                       dequant=None) -> Callable:
    """Serve-plane prefill program for ONE sequence-length bucket
    (sibling of :func:`build_train_step`; consumed by serve/engine.py).

    ``(params, k_caches, v_caches, tokens, slot, length) ->
    (k', v', first_token)`` where ``tokens`` is ``[1, bucket_len]``
    (right-padded), ``slot``/``length`` are traced int32 scalars — ONE
    compiled program per (bucket, topology), whatever slot or true
    length a request lands on.  The forward is the module's decode model
    applied normally with the ``kv_cache`` collection mutable, so the
    captured per-layer K/V are numerically THE training forward's;
    positions ``>= length`` hold pad garbage the causal mask keeps out
    of the first token's logits and :func:`cached_attention`'s position
    bound keeps out of every later one.

    ``model`` overrides the forward module (the DRAFT model's prefill
    over the draft KV cache, speculative decoding); ``dequant`` maps
    the params argument inside the traced body (int8-resident draft
    weights decode inline, comm/quant.py ``dequantize_blob``).
    """
    module.setup_model()
    if model is None:
        model = module.configure_decode_model()

    def step_fn(params, k_caches, v_caches, tokens, slot, length):
        if dequant is not None:
            params = dequant(params)
        logits, captured = model.apply({"params": params}, tokens, True,
                                       mutable=["kv_cache"])
        first = jnp.argmax(
            jax.lax.dynamic_index_in_dim(logits[0], length - 1, 0,
                                         keepdims=False),
            axis=-1).astype(tokens.dtype)
        # captured K/V ride the module tree ({'h0': {'attn': {'kv':
        # ((k, v),)}}}); stack to [n_layer, 1, Tb, H, D] and write every
        # layer's block with one dynamic_update_slice at the slot
        ks, vs = _stacked_kv(captured["kv_cache"])
        k_caches = jax.lax.dynamic_update_slice(
            k_caches, ks, (0, slot) + (0,) * (k_caches.ndim - 2))
        v_caches = jax.lax.dynamic_update_slice(
            v_caches, vs, (0, slot) + (0,) * (v_caches.ndim - 2))
        return k_caches, v_caches, first

    return step_fn


def kv_layer_pairs(kv_tree) -> "list[tuple]":
    """Per-layer ``(k, v)`` pairs from the sown ``kv_cache`` collection,
    in layer order (sorted on the numeric suffix of the flax block
    names h0, h1, ...).  Works on concrete arrays AND on ``eval_shape``
    avals (serve/engine.py derives the cache geometry from the latter).
    """
    def layer_no(name):
        digits = "".join(ch for ch in name if ch.isdigit())
        return int(digits) if digits else 0

    pairs = []
    for name in sorted(kv_tree, key=layer_no):
        sub = kv_tree[name]
        while isinstance(sub, dict):
            sub = next(iter(sub.values()))
        pairs.append(sub[0] if isinstance(sub, tuple) and len(sub) == 1
                     and isinstance(sub[0], tuple) else sub)
    return pairs


def _stacked_kv(kv_tree):
    """[n_layer, B, Tb, H, D] k/v stacks from the sown collection."""
    pairs = kv_layer_pairs(kv_tree)
    ks = jnp.stack([k for k, _ in pairs])
    vs = jnp.stack([v for _, v in pairs])
    return ks, vs


def build_decode_step(module, page_table=None) -> Callable:
    """Serve-plane continuous-batching decode program (sibling of
    :func:`build_train_step`; THE serving hot path).

    ``(params, k_caches, v_caches, tokens, positions) ->
    (k', v', next_tokens)``: advances EVERY batch slot one token in one
    compiled SPMD program — ``tokens``/``positions`` are ``[S]``, the
    caches ``[n_layer, S, L, H, D]``.  Static shapes by construction:
    request insertion/eviction is a slot-index change in the host-side
    scheduler, so decode never re-traces (serve/scheduler.py).

    ``page_table`` ([S, pages_per_slot] int32 host array,
    serve/fleet/pages.py ``identity_page_table``) selects the paged
    flash-decode kernel's indirect KV fetch.  It is closed over as a
    trace constant — the table geometry is fixed per engine, so the
    program signature (and the zero-retrace contract) is unchanged.
    """
    module.setup_model()
    model = module.configure_decode_model()
    kw = {} if page_table is None else {
        "page_table": jnp.asarray(page_table, jnp.int32)}

    def step_fn(params, k_caches, v_caches, tokens, positions):
        logits, new_k, new_v = model.apply(
            {"params": params}, tokens, positions, k_caches, v_caches,
            method="decode", **kw)
        return new_k, new_v, jnp.argmax(logits, axis=-1).astype(
            tokens.dtype)

    return step_fn


def build_draft_step(module, k: int, page_table=None, model=None,
                     dequant=None) -> Callable:
    """Speculative-decode draft program: ``k`` autoregressive greedy
    decode steps of the DRAFT model, unrolled into ONE compiled
    program over its own (smaller) KV cache.

    ``(draft_params, dk_caches, dv_caches, tokens, positions) ->
    (dk', dv', drafts)``: ``tokens``/``positions`` are the [S] last
    emitted token per slot at its position (exactly the plain-decode
    inputs); ``drafts`` is [S, k] — the k greedily drafted tokens per
    slot.  Each unrolled step writes its token's draft-cache row and
    feeds its argmax forward, so after the step the draft cache holds
    rows ``[0, pos+k)``; rows drafted past the verify's accepted
    prefix are stale-but-masked and the NEXT round (restarting at the
    corrected position) overwrites them — same induction as the target
    cache (models/gpt.py ``GPT.verify``).

    ``model`` is the draft flax module
    (``LightningModule.configure_draft()``); ``dequant`` decodes
    int8-resident draft params inline (``RLT_DRAFT_QUANT``).
    """
    module.setup_model()
    if model is None:
        model = module.configure_decode_model()
    kw = {} if page_table is None else {
        "page_table": jnp.asarray(page_table, jnp.int32)}

    def step_fn(params, dk_caches, dv_caches, tokens, positions):
        if dequant is not None:
            params = dequant(params)
        toks, pos, drafts = tokens, positions, []
        for _ in range(k):
            logits, dk_caches, dv_caches = model.apply(
                {"params": params}, toks, pos, dk_caches, dv_caches,
                method="decode", **kw)
            toks = jnp.argmax(logits, axis=-1).astype(tokens.dtype)
            pos = pos + 1
            drafts.append(toks)
        return dk_caches, dv_caches, jnp.stack(drafts, axis=1)

    return step_fn


def build_verify_step(module, k: int, page_table=None) -> Callable:
    """Speculative-decode verify program: ONE batched target forward
    over the k drafted positions per slot.

    ``(params, k_caches, v_caches, tokens, positions) ->
    (k', v', argmaxes)`` with ``tokens``/``positions`` [S, k+1] — per
    slot the last emitted token followed by its k drafts at
    consecutive positions.  ``argmaxes`` [S, k+1]: column j is the
    token the target would emit after the prefix extended by drafts
    ``1..j`` — the scheduler accepts the longest prefix where
    ``draft[j] == argmax[j]`` plus the one corrected token
    (serve/scheduler.py), which makes speculative output token-level
    IDENTICAL to target-only greedy decode.  Rides
    :meth:`models.gpt.GPT.verify`'s multi-query cached attention, so
    the flash-decode/paged kernels and per-query length masks are the
    plain decode path's.
    """
    module.setup_model()
    model = module.configure_decode_model()
    kw = {} if page_table is None else {
        "page_table": jnp.asarray(page_table, jnp.int32)}

    def step_fn(params, k_caches, v_caches, tokens, positions):
        logits, new_k, new_v = model.apply(
            {"params": params}, tokens, positions, k_caches, v_caches,
            method="verify", **kw)
        return new_k, new_v, jnp.argmax(logits, axis=-1).astype(
            tokens.dtype)

    return step_fn


def build_kv_copy() -> Callable:
    """Paged-KV page copy program (serve/fleet/pages.py prefix reuse).

    ``(k_caches, v_caches, src, dst, length) -> (k', v')`` copies cache
    rows ``[0, length)`` from slot ``src`` into slot ``dst`` across
    every layer — the device half of a prefix-cache hit: the matched
    pages move as one masked row-copy instead of being recomputed by a
    prefill.  ``src``/``dst``/``length`` are traced int32 scalars, so
    ONE compiled program serves every (donor, destination, match
    length) triple.  Sound because a cache row is a pure per-(token,
    position) value (ops/attention.py MultiHeadAttention decode path):
    identical prefixes have identical rows wherever they were computed.
    """

    def copy_fn(k_caches, v_caches, src, dst, length):
        L = k_caches.shape[2]
        mask = (jnp.arange(L) < length)[None, None, :, None, None]

        def one(c):
            src_rows = jax.lax.dynamic_slice_in_dim(c, src, 1, axis=1)
            dst_rows = jax.lax.dynamic_slice_in_dim(c, dst, 1, axis=1)
            merged = jnp.where(mask, src_rows, dst_rows)
            return jax.lax.dynamic_update_slice_in_dim(c, merged, dst,
                                                       axis=1)

        return one(k_caches), one(v_caches)

    return copy_fn


def build_suffix_step(module, page_table=None) -> Callable:
    """Single-slot suffix-prefill program (the compute leg of prefix
    reuse, serve/fleet/pages.py).

    ``(params, k_caches, v_caches, token, pos, slot) ->
    (k', v', next_token)``: advances ONE slot one token — the model's
    decode forward on a 1-slot batch sliced out of the cache, written
    back in place.  After a prefix-cache hit copies the matched pages
    (:func:`build_kv_copy`), the unmatched suffix is teacher-forced
    through this program one token at a time; only the suffix is ever
    computed, which is the measured ``prefill tokens computed vs
    requested`` savings.  Unlike the batched decode program this writes
    NOTHING outside ``slot`` — no dummy writes to neighbors — so it can
    run mid-step without the serve plan's dispatch-order contract.

    ``page_table`` here is the ONE-slot table (``identity_page_table(1,
    L, page_size)``): the decode forward sees the cache sliced down to
    its single slot, so physical pages are slice-relative — identical
    for every slot, which is what lets one compiled program serve them
    all.
    """
    module.setup_model()
    model = module.configure_decode_model()
    kw = {} if page_table is None else {
        "page_table": jnp.asarray(page_table, jnp.int32)}

    def step_fn(params, k_caches, v_caches, token, pos, slot):
        k1 = jax.lax.dynamic_slice_in_dim(k_caches, slot, 1, axis=1)
        v1 = jax.lax.dynamic_slice_in_dim(v_caches, slot, 1, axis=1)
        logits, nk, nv = model.apply(
            {"params": params}, token[None], pos[None], k1, v1,
            method="decode", **kw)
        k_caches = jax.lax.dynamic_update_slice_in_dim(k_caches, nk,
                                                       slot, axis=1)
        v_caches = jax.lax.dynamic_update_slice_in_dim(v_caches, nv,
                                                       slot, axis=1)
        nxt = jnp.argmax(logits[0], axis=-1).astype(token.dtype)
        return k_caches, v_caches, nxt

    return step_fn


def build_eval_step(module, stage: str) -> Callable:
    """(state, batch) -> logged metrics dict (pure, no state mutation)."""
    step = {"validate": module.validation_step,
            "test": module.test_step}[stage]

    def step_fn(state: TrainState, batch: Any):
        ctx = StepContext(module, state.params, state.model_state,
                          rng=None, training=False)
        out = step(ctx, batch)
        logged = ctx.logged
        if out is not None and not isinstance(out, dict) and not logged:
            # A bare returned scalar with nothing logged: surface it.
            logged = {"val_loss" if stage == "validate" else "test_loss":
                      jnp.asarray(out, jnp.float32)}
        elif isinstance(out, dict):
            logged = {**logged,
                      **{k: jnp.asarray(v, jnp.float32)
                         for k, v in out.items()}}
        return logged

    return step_fn


def build_predict_step(module) -> Callable:
    def step_fn(state: TrainState, batch: Any):
        ctx = StepContext(module, state.params, state.model_state,
                          rng=None, training=False)
        return module.predict_step(ctx, batch)

    return step_fn
