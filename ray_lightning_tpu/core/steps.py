"""Compiled step builders.

Each stage (train / eval / predict / init) is one pure function, jitted
once with the strategy's shardings.  This replaces the reference's hot
loop — PL's ``trainer.run_stage()`` driving torch autograd + DDP hooks
inside each worker (ray_ddp.py:472) — with XLA-compiled SPMD programs:
gradient sync is not an op we call, it is a sharding consequence the
compiler lowers to ICI collectives.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax

from ray_lightning_tpu.core.module import StepContext
from ray_lightning_tpu.core.state import TrainState


def build_init_fn(module, tx) -> Callable:
    """(rng, example_batch) -> TrainState with freshly initialized params."""

    def init_fn(rng, batch):
        init_rng, state_rng = jax.random.split(rng)
        variables = dict(module.init_params(init_rng, batch))
        params = variables.pop("params")
        model_state = variables
        # opt init sees the full-precision init values: an fp32_master tx
        # snapshots its master copy *before* any residency downcast
        opt_state = tx.init(params)
        pd = getattr(module, "param_dtype", None)
        if pd is not None:
            params = jax.tree_util.tree_map(
                lambda p: p.astype(pd)
                if jnp.issubdtype(p.dtype, jnp.floating) else p, params)
        return TrainState.create(params, model_state, opt_state, state_rng)

    return init_fn


def _split_loss(out) -> tuple[jax.Array, dict]:
    if isinstance(out, dict):
        if "loss" not in out:
            raise ValueError("training_step dict output must contain 'loss'")
        extra = {k: v for k, v in out.items() if k != "loss"}
        return out["loss"], extra
    return out, {}


def build_train_step(module, tx,
                     accumulate_grad_batches: int = 1) -> Callable:
    """(state, batch) -> (state', metrics).

    With ``accumulate_grad_batches=k`` the batch's leading dim is split
    into k microbatches folded with ``lax.scan`` (static trip count —
    XLA-friendly control flow, no data-dependent Python), gradients are
    averaged, and one optimizer step is applied.
    """

    def grads_of(params, model_state, rng, batch):
        def loss_fn(p):
            ctx = StepContext(module, p, model_state, rng, training=True)
            loss, extra = _split_loss(module.training_step(ctx, batch))
            return loss, (ctx.model_state, {**ctx.logged, **extra})
        (loss, (new_ms, logged)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        return loss, new_ms, logged, grads

    def step_fn(state: TrainState, batch: Any):
        if getattr(module, "uses_rng", True):
            new_rng, step_rng = jax.random.split(state.rng)
            step_rng = jax.random.fold_in(step_rng, state.step)
        else:
            # module declared itself deterministic: the per-step
            # split/fold is pure scalar-core work the compiled step can
            # drop — measurable on microsecond-scale models (the MNIST
            # MLP's device step is ~2/3 rng bookkeeping)
            new_rng, step_rng = state.rng, None

        if accumulate_grad_batches <= 1:
            loss, new_ms, logged, grads = grads_of(
                state.params, state.model_state, step_rng, batch)
        else:
            k = accumulate_grad_batches

            def to_micro(x):
                if getattr(x, "ndim", 0) == 0:
                    return x
                if x.shape[0] % k:
                    raise ValueError(
                        f"Batch size {x.shape[0]} must be divisible by "
                        f"accumulate_grad_batches={k}")
                return x.reshape((k, x.shape[0] // k) + x.shape[1:])

            micro = jax.tree_util.tree_map(to_micro, batch)

            def body(carry, mb):
                ms, acc = carry
                rng_i = (jax.random.fold_in(step_rng, acc["_i"])
                         if step_rng is not None else None)
                loss, ms, logged, grads = grads_of(state.params, ms, rng_i, mb)
                acc_g = jax.tree_util.tree_map(jnp.add, acc["g"], grads)
                return (ms, {"g": acc_g, "_i": acc["_i"] + 1}), (loss, logged)

            # accumulate in fp32 regardless of param residency dtype: k
            # bf16 additions would lose low bits the optimizer needs
            zero_g = jax.tree_util.tree_map(
                lambda p: jnp.zeros(
                    p.shape,
                    jnp.float32 if jnp.issubdtype(p.dtype, jnp.floating)
                    else p.dtype),
                state.params)
            (new_ms, acc), (losses, logged_seq) = jax.lax.scan(
                body, (state.model_state, {"g": zero_g, "_i": jnp.zeros(
                    (), jnp.int32)}), micro)
            grads = jax.tree_util.tree_map(lambda g: g / k, acc["g"])
            loss = losses.mean()
            logged = jax.tree_util.tree_map(lambda x: x.mean(), logged_seq)

        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics = {"loss": loss, **logged}
        new_state = state.replace(
            step=state.step + 1, params=new_params, model_state=new_ms,
            opt_state=new_opt, rng=new_rng)
        return new_state, metrics

    return step_fn


def build_eval_step(module, stage: str) -> Callable:
    """(state, batch) -> logged metrics dict (pure, no state mutation)."""
    step = {"validate": module.validation_step,
            "test": module.test_step}[stage]

    def step_fn(state: TrainState, batch: Any):
        ctx = StepContext(module, state.params, state.model_state,
                          rng=None, training=False)
        out = step(ctx, batch)
        logged = ctx.logged
        if out is not None and not isinstance(out, dict) and not logged:
            # A bare returned scalar with nothing logged: surface it.
            logged = {"val_loss" if stage == "validate" else "test_loss":
                      jnp.asarray(out, jnp.float32)}
        elif isinstance(out, dict):
            logged = {**logged,
                      **{k: jnp.asarray(v, jnp.float32)
                         for k, v in out.items()}}
        return logged

    return step_fn


def build_predict_step(module) -> Callable:
    def step_fn(state: TrainState, batch: Any):
        ctx = StepContext(module, state.params, state.model_state,
                          rng=None, training=False)
        return module.predict_step(ctx, batch)

    return step_fn
