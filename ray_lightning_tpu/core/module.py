"""JAX-native LightningModule.

The reference consumes ``pl.LightningModule`` unchanged because torch
modules are stateful objects that can be pickled to workers and mutated
in-place (ray_ddp.py:331, :439-443).  On TPU the training step must be a
*pure function* XLA can trace once and compile, so this module re-designs
the contract rather than porting it:

- the user's ``training_step`` / ``validation_step`` receive a
  :class:`StepContext` — a per-trace facade that carries params, mutable
  model collections (e.g. flax batch_stats), and a PRNG stream, and
  collects ``ctx.log(...)`` metrics functionally.  Inside a trace, all
  "mutation" is local to the context object and returned to the loop as
  values; there is no hidden module state.
- the module object itself holds only *static* things: the flax model
  definition, hyperparameters, dataloaders, host-side hooks.  It pickles
  cheaply driver→worker (params are initialized worker-side, sharded by
  the strategy — live device arrays never cross the boundary; cf. the
  "pickling across the boundary" hazard, SURVEY.md §7).
"""

from __future__ import annotations

import copy
import inspect
from typing import Any, Mapping

import jax
import jax.numpy as jnp


class StepContext:
    """Functional stand-in for a stateful module inside a traced step.

    Exposes:
      - ``ctx.apply(*args, **kwargs)`` — run the flax model with the right
        variable collections; under training, mutable collections (e.g.
        ``batch_stats``) are updated into the context and threaded back to
        the train state by the loop.
      - ``ctx.make_rng()`` — split a fresh PRNG key (dropout etc.).
      - ``ctx.log(name, value)`` — record a scalar metric; collected and
        returned from the compiled step, then surfaced in
        ``trainer.callback_metrics`` (reference metric flow:
        ray_ddp.py:488-492, :366-370).
    """

    __slots__ = (
        "module",
        "params",
        "model_state",
        "training",
        "_rng",
        "_logged",
    )

    def __init__(
        self,
        module: "LightningModule",
        params: Any,
        model_state: Any,
        rng: jax.Array | None,
        training: bool,
    ):
        self.module = module
        self.params = params
        self.model_state = dict(model_state) if model_state else {}
        self.training = training
        self._rng = rng
        self._logged: dict[str, jax.Array] = {}

    # -- model application -------------------------------------------------

    @property
    def model(self):
        return self.module.model

    def make_rng(self) -> jax.Array:
        if self._rng is None:
            raise RuntimeError("No PRNG key available in this step context.")
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def apply(self, *args, method=None, rngs=None, **kwargs):
        """Apply the flax model functionally.

        Mutable collections are updated in the context during training so
        consecutive ``apply`` calls in one step see each other's updates,
        and the loop persists them into the train state.
        """
        if self.model is None:
            raise RuntimeError(
                "ctx.apply() requires configure_model() to return a flax "
                "module; otherwise compute params directly in your step.")
        variables = {"params": self.params, **self.model_state}
        if rngs is None and self.training and self._rng is not None:
            rngs = {"dropout": self.make_rng()}
        mutable = list(self.model_state.keys()) if self.training else False
        if mutable:
            out, updated = self.model.apply(
                variables, *args, method=method, rngs=rngs, mutable=mutable,
                **kwargs)
            self.model_state = dict(updated)
            return out
        return self.model.apply(
            variables, *args, method=method, rngs=rngs, **kwargs)

    # -- metric logging ----------------------------------------------------

    def log(self, name: str, value, **_ignored) -> None:
        self._logged[name] = jnp.asarray(value, dtype=jnp.float32)

    def log_dict(self, metrics: Mapping[str, Any], **_ignored) -> None:
        for k, v in metrics.items():
            self.log(k, v)

    @property
    def logged(self) -> dict[str, jax.Array]:
        return dict(self._logged)


class _HParams(dict):
    """Attribute-accessible hyperparameter dict (PL ``hparams`` analog)."""

    def __getattr__(self, item):
        try:
            return self[item]
        except KeyError as e:
            raise AttributeError(item) from e

    def __setattr__(self, key, value):
        self[key] = value


class LightningModule:
    """Base class for user models (``pl.LightningModule`` analog).

    Subclasses implement (all step fns are pure and traced under jit):

    - ``configure_model() -> flax.linen.Module`` (or ``None`` and work with
      raw params via a custom ``init_params``)
    - ``configure_optimizers() -> optax.GradientTransformation``
    - ``training_step(ctx, batch) -> loss``  (log metrics via ``ctx.log``)
    - ``validation_step(ctx, batch) -> None | loss``
    - ``test_step(ctx, batch)``, ``predict_step(ctx, batch) -> outputs``
    - dataloaders: ``train_dataloader`` / ``val_dataloader`` /
      ``test_dataloader`` / ``predict_dataloader``
    """

    #: Residency dtype for float params (``None`` = leave as initialized,
    #: usually fp32).  Set to ``jnp.bfloat16`` (with an
    #: ``ops.optim.fp32_master``-wrapped optimizer) to keep the live
    #: params low-precision — deletes the per-step fp32->bf16 kernel
    #: casts from the compiled program while the fp32 master copy in the
    #: optimizer state preserves update precision.
    param_dtype = None

    #: Set False when ``training_step`` consumes no randomness (no
    #: dropout / ``ctx.make_rng``): the compiled train step then skips
    #: the per-step PRNG split+fold — scalar-core work that dominates
    #: microsecond-scale models.  Leave True (the safe default) for any
    #: stochastic module; a False-declaring module that calls
    #: ``ctx.make_rng`` raises at trace time.
    uses_rng = True

    def __init__(self):
        self.trainer = None
        self.model = None
        self._hparams = _HParams()
        self._example_batch = None

    # -- persistence across the driver→worker boundary ---------------------

    def __getstate__(self):
        state = self.__dict__.copy()
        state["trainer"] = None  # trainer holds live handles; re-bound remotely
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)

    # -- hyperparameters ---------------------------------------------------

    def save_hyperparameters(self, *args, **kwargs) -> None:
        """Record the calling constructor's arguments into ``self.hparams``."""
        frame = inspect.currentframe().f_back
        local_vars = frame.f_locals
        if args or kwargs:
            for a in args:
                if isinstance(a, dict):
                    self._hparams.update(a)
                elif isinstance(a, str) and a in local_vars:
                    self._hparams[a] = local_vars[a]
            self._hparams.update(kwargs)
            return
        init = type(self).__init__
        sig = inspect.signature(init)
        for name in sig.parameters:
            if name in ("self", "args", "kwargs"):
                continue
            if name in local_vars:
                self._hparams[name] = copy.deepcopy(local_vars[name])

    @property
    def hparams(self) -> _HParams:
        return self._hparams

    # -- model / optimizer configuration -----------------------------------

    def configure_model(self):
        """Return the flax module (or None for raw-param workflows)."""
        return None

    def configure_optimizers(self):
        raise NotImplementedError

    def configure_decode_model(self):
        """Serve-plane hook (ray_lightning_tpu/serve/): a flax module for
        the KV-cache generation path sharing this module's param tree.
        The module must accept the training forward's ``__call__`` (used
        for prefill, K/V captured via the ``kv_cache`` sow collection)
        and expose a ``decode(tokens, positions, k_caches, v_caches)``
        method (see models/gpt.py GPT.decode).  Default: the training
        model — override to strip training-only wrappers (remat,
        dropout) the way GPTLightningModule does."""
        return self.configure_model()

    def configure_draft(self, layers: "int | None" = None):
        """Speculative-decode hook (serve/engine.py): a smaller sibling
        flax module — fewer layers/heads, SAME tokenizer and param
        naming — whose param tree is a subtree of this module's, used
        as the draft model of the draft→verify speculative-decode loop.
        Must expose the same ``__call__`` (draft prefill) and
        ``decode`` surface as :meth:`configure_decode_model`'s module.
        ``layers`` optionally overrides the draft depth
        (``RLT_SPEC_DRAFT_LAYERS`` rides in through ``SpecConfig``,
        serve/spec.py).  Default: ``None`` — no draft available, the
        engine refuses ``spec=`` rather than silently serving without
        speculation.  See models/gpt.py for the layer-truncated
        weight-sharing reference implementation."""
        return None

    def configure_remat(self):
        """Planner-plane remat hook (core/remat.py): a ``RematSpec``
        describing this module's rematerialization ladder — which
        ``jax.checkpoint`` policies it supports, its current default,
        how to reconfigure it in place, and a per-policy cost probe
        (saved-activation bytes + recompute FLOPs from avals alone) —
        so ``Trainer(strategy="auto")`` can sweep recompute-vs-HBM
        tradeoffs as a scored axis instead of a hand A/B.  Default:
        ``None`` (no remat lever; the planner records the axis as
        ``remat_unsupported`` when a sweep was requested).  See
        models/gpt.py for the reference implementation."""
        return None

    def flops_per_step(self) -> "float | None":
        """Goodput-plane hook (telemetry/goodput.py): FLOPs one
        optimizer step executes over the global batch, the measured-MFU
        numerator.  Default ``None`` = the trainer prices the built
        train-step jaxpr itself (every ``dot_general``, forward +
        backward + update — core/remat.py ``step_dot_flops``), which is
        exact for matmul-dominated models.  Override when the analytic
        number is known (e.g. the 6·params·tokens transformer estimate)
        or the model's FLOPs are not dot-dominated."""
        return None

    def configure_mpmd(self):
        """MPMD-plane hook (ray_lightning_tpu/mpmd/): an ``MpmdSpec``
        describing this model as embed → N identical layers → head so
        the stage partitioner can slice it into per-stage programs
        (``Trainer(strategy="mpmd")``).  Models with a stacked-layer
        param tree (models/pipeline_gpt.py) implement this in a few
        lines; the default refuses with guidance."""
        raise NotImplementedError(
            f"{type(self).__name__} does not describe an MPMD "
            f"partition; implement configure_mpmd() returning an "
            f"ray_lightning_tpu.mpmd.partition.MpmdSpec (see "
            f"models/pipeline_gpt.py for the stacked-layer shape)")

    def setup_model(self) -> None:
        """Materialize ``self.model`` (idempotent; called on each process)."""
        if self.model is None:
            self.model = self.configure_model()

    def init_params(self, rng: jax.Array, batch: Any):
        """Initialize model variables from an example batch.

        Default: call ``model.init(rng, x)`` where ``x`` is ``batch[0]``
        for (input, target) tuples else the batch itself.  Override for
        models whose ``__call__`` takes a different signature.  Returns the
        full flax variables dict (``{'params': ..., possibly others}``).
        """
        self.setup_model()
        if self.model is None:
            raise NotImplementedError(
                "Provide configure_model() or override init_params().")
        x = batch[0] if isinstance(batch, (tuple, list)) else batch
        return self.model.init(rng, x)

    # -- steps (pure; traced) ----------------------------------------------

    def training_step(self, ctx: StepContext, batch) -> jax.Array:
        raise NotImplementedError

    def validation_step(self, ctx: StepContext, batch):
        return None

    def test_step(self, ctx: StepContext, batch):
        return self.validation_step(ctx, batch)

    def predict_step(self, ctx: StepContext, batch):
        if self.model is None:
            raise NotImplementedError
        x = batch[0] if isinstance(batch, (tuple, list)) else batch
        return ctx.apply(x)

    # -- data --------------------------------------------------------------

    def prepare_data(self) -> None:
        """Download / materialize data once per node (host-side hook)."""

    def setup(self, stage: str) -> None:
        """Per-process setup before dataloaders are requested."""

    def train_dataloader(self):
        return None

    def val_dataloader(self):
        return None

    def test_dataloader(self):
        return None

    def predict_dataloader(self):
        return None

    # -- host-side hooks (never traced) ------------------------------------

    def on_fit_start(self) -> None: ...
    def on_fit_end(self) -> None: ...
    def on_train_start(self) -> None: ...
    def on_train_end(self) -> None: ...
    def on_train_epoch_start(self) -> None: ...
    def on_train_epoch_end(self) -> None: ...
    def on_validation_epoch_start(self) -> None: ...
    def on_validation_epoch_end(self) -> None: ...
    def on_save_checkpoint(self, checkpoint: dict) -> None: ...

    def on_load_checkpoint(self, checkpoint: dict) -> None:
        """``checkpoint`` carries the top-level keys of
        :meth:`Trainer.dump_checkpoint` (epoch, global_step, hparams,
        callbacks, world_size, strategy).  ``checkpoint["state"]`` is
        present only when resuming from a single-file msgpack
        checkpoint; sharded (orbax) restores stream arrays straight to
        device shards, so the hook sees the metadata without a
        host-materialized state dict."""

    # -- trainer-delegated conveniences ------------------------------------

    @property
    def global_rank(self) -> int:
        return self.trainer.global_rank if self.trainer is not None else 0

    @property
    def local_rank(self) -> int:
        return self.trainer.local_rank if self.trainer is not None else 0

    @property
    def current_epoch(self) -> int:
        return self.trainer.current_epoch if self.trainer is not None else 0

    @property
    def global_step(self) -> int:
        return self.trainer.global_step if self.trainer is not None else 0

    def log(self, name: str, value, **kwargs) -> None:
        """Host-side logging from hooks (traced steps use ``ctx.log``)."""
        if self.trainer is not None:
            self.trainer._log_host_metric(name, value)
