"""Async per-step snapshotting off the critical path.

The loop engine calls :meth:`Snapshotter.maybe_snapshot` after every
optimizer step (``Trainer._engine_one`` / ``_engine_chunk``); on the
configured cadence it triggers ``Trainer.save_sharded_checkpoint`` with
orbax async enabled, so the only blocking cost on the training thread
is the device→host copy — the disk write proceeds behind subsequent
steps.

Backpressure is bounded by construction — at most ONE save is ever
outstanding, never an unbounded queue:

- single-process runs SKIP a cadence hit while the previous save is
  still writing (counted in ``rlt_snapshot_skipped_total``);
- multi-process runs must make the same save/skip decision on every
  rank (orbax per-shard saves are collective — a rank that skips while
  another saves deadlocks the fleet), and "is the previous save still
  writing" is a local, timing-dependent question.  So multi-process
  runs WAIT for the previous save instead of skipping — deterministic,
  still bounded at one outstanding save — and the wait is measured
  into ``rlt_snapshot_stall_seconds_total`` (the number the bench
  reports; near zero when the cadence out-paces the write).

**Failure hardening**: a failed async save must not kill training — a
flaky snapshot target (full disk, GCS blip) costs durability headroom,
not the run.  A save that raises is caught, counted
(``rlt_snapshot_failed_total``), and retried at the next cadence tick;
only ``ElasticConfig.max_snapshot_failures`` CONSECUTIVE failures
re-raise (a permanently broken target must not fail silently — the
elastic driver would otherwise keep "recovering" onto snapshots that
stopped landing).  The ``snapkill`` chaos fault (elastic/faults.py)
fires here, mid-async-write, so the uncommitted-step resume contract
is testable.

Instruments (metrics plane, PR 2): ``rlt_snapshot_total``,
``rlt_snapshot_skipped_total``, ``rlt_snapshot_failed_total``,
``rlt_snapshot_seconds_total`` (blocking host time of the save call),
and ``rlt_snapshot_stall_seconds_total``.  The same numbers accumulate
in :attr:`Snapshotter.stats` so benches and tests read them without
the metrics plane; the ``checkpoint`` span (utils/checkpoint.py)
already covers each save's blocking section in the trace.
"""

from __future__ import annotations

import logging
import time

from ray_lightning_tpu.telemetry import metrics as _metrics

_log = logging.getLogger(__name__)


class Snapshotter:
    """Cadence-driven async sharded snapshots for one fit stage."""

    def __init__(self, trainer, cfg):
        self.trainer = trainer
        self.cfg = cfg
        self.directory = cfg.resolve_dir(trainer.default_root_dir)
        #: cumulative counters mirrored into the metrics registry; read
        #: directly by bench_checkpoint and the chaos tests
        self.stats = {
            "snapshots": 0,
            "skipped": 0,
            "failed": 0,
            "save_seconds": 0.0,
            "stall_seconds": 0.0,
        }
        self._consecutive_failures = 0
        import jax
        self._multiprocess = jax.process_count() > 1

    def _count(self, name: str, value: float = 1.0) -> None:
        reg = _metrics.get_registry()
        if reg is not None:
            reg.counter(name).inc(value)

    def maybe_snapshot(self) -> bool:
        """One cadence check; returns True when a snapshot was taken.
        Collective in multi-process runs (every rank reaches the same
        decision from ``global_step`` alone)."""
        t = self.trainer
        n = self.cfg.snapshot_every_n_steps
        if n <= 0 or t.global_step <= 0 or t.global_step % n:
            return False
        ckpt = t._sharded_checkpointer(self.directory,
                                       max_to_keep=self.cfg.max_to_keep)
        if ckpt.saving_in_progress():
            if not self._multiprocess:
                # bounded backpressure: drop this cadence hit rather
                # than stacking saves behind a slow disk
                self.stats["skipped"] += 1
                self._count("rlt_snapshot_skipped_total")
                _log.debug("elastic snapshot at step %d skipped: "
                           "previous save still writing", t.global_step)
                return False
            # multi-process: the skip decision cannot be agreed without
            # a collective, so wait (still at most one outstanding save)
            # and make the cost visible
            t0 = time.monotonic()
            ckpt.wait()
            stall = time.monotonic() - t0
            self.stats["stall_seconds"] += stall
            self._count("rlt_snapshot_stall_seconds_total", stall)
            _log.info("elastic snapshot at step %d stalled %.3fs behind "
                      "the previous save", t.global_step, stall)
        t0 = time.monotonic()
        try:
            t.save_sharded_checkpoint(self.directory,
                                      max_to_keep=self.cfg.max_to_keep)
        except Exception:   # noqa: BLE001 - hardened: counted + retried
            self._consecutive_failures += 1
            self.stats["failed"] += 1
            self._count("rlt_snapshot_failed_total")
            limit = self.cfg.max_snapshot_failures
            if self._consecutive_failures >= limit:
                _log.error(
                    "elastic snapshot at step %d failed %d consecutive "
                    "times (limit %d); raising — the snapshot target is "
                    "broken, not flaky", t.global_step,
                    self._consecutive_failures, limit)
                raise
            _log.warning(
                "elastic snapshot at step %d failed (%d consecutive, "
                "limit %d); training continues, retrying next cadence "
                "tick", t.global_step, self._consecutive_failures,
                limit, exc_info=True)
            return False
        dt = time.monotonic() - t0
        self._consecutive_failures = 0
        self.stats["snapshots"] += 1
        self.stats["save_seconds"] += dt
        self._count("rlt_snapshot_total")
        self._count("rlt_snapshot_seconds_total", dt)
        # chaos hook: an armed snapkill fires HERE, while the async
        # orbax write is still in flight — the step dir never commits
        from ray_lightning_tpu.elastic.faults import (_elastic_restarts,
                                                      maybe_snapkill)
        maybe_snapkill(t.global_rank, t.global_step,
                       _elastic_restarts(t))
        return True
