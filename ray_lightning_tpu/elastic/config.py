"""Elastic-plane configuration.

``ElasticConfig`` turns on the fault-tolerance subsystem
(ray_lightning_tpu/elastic/): async per-step snapshots off the critical
path, reshardable restore of those snapshots onto a different topology,
and the shrink-to-continue driver that reacts to a dead rank by
rebuilding the fleet with the survivors instead of failing the run.

Construction paths (first match wins, mirroring TelemetryConfig /
CompileCacheConfig / CommPolicy):

- ``Trainer(elastic=ElasticConfig(...))`` — full control;
- ``Trainer(elastic=True)`` — defaults (snapshotting still needs
  ``snapshot_every_n_steps``/``RLT_ELASTIC_EVERY`` to be set);
- ``Trainer(elastic={...})`` — kwargs dict (enabled unless it says
  otherwise);
- ``RLT_ELASTIC=1`` (+ ``RLT_ELASTIC_EVERY=50``, ``RLT_ELASTIC_DIR``,
  ``RLT_ELASTIC_MAX_RESTARTS``, ``RLT_ELASTIC_MIN_WORKERS``,
  ``RLT_ELASTIC_KEEP``, ``RLT_ELASTIC_PRESERVE_BATCH``,
  ``RLT_ELASTIC_REDUNDANCY``, ``RLT_ELASTIC_REDUNDANCY_EVERY``,
  ``RLT_ELASTIC_SNAPSHOT_FAILURES``) — env knobs, read when the
  Trainer arg is ``None``.

The resolved config is a frozen dataclass pickled driver→worker with
the trainer; the env knobs additionally round-trip through
``worker_env()`` (plugins/xla.py) like ``RLT_COMM*`` does, so
worker-side tooling that consults ``RLT_ELASTIC*`` stays consistent.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Optional


def _env_flag(name: str, default: bool) -> bool:
    raw = os.environ.get(name, "").strip()
    if raw in ("0", "false", "False"):
        return False
    if raw in ("1", "true", "True"):
        return True
    return default


@dataclasses.dataclass(frozen=True)
class ElasticConfig:
    """How the run survives worker loss.

    enabled: master switch — snapshotting, fault injection plumbing and
        the shrink-to-continue driver all key off it.
    snapshot_every_n_steps: async sharded-snapshot cadence (0 = no
        periodic snapshots; the shrink driver then falls back to the
        original ``resume_from_checkpoint`` or a from-scratch restart).
    snapshot_dir: where snapshots land; ``None`` =
        ``<default_root_dir>/elastic``.  Must be visible to every worker
        process (shared FS or ``gs://...`` — orbax per-shard saves are
        collective).
    max_restarts: how many shrink-and-continue attempts before the
        original failure propagates.
    min_workers: never shrink the fleet below this.
    preserve_global_batch: rescale each surviving worker's loader batch
        by ``initial_workers / current_workers`` so the global batch
        (and therefore the optimization trajectory) is preserved across
        a shrink — the resume-with-fewer-workers redistribution the
        checkpoint re-shard already does for state (core/trainer.py).
    max_to_keep: snapshot retention (orbax ``max_to_keep``).
    redundancy: parity-redundant optimizer state (elastic/redundancy.py):
        each rank XORs the ZeRO-1 optimizer-state partitions of this
        many neighbor ranks into a parity block, enabling zero-replay
        reconstruct-and-continue on a single-rank loss.  0 (default)
        disables parity; snapshot replay is then the only recovery.
    redundancy_every_n_steps: parity refresh cadence piggybacked on the
        step — recovery resumes from the last completed tick, so 1
        (default) makes single-loss recovery exact at the current step
        while larger values amortize the ``k x shard_bytes`` wire cost.
    max_snapshot_failures: how many CONSECUTIVE async-snapshot save
        failures to absorb (counted, retried next cadence tick) before
        raising — a flaky snapshot target must not kill training, a
        permanently broken one must not fail silently.
    """

    enabled: bool = False
    snapshot_every_n_steps: int = 0
    snapshot_dir: Optional[str] = None
    max_restarts: int = 2
    min_workers: int = 1
    preserve_global_batch: bool = True
    max_to_keep: Optional[int] = 2
    redundancy: int = 0
    redundancy_every_n_steps: int = 1
    max_snapshot_failures: int = 3

    def __post_init__(self):
        if self.snapshot_every_n_steps < 0:
            raise ValueError("elastic snapshot_every_n_steps must be >= 0")
        if self.max_restarts < 0:
            raise ValueError("elastic max_restarts must be >= 0")
        if self.min_workers < 1:
            raise ValueError("elastic min_workers must be >= 1")
        if self.max_to_keep is not None and self.max_to_keep < 1:
            raise ValueError("elastic max_to_keep must be >= 1 or None")
        if self.redundancy < 0:
            raise ValueError("elastic redundancy must be >= 0")
        if self.redundancy_every_n_steps < 1:
            raise ValueError(
                "elastic redundancy_every_n_steps must be >= 1")
        if self.max_snapshot_failures < 1:
            raise ValueError("elastic max_snapshot_failures must be >= 1")

    # -- construction ----------------------------------------------------

    @classmethod
    def resolve(cls, value: Any) -> "ElasticConfig":
        if isinstance(value, cls):
            return value
        if isinstance(value, bool):
            return cls(enabled=value)
        if isinstance(value, dict):
            cfg = dict(value)
            cfg.setdefault("enabled", True)
            return cls(**cfg)
        if value is not None:
            raise TypeError(f"bad elastic config: {value!r}")
        keep_raw = os.environ.get("RLT_ELASTIC_KEEP", "").strip()
        return cls(
            enabled=_env_flag("RLT_ELASTIC", False),
            snapshot_every_n_steps=int(
                os.environ.get("RLT_ELASTIC_EVERY", "0") or 0),
            snapshot_dir=os.environ.get("RLT_ELASTIC_DIR") or None,
            max_restarts=int(
                os.environ.get("RLT_ELASTIC_MAX_RESTARTS", "2") or 2),
            min_workers=int(
                os.environ.get("RLT_ELASTIC_MIN_WORKERS", "1") or 1),
            preserve_global_batch=_env_flag(
                "RLT_ELASTIC_PRESERVE_BATCH", True),
            max_to_keep=int(keep_raw) if keep_raw else 2,
            redundancy=int(
                os.environ.get("RLT_ELASTIC_REDUNDANCY", "0") or 0),
            redundancy_every_n_steps=int(
                os.environ.get("RLT_ELASTIC_REDUNDANCY_EVERY", "1") or 1),
            max_snapshot_failures=int(
                os.environ.get("RLT_ELASTIC_SNAPSHOT_FAILURES", "3") or 3),
        )

    # -- env round-trip --------------------------------------------------

    def worker_env(self) -> dict:
        """Env mapping reproducing this config via :meth:`resolve` in a
        worker process (the pickled trainer already carries the config;
        the env keeps worker-side nested fits consistent)."""
        if not self.enabled:
            return {}
        env = {
            "RLT_ELASTIC": "1",
            "RLT_ELASTIC_EVERY": str(self.snapshot_every_n_steps),
            "RLT_ELASTIC_MAX_RESTARTS": str(self.max_restarts),
            "RLT_ELASTIC_MIN_WORKERS": str(self.min_workers),
            "RLT_ELASTIC_PRESERVE_BATCH":
                "1" if self.preserve_global_batch else "0",
            "RLT_ELASTIC_REDUNDANCY": str(self.redundancy),
            "RLT_ELASTIC_REDUNDANCY_EVERY":
                str(self.redundancy_every_n_steps),
            "RLT_ELASTIC_SNAPSHOT_FAILURES":
                str(self.max_snapshot_failures),
        }
        if self.snapshot_dir:
            env["RLT_ELASTIC_DIR"] = self.snapshot_dir
        if self.max_to_keep is not None:
            env["RLT_ELASTIC_KEEP"] = str(self.max_to_keep)
        return env

    # -- paths -----------------------------------------------------------

    def resolve_dir(self, default_root_dir: str) -> str:
        if self.snapshot_dir:
            return self.snapshot_dir
        return os.path.join(default_root_dir, "elastic")
