"""Parity-redundant optimizer state: zero-replay recovery (ROADMAP 4).

Shrink-to-continue (elastic/driver.py) survives a dead rank but pays a
full replay from the last durable snapshot.  This module closes that
gap with in-fleet redundancy over the state a dead rank takes with it:

- **What is rank-unique.**  Under ZeRO-1 the optimizer moments are
  sharded across data ranks (parallel/strategy.py ``opt_spec``) — a
  dead rank's shard exists nowhere else.  Params (replicated by the
  all-gather) and every other replicated leaf survive on any rank.
  The partition packer below derives this from the live shardings: a
  leaf that is not fully replicated contributes this process's
  addressable shards (with their global indices) to the rank's
  *unique blob*; fully-replicated leaves go into a *replicated blob*
  any one survivor can supply.

- **Parity, not replicas.**  On a configurable cadence piggybacked on
  the step (``ElasticConfig(redundancy_every_n_steps=...)``), each
  rank ships its unique blob to its parity holders over the cluster
  worker↔worker peer channel (cluster/peer.py — the same frames the
  MPMD activation exchange rides) and XORs the blobs of the ``k``
  neighbor ranks it covers into ONE parity block (``redundancy=k``):
  byte-wise XOR is dtype-agnostic and bit-exact, so
  encode→drop-one→decode round-trips exactly (elastic/selfcheck.py
  pins every rank position).  Storage overhead is one neighbor-shard
  parity block per rank; wire overhead is ``k x shard_bytes / cadence``
  per step, charged to the metrics plane as declared collective bytes
  (``parity_update`` next to ``grad_reduce_scatter`` et al.) and
  counted live in ``rlt_parity_bytes_total``.

- **Escrow.**  Each completed tick deposits this rank's recovery
  escrow — step, unique blob, replicated blob, parity block — into the
  worker-process escrow cell (cluster/worker_state.py).  The cell is
  served by the worker's *frame-reader thread*, so the driver can
  harvest it even while the main thread is wedged inside a collective
  that will never complete (the survivors' state at death time —
  exactly what a torn-down fleet otherwise loses).

- **Reconstruct-and-continue.**  On a classified single-rank death the
  elastic driver harvests survivor escrows before teardown
  (plugins/xla.py), recomputes the dead rank's unique blob as
  ``parity XOR (other covered members' escrowed blobs)``
  (:func:`build_recovery`), and hands the assembled in-memory state
  package to the N-1 attempt, which restores it directly into the new
  mesh (:func:`apply_recovery`) — no snapshot is read, and training
  resumes from the escrowed (current) step.  Snapshot replay remains
  the fallback for multi-rank loss, parity-disabled runs, or any gap
  in the escrow set; the route taken is reported in
  ``trainer._elastic_report["recovery"]`` (``parity|replay|scratch``).

The comm plane's ``[world, ...]`` error-feedback residual
(comm/collectives.py ``CommState``) reassembles at the OLD world size
and is re-bucketed N→M by the same mean-broadcast rule
elastic/reshard.py applies to snapshot restores.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Any, Callable, Optional

import numpy as np

from ray_lightning_tpu.cluster.peer import PeerTimeout
from ray_lightning_tpu.telemetry import metrics as _metrics
from ray_lightning_tpu.telemetry.spans import span

_log = logging.getLogger(__name__)

#: bound on one parity-tick peer receive: a peer that died mid-tick
#: must cost a skipped tick, not a wedged fleet
ENV_PARITY_TIMEOUT = "RLT_ELASTIC_PARITY_TIMEOUT_S"
DEFAULT_PARITY_TIMEOUT_S = 30.0

ESCROW_KIND = "rlt-parity-escrow"


def _key_str(entry) -> str:
    """One jax KeyPath entry → a stable string (same naming as
    elastic/reshard.py so escrow keys match orbax metadata paths)."""
    for attr in ("name", "key", "idx"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


def _leaf_paths(tree) -> list:
    import jax
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [("/".join(_key_str(p) for p in path), leaf)
            for path, leaf in flat]


def _norm_index(index, shape) -> tuple:
    """orbax-style shard index (tuple of slices) → ((start, stop), ...)
    pickles small and is hashable for piece dedup."""
    out = []
    for sl, dim in zip(index, shape):
        start, stop, _ = sl.indices(dim)
        out.append((int(start), int(stop)))
    # scalar leaves have empty indices
    return tuple(out)


# -- partition packing -------------------------------------------------------


def pack_partition(state, *, unique: bool) -> bytes:
    """Serialize this process's view of ``state``.

    ``unique=True``: only leaves that are NOT fully replicated — each
    contributes this process's addressable shards plus their global
    indices (the rank's ZeRO-1 partition; what parity must cover).
    ``unique=False``: the fully-replicated remainder (params, step,
    rng, ...), which any one survivor can supply.
    """
    import cloudpickle

    leaves: dict = {}
    for key, leaf in _leaf_paths(state):
        if not hasattr(leaf, "addressable_shards"):
            # python/numpy leaf: replicated by construction
            if not unique:
                arr = np.asarray(leaf)
                leaves[key] = {"shape": arr.shape, "dtype": str(arr.dtype),
                               "pieces": [((), arr)]}
            continue
        replicated = bool(leaf.sharding.is_fully_replicated)
        if replicated == unique:
            continue
        shape = tuple(leaf.shape)
        pieces = []
        if replicated:
            pieces.append((
                _norm_index((slice(None),) * len(shape), shape),
                np.asarray(leaf.addressable_shards[0].data)))
        else:
            seen = set()
            for sh in leaf.addressable_shards:
                idx = _norm_index(sh.index, shape)
                if idx in seen:
                    continue   # replica of a shard this process holds
                seen.add(idx)
                pieces.append((idx, np.asarray(sh.data)))
        leaves[key] = {"shape": shape,
                       "dtype": str(np.dtype(leaf.dtype)),
                       "pieces": pieces}
    return cloudpickle.dumps(leaves)


def unpack_partition(blob: bytes) -> dict:
    import cloudpickle
    return cloudpickle.loads(blob)


# -- XOR parity codec --------------------------------------------------------


def xor_blocks(blobs: list) -> bytes:
    """Byte-wise XOR of ``blobs`` zero-padded to the longest — the
    parity block.  XOR of uint8 views is dtype-agnostic and bit-exact,
    so any single missing blob is recoverable given the others and its
    recorded length (:func:`recover_block`)."""
    if not blobs:
        return b""
    n = max(len(b) for b in blobs)
    acc = np.zeros(n, dtype=np.uint8)
    for b in blobs:
        v = np.frombuffer(b, dtype=np.uint8)
        np.bitwise_xor(acc[:len(v)], v, out=acc[:len(v)])
    return acc.tobytes()


def recover_block(parity: bytes, others: list, length: int) -> bytes:
    """The missing member's blob: ``parity XOR others``, truncated to
    its recorded ``length`` (padding bytes XOR to zero)."""
    return xor_blocks([parity] + list(others))[:length]


class ParityGroup:
    """Who covers whom for ``redundancy=k`` on ``world`` ranks.

    Rank ``r`` holds ONE parity block over the unique blobs of its
    ``k`` next neighbors ``(r+1..r+k) mod world`` and ships its own
    blob to the ``k`` previous ranks — so any single dead rank is
    covered by ``k`` independent holders.
    """

    def __init__(self, rank: int, world: int, k: int):
        if world < 2 or k < 1:
            raise ValueError("parity needs world >= 2 and redundancy >= 1")
        self.rank = int(rank)
        self.world = int(world)
        self.k = min(int(k), world - 1)
        self.covers = [(rank + 1 + i) % world for i in range(self.k)]
        self.holders = [(rank - 1 - i) % world for i in range(self.k)]

    @staticmethod
    def holder_of(dead: int, world: int, k: int) -> int:
        """The canonical (nearest-preceding) parity holder for a dead
        rank."""
        del k
        return (dead - 1) % world


# -- transports --------------------------------------------------------------


class PeerParityTransport:
    """Parity exchange over the cluster worker↔worker peer channel
    (cluster/peer.py): sends ride ``worker_state.peer_send`` addressed
    by actor name, receives block on this process's peer mailbox."""

    def __init__(self, peer_names: list, rank: int, timeout_s: float):
        from ray_lightning_tpu.cluster import worker_state
        self.peer_names = list(peer_names)
        self.rank = int(rank)
        self.timeout_s = timeout_s
        self._mailbox = worker_state.peer_mailbox()

    def send(self, dst_rank: int, tag: tuple, wire) -> None:
        from ray_lightning_tpu.cluster import worker_state
        worker_state.peer_send(self.peer_names[dst_rank],
                               {"tag": tag, "wire": wire})

    def recv(self, tag: tuple):
        return self._mailbox.take(
            tag, self.timeout_s,
            who=f"rank {self.rank} parity tick",
            src="parity exchange (peer dead or ticks desynchronized)")


class LoopbackParityTransport:
    """In-process multi-"rank" transport for units/selfchecks: one
    shared mailbox dict keyed by rank."""

    def __init__(self, boxes: dict, rank: int, timeout_s: float = 2.0):
        self.boxes = boxes
        self.rank = int(rank)
        self.timeout_s = timeout_s

    def send(self, dst_rank: int, tag: tuple, wire) -> None:
        self.boxes[dst_rank].put(tag, wire)

    def recv(self, tag: tuple):
        return self.boxes[self.rank].take(tag, self.timeout_s,
                                          who=f"rank {self.rank} parity",
                                          src="loopback")


# -- worker-side manager -----------------------------------------------------


class RedundancyManager:
    """Cadence-driven parity maintenance for one fit stage.

    Created by ``Trainer._run_stage`` when the elastic config carries
    ``redundancy > 0`` and the fleet spans >1 process; ticked from the
    engine next to the snapshotter.  A tick that cannot complete (peer
    died mid-exchange, frames dropped) skips — the previous escrow
    stays valid and the run continues; recovery then resumes from the
    last COMPLETED tick's step.
    """

    def __init__(self, trainer, cfg, rank: int, world: int,
                 transport, store: Optional[Callable] = None):
        self.trainer = trainer
        self.cfg = cfg
        self.group = ParityGroup(rank, world, cfg.redundancy)
        self.every = max(1, int(cfg.redundancy_every_n_steps))
        self.transport = transport
        if store is None:
            from ray_lightning_tpu.cluster import worker_state
            store = worker_state.escrow_set
        self.store = store
        #: cumulative counters mirrored into the metrics registry;
        #: rank-0's copy rides elastic_stats() into _elastic_report
        self.stats = {"parity_ticks": 0, "parity_skipped": 0,
                      "parity_bytes": 0}

    def _count(self, name: str, value: float = 1.0) -> None:
        reg = _metrics.get_registry()
        if reg is not None:
            reg.counter(name).inc(value)

    def maybe_tick(self) -> bool:
        """One cadence check; True when a parity tick completed.  Every
        rank reaches the same decision from ``global_step`` alone (the
        exchange needs all ranks ticking the same steps)."""
        t = self.trainer
        if t.global_step <= 0 or t.global_step % self.every:
            return False
        step = int(t.global_step)
        with span("parity", step=step, k=self.group.k):
            try:
                self._tick(step)
            except PeerTimeout as e:
                self.stats["parity_skipped"] += 1
                self._count("rlt_parity_skipped_total")
                _log.warning("parity tick at step %d skipped: %s", step, e)
                return False
        return True

    def _pack(self, unique: bool) -> bytes:
        """One packing call (seam for units simulating rank-distinct
        partitions in a single process)."""
        return pack_partition(self.trainer.state, unique=unique)

    def _tick(self, step: int) -> None:
        t = self.trainer
        g = self.group
        unique = self._pack(unique=True)
        replicated = self._pack(unique=False)
        for h in g.holders:
            self.transport.send(h, ("parity", step, g.rank), unique)
        member_blobs = {}
        for m in g.covers:
            member_blobs[m] = self.transport.recv(("parity", step, m))
        parity = xor_blocks([member_blobs[m] for m in g.covers])
        module = getattr(t, "lightning_module", None)
        meta = {
            "epoch": int(t.current_epoch),
            "global_step": step,
            "world_size": g.world,
            "callbacks": {type(cb).__name__: cb.state_dict()
                          for cb in t.callbacks},
        }
        if module is not None and getattr(module, "hparams", None):
            meta["hparams"] = dict(module.hparams)
        wire = len(unique) * len(g.holders)
        self.stats["parity_ticks"] += 1
        self.stats["parity_bytes"] += wire
        self._count("rlt_parity_ticks_total")
        self._count("rlt_parity_bytes_total", wire)
        self.store({
            "kind": ESCROW_KIND,
            "rank": g.rank,
            "world": g.world,
            "k": g.k,
            "step": step,
            "epoch": int(t.current_epoch),
            "unique_blob": unique,
            "replicated_blob": replicated,
            "parity": parity,
            "parity_members": list(g.covers),
            "parity_lengths": {m: len(b)
                               for m, b in member_blobs.items()},
            "meta": meta,
            # cumulative tick counters ride the escrow so the driver's
            # report (and the bench) can still show the dead fleet's
            # parity overhead after teardown
            "stats": dict(self.stats),
        })


def declared_parity_bytes(abstract_opt, opt_shardings, k: int,
                          every: int) -> int:
    """Amortized per-step parity wire bytes from avals alone — what the
    trainer charges to the metrics plane as a declared collective
    (``parity_update``) next to the strategy's gradient traffic: each
    step pays ``k x unique-shard-bytes / cadence`` on average."""
    import jax

    shard_bytes = 0
    leaves = jax.tree_util.tree_leaves(abstract_opt)
    shs = jax.tree_util.tree_leaves(
        opt_shardings, is_leaf=lambda x: hasattr(x, "spec"))
    if len(shs) != len(leaves):
        return 0
    for aval, sh in zip(leaves, shs):
        if not hasattr(sh, "shard_shape"):
            continue
        shape = tuple(aval.shape)
        shard = tuple(sh.shard_shape(shape))
        if shard == shape:
            continue   # replicated: survives on every rank
        shard_bytes += (int(np.prod(shard, dtype=np.int64))
                        * np.dtype(aval.dtype).itemsize)
    return int(shard_bytes * max(1, k) / max(1, every))


# -- driver-side reconstruction ----------------------------------------------


def build_recovery(escrows: dict, dead: int, world: int,
                   k: int) -> tuple:
    """(package, reason): the in-memory recovery package for a
    single-rank loss, or (None, why-not).

    ``escrows`` maps OLD-fleet rank → the escrow harvested from that
    survivor's frame-reader thread.  Requires every survivor's escrow
    at one common step; the dead rank's unique blob is recovered from
    its nearest-preceding holder's parity block XOR the other covered
    members' escrowed blobs.
    """
    t0 = time.monotonic()
    survivors = [r for r in range(world) if r != dead]
    missing = [r for r in survivors if r not in escrows]
    if missing:
        return None, f"no escrow harvested from rank(s) {missing}"
    steps = {r: escrows[r].get("step") for r in survivors}
    if len(set(steps.values())) != 1:
        return None, f"escrow steps diverge across survivors: {steps}"
    step = steps[survivors[0]]
    holder = ParityGroup.holder_of(dead, world, k)
    esc_h = escrows.get(holder)
    if esc_h is None:
        return None, f"parity holder rank {holder} did not survive"
    members = list(esc_h.get("parity_members", ()))
    if dead not in members:
        return None, (f"holder rank {holder} parity covers {members}, "
                      f"not dead rank {dead}")
    lengths = esc_h.get("parity_lengths", {})
    if dead not in lengths:
        return None, f"holder parity lengths missing rank {dead}"
    try:
        others = [escrows[m]["unique_blob"] for m in members if m != dead]
        dead_blob = recover_block(esc_h["parity"], others, lengths[dead])
        leaves: dict = {}
        for blob in [escrows[r]["unique_blob"] for r in survivors] \
                + [dead_blob, escrows[survivors[0]]["replicated_blob"]]:
            for key, entry in unpack_partition(blob).items():
                slot = leaves.setdefault(
                    key, {"shape": tuple(entry["shape"]),
                          "dtype": entry["dtype"], "pieces": {}})
                for idx, arr in entry["pieces"]:
                    slot["pieces"][tuple(idx)] = arr
    except Exception as e:   # noqa: BLE001 - any gap falls back to replay
        return None, f"parity reconstruction failed: {e!r}"
    package = {
        "kind": ESCROW_KIND,
        "step": int(step),
        "epoch": int(escrows[survivors[0]].get("epoch", 0)),
        "world": int(world),
        "dead_rank": int(dead),
        "leaves": {key: {"shape": slot["shape"], "dtype": slot["dtype"],
                         "pieces": sorted(slot["pieces"].items())}
                   for key, slot in leaves.items()},
        "meta": dict(escrows[survivors[0]].get("meta", {})),
        # the dead fleet's cumulative parity counters (its workers never
        # returned a result package) — the driver folds these into
        # _elastic_report so the overhead that bought the recovery is
        # visible next to it
        "escrow_stats": dict(escrows[survivors[0]].get("stats", {})),
        "reconstruct_seconds": time.monotonic() - t0,
    }
    return package, None


def assemble_leaf(entry: dict) -> np.ndarray:
    """Global array from escrowed pieces; raises if the indices do not
    tile the full shape (a gap means the escrow set cannot express this
    leaf and the caller must fall back to replay)."""
    shape = tuple(entry["shape"])
    dtype = np.dtype(entry["dtype"])
    if shape == ():
        _idx, arr = entry["pieces"][0]
        return np.asarray(arr, dtype=dtype).reshape(())
    out = np.zeros(shape, dtype=dtype)
    filled = np.zeros(shape, dtype=bool)
    for idx, arr in entry["pieces"]:
        sl = tuple(slice(a, b) for a, b in idx) or (Ellipsis,)
        out[sl] = np.asarray(arr, dtype=dtype).reshape(out[sl].shape)
        filled[sl] = True
    if not filled.all():
        raise ValueError(
            f"escrowed pieces cover {int(filled.sum())}/{filled.size} "
            f"elements of shape {shape}")
    return out


# -- worker-side restore (the N-1 attempt) -----------------------------------


def apply_recovery(trainer, package: dict, module) -> None:
    """Restore the reconstructed in-memory state into the CURRENT mesh.

    Mirrors ``Trainer._restore_sharded`` minus the disk: every target
    leaf is assembled from escrowed pieces and placed per the live
    shardings via ``make_array_from_callback`` (each process supplies
    its own addressable shards).  The comm plane's ``[world, ...]``
    error-feedback residual re-buckets N→M by mean-broadcast exactly
    as elastic/reshard.py does for snapshot restores.
    """
    import jax

    state = trainer.state
    shardings = trainer._state_shardings
    flat_state, treedef = jax.tree_util.tree_flatten_with_path(state)
    sh_leaves = jax.tree_util.tree_leaves(
        shardings, is_leaf=lambda x: hasattr(x, "spec"))
    if len(sh_leaves) != len(flat_state):
        raise ValueError("shardings tree does not match the state tree")
    pkg_leaves = package["leaves"]
    new_leaves = []
    problems = []
    for (path, leaf), sh in zip(flat_state, sh_leaves):
        key = "/".join(_key_str(p) for p in path)
        entry = pkg_leaves.get(key)
        if entry is None:
            problems.append(f"{key}: missing from the recovery escrow")
            continue
        want = tuple(getattr(leaf, "shape", ()))
        got = tuple(entry["shape"])
        try:
            arr = assemble_leaf(entry)
        except ValueError as e:
            problems.append(f"{key}: {e}")
            continue
        if got != want:
            if _is_residual_path(key) and got[1:] == want[1:]:
                # stacked [world, ...] residual: old world N -> new M,
                # mean-broadcast (injected-correction sum preserved —
                # elastic/reshard.py rationale)
                _log.info(
                    "parity recovery: re-bucketing error-feedback "
                    "residual %s [%d, ...] -> [%d, ...]", key,
                    got[0], want[0])
                m = arr.astype(np.float32).mean(axis=0, keepdims=True)
                arr = np.broadcast_to(m, want).astype(entry["dtype"])
            else:
                problems.append(
                    f"{key}: escrowed shape {got} != target {want}")
                continue
        new_leaves.append(_place(arr, leaf, sh))
    if problems:
        raise ValueError(
            "recovery escrow does not restore onto this topology:\n  "
            + "\n  ".join(problems))
    trainer.state = jax.tree_util.tree_unflatten(
        treedef, new_leaves)
    trainer.global_step = int(package["step"])
    trainer.current_epoch = int(package["epoch"])
    meta = package.get("meta", {})
    cb_states = meta.get("callbacks", {})
    for cb in trainer.callbacks:
        st = cb_states.get(type(cb).__name__)
        if st:
            cb.load_state_dict(st)
    if module is not None:
        module.on_load_checkpoint(meta)
    for cb in trainer.callbacks:
        cb.on_load_checkpoint(trainer, module, meta)
    reg = _metrics.get_registry()
    if reg is not None:
        reg.counter("rlt_parity_restore_total").inc()
    _log.info("parity recovery: resumed in-memory at step %d "
              "(dead rank %d reconstructed from parity; no snapshot "
              "read)", package["step"], package.get("dead_rank", -1))


def _is_residual_path(key: str) -> bool:
    return key.startswith("opt_state/residual")


def _place(arr: np.ndarray, like, sh) -> Any:
    """Host array → device array under ``sh`` (every process runs this
    with the same global values, so addressable shards slice locally)."""
    import jax

    dtype = getattr(like, "dtype", arr.dtype)
    if not hasattr(sh, "shard_shape"):
        return jax.device_put(arr.astype(dtype))
    arr = np.asarray(arr, dtype=dtype)
    return jax.make_array_from_callback(
        arr.shape, sh, lambda idx: arr[idx])


def parity_timeout_s() -> float:
    raw = os.environ.get(ENV_PARITY_TIMEOUT, "").strip()
    try:
        return float(raw) if raw else DEFAULT_PARITY_TIMEOUT_S
    except ValueError:
        return DEFAULT_PARITY_TIMEOUT_S
