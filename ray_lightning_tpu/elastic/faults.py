"""Deterministic fault injection for the elastic plane.

Chaos testing a fault-tolerance subsystem needs *reproducible* faults:
"kill rank 1 at step 5" must mean exactly that, every run, so the
chaos tests (tests/test_failure.py) and the bench harness can assert on
what happens after.  A :class:`FaultSpec` names one fault:

- ``kill:rank=K,step=S[,code=C]`` — hard process exit (``os._exit``,
  no exception, no teardown — the preemption model);
- ``wedge:rank=K,step=S`` — the rank stops making progress WITHOUT
  dying (sleeps forever; the connection stays open, so only the
  heartbeat watchdog can name it);
- ``slow:rank=K,step=S[,seconds=T]`` — the rank stalls ``T`` seconds
  on every step from ``S`` on (a straggler, visible as skew in the
  telemetry summary).

:class:`FaultInjector` is a Callback armed with one spec; workers
auto-install it when ``RLT_FAULT`` is set in their environment
(``Trainer._run_stage``), so a test arms a fault with
``cpu_plugin(2, worker_env={"RLT_FAULT": "kill:rank=1,step=5"})`` and
nothing else.  kill/wedge take the whole process down — only arm them
on actor workers (a local in-process fit would kill the driver).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time
from typing import Optional

from ray_lightning_tpu.core.callbacks import Callback

_log = logging.getLogger(__name__)

ENV_FAULT = "RLT_FAULT"

VALID_KINDS = ("kill", "wedge", "slow")

#: distinctive default exit code so a driver log line can tell an
#: injected kill from a real crash
DEFAULT_EXIT_CODE = 43


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One deterministic fault: ``kind`` at (``rank``, ``step``)."""

    kind: str
    rank: int
    step: int
    exit_code: int = DEFAULT_EXIT_CODE
    seconds: float = 1.0

    def __post_init__(self):
        if self.kind not in VALID_KINDS:
            raise ValueError(
                f"fault kind {self.kind!r}; options: {VALID_KINDS}")
        if self.rank < 0:
            raise ValueError("fault rank must be >= 0")
        if self.step < 1:
            raise ValueError("fault step must be >= 1 (steps are "
                             "counted post-increment)")
        if self.seconds <= 0:
            raise ValueError("fault seconds must be positive")

    def should_fire(self, rank: int, step: int) -> bool:
        """kill/wedge fire once at the first step >= ``step`` on the
        target rank; slow fires on every such step."""
        return rank == self.rank and step >= self.step

    def describe(self) -> str:
        extra = ""
        if self.kind == "kill":
            extra = f",code={self.exit_code}"
        elif self.kind == "slow":
            extra = f",seconds={self.seconds}"
        return f"{self.kind}:rank={self.rank},step={self.step}{extra}"


def parse_fault(spec: str) -> FaultSpec:
    """``"kill:rank=1,step=5"`` → :class:`FaultSpec`.  Raises
    ``ValueError`` on malformed input (the selfcheck pins this)."""
    spec = spec.strip()
    if ":" not in spec:
        raise ValueError(
            f"fault spec {spec!r} must look like "
            f"'kill:rank=K,step=S' (kinds: {VALID_KINDS})")
    kind, _, rest = spec.partition(":")
    kw: dict = {}
    for part in rest.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"fault spec field {part!r} is not key=value")
        key, _, val = part.partition("=")
        key = key.strip()
        if key in ("rank", "step", "code", "exit_code"):
            kw["exit_code" if key == "code" else key] = int(val)
        elif key == "seconds":
            kw["seconds"] = float(val)
        else:
            raise ValueError(f"unknown fault spec field {key!r}")
    if "rank" not in kw or "step" not in kw:
        raise ValueError(f"fault spec {spec!r} needs rank= and step=")
    return FaultSpec(kind=kind.strip(), **kw)


class FaultInjector(Callback):
    """Callback arming one :class:`FaultSpec` against the live run."""

    needs_batch = False   # fires on (rank, step) alone

    def __init__(self, spec: FaultSpec):
        self.spec = spec
        self._fired = False

    def on_train_batch_end(self, trainer, module, outputs, batch,
                           batch_idx) -> None:
        spec = self.spec
        if not spec.should_fire(trainer.global_rank, trainer.global_step):
            return
        if spec.kind == "slow":
            _log.warning("fault injector: slowing rank %d at step %d "
                         "for %.2fs", spec.rank, trainer.global_step,
                         spec.seconds)
            time.sleep(spec.seconds)
            return
        if self._fired:
            return
        self._fired = True
        if spec.kind == "kill":
            _log.warning("fault injector: killing rank %d at step %d "
                         "(exit %d)", spec.rank, trainer.global_step,
                         spec.exit_code)
            # flush the log line before the no-cleanup exit
            logging.shutdown()
            os._exit(spec.exit_code)
        # wedge: stop making progress without dying — the connection
        # stays open, so only the heartbeat watchdog can diagnose it
        _log.warning("fault injector: wedging rank %d at step %d",
                     spec.rank, trainer.global_step)
        while True:
            time.sleep(3600)


def maybe_injector_from_env() -> Optional[FaultInjector]:
    """The ``RLT_FAULT`` auto-install hook (``Trainer._run_stage``):
    a malformed spec raises immediately — a chaos test whose fault never
    arms must fail loudly, not pass vacuously."""
    raw = os.environ.get(ENV_FAULT, "").strip()
    if not raw:
        return None
    return FaultInjector(parse_fault(raw))
