"""Deterministic fault injection for the elastic plane (tier 2).

Chaos testing a fault-tolerance subsystem needs *reproducible* faults:
"kill rank 1 at step 5" must mean exactly that, every run, so the
chaos tests (tests/test_failure.py) and the bench harness can assert on
what happens after.  A :class:`FaultSpec` names one fault:

- ``kill:rank=K,step=S[,code=C]`` — hard process exit (``os._exit``,
  no exception, no teardown — the preemption model);
- ``wedge:rank=K,step=S`` — the rank stops making progress WITHOUT
  dying (sleeps forever; the connection stays open, so only the
  heartbeat watchdog can name it);
- ``slow:rank=K,step=S[,seconds=T,count=N]`` — the rank stalls ``T``
  seconds on every step from ``S`` on (a straggler, visible as skew in
  the telemetry summary).  ``count=N`` (N > 1) bounds the straggler to
  steps ``[S, S+N)`` so it CLEARS — the incident plane's open-then-
  close path needs a fault with an end;
- ``snapkill:rank=K,step=S[,code=C]`` — hard exit *mid-async-snapshot
  write*: fires inside ``Snapshotter.maybe_snapshot`` right after the
  orbax save is dispatched, so the step directory exists but never
  commits — the case the "durable = committed only" resume contract
  (elastic/driver.py ``latest_snapshot_step``) must absorb;
- ``peerdrop:rank=K,step=S[,count=N]`` — drop the next N inbound
  peer-channel frames on the rank (cluster/worker_state.py) — the
  lossy-fabric case the peer retry/backoff and the parity tick's
  skip-and-continue must absorb.

``RLT_FAULT`` accepts a semicolon-separated *list* of specs
(``kill:rank=1,step=5;kill:rank=2,step=9``) so a chaos matrix —
double-kill, kill-after-drop — is one env var.  Parse errors name the
offending clause.  Every spec also takes ``restart=R``: arm only
during elastic attempt R — a replayed segment re-crosses the fault
step, so ``restart=0`` is how "exactly one preemption" stays
expressible when recovery rewinds past the kill.

:class:`FaultInjector` is a Callback armed with the spec list; workers
auto-install it when ``RLT_FAULT`` is set in their environment
(``Trainer._run_stage``), so a test arms faults with
``cpu_plugin(2, worker_env={"RLT_FAULT": "kill:rank=1,step=5"})`` and
nothing else.  kill/wedge/snapkill take the whole process down — only
arm them on actor workers (a local in-process fit would kill the
driver).  ``snapkill`` fires from the snapshot path, not the callback:
the snapshotter consults :func:`maybe_snapkill` while its async save
is in flight.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time
from typing import List, Optional

from ray_lightning_tpu.core.callbacks import Callback

_log = logging.getLogger(__name__)

ENV_FAULT = "RLT_FAULT"

VALID_KINDS = ("kill", "wedge", "slow", "snapkill", "peerdrop")

#: distinctive default exit code so a driver log line can tell an
#: injected kill from a real crash
DEFAULT_EXIT_CODE = 43


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One deterministic fault: ``kind`` at (``rank``, ``step``)."""

    kind: str
    rank: int
    step: int
    exit_code: int = DEFAULT_EXIT_CODE
    seconds: float = 1.0
    count: int = 1
    #: arm only on this elastic restart (None = every attempt).  A
    #: replayed segment re-crosses the fault step; ``restart=0`` makes
    #: "one preemption" expressible in a deterministic harness.
    restart: Optional[int] = None

    def __post_init__(self):
        if self.kind not in VALID_KINDS:
            raise ValueError(
                f"fault kind {self.kind!r}; options: {VALID_KINDS}")
        if self.rank < 0:
            raise ValueError("fault rank must be >= 0")
        if self.step < 1:
            raise ValueError("fault step must be >= 1 (steps are "
                             "counted post-increment)")
        if self.seconds <= 0:
            raise ValueError("fault seconds must be positive")
        if self.count < 1:
            raise ValueError("fault count must be >= 1")

    def should_fire(self, rank: int, step: int,
                    restarts: int = 0) -> bool:
        """kill/wedge/snapkill/peerdrop fire once at the first step >=
        ``step`` on the target rank; slow fires on every such step —
        bounded to steps ``[step, step + count)`` when ``count > 1``,
        so a straggler that CLEARS (the incident plane's close path)
        is expressible; the ``count=1`` default keeps the legacy
        unbounded straggler.  With ``restart=R`` set, only during
        elastic attempt R."""
        if self.restart is not None and restarts != self.restart:
            return False
        if rank != self.rank or step < self.step:
            return False
        if self.kind == "slow" and self.count > 1 \
                and step >= self.step + self.count:
            return False
        return True

    def describe(self) -> str:
        extra = ""
        if self.kind in ("kill", "snapkill"):
            extra = f",code={self.exit_code}"
        elif self.kind == "slow":
            extra = f",seconds={self.seconds}"
            if self.count > 1:
                extra += f",count={self.count}"
        elif self.kind == "peerdrop":
            extra = f",count={self.count}"
        if self.restart is not None:
            extra += f",restart={self.restart}"
        return f"{self.kind}:rank={self.rank},step={self.step}{extra}"


def parse_fault(spec: str) -> FaultSpec:
    """``"kill:rank=1,step=5"`` → :class:`FaultSpec`.  Raises
    ``ValueError`` on malformed input (the selfcheck pins this)."""
    spec = spec.strip()
    if ":" not in spec:
        raise ValueError(
            f"fault spec {spec!r} must look like "
            f"'kill:rank=K,step=S' (kinds: {VALID_KINDS})")
    kind, _, rest = spec.partition(":")
    kw: dict = {}
    for part in rest.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"fault spec field {part!r} is not key=value")
        key, _, val = part.partition("=")
        key = key.strip()
        if key in ("rank", "step", "code", "exit_code", "count",
                   "restart"):
            kw["exit_code" if key == "code" else key] = int(val)
        elif key == "seconds":
            kw["seconds"] = float(val)
        else:
            raise ValueError(f"unknown fault spec field {key!r}")
    if "rank" not in kw or "step" not in kw:
        raise ValueError(f"fault spec {spec!r} needs rank= and step=")
    return FaultSpec(kind=kind.strip(), **kw)


def parse_faults(raw: str) -> List[FaultSpec]:
    """Semicolon-separated fault list → specs; a bad clause raises
    naming ITSELF, not the whole string (the chaos matrix's parse
    contract)."""
    specs = []
    for clause in raw.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        try:
            specs.append(parse_fault(clause))
        except ValueError as e:
            raise ValueError(
                f"bad fault clause {clause!r} in {ENV_FAULT}: {e}"
            ) from e
    if not specs:
        raise ValueError(f"{ENV_FAULT} is set but names no fault")
    return specs


def _die(spec: FaultSpec, step: int, where: str) -> None:
    _log.warning("fault injector: killing rank %d at step %d %s "
                 "(exit %d)", spec.rank, step, where, spec.exit_code)
    # flush the log line before the no-cleanup exit
    logging.shutdown()
    os._exit(spec.exit_code)


class FaultInjector(Callback):
    """Callback arming one or more :class:`FaultSpec` against the run."""

    needs_batch = False   # fires on (rank, step) alone

    def __init__(self, specs):
        if isinstance(specs, FaultSpec):
            specs = [specs]
        self.specs = list(specs)
        self._fired: set = set()

    @property
    def spec(self) -> FaultSpec:
        """First spec (back-compat for single-fault callers)."""
        return self.specs[0]

    def on_train_batch_end(self, trainer, module, outputs, batch,
                           batch_idx) -> None:
        rank, step = trainer.global_rank, trainer.global_step
        restarts = _elastic_restarts(trainer)
        for i, spec in enumerate(self.specs):
            if spec.kind == "snapkill" \
                    or not spec.should_fire(rank, step, restarts):
                continue   # snapkill fires from the snapshot path
            if spec.kind == "slow":
                _log.warning("fault injector: slowing rank %d at step %d "
                             "for %.2fs", spec.rank, step, spec.seconds)
                time.sleep(spec.seconds)
                continue
            if i in self._fired:
                continue
            self._fired.add(i)
            if spec.kind == "kill":
                _die(spec, step, "")
            elif spec.kind == "peerdrop":
                from ray_lightning_tpu.cluster import worker_state
                _log.warning(
                    "fault injector: dropping the next %d inbound peer "
                    "frames on rank %d (step %d)", spec.count, spec.rank,
                    step)
                worker_state.arm_peer_drop(spec.count)
            else:
                # wedge: stop making progress without dying — the
                # connection stays open, so only the heartbeat watchdog
                # can diagnose it
                _log.warning("fault injector: wedging rank %d at step %d",
                             spec.rank, step)
                while True:
                    time.sleep(3600)


def _elastic_restarts(trainer) -> int:
    return (getattr(trainer, "_elastic_state", None) or {}).get(
        "restarts", 0)


def maybe_snapkill(rank: int, step: int, restarts: int = 0) -> None:
    """Snapshot-path hook (elastic/snapshot.py): hard-exit NOW if an
    armed ``snapkill`` spec matches — called while the async orbax
    write is in flight, so the save never commits."""
    raw = os.environ.get(ENV_FAULT, "").strip()
    if not raw or "snapkill" not in raw:
        return
    for spec in parse_faults(raw):
        if spec.kind == "snapkill" \
                and spec.should_fire(rank, step, restarts):
            _die(spec, step, "mid-async-snapshot write")


def maybe_injector_from_env() -> Optional[FaultInjector]:
    """The ``RLT_FAULT`` auto-install hook (``Trainer._run_stage``):
    a malformed spec raises immediately — a chaos test whose fault never
    arms must fail loudly, not pass vacuously."""
    raw = os.environ.get(ENV_FAULT, "").strip()
    if not raw:
        return None
    return FaultInjector(parse_faults(raw))
