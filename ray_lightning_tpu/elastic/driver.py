"""Shrink-to-continue: the driver-side reaction to a lost worker.

The reference's failure story (SURVEY.md §5) ends at "raise on the
driver"; the elastic driver goes the rest of the way.  When a fit
attempt fails because a rank *died* (process gone / connection lost /
heartbeat hard-timeout — NOT a deterministic user exception, which
still propagates), the driver:

1. tears down the surviving actors (the plugin's normal teardown —
   every attempt gets a fresh fleet, so a wedged-but-alive rank is
   removed the same way a dead one is);
2. shrinks ``plugin.num_workers`` by the number of dead ranks (at
   least one), bounded by ``min_workers``/``max_restarts``;
3. finds the latest durable elastic snapshot (orbax only lists
   committed steps, so a save the dead fleet never finalized is
   invisible) and points the resume at it — falling back to the
   original ``ckpt_path`` (or a from-scratch restart) when no snapshot
   landed;
4. re-runs the attempt: fresh actors, fresh PJRT rendezvous on the new
   world size, reshard-restore into the new mesh
   (elastic/reshard.py), per-worker batch rescaled so the global batch
   is preserved (``Trainer._elastic_rescale_loader``), training
   continuing to ``max_steps``.  Recompiles for the new topology
   warm-start through the persistent compile cache (compile/) — the
   topology namespace may be cold but the driver's cache dir survives
   the fleet.

``rlt_restarts_total`` and the per-rank ``rlt_worker_alive`` gauges
(telemetry/aggregator.py) put the shrink on ``/metrics`` so dashboards
see fleet health, not just driver-log text.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

from ray_lightning_tpu.telemetry.aggregator import WorkerHeartbeatTimeout

_log = logging.getLogger(__name__)

#: substrings of a failure message that mean "the process is gone"
#: even when the liveness probe could not say so (backends whose
#: ``alive()`` returns None)
_DEATH_MARKERS = ("connection lost", "died", "never connected",
                  "heartbeat")


def _restartable(err: BaseException, dead_ranks: list) -> bool:
    """A failure the elastic driver may absorb: a dead process, a lost
    connection, or a heartbeat hard-timeout.  Deterministic user
    exceptions re-raise — shrinking would just re-run the bug."""
    if dead_ranks:
        return True
    if isinstance(err, WorkerHeartbeatTimeout):
        return True
    msg = str(err).lower()
    return any(m in msg for m in _DEATH_MARKERS)


def _dump_flights(plugin, err: BaseException, dead_ranks: list) -> None:
    """Black-box dumps at death-classification time (telemetry/
    flight.py): the classified cause lands in ``flight_<rank>.json``
    next to each dead rank's last spans/heartbeats, so the postmortem
    starts from evidence instead of the silent gap a torn-down fleet
    otherwise leaves.  Falls back to every known rank when the probe
    could not name the dead one (the cause still says why).  No-op
    without telemetry; never raises into failure handling."""
    agg = getattr(plugin, "_telemetry_agg", None)
    if agg is None:
        return
    try:
        cause = (f"elastic death classification: {type(err).__name__}: "
                 f"{str(err).splitlines()[0][:300]}"
                 f" (dead ranks {dead_ranks or 'unknown'})")
        ranks = dead_ranks or agg.flight.ranks()
        agg.dump_flights([r for r in ranks if r >= 0], cause)
    except Exception:
        _log.warning("flight dump at death classification failed",
                     exc_info=True)


def latest_snapshot_step(directory: str) -> Optional[int]:
    """Latest COMMITTED snapshot step under ``directory`` (None when
    the directory is empty or absent)."""
    from ray_lightning_tpu.utils.checkpoint import ShardedCheckpointer
    if not ShardedCheckpointer.is_sharded_checkpoint(directory):
        return None
    ckpt = ShardedCheckpointer(directory)
    try:
        return ckpt.latest_step()
    finally:
        ckpt.close()


def run_elastic_fit(plugin, trainer, module, datamodule,
                    ckpt_path: Optional[str]):
    """Drive ``plugin._run_attempt`` with shrink-and-continue retries.

    Returns the (eventually) successful attempt's result; sets
    ``trainer._elastic_report`` with the restart history.
    """
    cfg = trainer.elastic
    snap_dir = cfg.resolve_dir(trainer.default_root_dir)
    initial = plugin.num_workers
    restarts = 0
    report = {"initial_workers": initial, "workers": initial,
              "restarts": 0, "resumed_step": None}
    while True:
        # rides the pickled trainer to the workers: the loader rescale
        # and the worker-side stats both read it
        trainer._elastic_state = dict(report)
        plugin._elastic_restarts = restarts
        try:
            result = plugin._run_attempt(trainer, module, datamodule,
                                         "fit", ckpt_path)
        except BaseException as err:   # noqa: BLE001 - classified below
            dead = list(getattr(plugin, "_last_dead_ranks", ()) or ())
            _dump_flights(plugin, err, dead)
            if not _restartable(err, dead):
                raise
            restarts += 1
            shrink = max(1, len(dead))
            new_workers = plugin.num_workers - shrink
            if restarts > cfg.max_restarts:
                _log.error(
                    "elastic: restart budget exhausted (%d); raising",
                    cfg.max_restarts)
                raise
            if new_workers < cfg.min_workers:
                _log.error(
                    "elastic: shrinking %d -> %d would go below "
                    "min_workers=%d; raising", plugin.num_workers,
                    new_workers, cfg.min_workers)
                raise
            step = latest_snapshot_step(snap_dir)
            if step is not None:
                resume = os.path.join(snap_dir, str(step))
            else:
                resume = ckpt_path
                _log.warning(
                    "elastic: no durable snapshot under %s; restarting "
                    "from %s", snap_dir,
                    resume or "scratch (step 0)")
            _log.warning(
                "elastic: worker failure (%s: %s); dead ranks %s — "
                "shrinking %d -> %d workers (restart %d/%d) and "
                "resuming from %s",
                type(err).__name__, str(err).splitlines()[0][:200],
                dead or "unknown", plugin.num_workers, new_workers,
                restarts, cfg.max_restarts, resume or "scratch")
            plugin.num_workers = new_workers
            # drop stale queue traffic from the dead fleet so a relayed
            # callable from attempt k never executes during attempt k+1
            backend = getattr(plugin, "_backend", None)
            if backend is not None:
                while backend.queue_get_nowait() is not None:
                    pass
            ckpt_path = resume
            report = {"initial_workers": initial,
                      "workers": new_workers, "restarts": restarts,
                      "resumed_step": step, "resumed_from": resume}
            continue
        report.update(getattr(trainer, "_elastic_worker_stats", None)
                      or {})
        trainer._elastic_report = report
        if restarts:
            _log.info("elastic: fit completed after %d restart(s) on "
                      "%d/%d workers (resumed from step %s)", restarts,
                      report["workers"], initial,
                      report.get("resumed_step"))
        return result
