"""Two-tier recovery: the driver-side reaction to a lost worker.

The reference's failure story (SURVEY.md §5) ends at "raise on the
driver"; the elastic driver goes the rest of the way.  When a fit
attempt fails because a rank *died* (process gone / connection lost /
heartbeat hard-timeout — NOT a deterministic user exception, which
still propagates), the driver routes between two recovery tiers:

**Tier 1 — reconstruct-and-continue (zero replay).**  With parity
redundancy on (``ElasticConfig(redundancy=k)``) and exactly ONE dead
rank, the survivors' recovery escrows — harvested by the plugin from
each worker's frame-reader thread BEFORE teardown, so a wedged main
thread cannot withhold them — carry everything the dead rank took with
it: the dead ZeRO-1 optimizer shard is recomputed from its holder's
parity block XOR the other covered members' escrowed shards
(elastic/redundancy.py :func:`~ray_lightning_tpu.elastic.redundancy.\
build_recovery`), the fleet reshards to N-1, and the next attempt
restores the assembled in-memory state at the escrowed (current) step
— the snapshot directory is never read.

**Tier 2 — snapshot replay.**  Multi-rank loss, parity off, or any gap
in the escrow set (a survivor that never completed a tick, diverging
tick steps) falls back to the PR 7 path: find the latest durable
elastic snapshot (orbax only lists committed steps, so a save the dead
fleet never finalized — the ``snapkill`` chaos case — is invisible)
and reshard-restore it; with no snapshot at all, restart from the
original ``ckpt_path`` or from scratch.

Either way the attempt re-runs with a fresh fleet: new actors, fresh
PJRT rendezvous on the new world size, per-worker batch rescaled so
the global batch is preserved (``Trainer._elastic_rescale_loader``),
training continuing to ``max_steps``.  Recompiles for the new topology
warm-start through the persistent compile cache (compile/).

The route taken lands everywhere a postmortem looks:
``trainer._elastic_report["recovery"]`` (``parity|replay|scratch``),
the classified-death flight dumps (``recovery=...`` in the cause
line), and the driver-side ``rlt_recovery_mode`` /
``rlt_recovery_seconds`` series next to ``rlt_restarts_total`` and the
per-rank ``rlt_worker_alive`` gauges on ``/metrics``.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Optional

from ray_lightning_tpu.telemetry.aggregator import WorkerHeartbeatTimeout

_log = logging.getLogger(__name__)

#: substrings of a failure message that mean "the process is gone"
#: even when the liveness probe could not say so (backends whose
#: ``alive()`` returns None)
_DEATH_MARKERS = ("connection lost", "died", "never connected",
                  "heartbeat")


def _restartable(err: BaseException, dead_ranks: list) -> bool:
    """A failure the elastic driver may absorb: a dead process, a lost
    connection, or a heartbeat hard-timeout.  Deterministic user
    exceptions re-raise — shrinking would just re-run the bug."""
    if dead_ranks:
        return True
    if isinstance(err, WorkerHeartbeatTimeout):
        return True
    msg = str(err).lower()
    return any(m in msg for m in _DEATH_MARKERS)


def _dump_flights(plugin, err: BaseException, dead_ranks: list,
                  recovery: Optional[str] = None) -> None:
    """Black-box dumps at death-classification time (telemetry/
    flight.py): the classified cause AND the chosen recovery route land
    in ``flight_<rank>.json`` next to each dead rank's last spans/
    heartbeats, so the postmortem starts from evidence instead of the
    silent gap a torn-down fleet otherwise leaves.  Falls back to every
    known rank when the probe could not name the dead one (the cause
    still says why).  No-op without telemetry; never raises into
    failure handling."""
    agg = getattr(plugin, "_telemetry_agg", None)
    if agg is None:
        return
    try:
        cause = (f"elastic death classification: {type(err).__name__}: "
                 f"{str(err).splitlines()[0][:300]}"
                 f" (dead ranks {dead_ranks or 'unknown'})")
        if recovery is not None:
            cause += f" recovery={recovery}"
        ranks = dead_ranks or agg.flight.ranks()
        agg.dump_flights([r for r in ranks if r >= 0], cause)
    except Exception:
        _log.warning("flight dump at death classification failed",
                     exc_info=True)


def latest_snapshot_step(directory: str) -> Optional[int]:
    """Latest COMMITTED snapshot step under ``directory`` (None when
    the directory is empty or absent)."""
    from ray_lightning_tpu.utils.checkpoint import ShardedCheckpointer
    if not ShardedCheckpointer.is_sharded_checkpoint(directory):
        return None
    ckpt = ShardedCheckpointer(directory)
    try:
        return ckpt.latest_step()
    finally:
        ckpt.close()


def _route_recovery(plugin, trainer, cfg, dead: list,
                    snap_dir: str, orig_ckpt: Optional[str]) -> dict:
    """Choose the recovery tier for one classified failure.

    Returns ``{"mode", "package", "resume", "step", "why"}`` — mode is
    ``parity`` (in-memory package attached), ``replay`` (a snapshot or
    the original ckpt_path to restore), or ``scratch``.
    """
    from ray_lightning_tpu.elastic import redundancy

    if cfg.redundancy > 0 and len(dead) == 1:
        escrows = dict(getattr(plugin, "_last_escrows", None) or {})
        package, why = redundancy.build_recovery(
            escrows, dead[0], plugin.num_workers, cfg.redundancy)
        if package is not None:
            return {"mode": "parity", "package": package, "resume": None,
                    "step": package["step"], "why": None}
        _log.warning("elastic: parity recovery unavailable (%s); "
                     "falling back to snapshot replay", why)
    elif cfg.redundancy > 0:
        _log.warning("elastic: parity covers single-rank loss only "
                     "(dead ranks %s); falling back to snapshot replay",
                     dead or "unknown")
    step = latest_snapshot_step(snap_dir)
    if step is not None:
        return {"mode": "replay", "package": None,
                "resume": os.path.join(snap_dir, str(step)),
                "step": step, "why": None}
    if orig_ckpt:
        return {"mode": "replay", "package": None, "resume": orig_ckpt,
                "step": None, "why": None}
    return {"mode": "scratch", "package": None, "resume": None,
            "step": None, "why": None}


def run_elastic_fit(plugin, trainer, module, datamodule,
                    ckpt_path: Optional[str]):
    """Drive ``plugin._run_attempt`` with two-tier recovery retries.

    Returns the (eventually) successful attempt's result; sets
    ``trainer._elastic_report`` with the restart history and the
    recovery route taken.
    """
    cfg = trainer.elastic
    snap_dir = cfg.resolve_dir(trainer.default_root_dir)
    initial = plugin.num_workers
    orig_ckpt = ckpt_path
    trainer._elastic_recovery = None   # never inherit a stale package
    restarts = 0
    decision_s = None
    report = {"initial_workers": initial, "workers": initial,
              "restarts": 0, "resumed_step": None}
    while True:
        # rides the pickled trainer to the workers: the loader rescale
        # and the worker-side stats both read it
        trainer._elastic_state = dict(report)
        plugin._elastic_restarts = restarts
        try:
            result = plugin._run_attempt(trainer, module, datamodule,
                                         "fit", ckpt_path)
        except BaseException as err:   # noqa: BLE001 - classified below
            dead = list(getattr(plugin, "_last_dead_ranks", ()) or ())
            if not _restartable(err, dead):
                _dump_flights(plugin, err, dead)
                raise
            restarts += 1
            shrink = max(1, len(dead))
            if dead and len(dead) >= plugin.num_workers:
                # full-fleet loss: when the COORDINATOR rank dies, the
                # survivors' jax.distributed clients abort with it —
                # one preemption, N-1 collateral deaths.  Count one and
                # keep going (the restart budget still bounds repeats);
                # parity cannot help here (no survivor escrowed), so
                # the route below falls to replay.
                _log.warning(
                    "elastic: full-fleet loss (%d/%d ranks dead — a "
                    "coordinator death takes the survivors with it); "
                    "counting one preemption and shrinking by 1",
                    len(dead), plugin.num_workers)
                shrink = 1
            new_workers = plugin.num_workers - shrink
            if restarts > cfg.max_restarts:
                _dump_flights(plugin, err, dead)
                _log.error(
                    "elastic: restart budget exhausted (%d); raising",
                    cfg.max_restarts)
                raise
            if new_workers < cfg.min_workers:
                _dump_flights(plugin, err, dead)
                _log.error(
                    "elastic: shrinking %d -> %d would go below "
                    "min_workers=%d; raising", plugin.num_workers,
                    new_workers, cfg.min_workers)
                raise
            t0 = time.monotonic()
            route = _route_recovery(plugin, trainer, cfg, dead,
                                    snap_dir, orig_ckpt)
            decision_s = time.monotonic() - t0
            _dump_flights(plugin, err, dead, recovery=route["mode"])
            trainer._elastic_recovery = route["package"]
            plugin._elastic_recovery_mode = route["mode"]
            plugin._elastic_recovery_seconds = decision_s
            # replayed-step badput (telemetry/goodput.py): how many
            # steps the resumed attempt re-executes because the resume
            # point is behind the crash step.  Parity reconstructs AT
            # the crash step (→ ~0); snapshot replay resumes at the
            # last durable snapshot (→ crash_step - resumed_step).
            # The crash step is the failed fleet's last scraped
            # rlt_steps_total, read off the attempt's aggregator.
            crash_step = None
            agg = getattr(plugin, "_telemetry_agg", None)
            if agg is not None:
                try:
                    steps = [b["step"] for b in
                             agg.metrics_briefs().values()
                             if b.get("step") is not None]
                    crash_step = max(steps) if steps else None
                except Exception:   # accounting must never block recovery
                    crash_step = None
            replayed = 0
            if crash_step is not None:
                replayed = max(0, int(crash_step)
                               - int(route["step"] or 0))
            plugin._elastic_replayed_steps = replayed
            resume = route["resume"]
            if route["mode"] == "scratch":
                _log.warning(
                    "elastic: no durable snapshot under %s and no "
                    "parity escrow; restarting from scratch (step 0)",
                    snap_dir)
            _log.warning(
                "elastic: worker failure (%s: %s); dead ranks %s — "
                "shrinking %d -> %d workers (restart %d/%d), recovery "
                "via %s from step %s",
                type(err).__name__, str(err).splitlines()[0][:200],
                dead or "unknown", plugin.num_workers, new_workers,
                restarts, cfg.max_restarts, route["mode"],
                route["step"] if route["step"] is not None else "0")
            plugin.num_workers = new_workers
            # drop stale queue traffic from the dead fleet so a relayed
            # callable from attempt k never executes during attempt k+1
            backend = getattr(plugin, "_backend", None)
            if backend is not None:
                while backend.queue_get_nowait() is not None:
                    pass
            ckpt_path = resume
            report = {"initial_workers": initial,
                      "workers": new_workers, "restarts": restarts,
                      "resumed_step": route["step"],
                      "resumed_from": resume,
                      "recovery": route["mode"],
                      "recovery_decision_seconds": decision_s,
                      "replayed_steps": replayed}
            if route["package"] is not None:
                # the dead fleet's parity counters rode the escrow —
                # its workers never returned a result package
                report.update(route["package"].get("escrow_stats", {}))
                report["reconstruct_seconds"] = \
                    route["package"].get("reconstruct_seconds")
            continue
        # the recovery package is one-shot: a completed attempt consumed
        # it (or never needed it) — a later fit must not resurrect it
        trainer._elastic_recovery = None
        report.update(getattr(trainer, "_elastic_worker_stats", None)
                      or {})
        if restarts:
            # time-to-recover: driver-side route decision + the resumed
            # attempt's time-to-first-step (rendezvous, recompile,
            # restore — everything between death and training again)
            ttfs = getattr(trainer, "time_to_first_step", None)
            if decision_s is not None and ttfs is not None:
                report["recovery_seconds"] = decision_s + ttfs
        trainer._elastic_report = report
        if restarts:
            _log.info("elastic: fit completed after %d restart(s) on "
                      "%d/%d workers (recovery=%s, resumed step %s)",
                      restarts, report["workers"], initial,
                      report.get("recovery"),
                      report.get("resumed_step"))
        return result
