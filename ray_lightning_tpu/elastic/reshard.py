"""Reshardable restore: load an orbax per-shard save onto a DIFFERENT
topology than the one that wrote it.

Most of the re-partitioning is free: orbax restores into whatever
shardings the target avals carry, and every TrainState leaf's GLOBAL
shape is topology-independent — params, optimizer moments, rng — so
``abstract_like`` built from the NEW mesh + strategy is a valid restore
target no matter who saved (DDP, ZeRO-1, FSDP, SPMD; the N→M host case
and the strategy-swap case are the same operation).

The exception is the comm plane's error-feedback residual
(comm/collectives.py ``CommState``): its leaves are stacked
``[world, *param_shape]`` where ``world`` is the SAVING run's
data-parallel size.  Blindly reloading it under a different world
either corrupts (orbax silently returns the saved shape when the
target disagrees) or crashes at the first dispatch.  This module:

1. reads the saved tree's shapes from orbax metadata (no array data);
2. verifies every non-residual leaf's saved shape matches the target —
   a mismatch raises naming the leaf instead of silently restoring the
   wrong shape;
3. restores the residual at its SAVED shape and re-buckets it N→M:
   ``new_r[j] = mean_i(old_r[i])`` for every new rank j.  The quantity
   error feedback actually injects into the model is
   ``(1/world)·Σ_i r_i`` (GradSync.sync adds each rank's slice before
   the mean-reduction), and the mean-broadcast preserves it exactly:
   ``(1/M)·Σ_j mean_i(old_r) = (1/N)·Σ_i old_r``.  What is NOT
   preserved is the per-rank attribution of the error — documented
   tolerance: the first post-restore step quantizes slightly different
   per-rank payloads than an uninterrupted run would have.
4. bridges structure changes: a save with a residual restored into a
   comm-off run drops it (losing one pending correction — logged); a
   comm-off save restored into a comm-on run keeps the target's zero
   residual and restores only the inner optimizer state.
"""

from __future__ import annotations

import logging
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_lightning_tpu.utils.checkpoint import abstract_like

_log = logging.getLogger(__name__)


def _md_array_leaves(node) -> list:
    """Array-metadata leaves under one orbax metadata subtree (plain
    nested dicts/lists keyed the way jax key-paths stringify; empty
    pytree nodes appear as ``None``)."""
    out: list = []
    if node is None:
        return out
    if isinstance(node, dict):
        for v in node.values():
            out.extend(_md_array_leaves(v))
        return out
    if isinstance(node, (list, tuple)):
        for v in node:
            out.extend(_md_array_leaves(v))
        return out
    if hasattr(node, "shape"):
        out.append(node)
    return out


def _md_paths(node, prefix: tuple = ()) -> dict:
    """{path tuple of str: shape} for every array leaf in the saved
    metadata tree."""
    out: dict = {}
    if node is None:
        return out
    if isinstance(node, dict):
        for k, v in node.items():
            out.update(_md_paths(v, prefix + (str(k),)))
        return out
    if isinstance(node, (list, tuple)):
        for i, v in enumerate(node):
            out.update(_md_paths(v, prefix + (str(i),)))
        return out
    if hasattr(node, "shape"):
        out[prefix] = tuple(node.shape)
    return out


def _key_str(entry) -> str:
    """One jax KeyPath entry → the string orbax names it with."""
    for attr in ("name", "key", "idx"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


def _target_paths(tree) -> dict:
    """{path tuple of str: aval} for every leaf of the restore target
    (same naming as :func:`_md_paths` so the two are comparable)."""
    out: dict = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        out[tuple(_key_str(p) for p in path)] = leaf
    return out


def saved_residual_world(md: Optional[dict]) -> Optional[int]:
    """The saved ``CommState`` residual's stacked world size, or None
    when the save carries no residual arrays (comm off, or EF off)."""
    if not isinstance(md, dict):
        return None
    opt = md.get("opt_state")
    if not isinstance(opt, dict):
        return None
    leaves = _md_array_leaves(opt.get("residual"))
    if not leaves:
        return None
    return int(leaves[0].shape[0])


def _saved_is_commstate(md: Optional[dict]) -> bool:
    if not isinstance(md, dict):
        return False
    opt = md.get("opt_state")
    return isinstance(opt, dict) and "residual" in opt and "inner" in opt


def _mesh_of(shardings) -> Any:
    for leaf in jax.tree_util.tree_leaves(
            shardings, is_leaf=lambda x: hasattr(x, "mesh")):
        if hasattr(leaf, "mesh"):
            return leaf.mesh
    raise ValueError("no NamedSharding leaf in the shardings tree")


def _rebucket(old: Any, new_world: int, target_shardings: Any) -> Any:
    """``[N, ...]`` residual tree → ``[M, ...]``: mean over the old
    world axis broadcast to every new rank (sum-of-injected-error
    preserving — module docstring), placed per the target shardings."""
    def leaf(r, sh):
        m = jnp.mean(jnp.asarray(r, jnp.float32), axis=0, keepdims=True)
        out = jnp.broadcast_to(m, (new_world,) + tuple(m.shape[1:]))
        return jax.device_put(out, sh)
    return jax.tree_util.tree_map(leaf, old, target_shardings)


def restore_resharded(ckpt, state_like: Any, shardings: Any,
                      step: Optional[int] = None) -> tuple:
    """Restore ``(state, meta)`` from ``ckpt`` into the CURRENT
    topology described by ``(state_like, shardings)``.

    ``ckpt`` is a :class:`~ray_lightning_tpu.utils.checkpoint.\
ShardedCheckpointer`; ``state_like`` is the live (freshly initialized)
    TrainState whose structure/shapes describe the restore target.
    Handles the ``CommState`` residual world change and comm-on/off
    structure bridging; any OTHER saved-vs-target shape divergence
    raises naming the leaf.
    """
    from ray_lightning_tpu.comm.collectives import CommState

    if step is None:
        step = ckpt.latest_step()
    abstract = abstract_like(state_like, shardings)
    md = ckpt.saved_state_metadata(step)
    if md is None:
        # metadata unavailable (very old save / remote backend quirk):
        # fall back to the plain same-topology restore
        return ckpt.restore(abstract, step=step)

    cur_opt = abstract.opt_state
    cur_is_comm = isinstance(cur_opt, CommState)
    cur_res_leaves = (jax.tree_util.tree_leaves(cur_opt.residual)
                      if cur_is_comm else [])
    cur_world = (int(cur_res_leaves[0].shape[0])
                 if cur_res_leaves else None)
    saved_world = saved_residual_world(md)
    saved_is_comm = _saved_is_commstate(md)
    mesh = _mesh_of(shardings)

    target = abstract
    fix = None   # post-restore adapter
    if saved_world is not None and cur_world is not None:
        if saved_world != cur_world:
            # case A: both runs carry a residual, worlds differ —
            # restore at the SAVED shape (replicated: the old
            # partitioning is gone), then re-bucket N→M
            res_avals = jax.tree_util.tree_map(
                lambda r: jax.ShapeDtypeStruct(
                    (saved_world,) + tuple(r.shape[1:]), r.dtype,
                    sharding=NamedSharding(mesh, P())),
                cur_opt.residual)
            target = abstract.replace(opt_state=CommState(
                residual=res_avals, inner=cur_opt.inner))

            def fix(state):
                _log.info(
                    "elastic reshard: re-bucketing error-feedback "
                    "residual [%d, ...] -> [%d, ...] (mean-broadcast; "
                    "total pending correction preserved)",
                    saved_world, cur_world)
                res = _rebucket(state.opt_state.residual, cur_world,
                                shardings.opt_state.residual)
                return state.replace(opt_state=CommState(
                    residual=res, inner=state.opt_state.inner))
    elif saved_world is not None and cur_world is None:
        # case B: the save carries a residual this run does not use
        # (comm/EF off now, or world shrank to 1).  Restore it at the
        # saved shape just to reach the inner state, then drop it.
        res_avals = jax.tree_util.tree_map(
            lambda node: jax.ShapeDtypeStruct(
                tuple(node.shape), node.dtype,
                sharding=NamedSharding(mesh, P())),
            md["opt_state"]["residual"],
            is_leaf=lambda n: hasattr(n, "shape"))
        target = abstract.replace(opt_state=CommState(
            residual=res_avals,
            inner=cur_opt.inner if cur_is_comm else cur_opt))

        def fix(state):
            _log.warning(
                "elastic reshard: dropping the saved [%d, ...] "
                "error-feedback residual (the restored run carries "
                "none) — one pending quantization correction is lost",
                saved_world)
            inner = state.opt_state.inner
            new_opt = (CommState(residual=state_like.opt_state.residual,
                                 inner=inner) if cur_is_comm else inner)
            return state.replace(opt_state=new_opt)
    elif saved_world is None and cur_world is not None:
        # case C: comm-off (or EF-off) save restored into a comm-on
        # run — restore only the inner state; error feedback restarts
        # from the target's zero residual.
        target = abstract.replace(
            opt_state=CommState(residual=(), inner=cur_opt.inner)
            if saved_is_comm else cur_opt.inner)

        def fix(state):
            inner = (state.opt_state.inner
                     if isinstance(state.opt_state, CommState)
                     else state.opt_state)
            return state.replace(opt_state=CommState(
                residual=state_like.opt_state.residual, inner=inner))

    _verify_shapes(md, target)
    state, meta = ckpt.restore(target, step=step)
    if fix is not None:
        state = fix(state)
    return state, meta


def _verify_shapes(md: dict, target: Any) -> None:
    """Every target leaf must exist in the save with the same global
    shape — a divergence would otherwise restore silently wrong (orbax
    returns the SAVED shape when the target disagrees)."""
    saved = _md_paths(md)
    want = _target_paths(target)
    problems = []
    for path, aval in want.items():
        got = saved.get(path)
        if got is None:
            problems.append(
                f"{'/'.join(path)}: missing from the checkpoint")
        elif tuple(got) != tuple(aval.shape):
            problems.append(
                f"{'/'.join(path)}: saved shape {tuple(got)} != "
                f"target {tuple(aval.shape)}")
    if problems:
        raise ValueError(
            "checkpoint does not reshard onto this topology:\n  "
            + "\n  ".join(problems))
