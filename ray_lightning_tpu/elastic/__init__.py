"""Elastic plane: reshardable checkpoints, async snapshots, parity
redundancy, and two-tier fault tolerance.

Preemptible TPU pools are the realistic deployment for this system:
workers WILL disappear mid-run.  The failure-detection half landed in
PR 1 (the heartbeat watchdog names a dead or wedged rank); this package
is the reaction:

- ``snapshot.py`` — async per-step sharded snapshots off the critical
  path, with bounded backpressure, failure hardening (a flaky save is
  counted and retried, not fatal) and cost instruments
  (``rlt_snapshot_*``) on ``/metrics``;
- ``reshard.py`` — restore an orbax per-shard save taken on N hosts
  onto M hosts (any strategy), re-bucketing the comm plane's
  ``[world, ...]`` error-feedback residual instead of blindly
  reloading it;
- ``redundancy.py`` — parity-redundant optimizer state: each rank XORs
  k neighbor ranks' ZeRO-1 partitions into a parity block over the
  worker↔worker peer channel, escrowing its own state host-side so a
  single-rank loss is reconstructed in-fleet and training continues
  from the *current* step, snapshot-free;
- ``driver.py`` — the recovery router: single-rank loss with parity on
  routes to reconstruct-and-continue; multi-rank loss or parity-off
  falls back to shrink-to-continue snapshot replay (rebuild the fleet
  with the survivors, reshard-restore the latest snapshot, rescale the
  per-worker batch, continue to ``max_steps``), reported as
  ``recovery: parity|replay|scratch``;
- ``faults.py`` — deterministic fault injection (kill / wedge / slow /
  snapkill / peerdrop; ``RLT_FAULT`` takes a semicolon-separated list)
  for chaos tests and benches;
- ``config.py`` — ``Trainer(elastic=...)`` / ``RLT_ELASTIC*`` knobs.

Only the light, jax-free pieces import here (config + faults): the
trainer touches this package on every construction, and worker
processes import it before jax exists.
"""

from ray_lightning_tpu.elastic.config import ElasticConfig  # noqa: F401
from ray_lightning_tpu.elastic.faults import (  # noqa: F401
    FaultInjector,
    FaultSpec,
    maybe_injector_from_env,
    parse_fault,
    parse_faults,
)

__all__ = [
    "ElasticConfig",
    "FaultInjector",
    "FaultSpec",
    "maybe_injector_from_env",
    "parse_fault",
    "parse_faults",
]
