"""Elastic plane: reshardable checkpoints, async snapshots, and
shrink-to-continue fault tolerance.

Preemptible TPU pools are the realistic deployment for this system:
workers WILL disappear mid-run.  The failure-detection half landed in
PR 1 (the heartbeat watchdog names a dead or wedged rank); this package
is the reaction:

- ``snapshot.py`` — async per-step sharded snapshots off the critical
  path, with bounded backpressure and cost instruments
  (``rlt_snapshot_*``) on ``/metrics``;
- ``reshard.py`` — restore an orbax per-shard save taken on N hosts
  onto M hosts (any strategy), re-bucketing the comm plane's
  ``[world, ...]`` error-feedback residual instead of blindly
  reloading it;
- ``driver.py`` — the shrink-to-continue loop: a dead rank tears down
  the fleet, the driver rebuilds it with the survivors, re-runs
  rendezvous, reshard-restores the latest snapshot, rescales the
  per-worker batch so the global batch is preserved, and continues to
  ``max_steps``;
- ``faults.py`` — deterministic fault injection
  (kill-rank-k-at-step-s / wedge / slow) for chaos tests and benches;
- ``config.py`` — ``Trainer(elastic=...)`` / ``RLT_ELASTIC*`` knobs.

Only the light, jax-free pieces import here (config + faults): the
trainer touches this package on every construction, and worker
processes import it before jax exists.
"""

from ray_lightning_tpu.elastic.config import ElasticConfig  # noqa: F401
from ray_lightning_tpu.elastic.faults import (  # noqa: F401
    FaultInjector,
    FaultSpec,
    maybe_injector_from_env,
    parse_fault,
)

__all__ = [
    "ElasticConfig",
    "FaultInjector",
    "FaultSpec",
    "maybe_injector_from_env",
    "parse_fault",
]
