"""Elastic-plane selfcheck for ``format.sh --check`` (CI gate).

Same contract as the comm/compile/serve selfchecks: cheap,
deterministic, no pytest — validates the invariants that would
otherwise only fail deep inside a shrinking fleet:

1. ``ElasticConfig`` validation + ``RLT_ELASTIC*`` env round-trip
   (``worker_env`` → ``resolve`` reproduces the config, redundancy
   knobs included);
2. fault-spec parsing (every kind round-trips; semicolon lists parse;
   malformed specs raise naming the bad clause);
3. every elastic metric name is Prometheus-clean (the PR 2 lint);
4. the residual re-bucket preserves the injected-error invariant
   ``(1/M)·Σ new = (1/N)·Σ old`` on a small CPU array;
5. parity invariants (elastic/redundancy.py): XOR
   encode→drop-one→decode round-trips BIT-EXACT for every dead-rank
   position at several (world, k), and the holder/coverage geometry is
   consistent (every rank's blob is covered by exactly k holders).
"""

from __future__ import annotations


def _check_config() -> None:
    import os
    from ray_lightning_tpu.elastic.config import ElasticConfig

    cfg = ElasticConfig(enabled=True, snapshot_every_n_steps=25,
                        snapshot_dir="/tmp/ck", max_restarts=3,
                        min_workers=2, preserve_global_batch=False,
                        max_to_keep=5, redundancy=2,
                        redundancy_every_n_steps=4,
                        max_snapshot_failures=7)
    saved = {k: os.environ.get(k) for k in list(os.environ)
             if k.startswith("RLT_ELASTIC")}
    try:
        for k in saved:
            os.environ.pop(k, None)
        os.environ.update(cfg.worker_env())
        assert ElasticConfig.resolve(None) == cfg, "env round-trip drifted"
    finally:
        for k in list(os.environ):
            if k.startswith("RLT_ELASTIC"):
                os.environ.pop(k, None)
        os.environ.update({k: v for k, v in saved.items() if v is not None})
    assert not ElasticConfig.resolve(None).enabled
    assert ElasticConfig.resolve({"snapshot_every_n_steps": 5}).enabled
    for bad in (dict(snapshot_every_n_steps=-1), dict(min_workers=0),
                dict(max_restarts=-1), dict(max_to_keep=0),
                dict(redundancy=-1), dict(redundancy_every_n_steps=0),
                dict(max_snapshot_failures=0)):
        try:
            ElasticConfig(enabled=True, **bad)
        except ValueError:
            pass
        else:
            raise AssertionError(f"expected ValueError for {bad}")
    print("elastic selfcheck: config validation + env round-trip OK")


def _check_faults() -> None:
    from ray_lightning_tpu.elastic.faults import (FaultSpec, parse_fault,
                                                  parse_faults)

    s = parse_fault("kill:rank=1,step=5,code=9")
    assert s == FaultSpec("kill", 1, 5, exit_code=9)
    assert s.should_fire(1, 5) and s.should_fire(1, 6)
    assert not s.should_fire(0, 5) and not s.should_fire(1, 4)
    assert parse_fault("wedge:rank=0,step=2").kind == "wedge"
    slow = parse_fault("slow:rank=2,step=3,seconds=0.5")
    assert slow.seconds == 0.5
    assert parse_fault(s.describe()) == s   # describe round-trips
    snap = parse_fault("snapkill:rank=1,step=4")
    assert snap.kind == "snapkill" and parse_fault(snap.describe()) == snap
    drop = parse_fault("peerdrop:rank=0,step=3,count=2")
    assert drop.count == 2 and parse_fault(drop.describe()) == drop
    once = parse_fault("kill:rank=0,step=5,restart=0")
    assert once.restart == 0 and parse_fault(once.describe()) == once
    assert once.should_fire(0, 5, restarts=0)
    assert not once.should_fire(0, 5, restarts=1)   # replayed segment
    # semicolon lists (the chaos matrix's double-kill shape)
    specs = parse_faults("kill:rank=1,step=5; kill:rank=2,step=5")
    assert [x.rank for x in specs] == [1, 2]
    try:
        parse_faults("kill:rank=1,step=5;boom:rank=2,step=5")
    except ValueError as e:
        assert "boom:rank=2,step=5" in str(e), e   # names the bad clause
    else:
        raise AssertionError("bad clause in a list did not raise")
    for bad in ("kill", "boom:rank=1,step=2", "kill:rank=1",
                "kill:rank=1,step=0", "kill:rank=-1,step=2",
                "kill:rank=1;step=2", "peerdrop:rank=0,step=1,count=0"):
        try:
            parse_fault(bad)
        except ValueError:
            pass
        else:
            raise AssertionError(f"expected ValueError for {bad!r}")
    print("elastic selfcheck: fault-spec parsing OK")


def _check_metric_names() -> None:
    from ray_lightning_tpu.telemetry.metrics import validate_metric_name
    for name in ("rlt_snapshot_total", "rlt_snapshot_skipped_total",
                 "rlt_snapshot_failed_total",
                 "rlt_snapshot_seconds_total",
                 "rlt_snapshot_stall_seconds_total",
                 "rlt_snapshot_restore_total",
                 "rlt_restarts_total", "rlt_worker_alive",
                 "rlt_parity_ticks_total", "rlt_parity_bytes_total",
                 "rlt_parity_skipped_total", "rlt_parity_restore_total",
                 "rlt_recovery_mode", "rlt_recovery_seconds",
                 "rlt_peer_retries_total"):
        validate_metric_name(name)
    print("elastic selfcheck: metric names Prometheus-clean")


def _check_rebucket() -> None:
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P
    import jax
    from ray_lightning_tpu.elastic.reshard import _rebucket

    mesh = jax.sharding.Mesh(np.array(jax.devices()[:1]), ("data",))
    rep = NamedSharding(mesh, P())
    old = {"w": np.arange(12, dtype=np.float32).reshape(3, 4)}
    for m in (1, 2, 6):
        new = _rebucket(old, m, {"w": rep})
        got = np.asarray(new["w"])
        assert got.shape == (m, 4)
        # injected-correction invariant: (1/M)·Σ new == (1/N)·Σ old
        np.testing.assert_allclose(
            got.sum(0) / m, old["w"].sum(0) / 3, rtol=1e-6)
    print("elastic selfcheck: residual re-bucket preserves the "
          "injected-error sum")


def _check_parity() -> None:
    import numpy as np
    from ray_lightning_tpu.elastic.redundancy import (ParityGroup,
                                                      recover_block,
                                                      xor_blocks)

    rng = np.random.default_rng(7)
    for world, k in ((2, 1), (3, 1), (3, 2), (5, 2), (4, 3)):
        # rank blobs of deliberately UNEQUAL lengths (zero-padding leg)
        blobs = [rng.bytes(64 + 13 * r) for r in range(world)]
        # geometry: every rank's blob held by exactly k parity holders
        held_by: dict = {r: [] for r in range(world)}
        for r in range(world):
            g = ParityGroup(r, world, k)
            assert g.holders == [(r - 1 - i) % world for i in range(g.k)]
            for m in g.covers:
                held_by[m].append(r)
        kk = min(k, world - 1)
        assert all(len(v) == kk for v in held_by.values()), held_by
        # encode → drop any one rank → decode, bit-exact
        for dead in range(world):
            holder = ParityGroup.holder_of(dead, world, k)
            g = ParityGroup(holder, world, k)
            assert dead in g.covers
            parity = xor_blocks([blobs[m] for m in g.covers])
            others = [blobs[m] for m in g.covers if m != dead]
            got = recover_block(parity, others, len(blobs[dead]))
            assert got == blobs[dead], (world, k, dead)
    print("elastic selfcheck: XOR parity encode→drop-one→decode "
          "bit-exact for every rank position")


def _main(argv: list) -> int:
    _check_config()
    _check_faults()
    _check_metric_names()
    _check_rebucket()
    _check_parity()
    return 0


if __name__ == "__main__":   # pragma: no cover - exercised via format.sh
    import sys
    sys.exit(_main(sys.argv[1:]))
