"""Comm plane: blockwise-quantized cross-replica gradient collectives.

On a multi-host pod the data-parallel gradient sync rides the slow DCN
link; EQuARX (PAPERS.md) shows an XLA all-reduce executed blockwise in
low precision recovers most of that bandwidth at negligible quality
cost.  This package is the userland version of that idea for the
framework's sharding-annotation strategies:

- :mod:`quant` — blockwise int8 / bf16 / fp8-e4m3 / packed-int4
  quantize–dequantize kernels in pure ``jax.numpy``/``lax`` (per-block
  scales, optional stochastic rounding) that fuse into the jitted step.
- :mod:`collectives` — ``compressed_psum`` / ``compressed_reduce_scatter``
  / ``compressed_all_gather`` built from ``all_to_all`` + ``all_gather``
  over a named mesh axis in the compressed dtype (summation always
  accumulates in fp32 — an int8 ``psum`` would wrap), the two-level
  ``hierarchical_psum`` (fp32 inside the fast ICI group, codec only
  across the DCN replica groups), and
  :class:`~ray_lightning_tpu.comm.collectives.GradSync`, the object a
  strategy's ``grad_transform(mesh, policy)`` hands the step builder —
  per-leaf or bucketed (``bucket_bytes``: overlap-schedulable
  per-bucket collectives).  Quantization error is carried as an
  **error-feedback residual** in the optimizer state and re-injected
  into the next step's gradients.
- :mod:`policy` — :class:`CommPolicy` (``Trainer(comm_policy=...)`` /
  ``RLT_COMM*`` env knobs): which mesh axes compress, codec, block
  size, rounding mode, error feedback, hierarchy split, bucket target,
  and the ZeRO-1 updated-param all-gather dtype.
- :mod:`audit` — HLO wire-byte accounting (now per link tier, over
  each collective's replica groups) used by the collective audits
  (tests/test_collective_audit.py) to prove the compressed programs
  actually move fewer bytes — and fewer DCN-crossing bytes.
- :mod:`calibrate` — measured link bandwidths replacing the cost-model
  constants (``RLT_PLAN_CALIBRATE=1``; cached per topology).

Off by default: with the policy unresolved (or no compressible axis on
the mesh) every strategy's ``grad_transform`` returns ``None`` and the
train step is byte-identical to the uncompressed build.
"""

from ray_lightning_tpu.comm.collectives import (  # noqa: F401
    CommState,
    GradSync,
    build_grad_sync,
    compressed_all_gather,
    compressed_psum,
    compressed_reduce_scatter,
    hierarchical_psum,
    hierarchy_groups,
    partition_buckets,
)
from ray_lightning_tpu.comm.policy import CommPolicy  # noqa: F401
from ray_lightning_tpu.comm.quant import (  # noqa: F401
    blockwise_dequantize,
    blockwise_quantize,
)
