"""Comm plane: blockwise-quantized cross-replica gradient collectives.

On a multi-host pod the data-parallel gradient sync rides the slow DCN
link; EQuARX (PAPERS.md) shows an XLA all-reduce executed blockwise in
low precision recovers most of that bandwidth at negligible quality
cost.  This package is the userland version of that idea for the
framework's sharding-annotation strategies:

- :mod:`quant` — blockwise int8 / bf16 quantize–dequantize kernels in
  pure ``jax.numpy``/``lax`` (per-block scales, optional stochastic
  rounding) that fuse into the jitted step.
- :mod:`collectives` — ``compressed_psum`` / ``compressed_reduce_scatter``
  / ``compressed_all_gather`` built from ``all_to_all`` + ``all_gather``
  over a named mesh axis in the compressed dtype (summation always
  accumulates in fp32 — an int8 ``psum`` would wrap), plus
  :class:`~ray_lightning_tpu.comm.collectives.GradSync`, the object a
  strategy's ``grad_transform(mesh, policy)`` hands the step builder.
  Quantization error is carried as an **error-feedback residual** in the
  optimizer state and re-injected into the next step's gradients.
- :mod:`policy` — :class:`CommPolicy` (``Trainer(comm_policy=...)`` /
  ``RLT_COMM*`` env knobs): which mesh axes compress, block size,
  rounding mode, error feedback, and the ZeRO-1 updated-param
  all-gather dtype.
- :mod:`audit` — HLO wire-byte accounting used by the collective audits
  (tests/test_collective_audit.py) to prove the compressed programs
  actually move fewer bytes.

Off by default: with the policy unresolved (or no compressible axis on
the mesh) every strategy's ``grad_transform`` returns ``None`` and the
train step is byte-identical to the uncompressed build.
"""

from ray_lightning_tpu.comm.collectives import (  # noqa: F401
    CommState,
    GradSync,
    build_grad_sync,
    compressed_all_gather,
    compressed_psum,
    compressed_reduce_scatter,
)
from ray_lightning_tpu.comm.policy import CommPolicy  # noqa: F401
from ray_lightning_tpu.comm.quant import (  # noqa: F401
    blockwise_dequantize,
    blockwise_quantize,
)
