"""Compressed cross-replica collectives + the strategy→step GradSync.

The framework's gradient sync is normally *implicit*: the partitioner
lowers the strategy's sharding annotations to an fp32 all-reduce inside
the backward pass, so there is no call site to compress.  The comm
plane therefore makes the sync explicit: the step builder wraps the
gradient computation in a ``shard_map`` region (params replicated,
batch sharded on the data axes) where each device computes LOCAL
gradients and this module performs the reduction in the compressed
dtype:

- :func:`compressed_reduce_scatter` — quantize the local payload,
  ``all_to_all`` the int8/bf16 rows, dequantize and SUM IN FP32 (an
  int8 ``psum`` would wrap at rank count 2); each rank ends with its
  1/N shard of the sum.
- :func:`compressed_all_gather` — re-quantize the shard, ``all_gather``
  the compressed rows, dequantize.
- :func:`compressed_psum` — the pair composed: the classic
  reduce-scatter + all-gather spelling of a ring all-reduce, with both
  wire phases compressed.  Per-rank wire bytes ≈ 2·n·itemsize(mode)
  versus the fp32 ring's 2·n·4 — the ~4x (int8) / 2x (bf16) the HLO
  audit pins.
- :func:`hierarchical_psum` — the two-level EQuARX split: when the
  reduction axis spans both a fast tier (ICI — chips sharing a host)
  and a slow one (DCN — cross-host), reduce fp32 within each ICI
  group first (all_to_all → local sum: a full-precision
  reduce-scatter), run the COMPRESSED psum only across the DCN groups
  on the 1/ici-sized shard, and fp32 all-gather back inside the ICI
  group.  Only inter-host bytes pay the codec, and they also shrink by
  the extra factor ``ici`` — so for the same DCN wire savings the
  error-feedback residual absorbs strictly less quantization noise.

Error feedback: the phase-1 local quantization error (``x − dq(q(x))``)
is returned alongside the result; :class:`GradSync` stores it per-rank
in the optimizer state (a ``[world, ...]``-stacked leaf sharded on the
compressed axes) and adds it back into the next step's local gradients,
so quantization error accumulates into the model as a one-step delay
instead of a bias (1-bit-Adam/EF-SGD construction).  The phase-2
re-quantization error is second-order (quantizing already block-scaled
values) and is not compensated.
"""

from __future__ import annotations

import logging
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_lightning_tpu.comm.policy import CommPolicy
from ray_lightning_tpu.comm.quant import (
    compress_cast,
    decompress_cast,
    payload_bytes,
)

_log = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# in-shard_map primitives
# ---------------------------------------------------------------------------


def _axis_arg(axis):
    """Normalize an axis spec for the lax collectives: bare name for a
    single axis (the common case; maximally compatible), tuple for a
    multi-axis product."""
    if isinstance(axis, str):
        return axis
    axis = tuple(axis)
    return axis[0] if len(axis) == 1 else axis


def _pad_rows(x: jax.Array, world: int, block_size: int):
    """Flatten ``x`` and pad to ``[world, chunk]`` rows with ``chunk`` a
    multiple of ``block_size`` (zero fill; zero blocks quantize to
    exact zeros).  Returns (rows, n) with n the true element count."""
    flat = x.astype(jnp.float32).ravel()
    n = flat.size
    chunk = -(-n // world)
    chunk = -(-chunk // block_size) * block_size
    flat = jnp.pad(flat, (0, world * chunk - n))
    return flat.reshape(world, chunk), n


def compressed_reduce_scatter(x: jax.Array, axis, world: int, *,
                              mode: str = "int8", block_size: int = 64,
                              stochastic: bool = False,
                              rng: Optional[jax.Array] = None,
                              with_error: bool = False,
                              groups: Optional[list] = None):
    """Inside ``shard_map``: reduce-scatter ``x`` (any shape) over
    ``axis`` in the compressed dtype.  Returns ``(shard, n)`` — this
    rank's fp32 ``[chunk]`` shard of the SUM and the true element count
    — plus the local quantization error (shaped like ``x``) when
    ``with_error``.  ``groups`` (``axis_index_groups``) restricts the
    exchange to subgroups of ``world`` ranks each (the hierarchical
    DCN tier)."""
    axes = _axis_arg(axis)
    rows, n = _pad_rows(x, world, block_size)
    q, scale = compress_cast(rows, mode, block_size,
                             stochastic=stochastic, rng=rng)
    qt = lax.all_to_all(q, axes, split_axis=0, concat_axis=0, tiled=True,
                        axis_index_groups=groups)
    if scale is not None:
        st = lax.all_to_all(scale, axes, split_axis=0, concat_axis=0,
                            tiled=True, axis_index_groups=groups)
        shard = jnp.sum(decompress_cast(qt, st, mode, block_size), axis=0)
    else:
        shard = jnp.sum(qt.astype(jnp.float32), axis=0)
    if not with_error:
        return shard, n
    err_rows = rows - decompress_cast(q, scale, mode, block_size)
    err = err_rows.ravel()[:n].reshape(x.shape)
    return shard, n, err


def compressed_all_gather(shard: jax.Array, axis, world: int, *,
                          mode: str = "int8", block_size: int = 64,
                          stochastic: bool = False,
                          rng: Optional[jax.Array] = None,
                          groups: Optional[list] = None) -> jax.Array:
    """Inside ``shard_map``: all-gather a per-rank ``[chunk]`` shard over
    ``axis`` (or its ``groups`` subgroups) in the compressed dtype.
    Returns the flat fp32 ``[world * chunk]`` result (replicated across
    the axis/group)."""
    axes = _axis_arg(axis)
    q, scale = compress_cast(shard[None], mode, block_size,
                             stochastic=stochastic, rng=rng)
    qg = lax.all_gather(q, axes, tiled=True, axis_index_groups=groups)
    if scale is not None:
        sg = lax.all_gather(scale, axes, tiled=True,
                            axis_index_groups=groups)
        full = decompress_cast(qg, sg, mode, block_size)
    else:
        full = qg.astype(jnp.float32)
    return full.ravel()


def compressed_psum(x: jax.Array, axis, world: int, *,
                    mode: str = "int8", block_size: int = 64,
                    mean: bool = False, stochastic: bool = False,
                    rng: Optional[jax.Array] = None,
                    with_error: bool = False,
                    groups: Optional[list] = None):
    """Inside ``shard_map``: all-reduce ``x`` over ``axis`` (or its
    ``groups`` subgroups of ``world`` ranks each) with both wire phases
    compressed (reduce-scatter + all-gather).  Returns the reduced
    array shaped like ``x`` (and the local phase-1 quantization error
    when ``with_error`` — in SUM units, i.e. NOT divided by ``world``
    even under ``mean``, which is what error feedback needs)."""
    r1 = rng
    r2 = None
    if rng is not None:
        r1, r2 = jax.random.split(rng)
    out = compressed_reduce_scatter(x, axis, world, mode=mode,
                                    block_size=block_size,
                                    stochastic=stochastic, rng=r1,
                                    with_error=with_error, groups=groups)
    shard, n = out[0], out[1]
    if mean:
        shard = shard / world
    full = compressed_all_gather(shard, axis, world, mode=mode,
                                 block_size=block_size,
                                 stochastic=stochastic, rng=r2,
                                 groups=groups)
    res = full[:n].reshape(x.shape)
    if with_error:
        return res, out[2]
    return res


# ---------------------------------------------------------------------------
# two-level (ICI x DCN) reduction
# ---------------------------------------------------------------------------


def hierarchy_groups(ici: int, dcn: int) -> "tuple[list, list]":
    """``(ici_groups, dcn_groups)`` over a ``world = ici * dcn`` axis
    under the contiguous-block layout ``rank = host * ici + local``
    (how the mesh builder orders ``jax.devices()``: process-major, so
    ranks sharing a host are adjacent).  ICI groups are the per-host
    blocks; DCN groups collect the ranks with the same local index
    across hosts."""
    ici_groups = [[h * ici + j for j in range(ici)] for h in range(dcn)]
    dcn_groups = [[h * ici + j for h in range(dcn)] for j in range(ici)]
    return ici_groups, dcn_groups


def hierarchical_psum(x: jax.Array, axis, ici: int, dcn: int, *,
                      mode: str = "int8", block_size: int = 64,
                      mean: bool = False, stochastic: bool = False,
                      rng: Optional[jax.Array] = None,
                      with_error: bool = False):
    """Inside ``shard_map``: two-level all-reduce of ``x`` over an
    ``ici * dcn``-rank axis (module docstring).  Level 1 reduce-scatters
    fp32 inside each ICI group (fast link, no codec), level 2 runs
    :func:`compressed_psum` across the DCN groups on the 1/ici-sized
    shard (slow link — the ONLY bytes that pay the quantization), level
    3 fp32 all-gathers inside the ICI group.  ``with_error`` returns
    the level-2 quantization error scattered back to ``x``'s shape
    (zeros outside this rank's shard) so the error-feedback residual
    keeps its flat-path layout: level 1 is an exact sum, so injecting
    the error into any single rank of the host group next step
    compensates exactly."""
    axes = _axis_arg(axis)
    world = ici * dcn
    ici_groups, dcn_groups = hierarchy_groups(ici, dcn)
    # level 1: full-precision reduce-scatter inside the fast ICI group
    rows, n = _pad_rows(x, ici, block_size)
    rows_t = lax.all_to_all(rows, axes, split_axis=0, concat_axis=0,
                            tiled=True, axis_index_groups=ici_groups)
    shard = jnp.sum(rows_t, axis=0)          # fp32 [chunk] of the host sum
    # level 2: compressed all-reduce across the slow DCN link only
    out = compressed_psum(shard, axis, dcn, mode=mode,
                          block_size=block_size, stochastic=stochastic,
                          rng=rng, with_error=with_error,
                          groups=dcn_groups)
    reduced, err = (out if with_error else (out, None))
    if mean:
        reduced = reduced / world
    # level 3: fp32 all-gather back inside the ICI group
    full = lax.all_gather(reduced, axes, tiled=True,
                          axis_index_groups=ici_groups)
    res = full[:n].reshape(x.shape)
    if not with_error:
        return res
    # scatter this rank's shard-local error back to the param shape:
    # the group-local index selects which chunk this rank quantized
    local = _combined_axis_index(axes) % ici
    chunk = shard.size
    err_flat = jnp.zeros((ici * chunk,), jnp.float32)
    err_flat = lax.dynamic_update_slice(err_flat, err.ravel(),
                                        (local * chunk,))
    return res, err_flat[:n].reshape(x.shape)


def _combined_axis_index(axes):
    """Index along the (possibly multi-)axis product inside shard_map."""
    if isinstance(axes, str):
        return lax.axis_index(axes)
    idx = jnp.zeros((), jnp.int32)
    for a in axes:
        idx = idx * lax.psum(1, a) + lax.axis_index(a)
    return idx


# ---------------------------------------------------------------------------
# bucket partitioning (comm/compute overlap scheduling)
# ---------------------------------------------------------------------------


def partition_buckets(leaf_bytes, bucket_bytes: int) -> "list[list[int]]":
    """Greedy contiguous partition of leaf indices into buckets whose
    cumulative payload reaches ``bucket_bytes`` (the last bucket may be
    smaller; a single oversized leaf gets its own bucket).  Every index
    appears exactly once, in order — the invariant comm/selfcheck.py
    pins."""
    if bucket_bytes <= 0:
        return [[i] for i in range(len(leaf_bytes))]
    buckets: list[list[int]] = []
    cur: list[int] = []
    acc = 0
    for i, b in enumerate(leaf_bytes):
        cur.append(i)
        acc += int(b)
        if acc >= bucket_bytes:
            buckets.append(cur)
            cur, acc = [], 0
    if cur:
        buckets.append(cur)
    return buckets


# ---------------------------------------------------------------------------
# optimizer-state carrier for the error-feedback residual
# ---------------------------------------------------------------------------


class CommState(NamedTuple):
    """Wraps the real optimizer state with the per-rank error-feedback
    residual.  ``residual`` leaves are ``[world, *param_shape]`` fp32,
    sharded on the compressed axes (each rank owns exactly its slice);
    ``()`` when error feedback is off so the pytree stays leafless."""

    residual: Any
    inner: Any


# ---------------------------------------------------------------------------
# GradSync: what a strategy's grad_transform hands the step builder
# ---------------------------------------------------------------------------


class GradSync:
    """Everything the compiled step needs to route its gradient sync
    through the compressed collectives for one (mesh, policy, strategy)
    resolution.  Stateless across steps (the residual lives in the
    optimizer state); safe to rebuild per stage."""

    def __init__(self, mesh, axes: tuple, policy: CommPolicy,
                 data_axis_names: tuple,
                 param_gather_spec_fn=None):
        self.mesh = mesh
        self.axes = tuple(axes)
        self.policy = policy
        self.world = int(np.prod([mesh.shape[a] for a in self.axes]))
        #: two-level split of the reduction axis (policy.hierarchy):
        #: (1, world) = flat, else ici * dcn == world and only the DCN
        #: tier carries the codec
        self.ici_size, self.dcn_size = policy.resolved_hierarchy(self.world)
        #: reduction axes the policy left uncompressed (fp32 pmean)
        self.plain_axes = tuple(
            a for a in data_axis_names
            if a in mesh.axis_names and a not in self.axes
            and mesh.shape[a] > 1)
        self.data_axis_names = tuple(
            a for a in data_axis_names if a in mesh.axis_names)
        self._param_gather_spec_fn = param_gather_spec_fn

    # -- descriptors -----------------------------------------------------

    @property
    def error_feedback(self) -> bool:
        return bool(self.policy.error_feedback)

    @property
    def hierarchical(self) -> bool:
        return self.ici_size > 1 and self.dcn_size > 1

    def describe(self) -> str:
        """Short tag for bench JSON / logs, e.g. ``int8[data]`` or
        ``fp8[data]/hier4x2/bkt4M``."""
        tag = f"{self.policy.compress}[{','.join(self.axes)}]"
        if self.hierarchical:
            tag += f"/hier{self.ici_size}x{self.dcn_size}"
        if self.policy.bucket_bytes > 0:
            tag += f"/bkt{self.policy.bucket_bytes >> 20}M"
        if self.policy.gather_bucket_bytes > 0:
            tag += f"/gbkt{self.policy.gather_bucket_bytes >> 20}M"
        return tag

    def _comm_kw(self) -> dict:
        return dict(mode=self.policy.compress,
                    block_size=self.policy.block_size,
                    stochastic=self.policy.stochastic_rounding)

    # -- residual plumbing (optimizer-state carrier) ---------------------

    def wrap_tx(self, tx):
        """Wrap ``tx`` so its state is a :class:`CommState` carrying the
        error-feedback residual.  The wrapper's ``update`` only threads
        the residual through — the step builder swaps in the new value
        after the sync (the residual is produced inside the shard_map
        region, not inside the optimizer)."""
        import optax

        ef = self.error_feedback
        world = self.world

        def init(params):
            residual = ()
            if ef:
                residual = jax.tree_util.tree_map(
                    lambda p: jnp.zeros((world,) + tuple(p.shape),
                                        jnp.float32), params)
            return CommState(residual=residual, inner=tx.init(params))

        def update(updates, state, params=None):
            new_updates, inner = tx.update(updates, state.inner, params)
            return new_updates, CommState(residual=state.residual,
                                          inner=inner)

        return optax.GradientTransformation(init, update)

    @staticmethod
    def residual_of(opt_state):
        if isinstance(opt_state, CommState):
            return opt_state.residual
        return ()

    @staticmethod
    def with_residual(opt_state, residual):
        if isinstance(opt_state, CommState):
            return opt_state._replace(residual=residual)
        return opt_state

    def fix_opt_shardings(self, opt_shardings, abstract_opt):
        """The strategy's ``opt_spec`` walked the residual subtree like
        any other optimizer leaf; its ``[world, ...]`` stacked dim must
        instead shard on the compressed axes (dim 0), so each rank holds
        exactly its own error slice."""
        if not isinstance(abstract_opt, CommState):
            return opt_shardings
        res_sh = jax.tree_util.tree_map(
            lambda _: NamedSharding(
                self.mesh,
                P(self.axes if len(self.axes) > 1 else self.axes[0])),
            abstract_opt.residual)
        return CommState(residual=res_sh, inner=opt_shardings.inner)

    # -- in-shard_map pieces ---------------------------------------------

    def axis_index(self):
        """Combined index along the full data-axis product (for rng
        decorrelation across shards inside the mapped region)."""
        idx = jnp.zeros((), jnp.int32)
        for a in self.data_axis_names:
            idx = idx * self.mesh.shape[a] + lax.axis_index(a)
        return idx

    def batch_spec(self, ndim: int) -> P:
        if ndim == 0:
            return P()
        axes = self.data_axis_names
        return P(axes if len(axes) > 1 else axes[0])

    def residual_specs(self, residual) -> Any:
        spec = P(self.axes if len(self.axes) > 1 else self.axes[0])
        return jax.tree_util.tree_map(lambda _: spec, residual)

    def pmean(self, tree):
        """fp32 mean over ALL data axes (loss / logged metrics / float
        model-state leaves — the tiny payloads that stay uncompressed)."""
        names = self.axes + self.plain_axes

        def leaf(x):
            if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
                return lax.pmean(x, names)
            return x
        return jax.tree_util.tree_map(leaf, tree)

    def _psum(self, x, rng, with_error: bool):
        """One mean-all-reduce of a flat/shaped fp32 payload through
        the configured path: two-level when the hierarchy is active,
        flat compressed otherwise."""
        kw = self._comm_kw()
        if self.hierarchical:
            return hierarchical_psum(x, self.axes, self.ici_size,
                                     self.dcn_size, mean=True, rng=rng,
                                     with_error=with_error, **kw)
        return compressed_psum(x, self.axes, self.world, mean=True,
                               rng=rng, with_error=with_error, **kw)

    def _leaf_keys(self, rng, count: int):
        keys = [None] * count
        if self.policy.stochastic_rounding:
            if rng is None:
                raise ValueError("stochastic rounding needs an rng key")
            keys = list(jax.random.split(rng, count))
        return keys

    def sync(self, grads, residual, rng: Optional[jax.Array] = None):
        """Inside ``shard_map``: compressed mean-reduction of the local
        gradient tree, one collective per leaf.  ``residual`` leaves
        arrive as this rank's ``[1, *shape]`` slice (or ``()`` with EF
        off).  Returns ``(synced, new_residual)`` with the residual
        re-stacked to ``[1, *shape]`` for the sharded out-spec."""
        ef = self.error_feedback
        g_leaves, treedef = jax.tree_util.tree_flatten(grads)
        r_leaves = jax.tree_util.tree_leaves(residual) if ef \
            else [None] * len(g_leaves)
        keys = self._leaf_keys(rng, len(g_leaves))
        synced, new_res = [], []
        for g, r, k in zip(g_leaves, r_leaves, keys):
            x = g.astype(jnp.float32)
            if ef:
                x = x + r[0]
            out = self._psum(x, k, with_error=ef)
            if ef:
                res, err = out
                new_res.append(err[None])
            else:
                res = out
            if self.plain_axes:
                res = lax.pmean(res, self.plain_axes)
            synced.append(res.astype(g.dtype))
        synced_tree = jax.tree_util.tree_unflatten(treedef, synced)
        residual_tree = (jax.tree_util.tree_unflatten(treedef, new_res)
                         if ef else ())
        return synced_tree, residual_tree

    def sync_bucketed(self, grads, residual,
                      rng: Optional[jax.Array] = None,
                      barrier: Optional[bool] = None):
        """Inside ``shard_map``: like :meth:`sync` but the gradient
        leaves coalesce into size-targeted buckets
        (``policy.bucket_bytes``) and each bucket syncs through ONE
        collective whose only data dependency is its own leaves — small
        leaves amortize collective latency, and XLA's latency-hiding
        scheduler is free to issue a bucket's (DCN) transfer as soon as
        its gradients exist, overlapping it with the remaining backward
        compute instead of paying the whole sync at an end-of-backward
        barrier.  ``barrier=True`` (bench A/B; default
        ``policy.barrier_sync``) deliberately re-creates that barrier:
        every bucket payload is tied to the COMPLETE gradient tree with
        an ``optimization_barrier`` so no collective can start until
        the full backward has finished."""
        barrier = self.policy.barrier_sync if barrier is None else barrier
        ef = self.error_feedback
        g_leaves, treedef = jax.tree_util.tree_flatten(grads)
        r_leaves = jax.tree_util.tree_leaves(residual) if ef \
            else [None] * len(g_leaves)
        buckets = partition_buckets(
            [leaf.size * 4 for leaf in g_leaves], self.policy.bucket_bytes)
        payloads = []
        for idxs in buckets:
            parts = []
            for i in idxs:
                x = g_leaves[i].astype(jnp.float32)
                if ef:
                    x = x + r_leaves[i][0]
                parts.append(x.ravel())
            payloads.append(parts[0] if len(parts) == 1
                            else jnp.concatenate(parts))
        if barrier:
            payloads = list(lax.optimization_barrier(tuple(payloads)))
        keys = self._leaf_keys(rng, len(payloads))
        synced = [None] * len(g_leaves)
        new_res = [None] * len(g_leaves)
        for idxs, payload, k in zip(buckets, payloads, keys):
            out = self._psum(payload, k, with_error=ef)
            res, err = (out if ef else (out, None))
            off = 0
            for i in idxs:
                g = g_leaves[i]
                piece = res[off:off + g.size].reshape(g.shape)
                if self.plain_axes:
                    piece = lax.pmean(piece, self.plain_axes)
                synced[i] = piece.astype(g.dtype)
                if ef:
                    new_res[i] = err[off:off + g.size].reshape(
                        g.shape)[None]
                off += g.size
        synced_tree = jax.tree_util.tree_unflatten(treedef, synced)
        residual_tree = (jax.tree_util.tree_unflatten(treedef, new_res)
                         if ef else ())
        return synced_tree, residual_tree

    def sync_step(self, grads, residual,
                  rng: Optional[jax.Array] = None):
        """The step builder's entry point: bucketed overlap scheduling
        when ``policy.bucket_bytes > 0``, per-leaf sync otherwise."""
        if self.policy.bucket_bytes > 0:
            return self.sync_bucketed(grads, residual, rng=rng)
        return self.sync(grads, residual, rng=rng)

    # -- global-view param re-gather (ZeRO-1 satellite path) -------------

    def regather_params(self, params):
        """Global view (NOT inside shard_map): route the updated params
        through a quantize→replicate→dequantize sandwich so the
        partitioner's post-update all-gather carries the compressed
        dtype.  ``with_sharding_constraint`` pins the update shard-wise
        (the ZeRO layout) and the replication constraint on the
        compressed payload forms the low-precision all-gather.

        With ``policy.gather_bucket_bytes > 0`` the gather is
        additionally LATENCY-HIDDEN: gatherable leaves are reordered
        into the next forward's consumption order
        (:func:`_consumption_order` — embeddings, then blocks by
        numeric layer index; flax's alphabetical h0/h1/h10 is not
        execution order), partitioned into size-targeted buckets
        (:func:`partition_buckets`, the sync_bucketed machinery), and
        each bucket's shard-side payloads are tied into one scheduling
        unit with ``optimization_barrier`` — every bucket's all-gather
        depends only on its own leaves' updates, so XLA's
        latency-hiding scheduler can stream early buckets (the params
        the next forward touches first) while later updates are still
        computing, instead of draining one monolithic end-of-step
        gather.  ``policy.barrier_sync`` (bench A/B) deliberately
        rebuilds the monolith: ONE barrier over the whole tree before
        any gather.  This path activates even with ``param_gather ==
        "none"`` — the explicit gather then moves the param dtype
        uncompressed; only the scheduling changes."""
        gather_bkt = self.policy.gather_bucket_bytes
        if self._param_gather_spec_fn is None or (
                self.policy.param_gather == "none" and gather_bkt <= 0):
            return params
        mesh = self.mesh

        flat, treedef = jax.tree_util.tree_flatten_with_path(params)
        sharded: list = [None] * len(flat)
        gatherable: list[int] = []
        for i, (path, p) in enumerate(flat):
            spec = self._param_gather_spec_fn(mesh, _path_str(path), p)
            if any(e is not None for e in spec):
                gatherable.append(i)
                sharded[i] = lax.with_sharding_constraint(
                    p, NamedSharding(mesh, spec))

        if gather_bkt > 0 and gatherable:
            ordered = [gatherable[j] for j in _consumption_order(
                [_path_str(flat[i][0]) for i in gatherable])]
            if self.policy.barrier_sync:
                groups = [ordered]       # monolithic A/B: one barrier
            else:
                sizes = [flat[i][1].size * flat[i][1].dtype.itemsize
                         for i in ordered]
                groups = [[ordered[j] for j in idxs]
                          for idxs in partition_buckets(sizes, gather_bkt)]
            for group in groups:
                tied = lax.optimization_barrier(
                    tuple(sharded[i] for i in group))
                for i, t in zip(group, tied):
                    sharded[i] = t

        out = [p if sharded[i] is None
               else self._gather_leaf(sharded[i], p)
               for i, (_, p) in enumerate(flat)]
        return jax.tree_util.tree_unflatten(treedef, out)

    def _gather_leaf(self, p_sh, p):
        """Form one leaf's explicit all-gather: replicate-constrain the
        (optionally codec-compressed) shard-constrained value."""
        mode = self.policy.param_gather
        bs = self.policy.block_size
        rep = NamedSharding(self.mesh, P())
        if mode == "none":
            return lax.with_sharding_constraint(p_sh, rep)
        if mode == "bf16":
            q = lax.with_sharding_constraint(
                p_sh.astype(jnp.bfloat16), rep)
            return q.astype(p.dtype)
        # int8: blockwise along the last dim when it divides, else a
        # per-tensor scale (padding a sharded dim inside global view
        # could cost a reshard — not worth it for odd shapes)
        if p.shape[-1] % bs == 0:
            from ray_lightning_tpu.comm.quant import (
                blockwise_dequantize, blockwise_quantize)
            q, scale = blockwise_quantize(p_sh.astype(jnp.float32), bs)
            q = lax.with_sharding_constraint(q, rep)
            scale = lax.with_sharding_constraint(scale, rep)
            return blockwise_dequantize(q, scale, bs).astype(p.dtype)
        amax = jnp.max(jnp.abs(p_sh.astype(jnp.float32)))
        scale = amax / 127.0
        inv = jnp.where(scale > 0, 1.0 / jnp.where(scale > 0, scale,
                                                   1.0), 0.0)
        q = jnp.clip(jnp.round(p_sh.astype(jnp.float32) * inv),
                     -127, 127).astype(jnp.int8)
        q = lax.with_sharding_constraint(q, rep)
        return (q.astype(jnp.float32) * scale).astype(p.dtype)

    # -- metrics accounting ----------------------------------------------

    def reduce_scatter_wire_bytes(self, n_elements: int) -> int:
        return payload_bytes(n_elements, self.policy.compress,
                             self.policy.block_size)

    def all_gather_wire_bytes(self, n_elements: int) -> int:
        return payload_bytes(n_elements, self.policy.compress,
                             self.policy.block_size)

    def psum_wire_bytes(self, n_elements: int) -> int:
        return (self.reduce_scatter_wire_bytes(n_elements)
                + self.all_gather_wire_bytes(n_elements))

    def psum_link_bytes(self, n_elements: int) -> dict:
        """Per-rank wire bytes of ONE mean-psum of ``n_elements``, split
        by link tier — the per-link attribution the planner's cost
        model and the ``rlt_comm_dcn_bytes_total`` series consume.
        Flat: both compressed phases ride whatever link the axis spans
        (charged as the slow tier; a single-host run has no DCN hop and
        the scorer maps it to ICI speed).  Hierarchical: only the
        level-2 phases on the 1/ici shard cross DCN; levels 1 and 3
        move fp32 inside the ICI group."""
        if not self.hierarchical:
            return {"dcn": self.psum_wire_bytes(n_elements), "ici": 0}
        shard = -(-n_elements // self.ici_size)
        dcn = 2 * payload_bytes(shard, self.policy.compress,
                                self.policy.block_size)
        # level 1 all_to_all moves the full fp32 rows, level 3
        # all-gathers the fp32 result back: ~8 bytes/element on the
        # fast link (the EQuARX trade: fp32 where bandwidth is cheap)
        ici = 4 * n_elements + 4 * shard * self.ici_size
        return {"dcn": dcn, "ici": ici}

    def param_gather_wire_bytes(self, abstract_params) -> int:
        total = 0
        for leaf in jax.tree_util.tree_leaves(abstract_params):
            n = int(np.prod(getattr(leaf, "shape", ()), dtype=np.int64))
            if self.policy.param_gather == "none":
                total += n * np.dtype(leaf.dtype).itemsize
            else:
                total += payload_bytes(n, self.policy.param_gather,
                                       self.policy.block_size)
        return total


def _consumption_order(paths: "list[str]") -> "list[int]":
    """Indices of ``paths`` sorted into the next forward's consumption
    order: the embedding tables first (``wte``/``wpe`` feed the first
    op of the next step), then transformer blocks by NUMERIC layer
    suffix (``h0, h1, ..., h10`` — flax's alphabetical flatten order
    puts h10 before h2), the final norm and any head last.  Ties break
    on the path string so the order is deterministic.  This is the
    order the latency-hidden ZeRO-1 gather buckets in: the earliest
    bucket holds the params the forward touches first, so its gather
    has the most downstream compute to hide behind."""
    import re

    def key(item):
        _, pstr = item
        head = pstr.split("/", 1)[0].lower()
        if head in ("wte", "wpe", "embed", "embedding", "embeddings"):
            return (0, 0, pstr)
        m = re.fullmatch(r"[a-z_]*?(\d+)", head)
        if m:
            return (1, int(m.group(1)), pstr)
        if head.startswith("ln_f") or head in ("final_norm", "norm_f"):
            return (2, 0, pstr)
        return (3, 0, pstr)

    return [i for i, _ in sorted(enumerate(paths), key=key)]


def _path_str(path) -> str:
    parts = []
    for p in path:
        for attr in ("key", "idx", "name"):
            if hasattr(p, attr):
                parts.append(str(getattr(p, attr)))
                break
        else:
            parts.append(str(p))
    return "/".join(parts)


def build_grad_sync(strategy, mesh, policy) -> Optional[GradSync]:
    """Resolve (strategy, mesh, policy) → :class:`GradSync` or ``None``.

    ``None`` (compression inert) when: the policy is off / unresolved,
    no compressible axis exists on this mesh, the strategy keeps its
    params sharded across the reduction axes (FSDP/SPMD — the mapped
    region assumes replicated params), or the mesh carries non-data
    axes the pure-data-parallel mapped region cannot represent."""
    policy = CommPolicy.resolve(policy)
    if not policy.enabled:
        return None
    if not getattr(strategy, "comm_compressible", False):
        _log.debug("comm policy inert: strategy %s does not support "
                   "compressed gradient collectives", strategy.name)
        return None
    extra = set(mesh.axis_names) - set(strategy.data_axis_names)
    if any(mesh.shape[a] > 1 for a in extra):
        _log.debug("comm policy inert: mesh has non-data axes %s",
                   sorted(extra))
        return None
    axes = policy.resolved_axes(mesh, strategy.data_axis_names)
    if not axes:
        return None
    spec_fn = None
    if policy.param_gather != "none" or policy.gather_bucket_bytes > 0:
        spec_fn = getattr(strategy, "param_gather_spec", None)
    return GradSync(mesh, axes, policy, strategy.data_axis_names,
                    param_gather_spec_fn=spec_fn)
