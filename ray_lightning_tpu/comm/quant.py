"""Blockwise quantize/dequantize kernels for the compressed collectives.

Everything here is pure ``jax.numpy`` — elementwise math plus small
reshapes — so the kernels trace into the jitted train step (inside or
outside a ``shard_map`` region) and fuse with the surrounding program;
there is no Python-side fallback path to diverge from.

int8 scheme: symmetric per-block scaling.  A flat payload is viewed as
``[..., n_blocks, block_size]``; each block carries one fp32 scale
``max|x| / 127`` and stores ``round(x / scale)`` in int8.  Zero blocks
quantize to zeros with a zero scale (the dequant multiply restores exact
zeros — no division guard needed on the decode side).  Stochastic
rounding (``floor(x/scale + u)``, u ~ U[0,1)) makes the quantizer
unbiased at the cost of one uniform draw per element — the EQuARX
recommendation for repeated-accumulation settings.

fp8 scheme (e4m3): per-block scaling to the e4m3fn range (max 448),
then a cast to ``float8_e4m3fn``.  Same byte count as int8 but a
*relative* error bound (~2^-4 per element at 3 mantissa bits) instead
of int8's absolute-within-block one — outlier-heavy blocks keep their
small elements.  The wire payload is bitcast to ``uint8`` so every
backend moves exactly one byte per element (XLA CPU would otherwise
widen an f8 collective to f16).  Stochastic rounding picks between the
two neighboring e4m3 grid points with probability proportional to the
distance — exactly unbiased, like the int path.

int4 scheme: per-block scales ``max|x| / 7``, values in [-7, 7] stored
offset-encoded (q+8) two to a byte — the wire payload's last dim is
HALF the element count.  The most aggressive codec; intended for the
DCN hop of a hierarchical reduction where error feedback absorbs the
coarser grid.

bf16 scheme: a plain cast (no scales).  Half the bytes of fp32, exact
for the ~8 mantissa bits kept; used when int8's 4x is too aggressive for
a workload.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

INT8_LEVELS = 127.0
INT4_LEVELS = 7.0
FP8_MAX = 448.0          # float8_e4m3fn max finite value
FP8_MANT_BITS = 3
FP8_MIN_EXP = -6         # smallest normal exponent of e4m3

#: every mode ``compress_cast`` accepts (policy.py validates against it)
CODEC_MODES = ("int8", "bf16", "fp8", "int4")


def _block_view(x: jax.Array, block_size: int) -> jax.Array:
    """[..., n] -> [..., n // bs, bs]; n must already divide."""
    if x.shape[-1] % block_size:
        raise ValueError(
            f"last dim {x.shape[-1]} not a multiple of block {block_size}")
    return x.reshape(x.shape[:-1] + (x.shape[-1] // block_size, block_size))


def _block_scale(blocks: jax.Array, levels: float):
    """(scale, inv_scale) per block; zero blocks get zero for both."""
    scale = jnp.max(jnp.abs(blocks), axis=-1) / levels
    inv = jnp.where(scale > 0, 1.0 / jnp.where(scale > 0, scale, 1.0), 0.0)
    return scale, inv


def _int_round(val: jax.Array, levels: float, *, stochastic: bool, rng):
    if stochastic:
        if rng is None:
            raise ValueError("stochastic rounding needs an rng key")
        val = jnp.floor(val + jax.random.uniform(rng, val.shape))
    else:
        val = jnp.round(val)
    return jnp.clip(val, -levels, levels)


def blockwise_quantize(x: jax.Array, block_size: int = 64, *,
                       stochastic: bool = False,
                       rng: "jax.Array | None" = None):
    """Quantize ``x`` (last dim a multiple of ``block_size``) to int8.

    Returns ``(q, scale)``: ``q`` int8 shaped like ``x``, ``scale`` fp32
    shaped ``[..., n_blocks]`` (one per block of the last dim).
    """
    blocks = _block_view(x.astype(jnp.float32), block_size)
    scale, inv = _block_scale(blocks, INT8_LEVELS)
    q = _int_round(blocks * inv[..., None], INT8_LEVELS,
                   stochastic=stochastic, rng=rng).astype(jnp.int8)
    return q.reshape(x.shape), scale


def blockwise_dequantize(q: jax.Array, scale: jax.Array,
                         block_size: int = 64) -> jax.Array:
    """Inverse of :func:`blockwise_quantize` (fp32 out)."""
    blocks = _block_view(q.astype(jnp.float32), block_size)
    return (blocks * scale[..., None]).reshape(q.shape)


# -- fp8 (e4m3) -------------------------------------------------------------


def _fp8_stochastic_round(v: jax.Array, rng) -> jax.Array:
    """Exact stochastic rounding onto the e4m3 grid: pick the lower /
    upper neighboring representable value with probability proportional
    to the fractional distance (E[result] == v).  ``v`` must already be
    scaled into [-FP8_MAX, FP8_MAX]; the result is exactly
    representable, so the following round-to-nearest cast is lossless.
    """
    if rng is None:
        raise ValueError("stochastic rounding needs an rng key")
    a = jnp.abs(v)
    e = jnp.floor(jnp.log2(jnp.where(a > 0, a, 1.0)))
    e = jnp.clip(e, FP8_MIN_EXP, 8)           # subnormals share 2^-6's ulp
    ulp = jnp.exp2(e - FP8_MANT_BITS)
    lower = jnp.floor(a / ulp) * ulp
    frac = (a - lower) / ulp
    u = jax.random.uniform(rng, v.shape)
    a_sr = jnp.minimum(lower + jnp.where(u < frac, ulp, 0.0), FP8_MAX)
    return jnp.sign(v) * a_sr


def fp8_blockwise_quantize(x: jax.Array, block_size: int = 64, *,
                           stochastic: bool = False,
                           rng: "jax.Array | None" = None):
    """Quantize to e4m3 with per-block range scaling.  Returns
    ``(payload, scale)`` with ``payload`` the f8 bit pattern as uint8
    (shaped like ``x``) — one byte per element on every backend."""
    blocks = _block_view(x.astype(jnp.float32), block_size)
    scale_range, inv = _block_scale(blocks, FP8_MAX)
    val = blocks * inv[..., None]
    if stochastic:
        val = _fp8_stochastic_round(val, rng)
    q8 = val.astype(jnp.float8_e4m3fn)        # RN cast; |val| <= 448 so
    #                                           it can never overflow
    payload = jax.lax.bitcast_convert_type(q8, jnp.uint8)
    return payload.reshape(x.shape), scale_range


def fp8_blockwise_dequantize(payload: jax.Array, scale: jax.Array,
                             block_size: int = 64) -> jax.Array:
    q8 = jax.lax.bitcast_convert_type(payload, jnp.float8_e4m3fn)
    blocks = _block_view(q8.astype(jnp.float32), block_size)
    return (blocks * scale[..., None]).reshape(payload.shape)


# -- int4 (nibble-packed) ---------------------------------------------------


def int4_blockwise_quantize(x: jax.Array, block_size: int = 64, *,
                            stochastic: bool = False,
                            rng: "jax.Array | None" = None):
    """Quantize to 4-bit levels [-7, 7] with per-block scales, packing
    two values per byte.  Returns ``(payload, scale)`` with ``payload``
    uint8 shaped ``[..., n // 2]`` — the only codec whose wire shape
    differs from the input's.  ``block_size`` must be even."""
    if block_size % 2:
        raise ValueError(f"int4 needs an even block size, got {block_size}")
    blocks = _block_view(x.astype(jnp.float32), block_size)
    scale, inv = _block_scale(blocks, INT4_LEVELS)
    q = _int_round(blocks * inv[..., None], INT4_LEVELS,
                   stochastic=stochastic, rng=rng)
    # offset-encode to [1, 15] and pack adjacent pairs into one byte
    q = (q + 8.0).astype(jnp.uint8).reshape(x.shape)
    pairs = q.reshape(x.shape[:-1] + (x.shape[-1] // 2, 2))
    packed = pairs[..., 0] | (pairs[..., 1] << 4)
    return packed, scale


def int4_blockwise_dequantize(payload: jax.Array, scale: jax.Array,
                              block_size: int = 64) -> jax.Array:
    lo = (payload & 0xF).astype(jnp.float32) - 8.0
    hi = (payload >> 4).astype(jnp.float32) - 8.0
    full = jnp.stack([lo, hi], axis=-1).reshape(
        payload.shape[:-1] + (payload.shape[-1] * 2,))
    blocks = _block_view(full, block_size)
    return (blocks * scale[..., None]).reshape(full.shape)


# -- uniform codec dispatch -------------------------------------------------


def compress_cast(x: jax.Array, mode: str, block_size: int = 64, *,
                  stochastic: bool = False,
                  rng: "jax.Array | None" = None):
    """Uniform ``(payload, scale)`` encode for every codec: int8/fp8
    return a 1-byte payload shaped like ``x`` plus per-block scales,
    int4 a half-length packed payload, bf16 the cast with
    ``scale=None``."""
    if mode == "bf16":
        return x.astype(jnp.bfloat16), None
    if mode == "int8":
        return blockwise_quantize(x, block_size, stochastic=stochastic,
                                  rng=rng)
    if mode == "fp8":
        return fp8_blockwise_quantize(x, block_size, stochastic=stochastic,
                                      rng=rng)
    if mode == "int4":
        return int4_blockwise_quantize(x, block_size, stochastic=stochastic,
                                       rng=rng)
    raise ValueError(f"unknown compression mode {mode!r}; "
                     f"options: {CODEC_MODES}")


def decompress_cast(q: jax.Array, scale, mode: str,
                    block_size: int = 64) -> jax.Array:
    """fp32 decode matching :func:`compress_cast`."""
    if mode == "bf16":
        return q.astype(jnp.float32)
    if mode == "int8":
        return blockwise_dequantize(q, scale, block_size)
    if mode == "fp8":
        return fp8_blockwise_dequantize(q, scale, block_size)
    if mode == "int4":
        return int4_blockwise_dequantize(q, scale, block_size)
    raise ValueError(f"unknown compression mode {mode!r}; "
                     f"options: {CODEC_MODES}")


def payload_bytes(n_elements: int, mode: str, block_size: int = 64) -> int:
    """Wire bytes one rank's ``n_elements`` payload occupies compressed
    (1-byte data + fp32 per-block scales for int8/fp8; int4 packs two
    elements per byte; bf16 has no scales; ``raw`` — and any other
    uncompressed mode — charges fp32).  Used by the strategies'
    ``step_collective_bytes`` so the metrics plane charges the
    *compressed* traffic, and by the fleet's KV-ship accounting
    (serve/fleet/router.py) so codec savings are measured in the same
    units as the raw A/B control leg."""
    if mode == "bf16":
        return 2 * n_elements
    n_blocks = -(-n_elements // block_size)
    if mode == "int8" or mode == "fp8":
        return n_elements + 4 * n_blocks
    if mode == "int4":
        return -(-n_elements // 2) + 4 * n_blocks
    return 4 * n_elements


def quantize_blob(x, mode: str, block_size: int = 64):
    """Shape-agnostic ``(payload, scale)`` encode for whole tensors.

    The blockwise kernels above require the last dim to divide
    ``block_size`` (they view a wire payload whose length the comm plane
    controls).  Arbitrary model/KV tensors don't oblige, so this wrapper
    flattens to 1-D and zero-pads up to a block multiple before
    encoding; :func:`dequantize_blob` strips the pad.  ``mode="raw"``
    passes through untouched (the A/B control leg of KV shipping).  Used
    for int8 draft-weight residency (serve/engine.py) and the fp8/int4
    KV-page ship codecs (serve/fleet/router.py) — both settings where
    the tensor, not a wire chunk, is the unit."""
    x = jnp.asarray(x)
    if mode == "raw":
        # fp32 on the wire: raw is the UNCOMPRESSED control leg, so it
        # must cost the full 4 bytes/element the codec ratios are
        # measured against (payload_bytes' fallback row).
        return x.astype(jnp.float32), None
    flat = x.reshape(-1).astype(jnp.float32)
    pad = (-flat.shape[0]) % block_size
    if pad and mode != "bf16":
        flat = jnp.pad(flat, (0, pad))
    return compress_cast(flat, mode, block_size)


def dequantize_blob(payload, scale, mode: str, shape,
                    block_size: int = 64, dtype=jnp.float32):
    """Decode matching :func:`quantize_blob`: unpad, reshape to
    ``shape``, cast to ``dtype``.  Pure ``jax.numpy`` — traces into
    jitted programs (the draft step dequantizes resident int8 weights
    inline) and runs eagerly host-side (KV-ship import)."""
    if mode == "raw":
        return jnp.asarray(payload).reshape(shape).astype(dtype)
    flat = decompress_cast(payload, scale, mode, block_size)
    n = 1
    for d in shape:
        n *= int(d)
    return flat[:n].reshape(shape).astype(dtype)
