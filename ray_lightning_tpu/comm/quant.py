"""Blockwise quantize/dequantize kernels for the compressed collectives.

Everything here is pure ``jax.numpy`` — elementwise math plus small
reshapes — so the kernels trace into the jitted train step (inside or
outside a ``shard_map`` region) and fuse with the surrounding program;
there is no Python-side fallback path to diverge from.

int8 scheme: symmetric per-block scaling.  A flat payload is viewed as
``[..., n_blocks, block_size]``; each block carries one fp32 scale
``max|x| / 127`` and stores ``round(x / scale)`` in int8.  Zero blocks
quantize to zeros with a zero scale (the dequant multiply restores exact
zeros — no division guard needed on the decode side).  Stochastic
rounding (``floor(x/scale + u)``, u ~ U[0,1)) makes the quantizer
unbiased at the cost of one uniform draw per element — the EQuARX
recommendation for repeated-accumulation settings.

bf16 scheme: a plain cast (no scales).  Half the bytes of fp32, exact
for the ~8 mantissa bits kept; used when int8's 4x is too aggressive for
a workload.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

INT8_LEVELS = 127.0


def _block_view(x: jax.Array, block_size: int) -> jax.Array:
    """[..., n] -> [..., n // bs, bs]; n must already divide."""
    if x.shape[-1] % block_size:
        raise ValueError(
            f"last dim {x.shape[-1]} not a multiple of block {block_size}")
    return x.reshape(x.shape[:-1] + (x.shape[-1] // block_size, block_size))


def blockwise_quantize(x: jax.Array, block_size: int = 64, *,
                       stochastic: bool = False,
                       rng: "jax.Array | None" = None):
    """Quantize ``x`` (last dim a multiple of ``block_size``) to int8.

    Returns ``(q, scale)``: ``q`` int8 shaped like ``x``, ``scale`` fp32
    shaped ``[..., n_blocks]`` (one per block of the last dim).
    """
    blocks = _block_view(x.astype(jnp.float32), block_size)
    scale = jnp.max(jnp.abs(blocks), axis=-1) / INT8_LEVELS
    inv = jnp.where(scale > 0, 1.0 / jnp.where(scale > 0, scale, 1.0), 0.0)
    val = blocks * inv[..., None]
    if stochastic:
        if rng is None:
            raise ValueError("stochastic rounding needs an rng key")
        val = jnp.floor(val + jax.random.uniform(rng, val.shape))
    else:
        val = jnp.round(val)
    q = jnp.clip(val, -INT8_LEVELS, INT8_LEVELS).astype(jnp.int8)
    return q.reshape(x.shape), scale


def blockwise_dequantize(q: jax.Array, scale: jax.Array,
                         block_size: int = 64) -> jax.Array:
    """Inverse of :func:`blockwise_quantize` (fp32 out)."""
    blocks = _block_view(q.astype(jnp.float32), block_size)
    return (blocks * scale[..., None]).reshape(q.shape)


def compress_cast(x: jax.Array, mode: str, block_size: int = 64, *,
                  stochastic: bool = False,
                  rng: "jax.Array | None" = None):
    """Uniform (q, scale) encode for either mode: int8 returns blockwise
    payload + scales, bf16 returns the cast payload with ``scale=None``."""
    if mode == "bf16":
        return x.astype(jnp.bfloat16), None
    if mode == "int8":
        return blockwise_quantize(x, block_size, stochastic=stochastic,
                                  rng=rng)
    raise ValueError(f"unknown compression mode {mode!r}")


def decompress_cast(q: jax.Array, scale, mode: str,
                    block_size: int = 64) -> jax.Array:
    """fp32 decode matching :func:`compress_cast`."""
    if mode == "bf16":
        return q.astype(jnp.float32)
    return blockwise_dequantize(q, scale, block_size)


def payload_bytes(n_elements: int, mode: str, block_size: int = 64) -> int:
    """Wire bytes one rank's ``n_elements`` payload occupies compressed
    (int8 data + fp32 per-block scales; bf16 has no scales).  Used by the
    strategies' ``step_collective_bytes`` so the metrics plane charges
    the *compressed* traffic."""
    if mode == "bf16":
        return 2 * n_elements
    if mode == "int8":
        n_blocks = -(-n_elements // block_size)
        return n_elements + 4 * n_blocks
    return 4 * n_elements
