"""Measured-bandwidth calibration for the byte→seconds cost model.

The planner's ranking (plan/cost.py) and the audit's byte→seconds
conversion (comm/audit.py) run on per-link GB/s constants that are
deliberately coarse — right order of magnitude per fabric generation,
wrong for any particular deployment.  ``RLT_PLAN_CALIBRATE=1`` replaces
them with MEASURED values: a tiny collective microbench (one fp32
all-reduce per link tier, a few repeats, first dispatch discarded as
compile) runs once and caches its result as JSON keyed by the exact
topology fingerprint, so every later fit/plan on the same machine reads
the file instead of re-measuring.

Cache location (first match wins): the explicit ``cache_dir`` argument,
``$RLT_CALIBRATE_DIR``, ``$RLT_TELEMETRY_DIR`` (the telemetry artifact
dir when the caller exports one), else ``~/.cache/ray_lightning_tpu``.

Links measured:

- **ICI**: all-reduce across this process's local devices (needs >= 2;
  a single-chip host keeps the constant).  On the CPU test mesh this
  measures the host's memcpy fabric — not a TPU number, but exactly
  what a CPU-mesh plan should rank with.
- **DCN**: all-reduce across processes (needs ``jax.process_count() >
  1``; single-process runs keep the constant — there is no DCN hop to
  measure).

Never raises into the planner: any measurement failure falls back to
the audit constants and records why.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Optional

from ray_lightning_tpu.comm.audit import DCN_GBPS, ICI_GBPS

_log = logging.getLogger(__name__)

#: payload of the microbench collective (fp32 elements).  8 MiB: big
#: enough to be bandwidth- not latency-bound on both tiers, small
#: enough to be instant anywhere.
PAYLOAD_ELEMENTS = 2 * 1024 * 1024
REPEATS = 5

ENV_DIR = "RLT_CALIBRATE_DIR"


def _cache_dir(cache_dir: Optional[str]) -> str:
    return (cache_dir or os.environ.get(ENV_DIR)
            or os.environ.get("RLT_TELEMETRY_DIR")
            or os.path.join(os.path.expanduser("~"), ".cache",
                            "ray_lightning_tpu"))


def topology_fingerprint() -> str:
    import jax
    dev = jax.devices()[0]
    return (f"jax{jax.__version__}-{dev.platform}-"
            f"{getattr(dev, 'device_kind', 'cpu').replace(' ', '_')}-"
            f"d{jax.device_count()}-p{jax.process_count()}")


def cache_path(cache_dir: Optional[str] = None) -> str:
    return os.path.join(_cache_dir(cache_dir),
                        f"bandwidth_{topology_fingerprint()}.json")


def _time_allreduce(devices) -> "tuple[float, int]":
    """(seconds per all-reduce, per-rank wire bytes) over ``devices``
    under the audit's ring model (all-reduce = 2 x result bytes)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    n = len(devices)
    mesh = Mesh(np.asarray(devices, dtype=object).reshape(n), ("x",))
    x = jax.device_put(
        np.ones((n, PAYLOAD_ELEMENTS // n), np.float32),
        NamedSharding(mesh, P("x")))

    @jax.jit
    def allreduce(v):
        return jnp.broadcast_to(jnp.sum(v, axis=0, keepdims=True),
                                v.shape)

    allreduce(x).block_until_ready()          # compile outside the clock
    t0 = time.monotonic()
    for _ in range(REPEATS):
        out = allreduce(x)
    out.block_until_ready()
    per_op = (time.monotonic() - t0) / REPEATS
    wire_bytes = 2 * 4 * PAYLOAD_ELEMENTS     # ring all-reduce, fp32
    return per_op, wire_bytes


def measure_bandwidths() -> dict:
    """One measurement pass (no cache): ``{"ici_gbps", "dcn_gbps",
    "measured": [...], "fingerprint", ...}`` with un-measurable links
    left at the audit constants."""
    import jax

    result = {
        "fingerprint": topology_fingerprint(),
        "ici_gbps": ICI_GBPS,
        "dcn_gbps": DCN_GBPS,
        "measured": [],
        "payload_bytes": 4 * PAYLOAD_ELEMENTS,
    }
    local = jax.local_devices()
    if len(local) >= 2:
        try:
            secs, wire = _time_allreduce(local)
            result["ici_gbps"] = round(wire / secs / 1e9, 3)
            result["ici_seconds"] = secs
            result["measured"].append("ici")
        except Exception as e:   # noqa: BLE001 - calibration never fails
            result["ici_error"] = repr(e)
    if jax.process_count() > 1:
        try:
            secs, wire = _time_allreduce(jax.devices())
            result["dcn_gbps"] = round(wire / secs / 1e9, 3)
            result["dcn_seconds"] = secs
            result["measured"].append("dcn")
        except Exception as e:   # noqa: BLE001
            result["dcn_error"] = repr(e)
    return result


def calibrated_gbps(cache_dir: Optional[str] = None,
                    force: bool = False) -> "tuple[float, float]":
    """``(ici_gbps, dcn_gbps)`` from the topology-keyed cache file,
    measuring (and writing the cache) on first use.  Falls back to the
    audit constants on any failure — the planner must always get a
    number."""
    path = cache_path(cache_dir)
    if not force:
        try:
            with open(path) as f:
                data = json.load(f)
            return float(data["ici_gbps"]), float(data["dcn_gbps"])
        except FileNotFoundError:
            pass
        except Exception as e:   # noqa: BLE001 - corrupt cache: remeasure
            _log.warning("bandwidth cache %s unreadable (%r); remeasuring",
                         path, e)
    try:
        data = measure_bandwidths()
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(data, f, indent=2, sort_keys=True)
        os.replace(tmp, path)
        _log.info("calibrated link bandwidths %s -> %s",
                  {k: data[k] for k in ("ici_gbps", "dcn_gbps")}, path)
        return float(data["ici_gbps"]), float(data["dcn_gbps"])
    except Exception as e:   # noqa: BLE001 - constants beat a crash
        _log.warning("bandwidth calibration failed (%r); using the "
                     "audit constants", e)
        return ICI_GBPS, DCN_GBPS


# -- live (anatomy-measured) calibration ----------------------------------
#
# The microbench above measures an idealized standalone all-reduce.  A
# real fit's anatomy window (telemetry/anatomy.py) measures the exposed
# comm of the ACTUAL step program — overlap, fusion boundaries and all.
# The ratio of measured exposed to the planner's modeled comm seconds is
# a per-topology correction factor (``comm_scale``): the trainer writes
# it at the end of every instrumented run
# (core/trainer.py _attach_observed_divergence), and
# ``RLT_PLAN_CALIBRATE=live`` divides the link constants by it so the
# NEXT plan's byte→seconds model starts from what the fabric actually
# delivered (ROADMAP 5(a) leg).

#: sane bounds on the correction: outside this the anatomy window was
#: degenerate (empty modeled comm, or a pathological capture) and the
#: sample is discarded rather than poisoning the next plan
LIVE_SCALE_BOUNDS = (0.1, 10.0)


def live_cache_path(cache_dir: Optional[str] = None) -> str:
    return os.path.join(_cache_dir(cache_dir),
                        f"live_{topology_fingerprint()}.json")


def save_live_calibration(step_wall_s: float, exposed_comm_s: float,
                          modeled_comm_s: Optional[float],
                          cache_dir: Optional[str] = None
                          ) -> Optional[str]:
    """Persist one run's measured-vs-modeled comm correction, keyed by
    topology fingerprint.  Returns the path, or None when the sample is
    unusable (no modeled comm, out-of-bounds ratio, any failure) — a
    bad window must never poison the next plan."""
    try:
        if not modeled_comm_s or float(modeled_comm_s) <= 0:
            return None
        scale = float(exposed_comm_s) / float(modeled_comm_s)
        if not (LIVE_SCALE_BOUNDS[0] <= scale <= LIVE_SCALE_BOUNDS[1]):
            _log.info("live calibration sample discarded: comm_scale "
                      "%.3f outside %s", scale, LIVE_SCALE_BOUNDS)
            return None
        path = live_cache_path(cache_dir)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        doc = {
            "fingerprint": topology_fingerprint(),
            "comm_scale": round(scale, 4),
            "step_wall_s": round(float(step_wall_s), 6),
            "exposed_comm_s": round(float(exposed_comm_s), 6),
            "modeled_comm_s": round(float(modeled_comm_s), 6),
            "ts": time.time(),
        }
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
        os.replace(tmp, path)
        _log.info("live comm calibration: measured/modeled = %.3f -> %s",
                  scale, path)
        return path
    except Exception:   # noqa: BLE001 - calibration never raises
        _log.debug("live calibration write failed", exc_info=True)
        return None


def live_calibration(cache_dir: Optional[str] = None) -> Optional[dict]:
    """The stored live correction for THIS topology, or None."""
    try:
        with open(live_cache_path(cache_dir)) as f:
            doc = json.load(f)
        scale = float(doc["comm_scale"])
        if not (LIVE_SCALE_BOUNDS[0] <= scale <= LIVE_SCALE_BOUNDS[1]):
            return None
        return doc
    except Exception:   # noqa: BLE001 - missing/corrupt = no correction
        return None
