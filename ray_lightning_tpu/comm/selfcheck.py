"""Comm-plane selfcheck (wired into ``format.sh --check``).

Runs in a fresh interpreter so it can force a small virtual CPU mesh
BEFORE jax initializes, then asserts the invariants that don't need a
full training run:

- policy resolution on every built-in strategy: DDP / ZeRO-1 resolve to
  a GradSync on a multi-device data mesh, FSDP / SPMD / pipeline
  decline (params sharded), and the off policy is inert everywhere;
- the RLT_COMM* env knobs (codec, hierarchy, buckets included)
  round-trip through ``worker_env()`` → ``resolve()`` unchanged;
- the compressed collectives LOWER without error on a CPU mesh (every
  codec: int8 / bf16 / fp8 / int4, via the shard_map compat wrapper),
  the two-level hierarchical psum lowers with its grouped collectives,
  and the quantizer round-trips exactly-representable payloads
  bit-exactly;
- the bucket partitioner covers every leaf exactly once, in order;
- the comm metric names (rlt_comm_dcn_bytes_total,
  rlt_comm_exposed_seconds) are registered in the lint's CORE_METRICS
  surface.
"""

from __future__ import annotations

import os


def _main(argv) -> int:   # noqa: ARG001 - argv kept for parity
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from ray_lightning_tpu.comm import CommPolicy, build_grad_sync
    from ray_lightning_tpu.comm.collectives import compressed_psum
    from ray_lightning_tpu.comm.quant import (blockwise_dequantize,
                                              blockwise_quantize)
    from ray_lightning_tpu.parallel.mesh import shard_map_compat
    from ray_lightning_tpu.parallel.pipeline import PipelineStrategy
    from ray_lightning_tpu.parallel.strategy import (_STRATEGIES,
                                                     resolve_strategy)

    problems: list[str] = []
    policy = CommPolicy(compress="int8", axes=("data",))
    off = CommPolicy()

    # 1. policy resolution per built-in strategy
    expect_sync = {"ddp": True, "dp": True, "zero1": True, "sharded": True,
                   "fsdp": False, "zero3": False, "spmd": False}
    for name in sorted(_STRATEGIES):
        if name == "auto":
            # planner sentinel (plan/): resolved into one of the
            # concrete strategies below before any mesh/grad_sync exists
            continue
        strat = resolve_strategy(name)
        mesh = strat.build_mesh()
        got = build_grad_sync(strat, mesh, policy) is not None
        if got != expect_sync[name]:
            problems.append(
                f"strategy {name!r}: grad_transform resolved to "
                f"{'GradSync' if got else 'None'}, expected "
                f"{'GradSync' if expect_sync[name] else 'None'}")
        if build_grad_sync(strat, mesh, off) is not None:
            problems.append(f"strategy {name!r}: off policy not inert")
    pstrat = PipelineStrategy(stages=2)
    if build_grad_sync(pstrat, pstrat.build_mesh(), policy) is not None:
        problems.append("pipeline strategy should decline compression")

    # 2. env knob round-trip (hierarchy/bucket/barrier knobs included)
    src = CommPolicy(compress="fp8", axes=("data",), block_size=128,
                     stochastic_rounding=True, error_feedback=False,
                     param_gather="bf16", hierarchy=2,
                     bucket_bytes=1 << 20, barrier_sync=True,
                     gather_bucket_bytes=1 << 14)
    saved = {k: os.environ.get(k) for k in src.worker_env()}
    os.environ.update(src.worker_env())
    try:
        if CommPolicy.resolve(None) != src:
            problems.append("RLT_COMM* env round-trip changed the policy")
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    # 3. compressed collectives lower on the CPU mesh (every codec,
    #    flat AND two-level); quantizer exact on representable payloads
    from jax.sharding import PartitionSpec as P

    from ray_lightning_tpu.comm.collectives import (hierarchical_psum,
                                                    partition_buckets)
    strat = resolve_strategy("ddp")
    mesh = strat.build_mesh()
    world = mesh.shape["data"]
    for mode in ("int8", "bf16", "fp8", "int4"):
        def body(x, mode=mode):
            return compressed_psum(x[0], "data", world, mode=mode,
                                   mean=True)[None]
        fn = shard_map_compat(body, mesh, in_specs=P("data"),
                              out_specs=P("data"))
        try:
            jax.jit(fn).lower(
                jax.ShapeDtypeStruct((world, 300), np.float32)).compile()
        except Exception as e:   # noqa: BLE001 - report, don't crash
            problems.append(f"compressed psum ({mode}) failed to lower "
                            f"on the CPU mesh: {e!r}")

    def hier_body(x):
        return hierarchical_psum(x[0], "data", 2, world // 2,
                                 mode="int8", mean=True)[None]
    try:
        fn = shard_map_compat(hier_body, mesh, in_specs=P("data"),
                              out_specs=P("data"))
        jax.jit(fn).lower(
            jax.ShapeDtypeStruct((world, 300), np.float32)).compile()
    except Exception as e:   # noqa: BLE001
        problems.append(f"hierarchical psum failed to lower on the CPU "
                        f"mesh: {e!r}")

    # 3b. bucket partitioner invariant: every index exactly once, in
    # order, and the target is respected (oversized leaves go alone)
    for sizes, target in (([100, 200, 4000, 50, 50], 300),
                          ([8] * 7, 16), ([1], 0)):
        buckets = partition_buckets(sizes, target)
        flat = [i for b in buckets for i in b]
        if flat != list(range(len(sizes))):
            problems.append(
                f"bucket partition {buckets} of {sizes} does not cover "
                f"every leaf exactly once in order")
        if target > 0 and any(sum(sizes[i] for i in b) < target
                              for b in buckets[:-1]):
            problems.append(f"bucket partition {buckets} closed a "
                            f"bucket under target {target}")

    # 3c. comm metric names are on the lint surface
    from ray_lightning_tpu.telemetry.metrics import CORE_METRICS
    for name in ("rlt_comm_dcn_bytes_total", "rlt_comm_exposed_seconds"):
        if name not in CORE_METRICS:
            problems.append(f"{name} missing from telemetry CORE_METRICS")
    # two blocks whose max-abs is exactly 127 -> scale 1.0 -> integer
    # payloads must round-trip bit-exactly
    x = np.concatenate([np.arange(-127, 1), np.arange(0, 128)]) \
        .astype(np.float32).reshape(2, 128)
    q, s = blockwise_quantize(jax.numpy.asarray(x), 128)
    if not np.array_equal(np.asarray(blockwise_dequantize(q, s, 128)), x):
        problems.append("int8 quantizer not exact on representable ints")

    for p in problems:
        print(f"comm selfcheck: {p}")
    if not problems:
        print("comm selfcheck: policy resolution, env round-trip, codec "
              "+ hierarchical CPU-mesh lowering, bucket partition, and "
              "metric names OK")
    return 1 if problems else 0


if __name__ == "__main__":   # pragma: no cover - exercised via format.sh
    import sys
    sys.exit(_main(sys.argv[1:]))
