"""HLO collective wire-byte accounting.

The collective audits (tests/test_collective_audit.py) pin op KINDS; to
prove the compressed programs actually move fewer bytes they also need
a byte model over the lowered HLO text.  This module parses collective
op definitions out of ``compiled.as_text()`` and charges each under the
standard ring-algorithm cost (per-rank bytes on the wire, dropping the
common (N−1)/N factor so ratios are exact):

==================  =========================================
op                  wire bytes charged
==================  =========================================
all-reduce          2 × bytes(result)   (reduce-scatter + all-gather phases)
reduce-scatter      N × bytes(result) = bytes(input)
all-gather          bytes(result)       (each rank receives the full output)
all-to-all          bytes(result)       (each rank sends/receives one row set)
collective-permute  bytes(result)       (one neighbor hop)
==================  =========================================

Async ``-start`` forms count once (their ``-done`` halves and
get-tuple-element references are not definitions and never match).
"""

from __future__ import annotations

import re
from typing import Dict, Tuple

COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                  "all-to-all", "collective-permute")

_ITEMSIZE = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8,
}

_WIRE_FACTOR = {
    "all-reduce": 2.0,
    "all-gather": 1.0,
    "reduce-scatter": None,     # input bytes = result × axis size
    "all-to-all": 1.0,
    "collective-permute": 1.0,
}

#: a collective definition: "<name> = <shape-or-tuple> <op>[-start](..."
_DEF_RE = re.compile(
    r"= ((?:\([^)]*\))|(?:[a-z0-9]+\[[^\]]*\]\S*)) "
    r"(" + "|".join(COLLECTIVE_OPS) + r")(-start)?\(")

_SHAPE_RE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")

#: replica_groups attribute: explicit list "{{0,1},{2,3}}" or the iota
#: form "[4,2]<=[8]" (optionally with a transpose "T(1,0)")
_GROUPS_LIST_RE = re.compile(r"replica_groups=\{(\{[0-9, ]*\}(?:,\{"
                             r"[0-9, ]*\})*)\}")
_GROUPS_IOTA_RE = re.compile(
    r"replica_groups=\[([0-9,]+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?")


def _parse_groups(def_line: str):
    """The ``replica_groups`` of one collective definition line as a
    list of rank lists, or ``None`` for a full-span collective (no
    groups / unparseable — charged as crossing every tier)."""
    m = _GROUPS_LIST_RE.search(def_line)
    if m:
        return [[int(r) for r in g.split(",") if r.strip()]
                for g in re.findall(r"\{([0-9, ]*)\}", m.group(1))]
    m = _GROUPS_IOTA_RE.search(def_line)
    if m:
        import numpy as _np
        lhs = [int(d) for d in m.group(1).split(",")]
        rhs = [int(d) for d in m.group(2).split(",")]
        arr = _np.arange(int(_np.prod(rhs))).reshape(rhs)
        if m.group(3):
            arr = arr.transpose([int(p) for p in m.group(3).split(",")])
        return [list(map(int, row)) for row in arr.reshape(lhs)]
    return None


def _shape_bytes(dtype: str, dims: str) -> int:
    n = 1
    for d in dims.split(","):
        d = d.strip()
        if d:
            n *= int(d)
    return n * _ITEMSIZE.get(dtype, 4)


def collective_defs(hlo_text: str):
    """Yield ``(op, dtypes, result_bytes)`` per collective definition.

    ``result_bytes`` sums every array in the definition's result shape;
    async ``-start`` tuples repeat the operand alongside the result, so
    their sum is halved to keep start/done and sync forms comparable.
    """
    for op, dtypes, total, _groups in collective_defs_with_groups(hlo_text):
        yield op, dtypes, total


def collective_defs_with_groups(hlo_text: str):
    """:func:`collective_defs` plus each definition's parsed
    ``replica_groups`` (``None`` = full span) — the raw material of the
    per-link attribution below."""
    for m in _DEF_RE.finditer(hlo_text):
        shapes, op, started = m.group(1), m.group(2), m.group(3)
        parts = _SHAPE_RE.findall(shapes)
        total = sum(_shape_bytes(dt, dims) for dt, dims in parts)
        if started and len(parts) >= 2 and len(parts) % 2 == 0:
            total //= 2
        eol = hlo_text.find("\n", m.end())
        def_line = hlo_text[m.end():eol if eol >= 0 else len(hlo_text)]
        yield op, {dt for dt, _ in parts}, total, _parse_groups(def_line)


def collective_wire_bytes(hlo_text: str,
                          axis_size: int = 1) -> Dict[Tuple[str, str], int]:
    """``(op, dtype) → wire bytes`` over every collective definition in
    ``hlo_text`` under the ring cost model above.  ``axis_size`` scales
    reduce-scatter (whose HLO result is the 1/N shard) back to input
    bytes.  Mixed-dtype tuple collectives are keyed under their widest
    element type."""
    out: Dict[Tuple[str, str], int] = {}
    for op, dtypes, nbytes in collective_defs(hlo_text):
        factor = _WIRE_FACTOR[op]
        wire = (nbytes * axis_size if factor is None
                else int(nbytes * factor))
        dtype = max(dtypes, key=lambda d: _ITEMSIZE.get(d, 4)) \
            if dtypes else "f32"
        key = (op, dtype)
        out[key] = out.get(key, 0) + wire
    return out


def total_wire_bytes(hlo_text: str, axis_size: int = 1, *,
                     ops=None, dtypes=None) -> int:
    """Sum of :func:`collective_wire_bytes`, optionally filtered to the
    given op kinds and/or element types."""
    total = 0
    for (op, dt), b in collective_wire_bytes(hlo_text, axis_size).items():
        if ops is not None and op not in ops:
            continue
        if dtypes is not None and dt not in dtypes:
            continue
        total += b
    return total


# -- per-link attribution (hierarchical collectives) -----------------------


def crosses_dcn(groups, ici_size: int) -> bool:
    """Whether a collective's replica groups span hosts, given
    ``ici_size`` consecutive ranks per host (the contiguous-block
    layout ``hierarchy_groups`` / the process-major device order
    imply).  Group-less (full-span) collectives cross by definition."""
    if not groups:
        return True
    return any(len({r // ici_size for r in g}) > 1 for g in groups)


def wire_bytes_by_link(hlo_text: str, ici_size: int, axis_size: int = 1, *,
                       ops=None, dtypes=None) -> Dict[str, int]:
    """``{"ici": bytes, "dcn": bytes}`` over every collective definition
    in ``hlo_text``: a collective whose every replica group stays
    within one ``ici_size``-rank host block charges the fast tier,
    anything spanning hosts (or group-less) charges DCN.  This is the
    audit side of the hierarchical declaration — tests pin the two-level
    programs' DCN-crossing bytes against the flat paths with it.
    Filters and the ring-cost factors match :func:`total_wire_bytes`."""
    out = {"ici": 0, "dcn": 0}
    for op, dts, nbytes, groups in collective_defs_with_groups(hlo_text):
        if ops is not None and op not in ops:
            continue
        dtype = max(dts, key=lambda d: _ITEMSIZE.get(d, 4)) if dts else "f32"
        if dtypes is not None and dtype not in dtypes:
            continue
        factor = _WIRE_FACTOR[op]
        wire = (nbytes * axis_size if factor is None
                else int(nbytes * factor))
        out["dcn" if crosses_dcn(groups, ici_size) else "ici"] += wire
    return out


def declared_dcn_bytes(op_bytes: dict, multi_process: bool) -> int:
    """DCN-crossing bytes of a ``step_collective_bytes`` declaration:
    the ``_dcn``-suffixed ops when the hierarchical sync attributed
    them, else (multi-process — the data axis spans hosts) everything
    not explicitly pinned to ICI.  Single-process runs have no DCN hop
    at all.  Feeds ``rlt_comm_dcn_bytes_total``."""
    dcn = sum(b for op, b in (op_bytes or {}).items()
              if op.endswith("_dcn"))
    if dcn == 0 and multi_process:
        dcn = sum(b for op, b in (op_bytes or {}).items()
                  if not op.endswith("_ici"))
    return int(dcn)


# -- trace-event classification (telemetry/anatomy.py) ---------------------


def collective_kind(name: str) -> "str | None":
    """The COLLECTIVE_OPS kind of a device-trace event name
    (``"all-reduce.3"`` → ``"all-reduce"``, fusion wrappers included by
    substring), or None for a non-collective event.  This is the ONE
    collective-name classification — the trace-anatomy parser and the
    HLO byte audit above must never disagree on what counts as comm."""
    n = name.lower()
    # Pallas / custom-call kernels are compute, never comm — explicit
    # guard so a kernel named after the data it touches (a fused
    # "…all-gather…" epilogue, say) can't be misfiled as a collective
    # and drain the anatomy's compute bucket.
    if "pallas" in n or "custom-call" in n or "flash" in n:
        return None
    for op in COLLECTIVE_OPS:
        if op in n:
            return op
    return None


def event_link(args: dict, ici_size: int, multi_process: bool) -> str:
    """``"ici"`` or ``"dcn"`` for one collective trace event.

    TPU device traces carry the lowered HLO (``long_name`` /
    ``hlo_text`` args) including ``replica_groups=...`` — when present,
    the same group parser + :func:`crosses_dcn` test the byte audit
    uses decides the link.  Without groups the topology decides: a
    multi-process mesh's group-less collective spans hosts by
    definition (matching :func:`crosses_dcn`'s group-less rule), a
    single-process mesh has no DCN hop at all."""
    text = " ".join(str(v) for v in (args or {}).values()
                    if isinstance(v, str))
    if "replica_groups=" in text:
        groups = _parse_groups(text)
        if groups is not None:
            return "dcn" if crosses_dcn(groups, ici_size) else "ici"
    return "dcn" if multi_process else "ici"


# -- byte → seconds (planner cost model) -----------------------------------

#: modeled payload bandwidths, in GB/s (1e9 bytes/s), of the two link
#: classes a collective can ride.  ICI: the intra-slice interconnect —
#: v4/v5e per-chip ~100 GB/s order of magnitude.  DCN: the cross-host
#: datacenter network — ~100 Gbit/s per host ≈ 12.5 GB/s, the slow link
#: the comm plane compresses across.  These are deliberately coarse
#: constants for RANKING candidate plans (the plan/ planner), not for
#: predicting absolute step time; override per fabric generation via
#: PlanConfig / RLT_PLAN_{ICI,DCN}_GBPS.
ICI_GBPS = 100.0
DCN_GBPS = 12.5


def bytes_to_seconds(nbytes, gbps: float) -> float:
    """Seconds the given wire payload occupies a ``gbps``-GB/s link —
    the planner's byte→seconds conversion.  ``nbytes`` may be an int or
    an op→bytes mapping (``step_collective_bytes`` /
    :func:`collective_wire_bytes` output); mappings sum their values.
    Strictly monotone in bytes (plan/selfcheck.py pins this — the
    ranking invariant the whole cost model rests on)."""
    if isinstance(nbytes, dict):
        nbytes = sum(nbytes.values())
    if gbps <= 0:
        raise ValueError(f"bandwidth must be positive, got {gbps}")
    return float(nbytes) / (gbps * 1e9)
