"""Per-axis compression policy for the gradient collectives.

``CommPolicy`` decides WHICH named mesh axes carry compressed
reductions and how.  The default resolution follows the fabric: on a
multi-process run the ``data`` axis spans hosts (DCN — the slow link
EQuARX targets) and is compressed; a single-process mesh is all-ICI and
stays fp32 unless axes are named explicitly (``axes=("data",)`` — which
is also how the CPU-mesh tests and single-host A/Bs opt in).

Construction paths (first match wins, mirroring TelemetryConfig /
CompileCacheConfig):

- ``Trainer(comm_policy=CommPolicy(...))`` — full control;
- ``Trainer(comm_policy="int8")`` — compress with defaults;
- ``Trainer(comm_policy={...})`` — kwargs dict;
- ``RLT_COMM=int8`` (+ ``RLT_COMM_AXES=data``, ``RLT_COMM_BLOCK=64``,
  ``RLT_COMM_SR=1``, ``RLT_COMM_EF=0``, ``RLT_COMM_PARAM_GATHER=bf16``,
  ``RLT_COMM_HIER=auto|K``, ``RLT_COMM_BUCKET_BYTES=N``,
  ``RLT_COMM_BARRIER=1``, ``RLT_ZERO1_GATHER_BUCKET_BYTES=N``) — env
  knobs, read when the Trainer arg is ``None``.

The resolved policy is a frozen dataclass that pickles with the trainer
driver→worker; the env knobs additionally round-trip through
``worker_env()`` so worker-side tooling (nested fits) stays consistent,
like the compile plane's knobs do.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

VALID_COMPRESS = ("none", "int8", "bf16", "fp8", "int4")
VALID_PARAM_GATHER = ("none", "bf16", "int8")

#: ``hierarchy`` sentinel: size the ICI tier from the runtime's
#: ``jax.local_device_count()`` (chips sharing this host's fast link)
HIER_AUTO = -1


def _env_flag(name: str, default: bool) -> bool:
    raw = os.environ.get(name, "").strip()
    if raw in ("0", "false", "False"):
        return False
    if raw in ("1", "true", "True"):
        return True
    return default


@dataclasses.dataclass(frozen=True)
class CommPolicy:
    """How cross-replica gradient collectives compress.

    compress: payload dtype of the gradient reduction over the selected
        axes — ``"int8"`` (blockwise scales, ~4x fewer bytes),
        ``"fp8"`` (e4m3, same bytes as int8, relative error bound),
        ``"int4"`` (nibble-packed, ~8x), ``"bf16"`` (plain cast, 2x),
        ``"none"`` (off; the default — bit-identical to the
        uncompressed build).
    axes: mesh axes whose reduction compresses.  ``None`` = auto:
        the strategy's data axes when the run spans processes (the
        DCN case), nothing on a single process (all-ICI stays fp32).
    block_size: scale-block length (int8/fp8/int4; must be even for
        int4's pair packing).
    stochastic_rounding: unbiased quantizer (one uniform per element).
    error_feedback: carry the per-rank quantization error in optimizer
        state and re-inject it next step (parity-critical; on by
        default whenever compression is on).
    param_gather: dtype of ZeRO-1's updated-param all-gather —
        ``"none"`` keeps it at the parameter dtype (no quality risk),
        ``"bf16"``/``"int8"`` compress it too (no error feedback exists
        on the parameter path, so this is the aggressive opt-in).
    hierarchy: two-level reduction (the EQuARX split): ``0`` = off
        (flat — today's behavior), ``HIER_AUTO``/-1 = size the fast
        tier from ``jax.local_device_count()``, ``K >= 2`` = explicit
        ICI group size.  When active (1 < K < world, K divides world)
        the gradient reduction sums fp32 within each K-rank ICI group
        first and only the cross-group (DCN) hop carries the codec —
        inter-host bytes shrink by ANOTHER factor K on top of the
        codec's, and error feedback absorbs strictly less noise (one
        quantization of a 1/K shard instead of the full payload).
    bucket_bytes: ``0`` = sync each gradient leaf separately (today's
        behavior); ``> 0`` = coalesce leaves into size-targeted buckets
        and issue one collective per bucket, each depending only on its
        own leaves — fewer dispatches for small leaves AND the dataflow
        freedom XLA's latency-hiding scheduler needs to overlap a
        bucket's DCN transfer with the rest of the backward pass
        (the TorchTitan bucketed-sync construction).
    barrier_sync: bench A/B knob: tie every bucket's payload to the
        COMPLETE gradient tree with an ``optimization_barrier`` before
        any collective is issued — the single end-of-backward barrier
        the bucketed path exists to beat.  Only meaningful with
        ``bucket_bytes > 0``; never enable outside measurements.  Also
        gates the gather side: with ``gather_bucket_bytes > 0`` it ties
        the ENTIRE updated-param tree before any gather (the monolithic
        end-of-step gather the bucketed path A/Bs against).
    gather_bucket_bytes: ``0`` = ZeRO-1's updated-param all-gather stays
        whatever ``param_gather`` makes it (implicit partitioner gather
        when that is ``"none"`` too); ``> 0`` = the gather becomes
        explicit and BUCKETED (``RLT_ZERO1_GATHER_BUCKET_BYTES``):
        leaves are ordered by the next forward's consumption order
        (embeddings, then blocks by numeric layer index), coalesced into
        size-targeted buckets, and each bucket's gathers depend only on
        its own leaves — the dataflow freedom XLA's latency-hiding
        scheduler needs to overlap early buckets' gather traffic with
        the remaining optimizer update and the next forward's first
        matmuls (the cross-replica weight-update overlap of 2004.13336,
        on the gather instead of the reduction).  Works with or without
        a ``param_gather`` codec.
    """

    compress: str = "none"
    axes: Optional[tuple] = None
    block_size: int = 64
    stochastic_rounding: bool = False
    error_feedback: bool = True
    param_gather: str = "none"
    hierarchy: int = 0
    bucket_bytes: int = 0
    barrier_sync: bool = False
    gather_bucket_bytes: int = 0

    def __post_init__(self):
        if self.compress not in VALID_COMPRESS:
            raise ValueError(
                f"comm_policy compress {self.compress!r}; "
                f"options: {VALID_COMPRESS}")
        if self.param_gather not in VALID_PARAM_GATHER:
            raise ValueError(
                f"comm_policy param_gather {self.param_gather!r}; "
                f"options: {VALID_PARAM_GATHER}")
        if self.block_size <= 0:
            raise ValueError("comm_policy block_size must be positive")
        if self.compress == "int4" and self.block_size % 2:
            raise ValueError("comm_policy int4 needs an even block_size "
                             "(two values pack per byte)")
        if self.hierarchy < HIER_AUTO or self.hierarchy == 1:
            raise ValueError(
                f"comm_policy hierarchy {self.hierarchy!r}: 0 (flat), "
                f"{HIER_AUTO} (auto: local device count) or an ICI "
                f"group size >= 2")
        if self.bucket_bytes < 0:
            raise ValueError("comm_policy bucket_bytes must be >= 0")
        if self.gather_bucket_bytes < 0:
            raise ValueError(
                "comm_policy gather_bucket_bytes must be >= 0")
        if self.axes is not None:
            object.__setattr__(self, "axes", tuple(self.axes))

    # -- construction ----------------------------------------------------

    @classmethod
    def resolve(cls, value) -> "CommPolicy":
        if isinstance(value, cls):
            return value
        if isinstance(value, str):
            return cls(compress=value)
        if isinstance(value, dict):
            return cls(**value)
        if value is not None:
            raise TypeError(f"bad comm_policy: {value!r}")
        compress = os.environ.get("RLT_COMM", "none").strip() or "none"
        axes_raw = os.environ.get("RLT_COMM_AXES", "").strip()
        axes = tuple(a for a in axes_raw.split(",") if a) or None
        hier_raw = os.environ.get("RLT_COMM_HIER", "0").strip() or "0"
        hierarchy = HIER_AUTO if hier_raw == "auto" else int(hier_raw)
        return cls(
            compress=compress,
            axes=axes,
            block_size=int(os.environ.get("RLT_COMM_BLOCK", "64")),
            stochastic_rounding=_env_flag("RLT_COMM_SR", False),
            error_feedback=_env_flag("RLT_COMM_EF", True),
            param_gather=os.environ.get(
                "RLT_COMM_PARAM_GATHER", "none").strip() or "none",
            hierarchy=hierarchy,
            bucket_bytes=int(os.environ.get("RLT_COMM_BUCKET_BYTES", "0")),
            barrier_sync=_env_flag("RLT_COMM_BARRIER", False),
            gather_bucket_bytes=int(os.environ.get(
                "RLT_ZERO1_GATHER_BUCKET_BYTES", "0")),
        )

    # -- queries ---------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self.compress != "none"

    def resolved_axes(self, mesh, data_axis_names) -> tuple:
        """Which of ``mesh``'s axes this policy compresses: the explicit
        ``axes`` when given, else (auto) the strategy's data axes only
        when the run spans processes — a single process has no DCN hop
        to save.  Only reduction (data) axes with size > 1 qualify."""
        if not self.enabled:
            return ()
        if self.axes is not None:
            candidates = self.axes
        else:
            import jax
            candidates = (tuple(data_axis_names)
                          if jax.process_count() > 1 else ())
        return tuple(a for a in candidates
                     if a in data_axis_names and a in mesh.axis_names
                     and mesh.shape[a] > 1)

    def resolved_hierarchy(self, world: int) -> "tuple[int, int]":
        """``(ici_size, dcn_size)`` of the two-level reduction over a
        ``world``-rank axis product: ``(1, world)`` = flat (hierarchy
        off, invalid, or degenerate — the whole axis on one tier).
        ``HIER_AUTO`` sizes the ICI tier from the runtime's local
        device count; the contiguous-block rank layout this implies
        (rank = host * local + local_index) is exactly how the mesh
        builder orders ``jax.devices()`` (process-major)."""
        h = self.hierarchy
        if h == HIER_AUTO:
            import jax
            h = jax.local_device_count()
        if h <= 1 or h >= world or world % h:
            return (1, world)
        return (h, world // h)

    # -- env round-trip --------------------------------------------------

    def worker_env(self) -> dict:
        """Env mapping reproducing this policy via :meth:`resolve` in a
        worker process (the pickled trainer already carries the policy;
        the env keeps worker-side nested fits consistent)."""
        if not self.enabled:
            return {}
        env = {
            "RLT_COMM": self.compress,
            "RLT_COMM_BLOCK": str(self.block_size),
            "RLT_COMM_SR": "1" if self.stochastic_rounding else "0",
            "RLT_COMM_EF": "1" if self.error_feedback else "0",
            "RLT_COMM_PARAM_GATHER": self.param_gather,
            "RLT_COMM_HIER": ("auto" if self.hierarchy == HIER_AUTO
                              else str(self.hierarchy)),
            "RLT_COMM_BUCKET_BYTES": str(self.bucket_bytes),
            "RLT_COMM_BARRIER": "1" if self.barrier_sync else "0",
            "RLT_ZERO1_GATHER_BUCKET_BYTES":
                str(self.gather_bucket_bytes),
        }
        if self.axes is not None:
            env["RLT_COMM_AXES"] = ",".join(self.axes)
        return env
