"""Execution plugins: who runs the training loop, and where.

``LocalPlugin`` runs it in-process (SPMD over whatever devices this
process sees — one v4-8 host, or 8 virtual CPU devices in tests).
Distributed plugins (plugins/xla.py) ship the run into actor workers.
The plugin's second job is carrying the sharding strategy.
"""

from __future__ import annotations

from typing import Optional, Sequence

from ray_lightning_tpu.parallel.strategy import (
    ShardingStrategy,
    resolve_strategy,
)


class ExecutionPlugin:
    strategy: Optional[ShardingStrategy] = None

    def run(self, trainer, module, datamodule, stage: str,
            ckpt_path: Optional[str]):
        raise NotImplementedError

    def local_devices(self) -> Optional[Sequence]:
        """Devices the mesh should span (None = all visible devices)."""
        return None


class LocalPlugin(ExecutionPlugin):
    """In-process execution (no actors).  The default when no distributed
    plugin is passed — the analog of running PL without any plugin, but
    still SPMD across every local chip."""

    def __init__(self, strategy=None, devices: Optional[Sequence] = None):
        self.strategy = resolve_strategy(strategy) if strategy else None
        self._devices = devices

    def run(self, trainer, module, datamodule, stage, ckpt_path):
        if self.strategy is None:
            self.strategy = resolve_strategy(None)
        cfg = getattr(trainer, "telemetry", None)
        if cfg is None or not cfg.enabled:
            return trainer._run_stage(module, datamodule, stage, ckpt_path)
        # single-process run: recorder and aggregator share the process,
        # so the span/metrics sinks feed the aggregator directly (no
        # queue hop)
        import os
        from ray_lightning_tpu import telemetry
        from ray_lightning_tpu.telemetry import exporter as _exporter
        from ray_lightning_tpu.telemetry import tracing
        agg = telemetry.TelemetryAggregator(
            cfg.resolve_dir(trainer.default_root_dir),
            heartbeat_timeout=cfg.heartbeat_timeout,
            hard_timeout=cfg.hard_timeout,
            flight_capacity=cfg.flight_capacity,
            incident_cfg=cfg.resolved_incident())
        telemetry.set_active(agg)
        telemetry.enable(rank=0, sink=lambda recs: agg.ingest_records(
            0, recs), capacity=cfg.capacity, flush_every=cfg.flush_every)
        if cfg.resolved_goodput():
            # goodput plane (telemetry/goodput.py): the trainer opens
            # the run ledger inside _run_stage; arming here gives the
            # finalized doc a direct path onto the aggregator
            telemetry.enable_goodput(rank=0, sink=agg.maybe_ingest)
        incident_env_set = False
        if agg.incidents.cfg.enabled:
            # incident plane arm channel: a detector trip writes this
            # file; the AnatomyController (the "worker" is this
            # process) polls it and forces an evidence window.  Set
            # BEFORE enable_anatomy so the controller sees it.
            from ray_lightning_tpu.telemetry import anatomy as _anatomy
            inc_control = os.path.join(agg.out_dir, "incident",
                                       "arm.json")
            agg.incidents.arm_path = inc_control
            if _anatomy.INCIDENT_CONTROL_ENV not in os.environ:
                os.environ[_anatomy.INCIDENT_CONTROL_ENV] = inc_control
                incident_env_set = True
        every_n, window = cfg.resolved_anatomy()
        if every_n is not None:
            # cadence-armed anatomy windows (telemetry/anatomy.py): the
            # "worker" is this process, so the compact dict lands on
            # the aggregator directly
            telemetry.enable_anatomy(rank=0, every_n=every_n,
                                     window=window, sink=agg.maybe_ingest)
        server = None
        profile_env_set = False
        if cfg.metrics:
            telemetry.enable_metrics(rank=0, sink=agg.ingest_metrics,
                                     interval=cfg.metrics_interval)
            # on-demand profiling (POST /debug/profile): the "worker" IS
            # this process, so the control file is trivially shared —
            # point the loop engine's poller at it for the fit's span
            control = os.path.join(agg.out_dir, "profile",
                                   "control.json")
            profile_ctl = tracing.FileProfileController(control)
            if tracing.PROFILE_CONTROL_ENV not in os.environ:
                os.environ[tracing.PROFILE_CONTROL_ENV] = control
                profile_env_set = True
                tracing.reset_profile_tick()
            server = _exporter.start_metrics_server(
                agg, cfg, profile_controller=profile_ctl)
        try:
            return trainer._run_stage(module, datamodule, stage, ckpt_path)
        finally:
            telemetry.disable_goodput()
            telemetry.disable_anatomy()
            telemetry.flush_metrics()
            telemetry.disable_metrics()
            telemetry.flush()
            telemetry.disable()
            telemetry.set_active(None)
            if profile_env_set:
                os.environ.pop(tracing.PROFILE_CONTROL_ENV, None)
                tracing.reset_profile_tick()
            if incident_env_set:
                from ray_lightning_tpu.telemetry import anatomy as _anatomy
                os.environ.pop(_anatomy.INCIDENT_CONTROL_ENV, None)
            if server is not None:
                server.stop()
            trainer._telemetry_paths = agg.export()
            if server is not None:
                trainer._telemetry_paths["metrics_url"] = server.url
            # driver-side goodput report + the planner's measured-vs-
            # modeled divergence (both read the aggregator this plugin
            # owns, so they land here in the teardown)
            gp = agg.goodput_stats()
            if gp:
                trainer._goodput_report = gp.get("fleet")
            trainer._attach_observed_divergence(agg)

    def local_devices(self):
        if self._devices is not None:
            return self._devices
        # inside a builtin-tune trial with a device lease, the mesh spans
        # only the trial's partition (tune/runner.py device isolation)
        from ray_lightning_tpu.tune.session import get_trial_devices
        return get_trial_devices()
