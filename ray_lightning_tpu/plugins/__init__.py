from ray_lightning_tpu.plugins.base import ExecutionPlugin, LocalPlugin
from ray_lightning_tpu.plugins.xla import (
    RayXlaPlugin,
    RayXlaShardedPlugin,
    RayXlaSpmdPlugin,
)

__all__ = [
    "ExecutionPlugin",
    "LocalPlugin",
    "RayXlaPlugin",
    "RayXlaShardedPlugin",
    "RayXlaSpmdPlugin",
]
