"""Distributed execution plugins: Ray-style actors driving TPU hosts.

``RayXlaPlugin`` is the flagship (reference: ``RayPlugin``,
ray_ddp.py:67-544).  Driver side, it:

  1. creates ``num_workers`` executor actors — one per TPU host, not one
     per device (the PJRT inversion, SURVEY.md §7) — with env plumbing
     (_setup_env_vars analog, ray_ddp.py:206-219);
  2. elects worker 0's node as the PJRT coordinator and broadcasts
     ``ip:port`` (replacing the MASTER_ADDR/PORT TCP store rendezvous);
  3. ships one pickled payload (trainer, module, datamodule) to all
     workers (ray.put fan-out analog, ray_ddp.py:331);
  4. busy-polls results while relaying queue side-effects
     (execution_loop → process_results, ray_ddp.py:308-351);
  5. unpacks rank-0's results: state stream → module weights on the
     driver, callback metrics, best checkpoint path; kills the actors
     (post_dispatch analog, ray_ddp.py:353-386).

Worker side (``_worker_run``), each actor joins ``jax.distributed``,
builds the global mesh spanning every chip of every host, and re-enters
``trainer._run_stage`` — the same double-life the reference's plugin
leads via its ``_is_remote`` flag (ray_ddp.py:127, :450).

Gradient sync is *not here*: it is compiled into the train step by XLA
from the strategy's shardings and rides ICI/DCN.  The plugin moves only
control, specs and results.

``HorovodRayPlugin`` has no analog because TPU has one collective fabric:
``RayXlaPlugin`` subsumes it (BASELINE.json north star).
"""

from __future__ import annotations

import logging
import os
import uuid
from typing import Any, Callable, Optional

from ray_lightning_tpu.cluster.backend import get_backend
from ray_lightning_tpu.cluster.executor import RLTExecutor
from ray_lightning_tpu.cluster.queue import WorkerQueueProxy
from ray_lightning_tpu.plugins.base import ExecutionPlugin
from ray_lightning_tpu.parallel.strategy import resolve_strategy
from ray_lightning_tpu.session import init_session, reset_session
from ray_lightning_tpu.util import process_results
from ray_lightning_tpu.utils.platform import host_device_count_flags
from ray_lightning_tpu.utils.seed import SEED_ENV_VAR
from ray_lightning_tpu.utils.states import load_state_stream, to_state_stream

_log = logging.getLogger(__name__)


def _configure_worker_jax() -> None:
    """Apply platform config inside a worker before first backend init."""
    import jax
    platform = os.environ.get("RLT_PLATFORM")
    if platform:
        jax.config.update("jax_platforms", platform)
        if platform == "cpu" \
                and int(os.environ.get("RLT_NUM_PROCESSES", "1")) > 1:
            # gloo carries cross-process CPU collectives — the test-time
            # stand-in for ICI, as gloo was the reference's CI stand-in
            # for NCCL (ray_ddp.py:149-151).  Multi-process ONLY: current
            # jaxlib's gloo backend requires a live distributed client,
            # so enabling it in a single-worker run (which never calls
            # jax.distributed.initialize) kills CPU backend init.
            jax.config.update("jax_cpu_collectives_implementation", "gloo")


def _worker_run(payload: tuple, rank: int, queue,
                cache_seed=None) -> Optional[dict]:
    """Runs inside each actor: join the distributed runtime, re-enter the
    trainer loop, package rank-0 results (execute_remote analog,
    ray_ddp.py:428-502)."""
    _configure_worker_jax()
    import jax

    trainer, module, datamodule, stage, ckpt_path = payload
    if cache_seed is not None:
        # no shared filesystem with the driver: seed this node's local
        # compilation-cache dir from the driver's packed snapshot BEFORE
        # the first compile (compile/shipping.py).  Additive and
        # best-effort — a failed seed just means cold compiles.
        try:
            from ray_lightning_tpu.compile import shipping
            shipping.unpack_cache_dir(cache_seed,
                                      trainer.compile_cache.root)
        except Exception:
            _log.warning("compile-cache seeding failed; compiling cold",
                         exc_info=True)
    nproc = int(os.environ.get("RLT_NUM_PROCESSES", "1"))
    if nproc > 1:
        jax.distributed.initialize(
            coordinator_address=os.environ["RLT_COORDINATOR"],
            num_processes=nproc,
            process_id=rank,
        )
    if queue is not None:
        reset_session()
        init_session(rank, queue)

    plugin = trainer.plugin
    plugin._is_remote = True

    hb = _setup_worker_telemetry(trainer, rank, queue)
    try:
        result = trainer._run_stage(module, datamodule, stage, ckpt_path)
    finally:
        _teardown_worker_telemetry(trainer, hb)
        if nproc > 1:
            # Disconnect from the coordination service before the driver
            # kills actors, so teardown is clean (otherwise surviving
            # workers see the coordinator vanish and abort fatally).
            try:
                jax.distributed.shutdown()
            except RuntimeError:
                pass

    if rank != 0:
        return None
    package: dict[str, Any] = {
        "result": result,
        "callback_metrics": dict(trainer.callback_metrics),
        "epoch": int(trainer.current_epoch),
        "global_step": int(trainer.global_step),
        # startup cost as rank 0 saw it (bench.py reports it; the
        # compile plane's cold/warm A/B is measured on this number)
        "time_to_first_step": trainer.time_to_first_step,
        # the planner's verdict when strategy="auto" ran in the workers
        # (every rank plans identically; rank 0's copy is THE report)
        "plan_report": trainer._plan_report,
        # rank 0's finalized goodput doc (telemetry/goodput.py) — the
        # driver's fallback when the queue-shipped copy was dropped
        "goodput": getattr(trainer, "_goodput_local", None),
    }
    if stage == "fit":
        # Weights return in-band as a state stream — PL's temp-file
        # handoff breaks multi-node (rationale at ray_ddp.py:480-486).
        package["state_stream"] = to_state_stream(module._trained_variables)
        # elastic-plane numbers (snapshot counters etc.) for the
        # driver's _elastic_report / bench JSON
        package["elastic"] = trainer.elastic_stats()
        ckpt_cb = trainer.checkpoint_callback
        if ckpt_cb is not None:
            package["best_model_path"] = ckpt_cb.best_model_path
            package["best_model_score"] = ckpt_cb.best_model_score
    return package


def _setup_worker_telemetry(trainer, rank: int, queue):
    """Enable span recording, the metrics registry and heartbeats inside
    an actor: span batches and cumulative metrics windows ride the
    worker→driver queue to the driver aggregator.  Returns the heartbeat
    sender to stop (None when telemetry is off or the process-level
    sender from worker_main already beats)."""
    cfg = getattr(trainer, "telemetry", None)
    if cfg is None or not cfg.enabled or queue is None:
        return None
    from ray_lightning_tpu import telemetry
    from ray_lightning_tpu.telemetry import heartbeat as hb_mod

    def sink(records, _q=queue, _rank=rank):
        _q.put((_rank, telemetry.spans_item(_rank, records)))

    telemetry.enable(rank=rank, sink=sink, capacity=cfg.capacity,
                     flush_every=cfg.flush_every)
    if cfg.metrics:
        telemetry.enable_metrics(
            rank=rank,
            sink=lambda item, _q=queue, _rank=rank: _q.put((_rank, item)),
            interval=cfg.metrics_interval)
    every_n, window = cfg.resolved_anatomy()
    if every_n is not None:
        # cadence-armed anatomy windows (telemetry/anatomy.py): each
        # rank captures + parses its OWN trace and ships only the
        # compact anatomy dict over the queue — never the raw capture
        telemetry.enable_anatomy(
            rank=rank, every_n=every_n, window=window,
            sink=lambda item, _q=queue, _rank=rank: _q.put((_rank, item)))
    if cfg.resolved_goodput():
        # goodput plane (telemetry/goodput.py): the run ledger opens
        # inside _run_stage; the finalized doc rides the same queue
        telemetry.enable_goodput(
            rank=rank,
            sink=lambda item, _q=queue, _rank=rank: _q.put((_rank, item)))
    if hb_mod.process_heartbeat_active():
        return None  # worker_main (built-in backend) already beats
    return hb_mod.HeartbeatSender(
        lambda item, _q=queue, _rank=rank: _q.put((_rank, item)),
        rank=rank, interval=cfg.heartbeat_interval).start()


def _teardown_worker_telemetry(trainer, hb) -> None:
    cfg = getattr(trainer, "telemetry", None)
    if cfg is None or not cfg.enabled:
        return
    from ray_lightning_tpu import telemetry
    # abandon any mid-capture anatomy window first (a partial trace is
    # not an anatomy), then the final metrics window: its cumulative
    # counters must be on the queue before the spans flush that follows
    # the last step
    telemetry.disable_goodput()
    telemetry.disable_anatomy()
    telemetry.flush_metrics()
    telemetry.disable_metrics()
    telemetry.flush()
    telemetry.disable()
    if hb is not None:
        hb.stop()


class RayXlaPlugin(ExecutionPlugin):
    """Data-parallel training over Ray-style actors, one per TPU host."""

    def __init__(
        self,
        num_workers: int = 1,
        num_cpus_per_worker: float = 1,
        use_tpu: bool = False,
        devices_per_worker: Optional[int] = None,
        platform: Optional[str] = None,
        strategy: Any = "ddp",
        init_hook: Optional[Callable] = None,
        resources_per_worker: Optional[dict] = None,
        worker_env: Optional[dict] = None,
    ):
        if num_workers < 1:
            raise ValueError("num_workers must be >= 1")
        self.num_workers = int(num_workers)
        self.num_cpus_per_worker = num_cpus_per_worker
        self.use_tpu = use_tpu
        self.devices_per_worker = devices_per_worker
        self.platform = platform or ("tpu" if use_tpu else None)
        self.strategy = resolve_strategy(strategy)
        self.init_hook = init_hook
        self.worker_env = dict(worker_env or {})
        # resources_per_worker overrides the convenience args; leftover
        # keys become custom resources (precedence parity with
        # ray_ddp.py:128-153, tested at test_ddp.py:136-174).
        resources = dict(resources_per_worker or {})
        self.num_cpus_per_worker = resources.pop("CPU",
                                                 self.num_cpus_per_worker)
        if "TPU" in resources:
            tpu = resources.pop("TPU")
            self.use_tpu = tpu > 0
            if self.devices_per_worker is None and tpu > 0:
                self.devices_per_worker = int(tpu)
        self.additional_resources = resources

        self._workers: list = []
        self._backend = None
        self._is_remote = False

    # -- pickling: drop live handles (ray_ddp.py:164-172 analog) ---------

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_workers"] = []
        state["_backend"] = None
        state["init_hook"] = None  # already executed before shipping
        state.pop("_telemetry_agg", None)  # live driver-side aggregator
        state.pop("_metrics_server", None)  # live driver HTTP listener
        # harvested escrow blobs are driver-side recovery state; only
        # the assembled package (trainer._elastic_recovery) ships
        state.pop("_last_escrows", None)
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)

    # -- resources --------------------------------------------------------

    def _worker_resources(self) -> dict:
        res = {"CPU": self.num_cpus_per_worker, **self.additional_resources}
        if self.use_tpu:
            res["TPU"] = self.devices_per_worker or 1
        return res

    def _worker_env_base(self) -> dict:
        env = {
            "RLT_NUM_PROCESSES": str(self.num_workers),
        }
        if SEED_ENV_VAR in os.environ:  # PL_GLOBAL_SEED propagation parity
            env[SEED_ENV_VAR] = os.environ[SEED_ENV_VAR]
        if os.environ.get("RLT_REMAT_POLICY", "").strip():
            # model-build remat override (models/gpt.py _remat_policy,
            # pinned by the planner's remat axis): actor fleets must
            # build the same program as the driver — ships like the
            # RLT_COMM*/RLT_MPMD* knobs below
            env["RLT_REMAT_POLICY"] = os.environ["RLT_REMAT_POLICY"]
        if self.platform:
            env["RLT_PLATFORM"] = self.platform
            env["JAX_PLATFORMS"] = self.platform
        if self.platform == "cpu":
            # each CPU worker gets exactly devices_per_worker virtual
            # devices (default 1)
            n = self.devices_per_worker or 1
            env["XLA_FLAGS"] = host_device_count_flags(n)
            env["RLT_NUM_LOCAL_DEVICES"] = str(n)
            # CPU workers must never touch a TPU attach/tunnel path the
            # driver environment may carry (single-client tunnels crash
            # concurrent registrants); empty disables such hooks
            env["PALLAS_AXON_POOL_IPS"] = ""
        env.update(self.worker_env)
        return env

    # -- driver-side run ---------------------------------------------------

    def run(self, trainer, module, datamodule, stage: str,
            ckpt_path: Optional[str]):
        if self._is_remote:
            raise RuntimeError("plugin.run called inside a worker")
        elastic = getattr(trainer, "elastic", None)
        if stage == "fit" and elastic is not None and elastic.enabled \
                and elastic.max_restarts > 0:
            # shrink-to-continue: a dead rank tears the fleet down, the
            # elastic driver rebuilds it with the survivors and resumes
            # from the latest snapshot (elastic/driver.py)
            from ray_lightning_tpu.elastic.driver import run_elastic_fit
            return run_elastic_fit(self, trainer, module, datamodule,
                                   ckpt_path)
        return self._run_attempt(trainer, module, datamodule, stage,
                                 ckpt_path)

    def _run_attempt(self, trainer, module, datamodule, stage: str,
                     ckpt_path: Optional[str]):
        """One fleet lifecycle: create actors, rendezvous, execute,
        tear down.  The elastic driver calls this repeatedly with a
        shrinking ``num_workers``; everything per-fleet (actors,
        aggregator, metrics server) is rebuilt per attempt."""
        backend = get_backend()
        self._backend = backend
        base_env = self._worker_env_base()
        cfg = trainer.telemetry
        profile_ctl = None
        incident_cfg = None
        incident_control = None
        if cfg.enabled:
            incident_cfg = cfg.resolved_incident()
            # workers heartbeat from process start (worker_main) and
            # record spans once the fit payload arrives (_worker_run)
            base_env["RLT_TELEMETRY"] = "1"
            base_env["RLT_HEARTBEAT_INTERVAL"] = str(cfg.heartbeat_interval)
            # anatomy cadence (RLT_ANATOMY* — telemetry/anatomy.py):
            # every rank must arm the same windows the driver resolved,
            # whether the cadence came from the config or the env
            base_env.update(cfg.worker_env())
            if cfg.metrics and getattr(backend, "shared_filesystem",
                                       False):
                # on-demand profiling for fits (POST /debug/profile):
                # shared-FS backends get a control file the loop engine
                # polls each dispatch; its location ships via env
                # (telemetry/tracing.py FileProfileController)
                from ray_lightning_tpu.telemetry import tracing
                control = os.path.join(
                    cfg.resolve_dir(trainer.default_root_dir),
                    "profile", "control.json")
                profile_ctl = tracing.FileProfileController(control)
                base_env[tracing.PROFILE_CONTROL_ENV] = control
            if incident_cfg.enabled and getattr(
                    backend, "shared_filesystem", False):
                # incident-plane arm channel (telemetry/incident.py):
                # on detector trip the driver writes this file; every
                # rank's AnatomyController polls it and forces an
                # off-cadence evidence window — same shared-FS idiom
                # as the profile control file above
                from ray_lightning_tpu.telemetry import anatomy as _anatomy
                incident_control = os.path.join(
                    cfg.resolve_dir(trainer.default_root_dir),
                    "incident", "arm.json")
                base_env[_anatomy.INCIDENT_CONTROL_ENV] = incident_control
        # persistent-compilation-cache knobs: the pickled trainer already
        # carries the config, but the env keeps worker-side tooling that
        # consults RLT_COMPILE_CACHE* (e.g. a nested fit) consistent.
        # Shared-FS backends (builtin subprocess actors) thereby point
        # every worker at the DRIVER'S cache root — sharing, not seeding.
        base_env.update(trainer.compile_cache.worker_env())
        # comm-plane knobs ride the same way: the pickled trainer carries
        # the resolved CommPolicy; the env keeps worker-side tooling that
        # consults RLT_COMM* (e.g. a nested fit) consistent with it
        base_env.update(trainer.comm_policy.worker_env())
        # elastic knobs too (RLT_ELASTIC* — elastic/config.py)
        base_env.update(trainer.elastic.worker_env())
        # planner knobs (RLT_PLAN* — plan/config.py): the pickled
        # trainer carries the resolved PlanConfig; the env keeps
        # worker-side tooling consistent, and identical config on every
        # rank is what the planner's deterministic-winner contract needs
        base_env.update(trainer.plan.worker_env())
        # MPMD knobs (RLT_MPMD* — mpmd/config.py): the strategy carries
        # the resolved config; the env keeps worker-side tooling that
        # consults RLT_MPMD* consistent with the driver's resolution
        strat = getattr(self, "strategy", None)
        if getattr(strat, "name", "") == "mpmd":
            base_env.update(strat.config.worker_env())
        from ray_lightning_tpu.core import datacheck
        if datacheck.enabled():
            # driver-set RLT_DATA_CHECK=1 reaches workers explicitly
            # (backends that don't inherit the driver env included)
            base_env[datacheck.ENV_DATA_CHECK] = "1"
        # unique per fit: reusing names across fits in one driver process
        # lets a late/stale connection from a previous run race the new
        # worker's attach
        run_tag = uuid.uuid4().hex[:8]
        worker_names = [f"rlt-worker-{os.getpid()}-{run_tag}-{i}"
                        for i in range(self.num_workers)]
        # rank-ordered actor names reach every worker so rank r can
        # peer_send to rank s by name — the worker↔worker channel the
        # elastic parity tick rides (elastic/redundancy.py)
        base_env["RLT_PEER_NAMES"] = ",".join(worker_names)
        self._workers = [
            backend.create_actor(
                RLTExecutor,
                # rank at spawn time so even pre-setup heartbeats carry
                # it (set_env_vars re-sends the same value later)
                env={**base_env, "RLT_PROCESS_ID": str(i)},
                resources=self._worker_resources(),
                name=worker_names[i],
                # Ray: peer deliveries + escrow harvests are concurrent
                # actor calls and must run beside a busy main call; the
                # builtin backend serves both from its reader thread
                # and ignores this
                max_concurrency=2,
            )
            for i in range(self.num_workers)
        ]
        agg = None
        server = None
        if cfg.enabled:
            from ray_lightning_tpu import telemetry
            from ray_lightning_tpu.telemetry import exporter as _exporter
            agg = telemetry.TelemetryAggregator(
                cfg.resolve_dir(trainer.default_root_dir),
                heartbeat_timeout=cfg.heartbeat_timeout,
                hard_timeout=cfg.hard_timeout,
                flight_capacity=cfg.flight_capacity,
                incident_cfg=incident_cfg)
            if incident_control is not None:
                agg.incidents.arm_path = incident_control
            # elastic restart count survives the per-attempt aggregator
            # rebuild so /metrics' rlt_restarts_total is cumulative,
            # and the recovery route the driver chose for THIS attempt
            # (parity vs replay) is a scrapeable series
            agg.set_restarts(getattr(self, "_elastic_restarts", 0))
            agg.set_recovery(getattr(self, "_elastic_recovery_mode", None),
                             getattr(self, "_elastic_recovery_seconds",
                                     None))
            # snapshot-replay badput: steps this attempt re-executes
            # because the snapshot was behind the crash step
            # (elastic/driver.py sets it when routing to replay)
            agg.set_replayed_steps(
                getattr(self, "_elastic_replayed_steps", 0))
            for i, w in enumerate(self._workers):
                agg.register_worker(i, w)
            telemetry.set_active(agg)
            self._telemetry_agg = agg
            if cfg.metrics:
                # live /metrics + /status on the driver: workers' metric
                # windows arrive over the queue during _execution_loop
                server = _exporter.start_metrics_server(
                    agg, cfg, profile_controller=profile_ctl)
                self._metrics_server = server
        from ray_lightning_tpu.core import datacheck
        dc = None
        if datacheck.enabled() \
                or self.worker_env.get(datacheck.ENV_DATA_CHECK) == "1":
            # opt-in divergent-loader detection: workers relay per-step
            # batch fingerprints over the queue; the driver cross-checks
            # ranks in process_results and raises on divergence
            dc = datacheck.DataCheckValidator()
            datacheck.set_active_validator(dc)
        try:
            return self._execution_loop(trainer, module, datamodule, stage,
                                        ckpt_path, backend)
        except BaseException:
            # probe fleet liveness BEFORE teardown kills everyone: the
            # elastic driver classifies the failure (a dead process is
            # restartable, a deterministic user exception is not) and
            # sizes the shrink from this list.  process_alive, not
            # alive: the strict probe never misreads a busy survivor
            # as dead (cluster/backend.py)
            self._last_dead_ranks = [
                i for i, w in enumerate(self._workers)
                if w.process_alive() is False]
            # harvest survivor escrows BEFORE the finally below kills
            # them: the parity-tick state deposited on each survivor
            # (elastic/redundancy.py) is what reconstruct-and-continue
            # recovers from, served by the workers' reader threads even
            # when their main threads are wedged in a dead collective
            self._last_escrows = {}
            elastic = getattr(trainer, "elastic", None)
            if stage == "fit" and elastic is not None \
                    and elastic.enabled and elastic.redundancy > 0:
                for i, w in enumerate(self._workers):
                    if i in self._last_dead_ranks:
                        continue
                    try:
                        esc = w.harvest_escrow(timeout=15.0)
                    except Exception:   # noqa: BLE001 - best-effort
                        esc = None
                    if esc is not None:
                        self._last_escrows[i] = esc
            raise
        finally:
            if dc is not None:
                datacheck.set_active_validator(None)
            for w in self._workers:
                w.kill()  # no_restart parity, ray_ddp.py:383-386
            self._workers = []
            if agg is not None:
                from ray_lightning_tpu import telemetry
                telemetry.set_active(None)
                if server is not None:
                    server.stop()
                trainer._telemetry_paths = agg.export()
                if server is not None:
                    trainer._telemetry_paths["metrics_url"] = server.url
                # fleet goodput aggregate + the planner's measured-vs-
                # modeled divergence, from the docs the workers shipped
                # over the queue (rank-0 package fallback in
                # _post_dispatch when the queue copy was dropped)
                gp = agg.goodput_stats()
                if gp:
                    trainer._goodput_report = gp.get("fleet")
                trainer._attach_observed_divergence(agg)

    def _execution_loop(self, trainer, module, datamodule, stage, ckpt_path,
                        backend):
        workers = self._workers
        if self.init_hook is not None:
            # dataset-download style hook on every worker before training
            # (examples/ray_ddp_tune.py:22-25 parity)
            process_results(
                [w.call("execute", self.init_hook) for w in workers], backend)

        # rendezvous: worker-0's node hosts the PJRT coordinator
        # (MASTER_ADDR/PORT analog, ray_ddp.py:206-219)
        if self.num_workers > 1:
            ip = workers[0].call("get_node_ip").result(timeout=120)
            port = workers[0].call("get_free_port").result(timeout=120)
            coord_env = {"RLT_COORDINATOR": f"{ip}:{port}"}
        else:
            coord_env = {}
        node_info = process_results(
            [w.call("get_node_and_device_info") for w in workers], backend)
        ranks = self._assign_local_ranks(node_info)
        tpu_env = self._tpu_partition_envs(node_info, ranks, backend)
        env_futs = []
        for i, w in enumerate(workers):
            node_rank, local_rank = ranks[i]
            env_futs.append(w.call("set_env_vars", {
                **coord_env,
                **tpu_env.get(i, {}),
                "RLT_PROCESS_ID": str(i),
                "RLT_NODE_RANK": str(node_rank),
                "RLT_LOCAL_RANK": str(local_rank),
            }))
        process_results(env_futs, backend)

        queue = None
        if stage == "fit" or trainer.telemetry.enabled:
            # telemetry needs the worker→driver queue on every stage
            queue = (backend.worker_queue_proxy()
                     if hasattr(backend, "worker_queue_proxy")
                     else WorkerQueueProxy())

        cache_seed, cache_seed_ref = self._pack_cache_seed(trainer, backend)
        payload = (trainer, module, datamodule, stage, ckpt_path)
        payload_ref = None
        if backend.supports_object_store:
            # ship once via the object store; workers deref on delivery
            payload = payload_ref = backend.put(payload)

        try:
            futures = [
                w.call("execute", _worker_run, payload, i, queue,
                       cache_seed)
                for i, w in enumerate(workers)
            ]
            results = process_results(futures, backend)
        finally:
            if payload_ref is not None:
                backend.free(payload_ref)
            if cache_seed_ref is not None:
                backend.free(cache_seed_ref)
        return self._post_dispatch(trainer, module, stage, results)

    @staticmethod
    def _pack_cache_seed(trainer, backend):
        """(seed, ref) for compile-cache seeding: a packed snapshot of
        the driver's cache root for backends whose workers cannot see
        the driver's filesystem (compile/shipping.py), shipped once via
        the object store when available.  (None, None) when the cache is
        off, the backend shares a filesystem, or the root is empty."""
        cc = trainer.compile_cache
        if not cc.enabled or getattr(backend, "shared_filesystem", False):
            return None, None
        from ray_lightning_tpu.compile import shipping
        blob = shipping.pack_cache_dir(cc.root)
        if blob is None:
            return None, None
        if backend.supports_object_store:
            ref = backend.put(blob)
            return ref, ref
        return blob, None

    def _tpu_partition_envs(self, node_info, ranks, backend) -> dict[int, dict]:
        """Per-worker TPU chip-visibility env for co-located actors
        (``_share_cuda_visible_devices`` analog, ray_ddp.py:221-265).

        Whenever several TPU workers share one node IP, each gets a
        ``TPU_*`` partition of that host's chips (utils/tpu_topology.py);
        impossible splits raise before any worker touches libtpu.  A
        worker alone on its host owns every chip and needs nothing.
        """
        if not self.use_tpu:
            return {}
        by_node: dict[int, list[int]] = {}
        for i in range(len(node_info)):
            node_rank, _local = ranks[i]
            by_node.setdefault(node_rank, []).append(i)
        out: dict[int, dict] = {}
        d = int(self.devices_per_worker or 1)
        from ray_lightning_tpu.utils.tpu_topology import partition_env
        for members in by_node.values():
            if len(members) < 2:
                continue  # sole owner of the host: no scoping needed
            members = sorted(members, key=lambda i: ranks[i][1])
            ports = process_results(
                [self._workers[i].call("get_free_port") for i in members],
                backend)
            ip = node_info[members[0]].get("ip", "?")
            for i in members:
                out[i] = partition_env(d, ranks[i][1], ip, ports)
        return out

    @staticmethod
    def _assign_local_ranks(node_info: list[dict]) -> dict[int, tuple[int, int]]:
        """Global rank → (node_rank, local_rank) from node IPs
        (get_local_ranks analog, ray_ddp.py:282-306)."""
        by_ip: dict[str, list[int]] = {}
        for i, info in enumerate(node_info):
            by_ip.setdefault(info.get("ip", "?"), []).append(i)
        out: dict[int, tuple[int, int]] = {}
        for node_rank, (_ip, members) in enumerate(sorted(by_ip.items())):
            for local_rank, grank in enumerate(members):
                out[grank] = (node_rank, local_rank)
        return out

    def _post_dispatch(self, trainer, module, stage, results):
        rank0 = next(r for r in results if r is not None)
        trainer.callback_metrics.update(rank0.get("callback_metrics", {}))
        trainer.current_epoch = rank0.get("epoch", trainer.current_epoch)
        trainer.global_step = rank0.get("global_step", trainer.global_step)
        trainer.time_to_first_step = rank0.get("time_to_first_step")
        trainer._elastic_worker_stats = rank0.get("elastic")
        if rank0.get("plan_report") is not None:
            trainer._plan_report = rank0.get("plan_report")
        if rank0.get("goodput") is not None:
            # rank 0's own doc as the provisional report; _run_attempt's
            # teardown upgrades it to the fleet aggregate when the
            # queue-shipped docs reached the aggregator
            trainer._goodput_report = rank0.get("goodput")
        if stage == "fit":
            stream = rank0.get("state_stream")
            if stream is not None:
                # driver-side weight rehydration (ray_ddp.py:375-377 analog)
                module._trained_variables = load_state_stream(stream)
            ckpt_cb = trainer.checkpoint_callback
            best = rank0.get("best_model_path")
            if ckpt_cb is not None and best:
                # a path on rank-0's node; valid on shared FS / GCS
                # (locality caveat, ray_ddp.py:378-380 / SURVEY.md §7)
                ckpt_cb.best_model_path = best
                ckpt_cb.best_model_score = rank0.get("best_model_score")
        return rank0.get("result")

    # -- worker-side mesh devices -----------------------------------------

    def local_devices(self):
        return None  # the global mesh spans all devices of all processes


class RayXlaShardedPlugin(RayXlaPlugin):
    """ZeRO-1 flavor (reference: ``RayShardedPlugin``,
    ray_ddp_sharded.py:17-34).  Identical orchestration; the difference is
    purely the sharding strategy — optimizer state sharded across data
    ranks, grads reduce-scattered, params all-gathered by XLA — where the
    reference swaps in FairScale OSS/SDP via PL's
    ``DDPSpawnShardedPlugin`` MRO."""

    def __init__(self, *args, **kwargs):
        kwargs.setdefault("strategy", "zero1")
        super().__init__(*args, **kwargs)


class RayXlaSpmdPlugin(RayXlaPlugin):
    """General SPMD flavor (beyond reference parity): tensor/sequence/
    expert-parallel meshes via partition rules (parallel/strategy.py
    SpmdStrategy).  Same actor orchestration."""

    def __init__(self, *args, **kwargs):
        kwargs.setdefault("strategy", "spmd")
        super().__init__(*args, **kwargs)
