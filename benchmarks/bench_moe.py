"""Beyond-parity workload: MoE GPT (routed FFN, ops/moe.py), steps/sec.

Single-chip this measures the routed-FFN cost (static-capacity
dispatch/combine einsums + per-expert FFN); multi-chip runs shard the
expert dim on the ``expert`` mesh axis and the same einsums lower to
the token all-to-all.

    python -m benchmarks.bench_moe
"""

import jax

from benchmarks.harness import run_steps_per_sec

BASELINES = {"tpu": 8.9}   # first v5e measurement, gpt2-moe-8e B=8 T=1024


def main():
    from ray_lightning_tpu.models.gpt import GPTLightningModule

    platform = jax.devices()[0].platform
    cfg = "gpt2-moe-8e" if platform != "cpu" else "moe-tiny"
    batch = 8
    module = GPTLightningModule(cfg, batch_size=batch,
                                dataset_size=batch * 40)
    run_steps_per_sec(module, f"{cfg}_b{batch}_steps_per_sec_{platform}",
                      baseline=BASELINES.get(platform))


if __name__ == "__main__":
    main()
