"""Long-context throughput: tokens/sec/chip at T = 2k / 4k / 8k.

The long-sequence story is first-class (SURVEY aux: ring attention +
flash kernels + chunked CE); this bench pins single-chip numbers for
it: a gpt2-small-width decoder at growing T with the levers the config
system flips at scale — triangular-grid causal flash kernels (default
where they engage, T>=2048), remat, and chunked CE (T=8k).  Ring
attention distributes T over a `sequence` mesh axis on real pods; its
equality tests run on the virtual mesh (tests/test_ring_attention.py).

    python -m benchmarks.bench_longcontext [2048 4096 8192]

Prints one JSON line per sequence length (tokens/sec = steps/sec × B·T).
"""

from __future__ import annotations

import dataclasses
import sys

import jax

from benchmarks.harness import run_steps_per_sec
from ray_lightning_tpu.models.gpt import CONFIGS, GPTLightningModule

# first-measurement baselines (v5e chip, round 3) so later rounds diff
BASELINES = {2048: 74_359.0, 4096: 57_500.0, 8192: 36_839.0}


def main() -> None:
    platform = jax.devices()[0].platform
    lengths = [int(a) for a in sys.argv[1:]] or [2048, 4096, 8192]
    for t in lengths:
        if platform == "cpu":
            cfg = dataclasses.replace(CONFIGS["tiny"], block_size=256)
            batch = 2
        else:
            # gpt2-small width; remat + (at 8k) chunked CE keep HBM sane,
            # batch shrinks with T to hold the token budget steady
            batch = max(1, 8192 // t)
            cfg = dataclasses.replace(
                CONFIGS["gpt2-small"], block_size=t, remat=True,
                chunked_ce=16 if t >= 8192 else 0)
        module = GPTLightningModule(cfg, dataset_size=batch * 16,
                                    batch_size=batch)
        res = run_steps_per_sec(
            module, f"gpt2s_T{t}_steps_per_sec_{platform}",
            warmup=2, timed=8)
        toks = res["value"] * batch * t
        base = BASELINES.get(t)
        print(__import__("json").dumps({
            "metric": f"gpt2s_T{t}_tokens_per_sec_{platform}",
            "value": round(toks, 0), "unit": "tokens/sec",
            "vs_baseline": round(toks / base, 3) if base else 1.0}))


if __name__ == "__main__":
    main()
