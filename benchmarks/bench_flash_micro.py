"""Micro-benchmark: flash attention fwd+bwd device time at a given
shape, isolated from the rest of the model.

Usage:  python -m benchmarks.bench_flash_micro [T] [steps]

Times ``jit(value_and_grad)`` of a scalar loss over
``flash_attention(q, k, v, causal=True)`` at the headline shape
(B=8, H=12, D=64, T=1024 by default) and prints wall ms/iter plus the
device ms/iter of the dominant XLA module (tunnel-immune).  The knobs
under test (RLT_FLASH_*) are env vars, so A/B runs are just env
changes — the same pattern as profile_headline.py.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def main() -> None:
    t = int(sys.argv[1]) if len(sys.argv) > 1 else 1024
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 20
    b, h, d = 8, 12, 64

    from ray_lightning_tpu.ops.flash_attention import flash_attention

    key = jax.random.PRNGKey(0)
    kq, kk, kv, kg = jax.random.split(key, 4)
    q = jax.random.normal(kq, (b, t, h, d), jnp.bfloat16)
    k = jax.random.normal(kk, (b, t, h, d), jnp.bfloat16)
    v = jax.random.normal(kv, (b, t, h, d), jnp.bfloat16)
    co = jax.random.normal(kg, (b, t, h, d), jnp.bfloat16)

    def loss(q, k, v):
        o = flash_attention(q, k, v, causal=True)
        return jnp.sum(o.astype(jnp.float32) * co.astype(jnp.float32))

    step = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))

    val, grads = step(q, k, v)
    for _ in range(2):
        val, grads = step(q, k, v)
    float(np.asarray(val))  # tunnel-safe sync

    t0 = time.monotonic()
    for _ in range(steps):
        val, grads = step(q, k, v)
    float(np.asarray(val))
    wall_ms = (time.monotonic() - t0) / steps * 1000

    from benchmarks import trace_tools

    def run():
        for _ in range(8):
            out = step(q, k, v)
        float(np.asarray(out[0]))

    try:
        trace_dir = trace_tools.capture_trace(run)
    except Exception as e:  # profiler-less backends still get wall time
        sys.stderr.write(f"trace skipped: {e}\n")
        trace_dir = None
    dev_ms = trace_tools.dominant_module_ms_or_none(trace_dir)

    print(json.dumps({
        "metric": f"flash_fwdbwd_T{t}",
        "wall_ms": round(wall_ms, 3),
        "device_ms": round(dev_ms, 3) if dev_ms else None,
        "unit": "ms/iter"}))


if __name__ == "__main__":
    main()
