"""Trace a GPT-config train step and print a device-time breakdown.

Usage:  python -m benchmarks.profile_headline [steps] [config]

``config`` is any ``models.gpt.CONFIGS`` name (default gpt2-small, the
headline).  Builds the same compiled train step the Trainer runs
(core/steps.py), warms it OUTSIDE the trace (the tunnel profiler drops
op events when compilation floods the capture window), then traces
``steps`` warm executions.  Env toggles under test (RLT_BF16_PARAMS /
RLT_REMAT_POLICY / RLT_FLASH_*) are read by the model as usual, so A/B
runs are just env changes.
"""

from __future__ import annotations

import json
import sys

import numpy as np

from benchmarks import trace_tools


def main() -> None:
    import jax

    from ray_lightning_tpu.core.steps import build_init_fn, build_train_step
    from ray_lightning_tpu.models.gpt import CONFIGS, GPTLightningModule

    timed = int(sys.argv[1]) if len(sys.argv) > 1 else 5
    platform = jax.devices()[0].platform
    default_cfg = "gpt2-small" if platform != "cpu" else "tiny"
    cfg_name = sys.argv[2] if len(sys.argv) > 2 else default_cfg
    if cfg_name not in CONFIGS:
        raise SystemExit(
            f"unknown config {cfg_name!r}; options: {sorted(CONFIGS)}")
    cfg = CONFIGS[cfg_name]
    batch_size = 8

    module = GPTLightningModule(cfg, dataset_size=batch_size * 2,
                                batch_size=batch_size)
    module.setup_model()
    tx = module.configure_optimizers()
    batch = next(iter(module.train_dataloader()))
    batch = jax.device_put(jax.tree_util.tree_map(np.asarray, batch))

    init_fn = jax.jit(build_init_fn(module, tx))
    step_fn = jax.jit(build_train_step(module, tx), donate_argnums=0)

    state = init_fn(jax.random.PRNGKey(0), batch)
    for _ in range(3):  # warm: compile + steady-state allocator
        state, metrics = step_fn(state, batch)
    float(np.asarray(metrics["loss"]))  # tunnel-safe sync

    def run():
        nonlocal state
        for _ in range(timed):
            state, m = step_fn(state, batch)
        float(np.asarray(m["loss"]))

    trace_dir = trace_tools.capture_trace(run)

    total = trace_tools.total_device_ms(trace_dir)
    print(json.dumps({"device_ms_per_step": round(total / timed, 2),
                      "steps": timed, "trace_dir": trace_dir}))
    print("\n# bucket ms/step")
    for b, ms in trace_tools.device_breakdown(trace_dir).items():
        print(f"{b:28s} {ms / timed:8.2f}")
    print("\n# roofline (per dedup'd op): ms/step  n/step  TFLOP/s  GB/s  "
          "bound")
    for r in trace_tools.roofline(trace_dir, timed):
        print(f"{r['ms_per_step']:8.2f} {r['count'] / timed:6.1f} "
              f"{r['tflops']:8.1f} {r['gbps']:7.1f}  "
              f"{r['bound_frac']:4.2f} {r['bound_by'][:4]}  "
              f"[{r['category']}] {r['source'][:60]}")


if __name__ == "__main__":
    main()
