"""Micro-benchmark: per-token decode attention A/B — dense masked
einsum vs the Pallas flash-decode kernel vs its paged variant
(ops/flash_decode.py) at serving shapes.

Usage:  python -m benchmarks.bench_decode_micro [steps] [L ...]

Times ``jit(cached_attention)`` — one new token per slot against a
[S, L, H, D] KV cache with RAGGED per-slot positions (the serve
plane's steady state: every slot at a different depth) — and prints
one JSON line per (impl, L) with wall ms/iter plus the device ms/iter
of the dominant XLA module (tunnel-immune, same discipline as
bench_flash_micro.py).

The acceptance bar is enforced where the kernel actually compiles
(TPU): at L >= 2048 the length-aware kernel must beat the dense
einsum on device ms — the dense path reads and scores all L cache
rows per token while the kernel's clamped index map stops fetching at
``positions[s]``.  On CPU the kernel runs under the Pallas
interpreter (numerics-only; orders of magnitude slower), so the bar
is reported but not asserted.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

#: serving shape: 8 slots, 8 heads x 64 = C 512 (128-aligned for TPU)
S, H, D = 8, 8, 64
PAGE_SIZE = 128


def _ragged_positions(L: int) -> np.ndarray:
    """Per-slot depths spread over [L/8, L-1] — the steady-state mix a
    continuous-batching scheduler produces (no two slots aligned)."""
    return np.linspace(L // 8, L - 1, S).astype(np.int32)


def _bench_impl(impl: str, L: int, steps: int, platform: str) -> dict:
    from benchmarks import trace_tools
    from ray_lightning_tpu.ops.attention import cached_attention
    from ray_lightning_tpu.serve.fleet.pages import identity_page_table

    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (S, 1, H, D), jnp.bfloat16)
    kc = jax.random.normal(kk, (S, L, H, D), jnp.bfloat16)
    vc = jax.random.normal(kv, (S, L, H, D), jnp.bfloat16)
    pos = jnp.asarray(_ragged_positions(L))
    table = (jnp.asarray(identity_page_table(S, L, PAGE_SIZE))
             if impl == "paged" else None)

    @jax.jit
    def step(q, kc, vc, pos):
        return cached_attention(q, kc, vc, pos, impl=impl,
                                page_table=table)

    out = step(q, kc, vc, pos)
    out.block_until_ready()
    for _ in range(2):
        step(q, kc, vc, pos).block_until_ready()

    t0 = time.monotonic()
    for _ in range(steps):
        out = step(q, kc, vc, pos)
    out.block_until_ready()
    wall_ms = (time.monotonic() - t0) / steps * 1000

    def run():
        for _ in range(8):
            out = step(q, kc, vc, pos)
        out.block_until_ready()

    try:
        trace_dir = trace_tools.capture_trace(run)
    except Exception as e:  # profiler-less backends still get wall time
        sys.stderr.write(f"trace skipped: {e}\n")
        trace_dir = None
    dev_ms = trace_tools.dominant_module_ms_or_none(trace_dir)

    return {
        "metric": f"decode_micro_{impl}_L{L}",
        "impl": impl,
        "L": L,
        "slots": S,
        "wall_ms": round(wall_ms, 3),
        "device_ms": round(dev_ms, 3) if dev_ms else None,
        "platform": platform,
        "unit": "ms/iter",
    }


def main() -> int:
    steps = int(sys.argv[1]) if len(sys.argv) > 1 else 30
    lengths = ([int(a) for a in sys.argv[2:]]
               if len(sys.argv) > 2 else [512, 2048])
    platform = jax.devices()[0].platform
    if platform == "cpu":
        # the interpreter is numerics-only; keep smoke runs tractable
        steps = min(steps, 5)
        lengths = [min(length, 512) for length in lengths]

    rows = []
    for L in sorted(set(lengths)):
        for impl in ("dense", "flash_decode", "paged"):
            row = _bench_impl(impl, L, steps, platform)
            rows.append(row)
            print(json.dumps(row), flush=True)

    # the acceptance bar, enforced where the kernel compiles
    if platform == "tpu":
        by = {(r["impl"], r["L"]): r for r in rows}
        for L in sorted({r["L"] for r in rows}):
            if L < 2048:
                continue
            dense = by[("dense", L)]
            flash = by[("flash_decode", L)]
            d = dense.get("device_ms") or dense["wall_ms"]
            f = flash.get("device_ms") or flash["wall_ms"]
            assert f < d, (
                f"flash-decode did not beat dense at L={L}: "
                f"{f} vs {d} ms/iter")
    return 0


if __name__ == "__main__":
    sys.exit(main())
