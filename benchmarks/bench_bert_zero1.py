"""BASELINE config #4: BERT-base masked-LM under ZeRO-1 sharding.

On one chip the zero1 annotations are identity (nothing to shard
across), so this measures the sharded code path's single-chip cost;
multi-chip runs shard optimizer state across the data axis.

    python -m benchmarks.bench_bert_zero1
"""

import jax

from benchmarks.harness import run_steps_per_sec

BASELINES = {"tpu": 8.4}   # first v5e measurement, B=32 T=128 bert-base


def main():
    from ray_lightning_tpu.models.bert import BertMLMModule

    platform = jax.devices()[0].platform
    batch = 32 if platform != "cpu" else 4
    cfg = "bert-base" if platform != "cpu" else "tiny"
    module = BertMLMModule(cfg, batch_size=batch, train_size=batch * 40)
    run_steps_per_sec(module,
                      f"bert_{cfg}_zero1_b{batch}_steps_per_sec_{platform}",
                      strategy="zero1", baseline=BASELINES.get(platform))


if __name__ == "__main__":
    main()
