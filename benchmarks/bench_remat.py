"""Remat-policy A/B ladder: compile and time EVERY feasible policy on
the headline fixture, emit one ``remat`` JSON field.

``bench.py`` runs this when ``RLT_REMAT_AB=1``.  Until PR 12 the
remat-policy walk was manual — hand-measured picks live as comments in
``models/gpt.py`` (e.g. ``dots`` bought +17% steps/s on gpt2-medium)
and every new claim meant a hand-driven re-run.  This ladder automates
the headroom hunt the 49.35 ms/step plateau has been waiting on: every
policy of the module's ``configure_remat()`` ladder gets

- an AOT memory probe (``memory_analysis`` of the compiled train step
  — argument + output + temp − alias, the planner's own peak account),
  which also decides *feasibility*: a policy whose modeled peak
  exceeds the device budget (when the runtime reports one) is recorded
  as infeasible instead of risking an OOM mid-ladder;
- a measured wall steps/sec leg through the shared harness, with the
  warm-tail ``device_ms`` when the platform's profiler cooperates.

One summary JSON line then carries per-policy device ms/step + HBM
peak + the measured winner NEXT TO the hand-picked default, with the
gap documented — so every future policy claim is one JSON diff, and a
ladder winner slower than the hand pick is visible, not silent.
"""

from __future__ import annotations

import json
import os
import sys

WARMUP = 3
TIMED = 15


def _compiled_peak(module) -> "tuple[int, str | None]":
    """(peak bytes of the single-device donated train step, error) —
    the same arg+out+temp−alias account the planner's verify stage
    reads (compile/aot.py ScoredCompile.peak_bytes)."""
    import jax
    import numpy as np

    from ray_lightning_tpu.core.steps import build_init_fn, build_train_step

    try:
        batch = jax.tree_util.tree_map(
            np.asarray, next(iter(module.train_dataloader())))
        tx = module.configure_optimizers()
        if isinstance(tx, dict):
            tx = tx["optimizer"]
        abstract = jax.eval_shape(build_init_fn(module, tx),
                                  jax.random.PRNGKey(0), batch)
        jitted = jax.jit(build_train_step(module, tx), donate_argnums=0)
        mem = jitted.lower(abstract, batch).compile().memory_analysis()
        peak = (int(mem.argument_size_in_bytes)
                + int(mem.output_size_in_bytes)
                + int(mem.temp_size_in_bytes)
                - int(mem.alias_size_in_bytes))
        return max(0, peak), None
    except Exception as e:   # noqa: BLE001 - per-policy soft fail
        return 0, f"{type(e).__name__}: {e}"


def _device_budget() -> "int | None":
    import jax
    dev = jax.devices()[0]
    try:
        stats = dev.memory_stats()
        if stats and "bytes_limit" in stats:
            return int(stats["bytes_limit"])
    except Exception:   # noqa: BLE001 - CPU / profiler-less backends
        pass
    if getattr(dev, "platform", None) == "tpu":
        from ray_lightning_tpu.core.trainer import Trainer
        return Trainer._HBM_BY_KIND.get(getattr(dev, "device_kind", ""))
    return None


def run_remat_ab(metric_prefix: str = "remat_ab") -> dict:
    """Emit one ladder leg per feasible policy plus the ``remat``
    summary line (module docstring)."""
    import jax

    from benchmarks.harness import run_steps_per_sec
    from ray_lightning_tpu.models.gpt import GPTLightningModule

    platform = jax.devices()[0].platform
    fixture = "tiny" if platform == "cpu" else "gpt2-small"
    batch = 8
    steps = WARMUP + TIMED + 4

    hand = GPTLightningModule(fixture).configure_remat().default
    budget = _device_budget()
    policies: dict = {}
    for policy in GPTLightningModule(fixture).configure_remat().policies:
        module = GPTLightningModule(fixture, dataset_size=batch * steps,
                                    batch_size=batch)
        module.configure_remat().apply(policy)
        peak, err = _compiled_peak(module)
        entry: dict = {"hbm_peak_bytes": peak}
        if err is not None:
            entry["error"] = f"compile: {err}"
            policies[policy] = entry
            continue
        if budget is not None and peak > budget:
            entry["error"] = (f"infeasible: compiled peak "
                              f"{peak >> 20} MiB > {budget >> 20} "
                              f"MiB device budget")
            policies[policy] = entry
            continue
        try:
            res = run_steps_per_sec(
                module, f"{metric_prefix}_{policy}", warmup=WARMUP,
                timed=TIMED, telemetry=False,
                trace_steps=4, inline_device_ms=True)
        except Exception as e:   # noqa: BLE001 - one bad leg != no ladder
            entry["error"] = f"run: {type(e).__name__}: {e}"
            policies[policy] = entry
            continue
        wall_ms = 1000.0 / res["value"]
        entry["steps_per_sec"] = res["value"]
        entry["wall_ms"] = round(wall_ms, 3)
        # device_ms is the tunnel-immune number of record when the
        # platform traces; CPU smoke runs rank on wall ms
        entry["device_ms"] = res.get("device_ms")
        entry["rank_ms"] = round(res.get("device_ms") or wall_ms, 3)
        policies[policy] = entry

    timed_ok = {p: e for p, e in policies.items() if "rank_ms" in e}
    winner = min(timed_ok, key=lambda p: timed_ok[p]["rank_ms"]) \
        if timed_ok else None
    summary = {
        "metric": metric_prefix,
        "remat": {
            "fixture": fixture,
            "batch": batch,
            "hand_picked": hand,
            "winner": winner,
            "policies": policies,
        },
    }
    if winner is not None and hand in timed_ok:
        win_ms = timed_ok[winner]["rank_ms"]
        hand_ms = timed_ok[hand]["rank_ms"]
        summary["remat"]["winner_ms"] = win_ms
        summary["remat"]["hand_picked_ms"] = hand_ms
        summary["remat"]["winner_le_hand_picked"] = win_ms <= hand_ms
        # the acceptance contract: the ladder's winner beats (or ties)
        # the hand pick — when it doesn't, the gap is documented here
        # rather than silently dropped
        summary["remat"]["gap_pct"] = round(
            100.0 * (win_ms - hand_ms) / hand_ms, 2)
    print(json.dumps(summary))
    return summary


def main() -> None:
    run_remat_ab(os.environ.get("RLT_REMAT_AB_METRIC", "remat_ab"))


if __name__ == "__main__":
    sys.exit(main())
