"""Benchmark: checkpoint save + restore wall time and bytes/s
(VERDICT #7 — make checkpoint stalls a round-over-round number).

Measures BOTH checkpoint paths on a real sharded TrainState:

- **msgpack full-gather** (``Trainer.save_checkpoint`` mechanics):
  all-gather the state to host, ``flax.serialization`` msgpack blob,
  one file; restore = read + ``from_state_dict`` + re-shard device_put.
- **orbax per-shard async** (``ShardedCheckpointer``): every process
  writes only its own shards; the save figure here includes
  ``wait()`` (durability) so it is the worst-case stall, not the async
  happy path; restore re-shards directly into the mesh.

Prints exactly ONE JSON line:

  {"metric": "checkpoint_io", "unit": "seconds", "rows": [
     {"config": ..., "path": "msgpack|orbax", "state_bytes": N,
      "save_seconds": S, "save_bytes_per_s": B,
      "restore_seconds": S2, "restore_bytes_per_s": B2}, ...]}

Two elastic-plane legs (PR 7) join the same JSON line:

- **reshard** (``--reshard``, default on): save the state on a 2-way
  data mesh, restore it onto 1-way and 4-way meshes through the
  elastic reshard path (elastic/reshard.py) — ``reshard_restore_s`` +
  bytes/s per target.  On CPU the 4-way target runs over 4 virtual
  host devices (the fake-multinode stand-in the tests use); on real
  hardware it uses the first 1/2/4 local devices.
- **snapshot** (``--snapshot-steps N``, default 8): a short
  BoringModel fit with ``elastic.snapshot_every_n_steps=1`` measuring
  the async snapshot cost off the critical path — ``snapshots``,
  ``skipped`` (bounded backpressure) and the measured
  ``rlt_snapshot_stall_seconds_total`` / ``rlt_snapshot_seconds_total``
  sums, so "async snapshots add bounded stall" is a number, not a
  claim.
- **elastic_recovery** (``--recovery-steps N``, default 8, PR 13): a
  2-worker ZeRO-1 chaos fit loses rank 1 mid-run, once with parity
  redundancy on (zero-replay reconstruct-and-continue) and once off
  (snapshot replay) — time-to-recover, replayed steps, parity-overhead
  bytes/step and the snapshot-restore count per mode, so "parity buys
  zero replay for k x shard bytes per cadence" is one JSON diff.

Defaults to the gpt2-small and gpt2-medium configs (the driver runs
this on TPU hosts); ``--configs tiny`` keeps CPU smoke runs tractable.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np


def _state_bytes(state) -> int:
    import jax
    return sum(
        int(np.prod(getattr(leaf, "shape", ()), dtype=np.int64))
        * np.dtype(leaf.dtype).itemsize
        for leaf in jax.tree_util.tree_leaves(state))


def _build_state(config: str, strategy_name: str):
    import jax

    from ray_lightning_tpu.core.steps import build_init_fn
    from ray_lightning_tpu.models.gpt import GPTLightningModule
    from ray_lightning_tpu.parallel.strategy import resolve_strategy

    module = GPTLightningModule(config, dataset_size=2, batch_size=1)
    module.setup_model()
    tx = module.configure_optimizers()
    strat = resolve_strategy(strategy_name)
    mesh = strat.build_mesh(batch_hint=1)
    batch = jax.tree_util.tree_map(
        np.asarray, next(iter(module.train_dataloader())))
    init_fn = build_init_fn(module, tx)
    abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0), batch)
    shardings = strat.state_shardings(mesh, abstract)
    state = jax.jit(init_fn, out_shardings=shardings)(
        jax.random.PRNGKey(0), batch)
    jax.block_until_ready(state)
    return state, shardings


def _bench_msgpack(state, shardings, workdir: str) -> dict:
    import jax
    from flax import serialization

    from ray_lightning_tpu.parallel.gather import fetch_tree

    path = os.path.join(workdir, "full.ckpt")
    t0 = time.monotonic()
    host_tree = fetch_tree(state)            # TrainState of host arrays
    payload = serialization.msgpack_serialize(
        serialization.to_state_dict(host_tree))
    with open(path, "wb") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    save_s = time.monotonic() - t0

    t0 = time.monotonic()
    with open(path, "rb") as f:
        blob = f.read()
    restored = serialization.from_state_dict(
        host_tree, serialization.msgpack_restore(blob))
    restored = jax.device_put(restored, shardings)
    jax.block_until_ready(restored)
    restore_s = time.monotonic() - t0
    return {"save_seconds": save_s, "restore_seconds": restore_s,
            "file_bytes": len(payload)}


def _bench_orbax(state, shardings, workdir: str) -> dict:
    import jax

    from ray_lightning_tpu.utils.checkpoint import (ShardedCheckpointer,
                                                    abstract_like)

    directory = os.path.join(workdir, "sharded")
    ckpt = ShardedCheckpointer(directory)
    t0 = time.monotonic()
    ckpt.save(0, state, {"bench": True})
    ckpt.wait()                      # durability, not dispatch
    save_s = time.monotonic() - t0
    ckpt.close()

    ckpt = ShardedCheckpointer(directory)
    t0 = time.monotonic()
    restored, _meta = ckpt.restore(abstract_like(state, shardings))
    jax.block_until_ready(restored)
    restore_s = time.monotonic() - t0
    ckpt.close()
    return {"save_seconds": save_s, "restore_seconds": restore_s}


def _build_state_on(config: str, strategy_name: str, devices):
    """Like :func:`_build_state` but meshed over an explicit device
    list — the reshard leg's way of standing up N-way topologies on
    one host."""
    import jax

    from ray_lightning_tpu.core.steps import build_init_fn
    from ray_lightning_tpu.models.gpt import GPTLightningModule
    from ray_lightning_tpu.parallel.strategy import resolve_strategy

    module = GPTLightningModule(config, dataset_size=2, batch_size=1)
    module.setup_model()
    tx = module.configure_optimizers()
    strat = resolve_strategy(strategy_name)
    mesh = strat.build_mesh(devices=devices, batch_hint=len(devices))
    batch = jax.tree_util.tree_map(
        np.asarray, next(iter(module.train_dataloader())))
    init_fn = build_init_fn(module, tx)
    abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0), batch)
    shardings = strat.state_shardings(mesh, abstract)
    state = jax.jit(init_fn, out_shardings=shardings)(
        jax.random.PRNGKey(0), batch)
    jax.block_until_ready(state)
    return state, shardings


def _bench_reshard(config: str, strategy: str, workdir: str) -> list:
    """Save on a 2-way data mesh; reshard-restore onto 1-way and 4-way
    meshes (elastic/reshard.py).  Emits one row per target world."""
    import jax

    from ray_lightning_tpu.elastic.reshard import restore_resharded
    from ray_lightning_tpu.utils.checkpoint import ShardedCheckpointer

    devices = jax.devices()
    if len(devices) < 4:
        print(f"# reshard leg skipped: {len(devices)} devices < 4",
              file=sys.stderr)
        return []
    state, _sh = _build_state_on(config, strategy, devices[:2])
    nbytes = _state_bytes(state)
    directory = os.path.join(workdir, "reshard_src")
    ckpt = ShardedCheckpointer(directory)
    ckpt.save(0, state, {"bench": True, "world": 2})
    ckpt.wait()
    ckpt.close()
    del state

    rows = []
    for target_world in (1, 4):
        tstate, tsh = _build_state_on(config, strategy,
                                      devices[:target_world])
        ckpt = ShardedCheckpointer(directory)
        t0 = time.monotonic()
        restored, _meta = restore_resharded(ckpt, tstate, tsh, step=0)
        jax.block_until_ready(restored)
        reshard_s = time.monotonic() - t0
        ckpt.close()
        rows.append({
            "config": config,
            "path": "orbax_reshard",
            "save_world": 2,
            "restore_world": target_world,
            "state_bytes": nbytes,
            "reshard_restore_s": round(reshard_s, 3),
            "restore_bytes_per_s": int(nbytes / max(reshard_s, 1e-9)),
        })
        del tstate, restored
    return rows


def _bench_snapshot(steps: int, workdir: str) -> dict:
    """Async per-step snapshot cost on a live (local) fit: the
    cadence fires EVERY step, so the stall/skip counters show the
    backpressure behavior at its worst."""
    from ray_lightning_tpu import Trainer
    from ray_lightning_tpu.models import BoringModel

    snap = os.path.join(workdir, "elastic")
    trainer = Trainer(
        max_epochs=10**6, max_steps=steps, enable_checkpointing=False,
        num_sanity_val_steps=0, limit_val_batches=0, seed=0,
        log_every_n_steps=10**6, default_root_dir=workdir,
        elastic={"snapshot_every_n_steps": 1, "snapshot_dir": snap,
                 "max_to_keep": 2})
    t0 = time.monotonic()
    trainer.fit(BoringModel(dataset_length=max(64, 2 * steps)))
    wall = time.monotonic() - t0
    stats = trainer.elastic_stats() or {}
    return {
        "config": "boring",
        "path": "elastic_snapshot",
        "steps": steps,
        "wall_seconds": round(wall, 3),
        "snapshots": stats.get("snapshots", 0),
        "skipped": stats.get("skipped", 0),
        "rlt_snapshot_seconds_total":
            round(stats.get("save_seconds", 0.0), 4),
        "rlt_snapshot_stall_seconds_total":
            round(stats.get("stall_seconds", 0.0), 4),
    }


def _bench_elastic_recovery(steps: int, workdir: str) -> list:
    """Zero-replay vs replay, measured (ISSUE 13): a 2-worker ZeRO-1
    chaos fit loses rank 1 mid-run, once with parity redundancy on and
    once off.  Emits one row per mode with time-to-recover (driver
    route decision + the resumed attempt's time-to-first-step), the
    parity overhead bytes/step that bought it, and the resume step —
    the parity row resumes at the kill step with ZERO snapshot
    restores, the replay row pays the rewind to the last durable
    snapshot."""
    import optax

    from ray_lightning_tpu import RayXlaPlugin, Trainer
    from ray_lightning_tpu.models import BoringModel

    class AdamBoring(BoringModel):
        def configure_optimizers(self):
            return optax.adam(0.05)

    kill = max(2, steps - 3)
    rows = []
    for redundancy in (1, 0):
        snap = os.path.join(workdir, f"elastic_r{redundancy}")
        trainer = Trainer(
            max_epochs=10**6, max_steps=steps, limit_val_batches=0,
            num_sanity_val_steps=0, enable_checkpointing=False, seed=0,
            log_every_n_steps=10**6,
            default_root_dir=os.path.join(workdir, f"root_r{redundancy}"),
            plugins=[RayXlaPlugin(
                2, platform="cpu", strategy="zero1",
                worker_env={"RLT_FAULT": f"kill:rank=1,step={kill}"})],
            elastic={"snapshot_every_n_steps": 2, "snapshot_dir": snap,
                     "max_restarts": 2, "redundancy": redundancy})
        t0 = time.monotonic()
        trainer.fit(AdamBoring(dataset_length=max(64, 4 * steps),
                               batch_size=2))
        wall = time.monotonic() - t0
        rep = trainer._elastic_report or {}
        rows.append({
            "config": "boring",
            "path": "elastic_recovery",
            "redundancy": redundancy,
            "recovery": rep.get("recovery"),
            "steps": steps,
            "kill_step": kill,
            "resumed_step": rep.get("resumed_step"),
            "replayed_steps": kill - (rep.get("resumed_step") or 0),
            "wall_seconds": round(wall, 3),
            "recovery_seconds": round(rep.get("recovery_seconds", 0.0)
                                      or 0.0, 3),
            "recovery_decision_seconds": round(
                rep.get("recovery_decision_seconds", 0.0) or 0.0, 4),
            "parity_bytes_per_step": int(
                (rep.get("parity_bytes") or 0) / max(1, kill)),
            "snapshot_restores": rep.get("snapshot_restores", 0),
        })
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--configs", default="gpt2-small,gpt2-medium",
                    help="comma-separated model configs (models/gpt.py)")
    ap.add_argument("--strategy", default="zero1",
                    help="sharding strategy for the measured state")
    ap.add_argument("--reshard", dest="reshard", action="store_true",
                    default=True, help="run the N->M reshard leg")
    ap.add_argument("--no-reshard", dest="reshard", action="store_false")
    ap.add_argument("--snapshot-steps", type=int, default=8,
                    help="steps for the async-snapshot leg (0 = skip)")
    ap.add_argument("--recovery-steps", type=int, default=8,
                    help="steps for the 2-worker zero-replay recovery "
                         "leg (0 = skip; spawns CPU subprocess workers)")
    args = ap.parse_args(argv)

    # the reshard leg needs >= 4 devices; on a forced-CPU run stand up
    # 4 virtual host devices BEFORE jax initializes (the conftest /
    # fake-multinode trick) — real TPU hosts already have >= 4 chips
    if args.reshard and os.environ.get("JAX_PLATFORMS", "") == "cpu" \
            and "xla_force_host_platform_device_count" \
            not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=4").strip()

    rows = []
    for config in [c for c in args.configs.split(",") if c]:
        state, shardings = _build_state(config, args.strategy)
        nbytes = _state_bytes(state)
        with tempfile.TemporaryDirectory(prefix="rlt_ckpt_bench_") as d:
            for path_name, bench in (("msgpack", _bench_msgpack),
                                     ("orbax", _bench_orbax)):
                r = bench(state, shardings, d)
                rows.append({
                    "config": config,
                    "path": path_name,
                    "state_bytes": nbytes,
                    "save_seconds": round(r["save_seconds"], 3),
                    "save_bytes_per_s": int(
                        nbytes / max(r["save_seconds"], 1e-9)),
                    "restore_seconds": round(r["restore_seconds"], 3),
                    "restore_bytes_per_s": int(
                        nbytes / max(r["restore_seconds"], 1e-9)),
                })
        del state
        if args.reshard:
            with tempfile.TemporaryDirectory(
                    prefix="rlt_ckpt_reshard_") as d:
                rows.extend(_bench_reshard(config, args.strategy, d))
    if args.snapshot_steps > 0:
        with tempfile.TemporaryDirectory(prefix="rlt_ckpt_snap_") as d:
            rows.append(_bench_snapshot(args.snapshot_steps, d))
    if args.recovery_steps > 0:
        with tempfile.TemporaryDirectory(prefix="rlt_ckpt_rec_") as d:
            rows.extend(_bench_elastic_recovery(args.recovery_steps, d))
    print(json.dumps({"metric": "checkpoint_io", "unit": "seconds",
                      "rows": rows}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
