"""Benchmark: checkpoint save + restore wall time and bytes/s
(VERDICT #7 — make checkpoint stalls a round-over-round number).

Measures BOTH checkpoint paths on a real sharded TrainState:

- **msgpack full-gather** (``Trainer.save_checkpoint`` mechanics):
  all-gather the state to host, ``flax.serialization`` msgpack blob,
  one file; restore = read + ``from_state_dict`` + re-shard device_put.
- **orbax per-shard async** (``ShardedCheckpointer``): every process
  writes only its own shards; the save figure here includes
  ``wait()`` (durability) so it is the worst-case stall, not the async
  happy path; restore re-shards directly into the mesh.

Prints exactly ONE JSON line:

  {"metric": "checkpoint_io", "unit": "seconds", "rows": [
     {"config": ..., "path": "msgpack|orbax", "state_bytes": N,
      "save_seconds": S, "save_bytes_per_s": B,
      "restore_seconds": S2, "restore_bytes_per_s": B2}, ...]}

Defaults to the gpt2-small and gpt2-medium configs (the driver runs
this on TPU hosts); ``--configs tiny`` keeps CPU smoke runs tractable.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np


def _state_bytes(state) -> int:
    import jax
    return sum(
        int(np.prod(getattr(leaf, "shape", ()), dtype=np.int64))
        * np.dtype(leaf.dtype).itemsize
        for leaf in jax.tree_util.tree_leaves(state))


def _build_state(config: str, strategy_name: str):
    import jax

    from ray_lightning_tpu.core.steps import build_init_fn
    from ray_lightning_tpu.models.gpt import GPTLightningModule
    from ray_lightning_tpu.parallel.strategy import resolve_strategy

    module = GPTLightningModule(config, dataset_size=2, batch_size=1)
    module.setup_model()
    tx = module.configure_optimizers()
    strat = resolve_strategy(strategy_name)
    mesh = strat.build_mesh(batch_hint=1)
    batch = jax.tree_util.tree_map(
        np.asarray, next(iter(module.train_dataloader())))
    init_fn = build_init_fn(module, tx)
    abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0), batch)
    shardings = strat.state_shardings(mesh, abstract)
    state = jax.jit(init_fn, out_shardings=shardings)(
        jax.random.PRNGKey(0), batch)
    jax.block_until_ready(state)
    return state, shardings


def _bench_msgpack(state, shardings, workdir: str) -> dict:
    import jax
    from flax import serialization

    from ray_lightning_tpu.parallel.gather import fetch_tree

    path = os.path.join(workdir, "full.ckpt")
    t0 = time.monotonic()
    host_tree = fetch_tree(state)            # TrainState of host arrays
    payload = serialization.msgpack_serialize(
        serialization.to_state_dict(host_tree))
    with open(path, "wb") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    save_s = time.monotonic() - t0

    t0 = time.monotonic()
    with open(path, "rb") as f:
        blob = f.read()
    restored = serialization.from_state_dict(
        host_tree, serialization.msgpack_restore(blob))
    restored = jax.device_put(restored, shardings)
    jax.block_until_ready(restored)
    restore_s = time.monotonic() - t0
    return {"save_seconds": save_s, "restore_seconds": restore_s,
            "file_bytes": len(payload)}


def _bench_orbax(state, shardings, workdir: str) -> dict:
    import jax

    from ray_lightning_tpu.utils.checkpoint import (ShardedCheckpointer,
                                                    abstract_like)

    directory = os.path.join(workdir, "sharded")
    ckpt = ShardedCheckpointer(directory)
    t0 = time.monotonic()
    ckpt.save(0, state, {"bench": True})
    ckpt.wait()                      # durability, not dispatch
    save_s = time.monotonic() - t0
    ckpt.close()

    ckpt = ShardedCheckpointer(directory)
    t0 = time.monotonic()
    restored, _meta = ckpt.restore(abstract_like(state, shardings))
    jax.block_until_ready(restored)
    restore_s = time.monotonic() - t0
    ckpt.close()
    return {"save_seconds": save_s, "restore_seconds": restore_s}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--configs", default="gpt2-small,gpt2-medium",
                    help="comma-separated model configs (models/gpt.py)")
    ap.add_argument("--strategy", default="zero1",
                    help="sharding strategy for the measured state")
    args = ap.parse_args(argv)

    rows = []
    for config in [c for c in args.configs.split(",") if c]:
        state, shardings = _build_state(config, args.strategy)
        nbytes = _state_bytes(state)
        with tempfile.TemporaryDirectory(prefix="rlt_ckpt_bench_") as d:
            for path_name, bench in (("msgpack", _bench_msgpack),
                                     ("orbax", _bench_orbax)):
                r = bench(state, shardings, d)
                rows.append({
                    "config": config,
                    "path": path_name,
                    "state_bytes": nbytes,
                    "save_seconds": round(r["save_seconds"], 3),
                    "save_bytes_per_s": int(
                        nbytes / max(r["save_seconds"], 1e-9)),
                    "restore_seconds": round(r["restore_seconds"], 3),
                    "restore_bytes_per_s": int(
                        nbytes / max(r["restore_seconds"], 1e-9)),
                })
        del state
    print(json.dumps({"metric": "checkpoint_io", "unit": "seconds",
                      "rows": rows}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
