"""BASELINE config #1: the MNIST classifier, steps/sec.

The reference's flagship example workload (examples/ray_ddp_example.py);
tiny by design — this measures per-step framework overhead more than
compute.

    python -m benchmarks.bench_mnist
"""

import jax

from benchmarks.harness import run_steps_per_sec

# first v5e measurement, B=128 MLP: per-step host dispatch through
# the device tunnel dominates at this size (compute is microseconds)
BASELINES = {"tpu": 63.9}


def main():
    from ray_lightning_tpu.models import LightningMNISTClassifier

    platform = jax.devices()[0].platform
    batch = 128
    module = LightningMNISTClassifier(config={"batch_size": batch},
                                      train_size=batch * 40)
    run_steps_per_sec(module, f"mnist_b{batch}_steps_per_sec_{platform}",
                      timed=100, baseline=BASELINES.get(platform))


if __name__ == "__main__":
    main()
