"""BASELINE config #1: the MNIST classifier, steps/sec.

The reference's flagship example workload (examples/ray_ddp_example.py);
tiny by design — this measures per-step framework overhead more than
compute.

    python -m benchmarks.bench_mnist
"""

import jax

from benchmarks.harness import run_steps_per_sec

# first v5e measurement, B=128 MLP: per-step host dispatch through
# the device tunnel dominates at this size (compute is microseconds)
BASELINES = {"tpu": 63.9}


def main():
    from ray_lightning_tpu.models import LightningMNISTClassifier

    platform = jax.devices()[0].platform
    batch = 128
    module = LightningMNISTClassifier(config={"batch_size": batch},
                                      train_size=batch * 40)
    run_steps_per_sec(module, f"mnist_b{batch}_steps_per_sec_{platform}",
                      timed=100, baseline=BASELINES.get(platform))

    # dispatch-bound workload fix: fold 32 steps into one compiled
    # program (Trainer(steps_per_execution=32)) — one host dispatch per
    # 32 optimizer steps.  train_size is a multiple of 32 batches so
    # every chunk is full.
    module = LightningMNISTClassifier(config={"batch_size": batch},
                                      train_size=batch * 64)
    run_steps_per_sec(
        module, f"mnist_b{batch}_k32_steps_per_sec_{platform}",
        timed=960, baseline=BASELINES.get(platform),
        trainer_kwargs={"steps_per_execution": 32})

    # transfer-bound workload fix (the measured bottleneck: ~28 MB/s
    # tunnel vs sub-ms compute): device-resident train set — batches are
    # gathered on-device by index, only int32 indices cross the link.
    # Measured v5e sweep: k=32 → 206/s, k=64 → 437/s, k=128 → 449/s.
    module = LightningMNISTClassifier(config={"batch_size": batch},
                                      train_size=batch * 128)
    run_steps_per_sec(
        module, f"mnist_b{batch}_cached_steps_per_sec_{platform}",
        timed=2560, baseline=BASELINES.get(platform),
        trainer_kwargs={"steps_per_execution": 64,
                        "cache_train_dataset": True})


if __name__ == "__main__":
    main()
