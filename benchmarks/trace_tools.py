"""Device-trace capture + bucketed breakdown for TPU benchmarking.

Wraps ``jax.profiler.trace`` and derives per-op/per-module figures from
the emitted Chrome-trace JSON to answer two questions the wall clock
cannot (the tunnel between host and chip adds tens of ms of jitter per
dispatch):

- where does *device* time go per step (op-category buckets)?
- what is the pure device time per step (compute + collectives), for
  framework-vs-native ratios that hold even when the host link drifts?

The trace PARSING itself — file locator, track/thread-layout handling,
the category-bucketing table — lives in
``ray_lightning_tpu/telemetry/anatomy.py`` (ONE parser for the whole
repo; the anatomy plane, the profile controllers and these bench
helpers all read traces through it).  This module keeps the
bench-facing derivations: roofline, breakdown, top-ops, dominant
module.  Used by ``bench_native_baseline.py`` (device-time ratio legs),
``profile_headline.py`` and the ad-hoc perf work in
benchmarks/README.md.
"""

from __future__ import annotations

import collections
import tempfile
from typing import Callable

from ray_lightning_tpu.telemetry.anatomy import (  # noqa: F401  (re-export)
    bucket_of,
    device_track_events,
    locate_trace_json,
)

#: legacy aliases (pre-anatomy private names, kept for ad-hoc scripts)
_latest_trace_json = locate_trace_json
_device_events = device_track_events


def capture_trace(run: Callable[[], None], out_dir: str | None = None) -> str:
    """Run ``run()`` under the JAX profiler; return the trace directory."""
    import jax

    out_dir = out_dir or tempfile.mkdtemp(prefix="rlt_trace_")
    with jax.profiler.trace(out_dir):
        run()
    return out_dir


def roofline(trace_dir: str, steps: int, *,
             peak_tflops: float = 197.0, peak_gbps: float = 819.0,
             k: int = 30) -> list[dict]:
    """Per-op roofline table from the trace's own HLO cost metadata.

    Each "XLA Ops" event carries ``model_flops`` and ``bytes_accessed``;
    dividing by measured device time gives achieved TFLOP/s and GB/s,
    and max(flops/peak_flops, bytes/peak_bw) gives the roofline-bound
    fraction — ops far below 1.0 on *both* axes are overhead and
    therefore levers.  Defaults are TPU v5e peaks (bf16 MXU ~197
    TFLOP/s, HBM ~819 GB/s).

    Returns rows sorted by total time: {op, category, source, ms_per_step,
    count, tflops, gbps, bound_frac, bound_by}.
    """
    agg: dict[str, dict] = {}
    for e in device_track_events(locate_trace_json(trace_dir)):
        args = e.get("args", {})
        # deduplicated_name: XLA emitted one program for several
        # identical ops (e.g. the 12 per-layer attention kernels);
        # aggregate under the canonical name + category
        key = args.get("deduplicated_name") or e["name"]
        row = agg.setdefault(key, {
            "op": key,
            "category": args.get("hlo_category", "?"),
            "source": (args.get("tf_op") or args.get("source") or "")[:80],
            "ms": 0.0, "count": 0, "flops": 0.0, "bytes": 0.0})
        row["ms"] += e["dur"] / 1000.0
        row["count"] += 1
        row["flops"] += float(args.get("model_flops", 0) or 0)
        row["bytes"] += float(args.get("bytes_accessed", 0) or 0)
    rows = sorted(agg.values(), key=lambda r: -r["ms"])[:k]
    for r in rows:
        secs = r["ms"] / 1000.0
        r["ms_per_step"] = round(r["ms"] / steps, 3)
        r["tflops"] = round(r["flops"] / secs / 1e12, 1) if secs else 0.0
        r["gbps"] = round(r["bytes"] / secs / 1e9, 1) if secs else 0.0
        cf = r["tflops"] / peak_tflops
        bf = r["gbps"] / peak_gbps
        r["bound_frac"] = round(max(cf, bf), 2)
        r["bound_by"] = "compute" if cf >= bf else "bandwidth"
        del r["ms"], r["flops"], r["bytes"]
    return rows


def device_breakdown(trace_dir: str) -> dict[str, float]:
    """Total device time (ms) per bucket across the whole trace."""
    out: dict[str, float] = collections.defaultdict(float)
    for e in device_track_events(locate_trace_json(trace_dir)):
        out[bucket_of(e["name"])] += e["dur"] / 1000.0
    return dict(sorted(out.items(), key=lambda kv: -kv[1]))


def top_ops(trace_dir: str, k: int = 25) -> list[tuple[str, float, int]]:
    """(name, total ms, count) for the k most expensive device ops."""
    tot: dict[str, float] = collections.defaultdict(float)
    cnt: dict[str, int] = collections.defaultdict(int)
    for e in device_track_events(locate_trace_json(trace_dir)):
        tot[e["name"]] += e["dur"] / 1000.0
        cnt[e["name"]] += 1
    ranked = sorted(tot.items(), key=lambda kv: -kv[1])[:k]
    return [(name, ms, cnt[name]) for name, ms in ranked]


def dominant_module(trace_dir: str) -> tuple[str, float, int]:
    """(name, median_ms, count) of the XLA module with the largest total
    device time in the trace.

    In a traced training window that module is the train step; taking
    the MEDIAN event duration makes the figure robust to a first
    execution inflated by compilation and to stragglers, and using
    device-track module events makes it immune to host/tunnel jitter —
    the property the framework-vs-native ratios need on transfer-bound
    workloads (a wall clock cannot resolve the 0.9 bar when the tunnel
    drifts ±2-4×, benchmarks/README.md).
    """
    import statistics

    evs = device_track_events(locate_trace_json(trace_dir),
                              track="XLA Modules")
    agg: dict[str, list] = collections.defaultdict(list)
    for e in evs:
        agg[e["name"]].append(e["dur"] / 1000.0)
    if not agg:
        raise ValueError(f"no XLA module events under {trace_dir}")
    name, durs = max(agg.items(), key=lambda kv: sum(kv[1]))
    return name, float(statistics.median(durs)), len(durs)


def dominant_module_ms_or_none(trace_dir: "str | None",
                               *, consume: bool = True) -> "float | None":
    """Median device ms of the dominant module, or None when the trace
    is missing/unparseable (profiler-less backends) — the shared
    capture-and-fallback recipe for benches that must still emit wall
    numbers without a profiler.  ``consume`` removes the trace dir."""
    import shutil
    import sys

    if not trace_dir:
        return None
    try:
        _, med, _ = dominant_module(trace_dir)
        return med
    except Exception as e:
        sys.stderr.write(f"device-time capture skipped: {e}\n")
        return None
    finally:
        if consume:
            shutil.rmtree(trace_dir, ignore_errors=True)


def total_device_ms(trace_dir: str, module_filter: str = "") -> float:
    """Total device time (ms) spent executing XLA modules in the trace.

    Uses the "XLA Modules" track (one event per module execution, no
    nesting) so the result is pure device busy time — immune to host /
    tunnel jitter.  ``module_filter``: only count modules whose name
    contains it (e.g. "train_step" to exclude init/eval programs).
    """
    evs = device_track_events(locate_trace_json(trace_dir),
                              track="XLA Modules")
    return sum(e["dur"] / 1000.0 for e in evs
               if module_filter in e.get("name", ""))
