"""BASELINE config #3: Tune PBT over MNIST lr, 4 trials — measured.

The full config asks for 4 × v4-8 (one pod slice per trial); on this
box the same sweep runs TIME-SLICED on one chip: ``resources_per_trial``
declares one TPU per trial, the builtin runner's device leaser
partitions the single visible chip into one lease, and the four trial
threads serialize on it (tune/runner.py _DeviceLeaser — the same
mechanism that gives concurrent trials disjoint chip halves on larger
hosts).  PBT still exploits: the population dict accumulates across the
serialized trials, so later trials clone earlier winners' checkpoints
(tune/schedulers.py PopulationBasedTraining works on recorded results,
not on wall-clock coexistence).

What the one JSON line measures, round over round:

- ``value``: sweep wall seconds for 4 trials × 6 epochs of the MNIST
  classifier with per-epoch checkpoint+report — the Tune layer's
  end-to-end overhead (scheduling, lease churn, checkpoint
  serialization, exploit restarts) on top of training compute.
- ``best_accuracy``: the sweep must still LEARN (PBT pulls the
  population toward the good lr).
- ``exploits``: exploit restarts that actually happened (0 would mean
  the PBT path went untested).
- ``compute_floor_s`` + ``tune_overhead_ratio``: the sweep wall
  DECOMPOSED.  A standalone fit of one trial's exact workload measures
  the steady per-step seconds; the floor is
  ``trials x epochs x batches x measured_step`` — pure training
  compute, no Tune.  ``wall / floor`` is then the Tune layer's overhead
  as a TRACKED RATIO, round over round, instead of an absolute wall
  number that moves with the box (benchmarks/README.md row).

    python -m benchmarks.bench_tune_pbt

Reference surface: ray_lightning/tests/test_tune.py:42-57 (per-trial
isolation) + the reference's PBT usage via ray.tune schedulers
(SURVEY.md §3.3); BASELINE.md config #3.
"""

from __future__ import annotations

import json
import os
import time

import jax


def main() -> None:
    from ray_lightning_tpu import Trainer, tune
    from ray_lightning_tpu.models import LightningMNISTClassifier

    platform = jax.devices()[0].platform
    # CPU smoke (CI): shrink the workload, keep every moving part
    epochs = 6 if platform != "cpu" else 2
    train_batches = 30 if platform != "cpu" else 4
    batch_size = 128 if platform != "cpu" else 16

    exploits: list[str] = []
    trials = 4

    def measured_step_s() -> float:
        """Steady per-step seconds of ONE trial's exact workload,
        measured by a standalone fit (no Tune): median over the
        post-compile steps — the compute-only number the floor is
        built from."""
        from ray_lightning_tpu.core.callbacks import Callback

        class StepTimer(Callback):
            needs_batch = False

            def __init__(self):
                self.marks = []

            def on_train_batch_end(self, trainer, module, outputs,
                                   batch, idx):
                self.marks.append(time.monotonic())

        timer = StepTimer()
        module = LightningMNISTClassifier(
            config={"batch_size": batch_size, "lr": 0.05},
            train_size=batch_size * train_batches)
        Trainer(max_epochs=2, limit_train_batches=train_batches,
                limit_val_batches=0, num_sanity_val_steps=0,
                enable_checkpointing=False, logger=False, seed=0,
                callbacks=[timer]).fit(module)
        import numpy as np
        deltas = np.diff(np.asarray(timer.marks))
        # skip the compile-bearing first step; median is tunnel-robust
        return float(np.median(deltas[1:])) if len(deltas) > 1 else 0.0

    step_s = measured_step_s()

    def train_fn(config, checkpoint_dir=None):
        module = LightningMNISTClassifier(
            config={"batch_size": batch_size, "lr": config["lr"]},
            train_size=batch_size * train_batches)
        trainer = Trainer(
            max_epochs=epochs,
            limit_train_batches=train_batches,
            limit_val_batches=2,
            num_sanity_val_steps=0,
            enable_checkpointing=False,
            logger=False,
            seed=0,
            callbacks=[tune.TuneReportCheckpointCallback(
                on="validation_end")],
            default_root_dir=tune.get_trial_dir(),
        )
        ckpt_path = None
        if checkpoint_dir:
            exploits.append(checkpoint_dir)
            ckpt_path = os.path.join(checkpoint_dir, "checkpoint")
        trainer.fit(module, ckpt_path=ckpt_path)

    t0 = time.monotonic()
    analysis = tune.run(
        train_fn,
        # deliberately includes two lrs too small to compete: PBT's job
        # in this sweep is to exploit them onto the winners' weights
        config={"lr": tune.grid_search([0.05, 0.01, 1e-4, 1e-5])},
        resources_per_trial=tune.get_tune_resources(
            num_workers=1, use_tpu=True, tpus_per_worker=1),
        scheduler=tune.PopulationBasedTraining(
            metric="ptl/val_accuracy", mode="max",
            perturbation_interval=2,
            hyperparam_mutations={"lr": [0.05, 0.01]}),
        local_dir=os.environ.get("RLT_TUNE_DIR", "rlt_tune"),
        name=f"pbt_bench_{int(time.time())}",
    )
    wall = time.monotonic() - t0

    best = analysis.get_best_trial("ptl/val_accuracy", "max")
    # compute-only floor: what the sweep's training steps alone cost —
    # everything above it is the Tune layer (scheduling, lease churn,
    # checkpoint serialization, exploit restarts, validation)
    floor = trials * epochs * train_batches * step_s
    line = {
        "metric": f"tune_pbt_mnist_4trials_wall_s_{platform}",
        "value": round(wall, 2),
        "unit": "s",
        "best_accuracy": round(
            float(best.last_result["ptl/val_accuracy"]), 3),
        "exploits": len(exploits),
        "trials_terminated": sum(
            t.status == "TERMINATED" for t in analysis.trials),
        "measured_step_s": round(step_s, 5),
        "compute_floor_s": round(floor, 2),
        "tune_overhead_ratio": round(wall / floor, 2) if floor else None,
    }
    print(json.dumps(line), flush=True)
    assert line["trials_terminated"] == trials, analysis.trials


if __name__ == "__main__":
    main()
