"""Benchmark: what does ``Trainer(strategy="auto")`` cost, and what
does it pick?

Prints exactly ONE JSON line (the ``plan`` row of the benchmark
suite):

  {"metric": "plan", "candidates": N, "pruned": N, "rejected": N,
   "compiled": N, "plan_seconds": S, "winner": "...",
   "auto_time_to_first_step_seconds": A,
   "manual_time_to_first_step_seconds": M,
   "compile_cache": "hit|miss|off", "plan": "auto"}

Two fits of the same GPT config back to back: ``strategy="auto"``
(planning + top-k AOT verify + training) vs the best hand-picked
configuration for this topology (the manual baseline the planner is
supposed to match).  ``auto − manual`` time-to-first-step is the
planner's real overhead — with the persistent compile cache active the
winner's verify compile IS the fit's first-dispatch cache hit, so the
gap shrinks to the scoring cost.  Both fits share one cache dir, so
run order matters and is fixed: auto first (cold), manual second
(warm from the planner's own artifacts — the reuse story, measured).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile


def _fit(cfg, batch: int, steps: int, root: str, cache: str, **kw):
    from ray_lightning_tpu import Trainer
    from ray_lightning_tpu.models.gpt import GPTLightningModule

    module = GPTLightningModule(cfg, dataset_size=batch * steps,
                                batch_size=batch)
    trainer = Trainer(max_steps=steps, max_epochs=10**6, seed=0,
                      default_root_dir=root, enable_checkpointing=False,
                      num_sanity_val_steps=0, limit_val_batches=0,
                      log_every_n_steps=10**9, compile_cache=cache, **kw)
    trainer.fit(module)
    return trainer


def main() -> None:
    import jax

    from ray_lightning_tpu.compile import cache as compile_cache

    platform = jax.devices()[0].platform
    cfg = "tiny" if platform == "cpu" else "gpt2-small"
    batch, steps = 8, 4

    with tempfile.TemporaryDirectory() as td:
        cache = os.path.join(td, "compile_cache")
        auto = _fit(cfg, batch, steps, os.path.join(td, "auto"), cache,
                    strategy="auto")
        report = auto._plan_report or {}
        # manual baseline: the same plan hand-picked (DDP over every
        # chip is the measured-best manual config for these sizes)
        manual = _fit(cfg, batch, steps, os.path.join(td, "manual"),
                      cache, strategy="ddp")
        result = {
            "metric": "plan",
            "candidates": report.get("enumerated", 0),
            "pruned": report.get("pruned", 0),
            "rejected": report.get("rejected", 0),
            "compiled": report.get("compiled", 0),
            "plan_seconds": report.get("plan_seconds", 0.0),
            "winner": report.get("winner"),
            "auto_time_to_first_step_seconds": round(
                auto.time_to_first_step or 0.0, 3),
            "manual_time_to_first_step_seconds": round(
                manual.time_to_first_step or 0.0, 3),
            "compile_cache": compile_cache.status_word(),
            "plan": "auto",
        }
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
