"""Perf-regression ledger: turn bench JSON trajectories into a gate.

The repo accumulates one measured JSON blob per round (the driver's
``BENCH_r*.json``, any ``bench.py``-family output) but until now a
regression was something a human noticed diffing them.  This module
compares two rounds record-by-record and exits nonzero when a tracked
figure regresses past its band — the pre-merge perf gate
(``python bench.py --compare BENCH_r05.json`` or
``python -m benchmarks.ledger prev.json curr.json``).

Accepted inputs, auto-detected per file:

- a driver ``BENCH_r*.json`` blob (``{"parsed": {...}, "tail": "..."}``
  — every JSON object line in ``tail`` is a record, ``parsed`` too);
- a file of JSON lines (one record per line, non-JSON lines ignored);
- one JSON object / array of objects.

Records join on their ``metric`` name.  Tracked figures and their
regression direction:

==============================  ======  ==============================
figure                          worse    band
==============================  ======  ==============================
``value`` (steps/sec legs)      lower   ``step_band`` (default 5%)
``device_ms``                   higher  ``step_band``
``exposed_comm_seconds`` /
``measured_exposed_comm_seconds``  higher  ``exposed_band`` (default
                                        10%) + ``min_exposed_s``
                                        absolute floor, so sub-ms CPU
                                        noise never trips the gate
``serve.tokens_per_sec`` /
``fleet.tokens_per_sec``        lower   ``serve_band`` (default 15% —
                                        CPU-proxy serving wall clock
                                        is noisier than steps/sec)
``serve.ttft_p99_ms`` /
``fleet.ttft_p99_ms``           higher  ``serve_band`` +
                                        ``min_ttft_ms`` floor
``serve.tpot_p50_ms`` /
``fleet.tpot_p50_ms``           higher  ``serve_band`` +
                                        ``min_tpot_ms`` floor
``goodput.fraction``            lower   ``goodput_band`` (default 10%)
                                        + ``min_goodput_delta``
                                        absolute floor
``goodput.mfu``                 lower   ``goodput_band``
``measured_bubble_fraction_*``  higher  ``goodput_band`` + the same
                                        absolute floor (bench_pipeline
                                        1f1b/gpipe audit)
``incident_ab.overhead_pct``    higher  ``incident_band`` (default 2%,
                                        ABSOLUTE: the current round's
                                        incident-plane on-vs-off
                                        steps/sec delta, gated even
                                        without a previous round —
                                        bench_incident.py A/B leg)
``serve.spec.acceptance_rate``
/ ``...tokens_per_target_
forward``                       lower   ``serve_band`` + 5-point
                                        acceptance floor (draft-quality
                                        collapse is a regression even
                                        while tokens/s holds —
                                        bench_serve.py spec leg)
``fleet.disagg.ttft_p99_ms``    higher  ``serve_band`` + ``min_ttft_ms``
                                        (the 4x-burst prefill/decode
                                        split leg, bench_fleet.py)
``fleet.disagg.fp8_
compression_ratio``             lower   ``serve_band`` (KV wire bytes
                                        vs the raw fp32 control)
``fleet.federated_reuse_
ratio``                         lower   ``serve_band`` + 2-point
                                        absolute floor (prefix pages
                                        PULLED from other replicas on
                                        the fed-on leg — a collapse
                                        means the directory stopped
                                        federating even while
                                        tokens/s holds)
==============================  ======  ==============================

Improvements are reported too (the ledger is a trajectory, not just an
alarm); metrics present on only one side are listed as uncompared so a
silently dropped leg can't read as "no regression".  A figure present
on only ONE side of a joined metric (a field added or dropped between
rounds — e.g. comparing a goodput-aware round against a pre-goodput
``BENCH_r*.json``) is skipped with a note in ``skipped``, never a
KeyError and never a regression: new instrumentation bootstraps
cleanly against old baselines.
"""

from __future__ import annotations

import json
from typing import Any, Iterable

#: default relative bands (fraction of the previous value)
STEP_BAND = 0.05
EXPOSED_BAND = 0.10
#: serve-side figures (bench_serve.py `serve`, bench_fleet.py `fleet`):
#: wall-clock tokens/s + latency tails on the CPU proxy swing more than
#: compiled-step device time, so the band is wider
SERVE_BAND = 0.15
#: absolute floor under which exposed-comm drift is noise, not signal
MIN_EXPOSED_S = 1e-4
#: absolute TTFT floor: p99 jitter below this is scheduler noise
MIN_TTFT_MS = 2.0
#: absolute TPOT floor: per-token p50 drift below half a millisecond is
#: dispatch noise on the CPU proxy, not a decode-kernel regression
MIN_TPOT_MS = 0.5
#: goodput-fraction / MFU band (telemetry/goodput.py): whole-run wall
#: attribution swings more than compiled-step time (compile/init share
#: varies with cache state), so the band is wider than step_band
GOODPUT_BAND = 0.10
#: absolute goodput-fraction / bubble-fraction floor: drift smaller
#: than 2 points of fraction is wall-clock noise, not a regression
MIN_GOODPUT_DELTA = 0.02
#: absolute spec-decode acceptance floor: under 5 points of
#: accepted/drafted drift is workload mix, not draft-model regression
MIN_ACCEPT_DELTA = 0.05
#: detector-overhead ceiling (telemetry/incident.py): the incident
#: plane runs on every fit, so its measured on-vs-off step-wall cost
#: (benchmarks/bench_incident.py) is gated ABSOLUTELY at 2%
INCIDENT_BAND = 0.02


def _iter_records(obj: Any) -> Iterable[dict]:
    """Yield every bench record (dict with a ``metric`` key) inside an
    arbitrary loaded JSON value / raw text blob."""
    if isinstance(obj, dict):
        if "metric" in obj:
            yield obj
        for key in ("parsed",):
            if isinstance(obj.get(key), dict):
                yield from _iter_records(obj[key])
        tail = obj.get("tail")
        if isinstance(tail, str):
            yield from _iter_text(tail)
    elif isinstance(obj, list):
        for item in obj:
            yield from _iter_records(item)


def _iter_text(text: str) -> Iterable[dict]:
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        yield from _iter_records(obj)


def load_records(source: Any) -> dict[str, dict]:
    """``metric name → record`` from a path, loaded JSON value, or a
    list of record dicts (later duplicates win — the newest emission
    of a re-run leg is the round's figure)."""
    if isinstance(source, str):
        with open(source) as f:
            text = f.read()
        try:
            records = list(_iter_records(json.loads(text)))
        except ValueError:
            records = list(_iter_text(text))
    else:
        records = list(_iter_records(source))
    return {r["metric"]: r for r in records}


def _exposed_of(rec: dict) -> "float | None":
    """The record's exposed-comm figure, measured preferred."""
    v = rec.get("measured_exposed_comm_seconds")
    if v is None:
        v = rec.get("exposed_comm_seconds")
    return None if v is None else float(v)


def compare(prev: Any, curr: Any, *, step_band: float = STEP_BAND,
            exposed_band: float = EXPOSED_BAND,
            serve_band: float = SERVE_BAND,
            goodput_band: float = GOODPUT_BAND,
            incident_band: float = INCIDENT_BAND,
            min_exposed_s: float = MIN_EXPOSED_S,
            min_ttft_ms: float = MIN_TTFT_MS,
            min_tpot_ms: float = MIN_TPOT_MS) -> dict:
    """Compare two rounds; the returned report's ``ok`` is the gate.

    ``prev``/``curr``: anything :func:`load_records` accepts.
    """
    prev_by = load_records(prev)
    curr_by = load_records(curr)
    regressions: list[dict] = []
    improvements: list[dict] = []
    skipped: list[dict] = []
    compared = 0

    def check(metric, figure, old, new, worse_is, band, floor=0.0):
        nonlocal compared
        if (old is None) != (new is None):
            # one-sided figure: a field this round of instrumentation
            # added (old side predates it) or dropped.  Note it —
            # silence would read as "compared, fine" — but never gate:
            # new figures must bootstrap cleanly against old rounds
            skipped.append({
                "metric": metric, "figure": figure,
                "note": ("not in previous round (bootstrapping)"
                         if old is None else
                         "missing from current round")})
            return
        if old is None or new is None or old <= 0:
            return
        compared += 1
        delta = (new - old) / old
        worse = delta if worse_is == "higher" else -delta
        row = {"metric": metric, "figure": figure,
               "prev": old, "curr": new, "delta_pct": round(delta * 100, 2)}
        if worse > band and abs(new - old) > floor:
            regressions.append(row)
        elif worse < -band:
            improvements.append(row)

    for metric in sorted(set(prev_by) & set(curr_by)):
        p, c = prev_by[metric], curr_by[metric]
        if p.get("unit") == "steps/sec" and c.get("unit") == "steps/sec":
            check(metric, "steps_per_sec", p.get("value"), c.get("value"),
                  "lower", step_band)
        if p.get("device_ms") is not None and c.get("device_ms") is not None:
            check(metric, "device_ms", p["device_ms"], c["device_ms"],
                  "higher", step_band)
        pe, ce = _exposed_of(p), _exposed_of(c)
        if pe is not None and ce is not None:
            check(metric, "exposed_comm_seconds", pe, ce, "higher",
                  exposed_band, floor=min_exposed_s)
        # serve-side fields (bench_serve.py `serve` dict, bench_fleet.py
        # `fleet` dict): throughput lower-is-worse, TTFT tail
        # higher-is-worse — the serving legs join the same gate as the
        # fit-side steps/sec instead of regressing silently
        for key in ("serve", "fleet"):
            ps, cs = p.get(key), c.get(key)
            if not (isinstance(ps, dict) and isinstance(cs, dict)):
                continue
            check(metric, f"{key}.tokens_per_sec",
                  ps.get("tokens_per_sec"), cs.get("tokens_per_sec"),
                  "lower", serve_band)
            check(metric, f"{key}.ttft_p99_ms", ps.get("ttft_p99_ms"),
                  cs.get("ttft_p99_ms"), "higher", serve_band,
                  floor=min_ttft_ms)
            # per-output-token latency: the decode-kernel tier's
            # headline — a slower hot path shows here before it moves
            # tokens/s on a queue-bound replay
            check(metric, f"{key}.tpot_p50_ms", ps.get("tpot_p50_ms"),
                  cs.get("tpot_p50_ms"), "higher", serve_band,
                  floor=min_tpot_ms)
            # speculative decode (bench_serve.py spec leg): an
            # acceptance-rate collapse or a tokens-per-target-forward
            # slide is a draft-quality regression even while wall-clock
            # tokens/s holds on the CPU proxy
            psp = ps.get("spec") if isinstance(ps.get("spec"), dict) \
                else {}
            csp = cs.get("spec") if isinstance(cs.get("spec"), dict) \
                else {}
            if psp or csp:
                check(metric, f"{key}.spec.acceptance_rate",
                      psp.get("acceptance_rate"),
                      csp.get("acceptance_rate"), "lower", serve_band,
                      floor=MIN_ACCEPT_DELTA)
                check(metric, f"{key}.spec.tokens_per_target_forward",
                      psp.get("tokens_per_target_forward"),
                      csp.get("tokens_per_target_forward"), "lower",
                      serve_band)
            # disaggregated decode (bench_fleet.py disagg legs): the
            # split-pool TTFT tail and the fp8 wire-compression ratio
            pd = ps.get("disagg") if isinstance(ps.get("disagg"), dict) \
                else {}
            cd = cs.get("disagg") if isinstance(cs.get("disagg"), dict) \
                else {}
            if pd or cd:
                check(metric, f"{key}.disagg.ttft_p99_ms",
                      pd.get("ttft_p99_ms"), cd.get("ttft_p99_ms"),
                      "higher", serve_band, floor=min_ttft_ms)
                check(metric, f"{key}.disagg.fp8_compression_ratio",
                      pd.get("fp8_compression_ratio"),
                      cd.get("fp8_compression_ratio"), "lower",
                      serve_band)
            # prefix federation (bench_fleet.py fed-on leg): fraction
            # of requested prefill tokens satisfied by pages PULLED
            # from another replica over the kvship plane — lower means
            # the directory stopped federating, a regression even
            # while tokens/s holds on the CPU proxy
            check(metric, f"{key}.federated_reuse_ratio",
                  ps.get("federated_reuse_ratio"),
                  cs.get("federated_reuse_ratio"), "lower", serve_band,
                  floor=MIN_GOODPUT_DELTA)
        # goodput plane (telemetry/goodput.py `goodput` dict): the
        # useful-fraction of run wall and measured MFU are both
        # lower-is-worse; one-sided presence (a pre-goodput baseline)
        # lands in `skipped` via check()'s bootstrap path
        pg = p.get("goodput") if isinstance(p.get("goodput"), dict) \
            else {}
        cg = c.get("goodput") if isinstance(c.get("goodput"), dict) \
            else {}
        if pg or cg:
            check(metric, "goodput.fraction", pg.get("fraction"),
                  cg.get("fraction"), "lower", goodput_band,
                  floor=MIN_GOODPUT_DELTA)
            check(metric, "goodput.mfu", pg.get("mfu"), cg.get("mfu"),
                  "lower", goodput_band)
        # measured pipeline-bubble fractions (bench_pipeline.py anatomy
        # audit): schedule-idle share of device time, higher-is-worse
        for fig in ("measured_bubble_fraction_1f1b",
                    "measured_bubble_fraction_gpipe"):
            if p.get(fig) is not None or c.get(fig) is not None:
                check(metric, fig, p.get(fig), c.get(fig), "higher",
                      goodput_band, floor=MIN_GOODPUT_DELTA)
    # incident-plane detector overhead (bench_incident.py A/B leg):
    # an ABSOLUTE gate on the CURRENT round — the measured incident
    # on-vs-off steps/sec delta must stay within incident_band even
    # when the previous round has no such leg (overhead that merely
    # holds steady at 5% is still a broken contract)
    for metric in sorted(curr_by):
        ia = curr_by[metric].get("incident_ab")
        if not isinstance(ia, dict) or ia.get("overhead_pct") is None:
            continue
        compared += 1
        pct = float(ia["overhead_pct"])
        row = {"metric": metric, "figure": "incident_ab.overhead_pct",
               "prev": ia.get("steps_per_sec_off"),
               "curr": ia.get("steps_per_sec_on"),
               "delta_pct": round(pct, 2),
               "note": "absolute gate: incident plane on vs off"}
        if pct > incident_band * 100:
            regressions.append(row)
    report = {
        "metric": "perf_ledger",
        "compared": compared,
        "regressions": regressions,
        "improvements": improvements,
        "skipped": skipped,
        "only_prev": sorted(set(prev_by) - set(curr_by)),
        "only_curr": sorted(set(curr_by) - set(prev_by)),
        "bands": {"step": step_band, "exposed": exposed_band,
                  "serve": serve_band, "goodput": goodput_band,
                  "incident": incident_band,
                  "min_exposed_s": min_exposed_s,
                  "min_ttft_ms": min_ttft_ms,
                  "min_goodput_delta": MIN_GOODPUT_DELTA},
        "ok": not regressions,
    }
    return report


def main(argv: list) -> int:
    import argparse
    parser = argparse.ArgumentParser(
        prog="python -m benchmarks.ledger",
        description="Compare two bench JSON rounds; exit 1 on regression.")
    parser.add_argument("prev", help="previous round (BENCH_r*.json or "
                        "a file of bench JSON lines)")
    parser.add_argument("curr", help="current round, same formats")
    parser.add_argument("--step-band", type=float, default=STEP_BAND,
                        help="relative band for steps/sec + device_ms "
                        f"(default {STEP_BAND})")
    parser.add_argument("--exposed-band", type=float, default=EXPOSED_BAND,
                        help="relative band for exposed-comm seconds "
                        f"(default {EXPOSED_BAND})")
    parser.add_argument("--serve-band", type=float, default=SERVE_BAND,
                        help="relative band for serve/fleet tokens-per-"
                        f"sec and TTFT p99 (default {SERVE_BAND})")
    parser.add_argument("--goodput-band", type=float,
                        default=GOODPUT_BAND,
                        help="relative band for goodput fraction, MFU "
                        "and measured bubble fractions "
                        f"(default {GOODPUT_BAND})")
    parser.add_argument("--incident-band", type=float,
                        default=INCIDENT_BAND,
                        help="ABSOLUTE ceiling on the incident plane's "
                        "measured on-vs-off steps/sec overhead "
                        f"(default {INCIDENT_BAND})")
    args = parser.parse_args(argv)
    report = compare(args.prev, args.curr, step_band=args.step_band,
                     exposed_band=args.exposed_band,
                     serve_band=args.serve_band,
                     goodput_band=args.goodput_band,
                     incident_band=args.incident_band)
    print(json.dumps(report))
    return 0 if report["ok"] else 1


if __name__ == "__main__":   # pragma: no cover - exercised via bench.py
    import sys
    sys.exit(main(sys.argv[1:]))
