"""Fleet-plane benchmark: traffic-record-and-replay against 1 vs N
serve replicas — the "heavy traffic" leg made measurable.

The harness RECORDS a request trace (a multi-tenant session: arrival
offsets, tenant, prompt tokens with a shared system prompt inside each
tenant group, per-request token budget) to a JSON file, then REPLAYS it
at 1x/2x/4x time compression:

- **1x / 2x, 1 vs 2 replicas** — a plain :class:`Server` (the
  single-fleet reference; also the greedy-parity oracle) vs a
  :class:`FleetServer` with 2 replicas and paged-KV prefix reuse.  The
  acceptance bar: 2 replicas sustain strictly higher tokens/s than 1
  at the 2x multiplier.
- **4x, autoscaling 1→3 replicas** — the burst drives queue depth past
  the grow threshold (at least one grow event), and the idle tail
  after the burst drives occupancy to zero (at least one shrink, the
  drained replica's requests completing elsewhere).
- **4x, disaggregated 1 prefill + 1 decode vs 2 pooled** — role-split
  replicas (``FleetConfig(roles=...)``) with codec-compressed KV-page
  shipping (fp8 wire leg + raw fp32 control).  The acceptance bars:
  disaggregated TTFT p99 strictly below the 2-pooled-replica baseline
  at 4x, KV pages genuinely shipped on both codec legs, and fp8 wire
  bytes >= 3x under raw.
- **prefix reuse** — each tenant group shares a system prompt, so the
  fleet's ``prefill tokens computed vs requested`` ratio must come out
  nonzero.
- **2x, prefix federation A/B** — its own 8-group trace, 2 replicas
  with stickiness defeated (scrambled per-request tenants), the fleet
  prefix directory off vs on, plus a 1-replica locality control.  The
  acceptance bars: fed-on reuse ratio recovers at least the
  single-replica control and beats fed-off outright, KV pages
  genuinely federate (directory hits → wire ships → federated tokens
  reused), TTFT p50 holds, and every leg is greedy-parity-exact
  against its own reference replay.  A fourth leg runs the
  disaggregated pair with federation on: decode-pool donors serve
  fetch-backs so prefill-pool evictions do not force re-prefills.
- **parity** — every routed request's tokens are compared with the
  single-``Server`` reference; bf16 near-tie flips fall back to the
  teacher-forced tolerance bar (tests/test_serve.py's 2e-2).

Emits ONE ``fleet`` JSON line with tokens/s + TTFT p50/p99 per
multiplier, the replicas A/B, autoscale events (with actuation
seconds), the prefix-reuse ratio and the parity verdict.  Wired into
``bench.py`` as the ``RLT_FLEET_AB=1`` leg and into the perf ledger
(``bench.py --compare``) through the ``fleet.tokens_per_sec`` /
``fleet.ttft_p99_ms`` bands.

    python -m benchmarks.bench_fleet [--requests N] [--trace PATH]
"""

from __future__ import annotations

import argparse
import json
import os
import tempfile
import time

import numpy as np

from ray_lightning_tpu.ops.flash_decode import resolve_decode_impl

#: serving geometry for the CPU-proxy run (tiny GPT, block 32)
BUCKETS = (16, 32)
SLOTS = 4
PAGE_SIZE = 8
MAX_NEW = 14
#: absolute TTFT-p50 slack for the federation A/B gate: median drift
#: under this is scheduler noise on the CPU proxy, not a signal
MIN_TTFT_FLOOR_MS = 5.0


def record_trace(path: str, requests: int = 64, seed: int = 0,
                 duration_s: float = 0.8,
                 groups: "int | None" = None) -> list:
    """Record a multi-tenant request trace to ``path``.

    Three tenant groups; the tenants inside a group share a 2-page
    system prompt (the prefix-reuse mix), each request appending its
    own suffix.  Arrival offsets spread over ``duration_s`` with a
    front-loaded burst so compressed replays genuinely queue.

    ``groups=N`` records the federation-A/B shape instead: N shared-
    prompt groups with FEW requests each, arriving group-staggered
    over ``duration_s`` — a group's first request completes (and its
    pages become a retained, advertised donor) while load from the
    other groups keeps both replicas busy, so the group's later
    requests land on a replica that does NOT hold the prefix and the
    only alternatives are a federated pull or a duplicate prefill.
    """
    rng = np.random.default_rng(seed)
    if groups is not None:
        group_map = {
            f"g{i}": np.asarray(rng.integers(1, 100, size=2 * PAGE_SIZE))
            for i in range(int(groups))}
        tenants = list(group_map)
        trace = []
        for i in range(requests):
            # round-robin over the groups: consecutive same-group
            # arrivals are ``groups`` slots apart, so a group's donor
            # is retained before its next request, while the OTHER
            # groups' decode tails keep every replica busy enough
            # that affinity routing can't always land on the donor
            tenant = tenants[i % len(tenants)]
            shared = group_map[tenant]
            suffix = rng.integers(1, 100, size=int(rng.integers(3, 9)))
            trace.append({
                "at": round(i * duration_s / requests
                            + float(rng.uniform(0, 0.5))
                            * duration_s / requests, 4),
                "tenant": tenant,
                "prompt": [int(t) for t in
                           np.concatenate([shared, suffix])],
                "max_new": int(MAX_NEW),
            })
        trace.sort(key=lambda r: r["at"])
        with open(path, "w") as f:
            json.dump({"version": 1, "requests": trace}, f)
        return trace
    groups_map = {
        "alice": np.asarray(rng.integers(1, 100, size=2 * PAGE_SIZE)),
        "bob": np.asarray(rng.integers(1, 100, size=2 * PAGE_SIZE)),
        "carol": None,    # no shared prompt: the cold-path control
    }
    groups = groups_map
    tenants = list(groups)
    trace = []
    for i in range(requests):
        tenant = tenants[i % len(tenants)]
        shared = groups[tenant]
        suffix = rng.integers(1, 100, size=int(rng.integers(3, 9)))
        if shared is None:
            # cold tenant: no shared prefix (nothing for the prefix
            # cache), but still a page-sized prompt — every request
            # owns >= 1 whole page, so the cold path rides every
            # serving mode including disaggregation (sub-page prompts
            # are covered by tests/test_fleet.py)
            prompt = np.concatenate(
                [rng.integers(1, 100, size=PAGE_SIZE), suffix])
        else:
            prompt = np.concatenate([shared, suffix])
        trace.append({
            # front-loaded: 70% of arrivals in the first half
            "at": round(float(rng.beta(1.2, 2.0)) * duration_s, 4),
            "tenant": tenant,
            "prompt": [int(t) for t in prompt],
            "max_new": int(MAX_NEW),
        })
    trace.sort(key=lambda r: r["at"])
    with open(path, "w") as f:
        json.dump({"version": 1, "requests": trace}, f)
    return trace


def load_trace(path: str) -> list:
    with open(path) as f:
        return json.load(f)["requests"]


def replay(endpoint, trace: list, multiplier: float,
           timeout: float = 600.0, scramble: bool = False) -> dict:
    """Replay the trace at ``multiplier``x time compression against any
    ``submit``-surface endpoint (Server or FleetServer); returns the
    measured leg.  ``scramble`` suffixes every tenant with its request
    index, defeating tenant stickiness entirely — the worst case for
    per-replica prefix locality and the federation A/B's substrate
    (tokens are tenant-independent, so parity is unaffected)."""
    t0 = time.monotonic()
    handles = []
    for i, rec in enumerate(trace):
        due = t0 + rec["at"] / multiplier
        delay = due - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        tenant = f"{rec['tenant']}~{i}" if scramble else rec["tenant"]
        handles.append(endpoint.submit(
            np.asarray(rec["prompt"], np.int32), tenant=tenant,
            max_new_tokens=rec["max_new"]))
    outs = [h.result(timeout=timeout) for h in handles]
    wall = time.monotonic() - t0
    ttfts = np.asarray([h.ttft_s for h in handles
                        if h.ttft_s is not None]) * 1e3
    tokens = int(sum(len(o) for o in outs))
    return {
        "tokens_per_sec": round(tokens / wall, 2),
        "total_tokens": tokens,
        "wall_s": round(wall, 3),
        "requests": len(handles),
        "ttft_p50_ms": round(float(np.percentile(ttfts, 50)), 2)
        if len(ttfts) else None,
        "ttft_p99_ms": round(float(np.percentile(ttfts, 99)), 2)
        if len(ttfts) else None,
        "outputs": [o.tolist() for o in outs],
    }


def check_parity(module, engine_params_ref, trace: list, legs: dict
                 ) -> dict:
    """Every routed request greedy-parity-equal to the single-Server
    reference: exact token match, with the teacher-forced 2e-2
    tolerance bar (tests/test_serve.py) deciding bf16 near-tie flips."""
    model = module.configure_decode_model()
    params = engine_params_ref
    ref_outputs = legs["reference"]["outputs"]
    checked = flipped = bad = 0
    for leg_name, leg in legs.items():
        if leg_name == "reference":
            continue
        for rec, got, want in zip(trace, leg["outputs"], ref_outputs):
            checked += 1
            if got == want:
                continue
            flipped += 1
            seq = [int(t) for t in rec["prompt"]]
            for tok in got:
                logits = np.asarray(model.apply(
                    {"params": params},
                    np.asarray([seq], np.int32), True))[0, -1]
                best = int(np.argmax(logits))
                if tok != best and logits[tok] < logits[best] - 2e-2:
                    bad += 1
                    break
                seq.append(int(tok))
    return {"checked": checked, "exact": checked - flipped,
            "tolerance_flips": flipped - bad, "mismatched": bad,
            "ok": bad == 0}


def run_fleet_ab(metric: str, requests: int = 64,
                 trace_path: "str | None" = None) -> "list[dict]":
    """The RLT_FLEET_AB=1 bench leg; returns the emitted records."""
    from ray_lightning_tpu.models.gpt import GPTConfig, GPTLightningModule
    from ray_lightning_tpu.serve import Server
    from ray_lightning_tpu.serve.fleet import FleetServer

    cfg = GPTConfig(vocab_size=128, block_size=32, n_layer=2, n_head=2,
                    n_embd=32, remat=False)
    num_workers = int(os.environ.get("RLT_FLEET_WORKERS", "1"))
    platform = os.environ.get("RLT_FLEET_PLATFORM", "cpu")
    root = os.environ.get("RLT_FLEET_DIR") or tempfile.mkdtemp(
        prefix="rlt_bench_fleet_")
    cache = os.path.join(root, "compile_cache")

    if trace_path and os.path.exists(trace_path):
        trace = load_trace(trace_path)
    else:
        trace_path = trace_path or os.path.join(root, "trace.json")
        trace = record_trace(trace_path, requests=requests)
    # the federation A/B's own trace (record_trace ``groups=``): 8
    # small groups round-robin over a spread window — a group's donor
    # is retained before its next request arrives, while the other
    # groups' decode tails keep the donor replica busy enough that
    # affinity routing regularly loses and the pages must be PULLED
    fed_trace = record_trace(os.path.join(root, "fed_trace.json"),
                             requests=48, seed=3, duration_s=1.0,
                             groups=8)

    server_kw = dict(
        num_workers=num_workers, platform=platform, buckets=BUCKETS,
        max_batch_slots=SLOTS, max_new_tokens=MAX_NEW,
        compile_cache=cache, telemetry=False)

    legs: dict = {}
    # -- single Server: the reference fleet AND the parity oracle ------
    module = GPTLightningModule(cfg)
    server = Server(module, default_root_dir=os.path.join(root, "ref"),
                    paged=False, **server_kw).start()
    legs_fed: dict = {}
    try:
        legs["reference"] = replay(server, trace, 1.0)
        legs["single_2x"] = replay(server, trace, 2.0)
        # the federation trace's parity oracle rides the same Server
        legs_fed["reference"] = replay(server, fed_trace, 2.0)
    finally:
        server.shutdown()

    # -- 2 fixed replicas, paged prefix reuse --------------------------
    fleet2 = FleetServer(
        GPTLightningModule(cfg), replicas=2, autoscale=False,
        paged={"page_size": PAGE_SIZE},
        default_root_dir=os.path.join(root, "fleet2"),
        **server_kw).start()
    try:
        legs["fleet2_1x"] = replay(fleet2, trace, 1.0)
        legs["fleet2_2x"] = replay(fleet2, trace, 2.0)
        # the 4x burst is the disaggregation baseline: same 2 replicas,
        # both pooled, slots held hostage by 14-token decode tails
        legs["pooled2_4x"] = replay(fleet2, trace, 4.0)
        fleet2_pages = fleet2.pages_stats()
        fleet2_status = fleet2.status()["fleet"]
    finally:
        fleet2.shutdown()

    # -- prefix federation A/B: 2 replicas, NO stickiness, fed off/on --
    # Scrambled per-request tenants mean nothing keeps a group's
    # requests on the replica that already holds their prefix — the
    # worst case for per-replica reuse.  fed_off pays one group-prompt
    # prefill PER REPLICA; fed_on pulls the pages over the kvship
    # plane and prefills once per FLEET.  Replica goodput ledgers are
    # armed on these legs so prefill-seconds-saved is MEASURED wall,
    # not an estimate; the 1-replica leg is the sticky upper bound
    # (perfect locality) the federated ratio is held against.
    fed_kw = {**server_kw,
              "telemetry": {"enabled": True, "metrics": False,
                            "incident": False}}
    # the kvship codec's jnp kernels compile per rows-shape on first
    # use.  That cache is process-global XLA state, not fleet state —
    # the fed fleets stay COLD (donor/directory state is the A/B) but
    # a timed fetch must not pay a one-time compile the disagg legs
    # amortize in their warm replay
    from ray_lightning_tpu.comm.quant import (dequantize_blob,
                                              quantize_blob)
    for pages in (1, 2, 3, 4):
        rows = np.zeros((cfg.n_layer, pages * PAGE_SIZE, cfg.n_embd),
                        np.float32)
        payload, scale = quantize_blob(rows, "fp8")
        dequantize_blob(np.asarray(payload),
                        None if scale is None else np.asarray(scale),
                        "fp8", rows.shape)
    single1 = FleetServer(
        GPTLightningModule(cfg), replicas=1, autoscale=False,
        paged={"page_size": PAGE_SIZE},
        default_root_dir=os.path.join(root, "single1"),
        **fed_kw).start()
    try:
        legs_fed["single1_2x"] = replay(single1, fed_trace, 2.0,
                                        scramble=True)
        single1_pages = single1.pages_stats()
    finally:
        single1.shutdown()
    fed_status, fed_pages, fed_gp = {}, {}, {}
    for fed_on in (False, True):
        tag = "fed_on" if fed_on else "fed_off"
        f = FleetServer(
            GPTLightningModule(cfg), replicas=2, autoscale=False,
            fleet={"sticky_slack": 0, "prefix_fed": fed_on},
            paged={"page_size": PAGE_SIZE},
            default_root_dir=os.path.join(root, tag),
            **fed_kw).start()
        try:
            legs_fed[f"{tag}_2x"] = replay(f, fed_trace, 2.0,
                                           scramble=True)
            fed_status[tag] = f.status()["fleet"]
            fed_pages[tag] = f.pages_stats()
            fed_gp[tag] = f.goodput_stats() or {"buckets": {}}
        finally:
            f.shutdown()

    # -- disaggregated: 1 prefill + 1 decode replica, KV pages ship ----
    # over the peer channel.  The prefill replica's slots free after
    # ONE token (no decode tail), so burst admissions stop queueing
    # behind held slots — the TTFT-p99 win the 4x comparison pins.
    # fp8 is the compressed wire leg; raw (fp32) is the A/B control.
    disagg_status = {}
    for codec in ("fp8", "raw"):
        dis = FleetServer(
            GPTLightningModule(cfg), replicas=2, autoscale=False,
            fleet={"roles": ("prefill", "decode"),
                   "kvship_codec": codec},
            paged={"page_size": PAGE_SIZE},
            default_root_dir=os.path.join(root, f"disagg_{codec}"),
            **server_kw).start()
        try:
            # warm pass (discarded): the pooled2 baseline replays 1x
            # and 2x before ITS timed 4x leg, so its programs, donors
            # and pools are hot — the A/B is only fair if the disagg
            # fleet starts its timed leg equally warm
            replay(dis, trace, 1.0)
            legs[f"disagg_{codec}_4x"] = replay(dis, trace, 4.0)
            disagg_status[codec] = dis.status()["fleet"]
        finally:
            dis.shutdown()

    # -- disaggregated + federation: decode donors feed the prefill ----
    # pool.  A prefill replica whose donor evicted under burst churn
    # would re-prefill a prefix the decode replica ALREADY adopted
    # (the shipped pages retained there); with the directory on, the
    # prefill pool fetches those pages back over the same wire instead
    # of paying the prefill twice.
    disfed = FleetServer(
        GPTLightningModule(cfg), replicas=2, autoscale=False,
        fleet={"roles": ("prefill", "decode"), "prefix_fed": True},
        paged={"page_size": PAGE_SIZE},
        default_root_dir=os.path.join(root, "disagg_fed"),
        **server_kw).start()
    try:
        replay(disfed, trace, 1.0)     # warm, like the other disagg legs
        legs["disagg_fed_4x"] = replay(disfed, trace, 4.0)
        disfed_status = disfed.status()["fleet"]
        disfed_pages = disfed.pages_stats()
    finally:
        disfed.shutdown()

    # -- autoscaling fleet under the 4x burst --------------------------
    auto = FleetServer(
        GPTLightningModule(cfg), replicas=1,
        fleet={"min_replicas": 1, "max_replicas": 3,
               "grow_queue_depth": 2.0, "patience_ticks": 2,
               "cooldown_s": 1.0, "tick_interval_s": 0.1,
               "shrink_occupancy": 0.25},
        paged={"page_size": PAGE_SIZE},
        default_root_dir=os.path.join(root, "auto"),
        **server_kw).start()
    try:
        legs["auto_4x"] = replay(auto, trace, 4.0)
        # idle tail: empty queue + zero occupancy drives the shrink
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            st = auto.autoscaler.stats()
            if st["shrinks"] >= 1 and not st["actuating"]:
                break
            time.sleep(0.2)
        autoscale = auto.autoscaler.stats()
        auto_status = auto.status()["fleet"]
        auto_pages = auto.pages_stats()
    finally:
        auto.shutdown()

    # -- parity: every routed request vs the single-Server reference ---
    import jax
    eng = None
    try:
        from ray_lightning_tpu.parallel.strategy import (
            DataParallelStrategy)
        from ray_lightning_tpu.serve.engine import ServeEngine
        eng = ServeEngine(module, DataParallelStrategy(),
                          buckets=BUCKETS, slots=SLOTS,
                          max_seq_len=cfg.block_size, seed=0).setup()
        ref_params = jax.device_get(eng.params)
    finally:
        del eng
    parity = check_parity(module, ref_params, trace, legs)
    # the federation legs replay their own trace — same oracle, its
    # own reference outputs (scrambled tenants don't touch tokens)
    parity_fed = check_parity(module, ref_params, fed_trace, legs_fed)

    headline = legs["fleet2_2x"]
    fleet_doc = {
        "trace": {"path": trace_path, "requests": len(trace),
                  "tenants": len({r['tenant'] for r in trace})},
        "workers_per_replica": num_workers,
        "platform": platform,
        "slots": SLOTS,
        "page_size": PAGE_SIZE,
        # env-resolved decode kernel (ops/flash_decode.py); paging is on
        # and page-aligned here, so engines see the same resolution
        "decode_kernel": resolve_decode_impl(None),
        "tokens_per_sec": headline["tokens_per_sec"],
        "ttft_p99_ms": headline["ttft_p99_ms"],
        "multipliers": {
            "1x": {"single": _slim(legs["reference"]),
                   "fleet2": _slim(legs["fleet2_1x"])},
            "2x": {"single": _slim(legs["single_2x"]),
                   "fleet2": _slim(legs["fleet2_2x"])},
            "4x": {"autoscale": _slim(legs["auto_4x"]),
                   "pooled2": _slim(legs["pooled2_4x"]),
                   "disagg": _slim(legs["disagg_fp8_4x"]),
                   "disagg_raw": _slim(legs["disagg_raw_4x"]),
                   "disagg_fed": _slim(legs["disagg_fed_4x"])},
        },
        "disagg": {
            "roles": ["prefill", "decode"],
            "ttft_p99_ms": legs["disagg_fp8_4x"]["ttft_p99_ms"],
            "pooled2_ttft_p99_ms": legs["pooled2_4x"]["ttft_p99_ms"],
            "kvship": {c: disagg_status[c]["kvship"]
                       for c in disagg_status},
            # fp8's own raw-baseline ratio (bytes_raw is the fp32 size
            # of the same shipped rows — the raw control leg's wire)
            "fp8_compression_ratio":
                disagg_status["fp8"]["kvship"]["compression_ratio"],
        },
        "federation": {
            "trace": {"requests": len(fed_trace), "groups": 8},
            # the locality control: ONE replica sees every request, so
            # its donors get perfect routing — but also only one
            # replica's worth of slots to retain them in.  The fed-on
            # fleet must recover at least this reuse ratio with ZERO
            # tenant locality across twice the slots.
            "single_sticky_reuse_ratio":
                single1_pages["prefix_reuse_ratio"],
            "single1": _slim(legs_fed["single1_2x"]),
            "fed_off": {
                **_slim(legs_fed["fed_off_2x"]),
                "prefix_reuse_ratio":
                    fed_pages["fed_off"]["prefix_reuse_ratio"],
                "prefill_s":
                    round(fed_gp["fed_off"]["buckets"].get(
                        "prefill", 0.0), 3),
            },
            "fed_on": {
                **_slim(legs_fed["fed_on_2x"]),
                "prefix_reuse_ratio":
                    fed_pages["fed_on"]["prefix_reuse_ratio"],
                "federated_reuse_ratio":
                    fed_pages["fed_on"].get("federated_reuse_ratio"),
                "federated_tokens_reused":
                    fed_pages["fed_on"].get("federated_tokens_reused"),
                "prefill_s":
                    round(fed_gp["fed_on"]["buckets"].get(
                        "prefill", 0.0), 3),
                "kv_fed_s":
                    round(fed_gp["fed_on"]["buckets"].get(
                        "kv_fed", 0.0), 3),
                "counters": fed_status["fed_on"]["federation"],
            },
            # MEASURED prefill wall delta (replica goodput ledgers),
            # not an estimate from token counts.  Reported, not
            # asserted: on the CPU proxy a 16-token prefill costs
            # single milliseconds, so the delta is noise-band — the
            # reuse-ratio recovery above is the contract
            "prefill_seconds_saved": round(
                fed_gp["fed_off"]["buckets"].get("prefill", 0.0)
                - fed_gp["fed_on"]["buckets"].get("prefill", 0.0), 3),
        },
        "disagg_fed": {
            "ttft_p99_ms": legs["disagg_fed_4x"]["ttft_p99_ms"],
            "federation": disfed_status.get("federation"),
            "federated_tokens_reused":
                disfed_pages.get("federated_tokens_reused"),
            "kvship_ships": disfed_status["kvship"]["ships"],
        },
        "autoscale": {
            "events": autoscale["events"],
            "grows": autoscale["grows"],
            "shrinks": autoscale["shrinks"],
        },
        "prefix_reuse": fleet2_pages,
        "prefix_reuse_auto": auto_pages,
        # fraction of requested prefill tokens satisfied by pages
        # PULLED from another replica (the fed_on leg) — the ledger's
        # fleet.federated_reuse_ratio band
        "federated_reuse_ratio":
            fed_pages["fed_on"].get("federated_reuse_ratio", 0.0),
        "failovers": (fleet2_status["failovers"]
                      + auto_status["failovers"]),
        "requests_lost": fleet2_status["failed"] + auto_status["failed"],
        "parity": parity,
        "parity_federation": parity_fed,
    }
    record = {"metric": metric, "value": headline["tokens_per_sec"],
              "unit": "tokens/s", "fleet": fleet_doc}
    print(json.dumps(record), flush=True)

    # the acceptance bars, enforced where the bench runs
    assert legs["fleet2_2x"]["tokens_per_sec"] \
        > legs["single_2x"]["tokens_per_sec"], (
        "2 replicas did not beat 1 at the 2x replay",
        legs["fleet2_2x"]["tokens_per_sec"],
        legs["single_2x"]["tokens_per_sec"])
    assert autoscale["grows"] >= 1, autoscale
    assert autoscale["shrinks"] >= 1, autoscale
    assert fleet_doc["prefix_reuse"]["prefix_reuse_ratio"] > 0, \
        fleet_doc["prefix_reuse"]
    assert fleet_doc["requests_lost"] == 0, fleet_doc["failovers"]
    assert parity["ok"], parity
    # disaggregation bars: prefill/decode split beats 2 pooled replicas
    # on 4x-burst TTFT p99; KV pages genuinely shipped; fp8 rides the
    # wire at >= 3x under the raw (fp32) control leg
    dis = fleet_doc["disagg"]
    assert dis["ttft_p99_ms"] < dis["pooled2_ttft_p99_ms"], dis
    for codec, kv in dis["kvship"].items():
        assert kv["ships"] > 0, (codec, kv)
    assert dis["fp8_compression_ratio"] >= 3.0, dis
    assert all(st["failed"] == 0 for st in disagg_status.values()), \
        disagg_status
    # federation bars: pages genuinely federate (directory hits turn
    # into wire ships that save real prefill tokens); the fed-on reuse
    # ratio beats fed-off outright AND recovers the single-replica
    # sticky control (small slack: capacity-gated fetches may skip
    # under burst).  TTFT: the MEDIAN must hold — fetches ride the
    # tail by construction on this proxy, where a 2-page wire pull
    # (two worker RPCs against a busy donor) costs more wall than the
    # 16-token prefill it replaces; the tail win needs prefix lengths
    # that only exist off the CPU proxy, so p99 is reported, not gated
    fed = fleet_doc["federation"]
    assert fed["fed_on"]["counters"]["fetches"] > 0, fed
    assert fed["fed_on"]["counters"]["ships"] > 0, fed
    assert fed["fed_on"]["federated_tokens_reused"] > 0, fed
    assert fed["fed_on"]["federated_reuse_ratio"] > 0, fed
    assert fed["fed_on"]["prefix_reuse_ratio"] \
        > fed["fed_off"]["prefix_reuse_ratio"], fed
    assert fed["fed_on"]["prefix_reuse_ratio"] \
        >= fed["single_sticky_reuse_ratio"] - 0.05, fed
    assert fed["fed_on"]["ttft_p50_ms"] \
        <= 2.0 * fed["fed_off"]["ttft_p50_ms"] + MIN_TTFT_FLOOR_MS, fed
    assert fed_status["fed_on"]["failed"] == 0, fed_status["fed_on"]
    assert parity_fed["ok"], parity_fed
    # disaggregated + federation: decode-held prefixes come back over
    # the wire instead of being re-prefilled, and nothing is lost
    disf = fleet_doc["disagg_fed"]
    assert disf["kvship_ships"] > 0, disf
    assert disfed_status["failed"] == 0, disfed_status
    return [record]


def _slim(leg: dict) -> dict:
    return {k: v for k, v in leg.items() if k != "outputs"}


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--requests", type=int, default=64)
    parser.add_argument("--trace", default=None,
                        help="replay this recorded trace JSON instead "
                        "of recording a fresh one")
    args = parser.parse_args()
    run_fleet_ab("fleet_serve", requests=args.requests,
                 trace_path=args.trace)


if __name__ == "__main__":
    main()
