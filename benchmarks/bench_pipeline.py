"""Pipeline bench: MPMD per-stage programs vs the SPMD GPipe monolith.

One JSON line per leg in the shared harness format
(``python -m benchmarks.bench_pipeline``):

- ``pipeline_spmd`` — the existing one-program GPipe
  (parallel/pipeline.py, ``PipelineStrategy(stages=2)``): the baseline
  the MPMD legs one-diff against (same model, same microbatches, same
  seed).
- ``mpmd_gpipe`` / ``mpmd_1f1b`` — the MPMD engine under each
  schedule.  Each line's ``mpmd`` field carries per-stage compile
  seconds, the simulated bubble fraction PER SCHEDULE (replayed from
  measured per-op times — the CPU proxy executes serially, so wall
  clock cannot show overlap; same caveat as bench_comm), and
  activation bytes/step.  The 1f1b leg auto-interleaves (v=2 on the
  4-layer config), which is where its bubble drops below GPipe's —
  plain 1F1B ties GPipe analytically (mpmd/schedule.py).
- ``mpmd_1f1b_fp8`` — the codec-on-activations leg; its line adds
  ``activation_bytes_by_codec``, the wire-size menu of the whole codec
  family for this boundary shape.

A ``bubble_win`` summary line states the 1f1b-vs-gpipe comparison the
acceptance bar reads — including ``measured_bubble_fraction_1f1b``,
the trace-anatomy host-gap fraction of the 1f1b leg's own warm-tail
capture (telemetry/anatomy.py): the measured-bubble leg next to the
replay-simulated fractions.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

WARMUP = 2
TIMED = 8
STAGES = 2
MICRO = 4


def _model():
    from ray_lightning_tpu.models.gpt import GPTConfig
    from ray_lightning_tpu.models.pipeline_gpt import PipelinedGPT

    # 4 layers so the 1f1b leg can interleave (2 chunks/stage); tiny
    # dims keep the CPU legs honest about schedule, not matmul, time
    cfg = GPTConfig(vocab_size=512, block_size=64, n_layer=4, n_head=2,
                    n_embd=64, remat=False)
    # batch 16: the SPMD baseline's (data=4, stage=2) mesh leaves a
    # per-shard batch of 4 = MICRO microbatches; the MPMD legs split
    # the same global batch into the same 4 microbatches
    return PipelinedGPT(cfg, n_microbatches=MICRO, dataset_size=256,
                        batch_size=16)


def _bubble_goodput_view(rec: dict) -> "dict | None":
    """The leg's timed window as a goodput partition: all wall is
    ``step`` (the window excludes compile/init by construction), and
    the measured anatomy sub-splits it — ``bubble_s`` is the
    schedule-idle share the 1f1b-vs-gpipe claim is about."""
    anatomy = rec.get("anatomy")
    sps = rec.get("value")
    if not anatomy or not sps:
        return None
    from ray_lightning_tpu.telemetry.goodput import GoodputLedger
    wall = TIMED / float(sps)
    ledger = GoodputLedger("fit")
    ledger.note_step(wall, k=TIMED)
    ledger.set_anatomy(anatomy)
    doc = ledger.finalize(wall)
    return {"run_wall_s": doc["run_wall_s"],
            "buckets": doc["buckets"],
            "goodput_fraction": doc["goodput_fraction"],
            "useful_split": doc["useful_split"]}


def main() -> None:
    import jax

    if len(jax.devices()) < 2:
        # same re-exec proxy bench_comm uses: the SPMD baseline needs a
        # real stage axis
        env = dict(os.environ)
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=8"
                            ).strip()
        env["JAX_PLATFORMS"] = "cpu"
        raise SystemExit(subprocess.call(
            [sys.executable, "-m", "benchmarks.bench_pipeline"], env=env))

    from benchmarks.harness import run_steps_per_sec
    from ray_lightning_tpu.mpmd import MpmdConfig, MpmdPipelineStrategy
    from ray_lightning_tpu.mpmd.partition import activation_wire_bytes
    from ray_lightning_tpu.parallel.pipeline import PipelineStrategy

    run_steps_per_sec(
        _model(), "pipeline_spmd_steps_per_sec", warmup=WARMUP,
        timed=TIMED, strategy=PipelineStrategy(stages=STAGES),
        telemetry=False,
        extra_fields={"stages": STAGES, "microbatches": MICRO,
                      "schedule": "gpipe-spmd"})

    import shutil

    results = {}
    for tag, cfg in (
        ("mpmd_gpipe", MpmdConfig(stages=STAGES, schedule="gpipe",
                                  microbatches=MICRO)),
        ("mpmd_1f1b", MpmdConfig(stages=STAGES, schedule="1f1b",
                                 microbatches=MICRO)),
        ("mpmd_1f1b_fp8", MpmdConfig(stages=STAGES, schedule="1f1b",
                                     microbatches=MICRO, codec="fp8")),
    ):
        extra = None
        if cfg.codec != "none":
            # wire-size menu for this boundary shape: [mb, T, C] bf16
            module = _model()
            mcfg = module.config
            boundary = (module.batch_size // MICRO) * mcfg.block_size \
                * mcfg.n_embd * 2
            extra = {"activation_bytes_by_codec": {
                c: activation_wire_bytes(boundary, STAGES - 1, MICRO,
                                         codec=c)
                for c in ("none", "bf16", "int8", "fp8", "int4")}}
        # measured-bubble legs (ROADMAP 5b): the gpipe and 1f1b runs
        # each capture a warm-tail trace, whose anatomy host-gap
        # fraction is the MEASURED bubble (telemetry/anatomy.py) next
        # to the replay-simulated one — and the ledger gates both
        # (benchmarks/ledger.py measured_bubble_fraction_* bands)
        trace_steps = 4 if tag in ("mpmd_gpipe", "mpmd_1f1b") else 0
        results[tag] = run_steps_per_sec(
            _model(), f"{tag}_steps_per_sec", warmup=WARMUP,
            timed=TIMED, strategy=MpmdPipelineStrategy(cfg),
            telemetry=False, extra_fields=extra, trace_steps=trace_steps)
        if results[tag].get("trace_dir"):
            shutil.rmtree(results[tag].pop("trace_dir"),
                          ignore_errors=True)

    bubbles = results["mpmd_1f1b"].get("mpmd", {}).get(
        "bubble_fraction", {})
    measured = (results["mpmd_1f1b"].get("anatomy") or {}).get(
        "bubble_fraction")
    measured_gpipe = (results["mpmd_gpipe"].get("anatomy") or {}).get(
        "bubble_fraction")
    print(json.dumps({
        "metric": "mpmd_bubble_win",
        "gpipe_bubble_fraction": bubbles.get("gpipe"),
        "1f1b_bubble_fraction": bubbles.get("1f1b"),
        "1f1b_below_gpipe": (
            bubbles.get("1f1b", 1.0) < bubbles.get("gpipe", 0.0)),
        "measured_bubble_fraction_1f1b": measured,
        "measured_bubble_fraction_gpipe": measured_gpipe,
        # goodput-bucket view of the bubble (telemetry/goodput.py):
        # the 1f1b leg's timed window recast as a goodput partition,
        # with the measured bubble carved out of the useful bucket's
        # sub-split — the same shape the fit/serve surfaces report
        "goodput_view": _bubble_goodput_view(results["mpmd_1f1b"]),
        "microbatches": MICRO,
        "note": "bubble_fraction legs are simulated from measured "
                "per-op seconds; measured_bubble_fraction_1f1b is the "
                "trace-anatomy host-gap share of the same run "
                "(telemetry/anatomy.py) — on the serial CPU proxy it "
                "measures dispatch gap, the real-fabric leg is ROADMAP "
                "item 1c.  1f1b interleaves (v=2) — plain 1f1b ties "
                "gpipe (mpmd/schedule.py)",
    }))


if __name__ == "__main__":
    main()
