"""Shared benchmark harness: time steady-state training steps through
the full framework path (Trainer → compiled SPMD step) and print one
JSON line per metric, the same contract as the repo-root ``bench.py``.

The BASELINE configs (BASELINE.md) are each covered by a script in this
directory; ``python -m benchmarks.bench_resnet50`` etc.  The timing
method matches bench.py: warmup to steady state, then fetch a loss
scalar as the device sync point (block_until_ready does not reliably
drain remote-tunnel platforms).
"""

from __future__ import annotations

import json
import time

import numpy as np


def run_steps_per_sec(module, metric: str, *, warmup: int = 3,
                      timed: int = 30, baseline: "float | None" = None,
                      strategy=None, trainer_kwargs=None) -> dict:
    from ray_lightning_tpu import Trainer
    from ray_lightning_tpu.core.callbacks import Callback

    class Timer(Callback):
        def __init__(self):
            self.t0 = None
            self.elapsed = None

        def on_train_batch_end(self, trainer, mod, metrics, batch, idx):
            if trainer.global_step == warmup:
                float(np.asarray(metrics["loss"]))
                self.t0 = time.monotonic()
            elif trainer.global_step == warmup + timed:
                float(np.asarray(metrics["loss"]))
                self.elapsed = time.monotonic() - self.t0

    timer = Timer()
    trainer = Trainer(
        max_steps=warmup + timed, max_epochs=10**6, strategy=strategy,
        enable_checkpointing=False, num_sanity_val_steps=0,
        limit_val_batches=0, log_every_n_steps=10**9, callbacks=[timer],
        seed=0, **(trainer_kwargs or {}))
    trainer.fit(module)
    assert timer.elapsed is not None, "did not reach timed steps"
    steps_per_sec = timed / timer.elapsed
    result = {
        "metric": metric,
        "value": round(steps_per_sec, 3),
        "unit": "steps/sec",
        "vs_baseline": round(steps_per_sec / (baseline or steps_per_sec), 3),
    }
    print(json.dumps(result))
    return result
