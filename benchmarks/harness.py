"""Shared benchmark harness: time steady-state training steps through
the full framework path (Trainer → compiled SPMD step) and print one
JSON line per metric, the same contract as the repo-root ``bench.py``.

The BASELINE configs (BASELINE.md) are each covered by a script in this
directory; ``python -m benchmarks.bench_resnet50`` etc.  The timing
method matches bench.py: warmup to steady state, then fetch a loss
scalar as the device sync point (block_until_ready does not reliably
drain remote-tunnel platforms).
"""

from __future__ import annotations

import json
import time

import numpy as np


def run_steps_per_sec(module, metric: str, *, warmup: int = 3,
                      timed: int = 30, baseline: "float | None" = None,
                      strategy=None, trainer_kwargs=None,
                      trace_steps: int = 0,
                      inline_device_ms: bool = False,
                      telemetry: bool = True,
                      extra_fields: "dict | None" = None) -> dict:
    """Time steady-state steps; optionally profile a WARM tail.

    ``trace_steps > 0``: after the timed window closes (and its sync
    lands), the profiler traces that many additional steps of the SAME
    fit — the compiled program is warm, so the tunnel profiler actually
    records the step executions (tracing a fresh Trainer recompiles
    inside the window and the device events never materialize).  The
    result dict then carries ``trace_dir``.

    ``inline_device_ms``: fold the dominant XLA module's median device
    ms/step (from the warm-tail trace) into the ONE printed JSON line
    as ``device_ms`` — the tunnel-immune number of record alongside the
    wall steps/sec, which swings ±3-5% with host-link state that has
    nothing to do with the framework.  The trace dir is consumed.

    ``telemetry`` (default on): run with the framework telemetry layer
    enabled and report the exported ``telemetry.jsonl`` path as
    ``telemetry_jsonl`` in the JSON line, so a BENCH regression can be
    attributed to a phase (step vs data_wait vs compile) from the span
    stream instead of re-running under a profiler.

    Any captured warm-tail trace additionally lands as an ``anatomy``
    field (telemetry/anatomy.py): the MEASURED per-step device-time
    split — compute / collective (by op + ici/dcn link) /
    trace-measured exposed comm / host gap — so every leg's claim is
    one JSON diff against the previous round
    (``bench.py --compare`` / benchmarks/ledger.py gates on it).
    """
    from ray_lightning_tpu import Trainer
    from ray_lightning_tpu.core.callbacks import Callback

    class Timer(Callback):
        """>=` comparisons + actual step counting so chunked dispatch
        (steps_per_execution>1: global_step advances k at a time) is
        timed correctly."""

        needs_batch = False   # reads metrics/step only, never the batch

        def __init__(self):
            self.t0 = None
            self.start_step = None
            self.steps = None
            self.elapsed = None
            self.trace_dir = None
            self._last_metrics = None

        @staticmethod
        def _sync(metrics):
            # fetch a loss value: the only reliable device sync point on
            # remote-tunnel platforms
            float(np.asarray(metrics["loss"]).ravel()[-1])

        def on_train_batch_end(self, trainer, mod, metrics, batch, idx):
            self._last_metrics = metrics
            if self.t0 is None and trainer.global_step >= warmup:
                self._sync(metrics)
                self.start_step = trainer.global_step
                self.t0 = time.monotonic()
            elif self.t0 is not None and self.elapsed is None \
                    and trainer.global_step >= self.start_step + timed:
                self._sync(metrics)
                self.elapsed = time.monotonic() - self.t0
                self.steps = trainer.global_step - self.start_step
                if trace_steps > 0 and self.trace_dir is None:
                    import tempfile

                    import jax
                    d = tempfile.mkdtemp(prefix="rlt_trace_")
                    try:
                        jax.profiler.start_trace(d)
                    except Exception:   # profiler-less backends: the
                        pass            # wall numbers must still emit
                    else:
                        self.trace_dir = d

        def on_train_end(self, trainer, mod):
            if self.trace_dir is not None:
                import jax
                if self._last_metrics is not None:
                    self._sync(self._last_metrics)
                try:
                    jax.profiler.stop_trace()
                except Exception:
                    self.trace_dir = None

    timer = Timer()
    # chunked dispatch rounds the warmup boundary up to a chunk edge, so
    # leave 2 chunks of slack past warmup+timed
    slack = 2 * (trainer_kwargs or {}).get("steps_per_execution", 1)
    trainer = Trainer(
        max_steps=warmup + timed + slack + trace_steps, max_epochs=10**6,
        strategy=strategy,
        enable_checkpointing=False, num_sanity_val_steps=0,
        limit_val_batches=0, log_every_n_steps=10**9, callbacks=[timer],
        seed=0, telemetry=bool(telemetry), **(trainer_kwargs or {}))
    trainer.fit(module)
    assert timer.elapsed is not None, "did not reach timed steps"
    steps_per_sec = timer.steps / timer.elapsed
    result = {
        "metric": metric,
        "value": round(steps_per_sec, 3),
        "unit": "steps/sec",
        "vs_baseline": round(steps_per_sec / (baseline or steps_per_sec), 3),
    }
    # cold-vs-warm startup tracking (compile/): how long until the first
    # step ran, and whether the persistent compilation cache served this
    # process ("hit"), compiled everything fresh ("miss") or was off —
    # so BENCH rounds catch startup regressions steps/sec can't see
    ttfs = getattr(trainer, "time_to_first_step", None)
    if ttfs is not None:
        result["time_to_first_step_seconds"] = round(ttfs, 3)
    from ray_lightning_tpu.compile import cache as compile_cache
    result["compile_cache"] = compile_cache.status_word()
    # comm plane: which dtype the gradient collectives rode ("fp32" =
    # uncompressed).  _grad_sync is the worker-side resolution (present
    # after a LocalPlugin fit); distributed drivers fall back to the
    # policy, which only activates on multi-process meshes.
    pol = getattr(trainer, "comm_policy", None)
    sync = getattr(trainer, "_grad_sync", None)
    active = sync is not None or (
        pol is not None and pol.enabled and trainer.world_size > 1)
    result["comm"] = pol.compress if (active and pol is not None) else "fp32"
    # planner plane: whether this run's parallelism was picked by the
    # strategy="auto" cost model ("auto" — the PlanReport landed on
    # trainer._plan_report) or hand-configured ("manual")
    result["plan"] = ("auto" if getattr(trainer, "_plan_report", None)
                      else "manual")
    # MPMD plane: per-stage compile seconds, simulated bubble fractions
    # per schedule and activation wire bytes (mpmd/engine.py report) —
    # the fields bench_pipeline.py's one-diff comparison reads
    rep = getattr(trainer, "_mpmd_report", None)
    if rep:
        result["mpmd"] = {
            "schedule": rep["schedule"],
            "stages": rep["stages"],
            "virtual": rep.get("virtual", 1),
            "cuts": rep.get("cuts"),
            "codec": rep["codec"],
            "per_stage_compile_seconds":
                rep.get("per_stage_compile_seconds"),
            "bubble_fraction": {
                k: v["bubble_fraction"]
                for k, v in rep.get("bubble", {}).items()},
            "activation_bytes_per_step":
                rep.get("activation_bytes_per_step"),
        }
    if timer.trace_dir is not None:
        # measured step anatomy from the warm-tail trace
        # (telemetry/anatomy.py): where the device time of THIS leg's
        # steps actually went — compute / collective (by op and
        # ici/dcn link) / trace-measured exposed comm / host gap.
        # Parsed before the device_ms path below consumes the dir.
        from ray_lightning_tpu.telemetry.anatomy import (
            parse_anatomy_or_none,
        )
        anatomy = parse_anatomy_or_none(timer.trace_dir)
        if anatomy is not None:
            result["anatomy"] = anatomy
    # goodput plane (telemetry/goodput.py): the run's wall-clock
    # partition + measured MFU, compacted to the fields the ledger
    # gates on (benchmarks/ledger.py goodput-fraction / MFU bands)
    gp = getattr(trainer, "_goodput_report", None)
    if gp:
        result["goodput"] = {
            "fraction": gp.get("goodput_fraction"),
            "mfu": gp.get("mfu"),
            "run_wall_s": gp.get("run_wall_s"),
            "buckets": gp.get("buckets"),
        }
    paths = getattr(trainer, "_telemetry_paths", None)
    if paths:
        result["telemetry_jsonl"] = paths["jsonl"]
        # memory + comms alongside steps/sec, so BENCH rounds catch HBM
        # and collective-traffic regressions that leave wall time alone
        summary = paths.get("summary") or {}
        if "hbm_peak_bytes" in summary:
            result["hbm_peak_bytes"] = summary["hbm_peak_bytes"]
        if "collective_gibs" in summary:
            result["collective_gibs"] = summary["collective_gibs"]
    if inline_device_ms and timer.trace_dir is not None:
        from benchmarks import trace_tools
        med = trace_tools.dominant_module_ms_or_none(timer.trace_dir)
        timer.trace_dir = None
        if med is not None:
            result["device_ms"] = round(med, 2)
    if callable(extra_fields):
        # derived fields (e.g. bench_comm's exposed_comm_seconds need
        # the measured value): compute from the assembled result
        result.update(extra_fields(result) or {})
    elif extra_fields:
        result.update(extra_fields)
    print(json.dumps(result))
    if timer.trace_dir is not None:
        result["trace_dir"] = timer.trace_dir
    return result
