"""Incident-plane detector overhead: the same fit A/B'd with the
incident plane OFF then ON (``RLT_INCIDENT``), reported as one
``incident_ab`` record the perf ledger gates ABSOLUTELY at 2%
(benchmarks/ledger.py ``incident_band``).

The incident plane is always-on telemetry — timelines fed from every
span batch, a detector ticked per sample, the heartbeat sample tail —
so its cost rides every training step of every run.  A relative
round-over-round band can't see that cost (it is identical on both
sides); this leg measures it directly by differencing steps/sec with
the plane disabled vs enabled on an otherwise identical fit.

    python -m benchmarks.bench_incident
"""

import json
import os

import jax

from benchmarks.harness import run_steps_per_sec


def _leg(enabled: bool, platform: str, batch: int) -> dict:
    from ray_lightning_tpu.models import LightningMNISTClassifier
    from ray_lightning_tpu.telemetry import incident

    # dispatch-bound MLP: per-step framework overhead dominates, which
    # is exactly the regime where detector cost would show
    module = LightningMNISTClassifier(config={"batch_size": batch},
                                      train_size=batch * 40)
    prev = os.environ.get(incident.INCIDENT_ENV)
    os.environ[incident.INCIDENT_ENV] = "1" if enabled else "0"
    try:
        return run_steps_per_sec(
            module,
            f"incident_{'on' if enabled else 'off'}_b{batch}"
            f"_steps_per_sec_{platform}",
            timed=100)
    finally:
        if prev is None:
            os.environ.pop(incident.INCIDENT_ENV, None)
        else:
            os.environ[incident.INCIDENT_ENV] = prev


def main():
    platform = jax.devices()[0].platform
    batch = 128
    off = _leg(False, platform, batch)
    on = _leg(True, platform, batch)
    overhead_pct = round(
        (off["value"] - on["value"]) / off["value"] * 100, 2)
    print(json.dumps({
        "metric": f"incident_overhead_b{batch}_{platform}",
        "incident_ab": {
            "steps_per_sec_off": off["value"],
            "steps_per_sec_on": on["value"],
            "overhead_pct": overhead_pct,
        },
    }))


if __name__ == "__main__":
    main()
