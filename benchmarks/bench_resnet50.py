"""BASELINE config #2: ResNet-50 / CIFAR-10-shaped data, steps/sec/chip.

    python -m benchmarks.bench_resnet50
"""

import jax

from benchmarks.harness import run_steps_per_sec

# first TPU measurement of this exact config (v5e chip, B=128, 32x32,
# NHWC bf16) — later rounds compare against it
BASELINES = {"tpu": 26.4}


def main():
    from ray_lightning_tpu.models.resnet import ResNetLightningModule

    platform = jax.devices()[0].platform
    batch = 128 if platform != "cpu" else 8
    cfg = "resnet50" if platform != "cpu" else "resnet18"
    module = ResNetLightningModule(cfg, batch_size=batch,
                                   train_size=batch * 40)
    run_steps_per_sec(module, f"{cfg}_b{batch}_steps_per_sec_{platform}",
                      baseline=BASELINES.get(platform))

    # image batches are ~1.6 MB: on a tunneled chip the host link (not
    # compute) can bound the streamed number, so also measure with the
    # train set resident on device — the tunnel-independent figure
    module = ResNetLightningModule(cfg, batch_size=batch,
                                   train_size=batch * 40)
    run_steps_per_sec(
        module, f"{cfg}_b{batch}_cached_steps_per_sec_{platform}",
        timed=120, baseline=BASELINES.get(platform),
        trainer_kwargs={"steps_per_execution": 8,
                        "cache_train_dataset": True})


if __name__ == "__main__":
    main()
