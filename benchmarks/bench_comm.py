"""Comm-plane A/B bench: flat / hierarchical / fp8 / int4 legs plus the
exposed-vs-overlapped comm measurement.

``bench.py`` runs this when ``RLT_COMM_AB=1``.  Each leg is ONE JSON
line in the shared harness format with two extra fields:

- ``exposed_comm_seconds``: this leg's wall seconds/step minus the
  comm-off (fp32) floor measured in the same process on the same mesh —
  the differential cost the gradient sync ADDS per step after whatever
  overlap the schedule achieved.  The overlap win is the single diff
  ``int8_bucketed.exposed_comm_seconds <
  int8_barrier.exposed_comm_seconds`` (same codec, same bytes; the only
  difference is the end-of-backward ``optimization_barrier`` the
  barrier leg re-inserts).
- ``step_seconds``: the raw wall seconds/step the subtraction started
  from, so rounds can recompute against any floor.
- ``measured_exposed_comm_seconds`` (bucketed/barrier legs): the
  TRACE-MEASURED exposed comm from a warm-tail capture of the same leg
  (telemetry/anatomy.py — collective device intervals not overlapped
  by compute), next to ``exposed_divergence_seconds`` =
  wall-minus-floor − measured.  The divergence IS a finding: the
  proxy also pays codec quantize/dequantize compute and host jitter,
  the measured number is pure serialization — and on this CPU proxy's
  serial thunk executor measured exposed ≈ collective seconds by
  construction (no overlap is possible), which is exactly PR 10's
  caveat made visible in the JSON.

The ZeRO-1 gather pair (``zero1_gather_bucketed`` vs
``zero1_gather_barrier``, plus the ``zero1_int8`` floor they subtract)
plays the same game on the OTHER collective: the updated-param
all-gather, explicit + consumption-ordered + bucketed vs tied whole-tree
monolithic, summarized in ``*_gather_overlap``.

A meaningful A/B needs a real multi-device data mesh.  When the
current process has one (a TPU slice / multi-host fleet), the legs run
inline; on a single-device (or CPU) session the whole suite re-runs in
a subprocess with an 8-virtual-device CPU mesh — the same proxy the
test suite audits — so ``RLT_COMM_AB=1 python bench.py`` always emits
comparable legs.  The bucketed/barrier pair additionally feeds
``rlt_comm_exposed_seconds`` via the metrics plane when telemetry is
live.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

WARMUP = 3
TIMED = 20

#: (leg tag, CommPolicy kwargs); hierarchy=4 on the 8-way proxy mesh
#: (auto would be inert in one process), HIER_AUTO on real fleets —
#: resolved in ``_legs``.
LEG_SPECS = (
    ("int8", dict(compress="int8")),
    ("int8_hier", dict(compress="int8", hierarchy=True)),
    ("fp8_hier", dict(compress="fp8", hierarchy=True)),
    ("int4_hier", dict(compress="int4", hierarchy=True)),
    ("int8_bucketed", dict(compress="int8", bucket_bytes=1 << 20)),
    ("int8_barrier", dict(compress="int8", bucket_bytes=1 << 20,
                          barrier_sync=True)),
)


def _legs(world: int, multi_process: bool):
    """Resolve LEG_SPECS into CommPolicy objects for this topology."""
    from ray_lightning_tpu.comm import CommPolicy
    from ray_lightning_tpu.comm.policy import HIER_AUTO

    hier = HIER_AUTO if multi_process else \
        next((k for k in (4, 2) if world % k == 0 and k < world), 0)
    legs = []
    for tag, spec in LEG_SPECS:
        kw = dict(spec)
        if kw.pop("hierarchy", False):
            if not hier:
                continue          # no two-tier split exists here
            kw["hierarchy"] = hier
        legs.append((tag, CommPolicy(axes=("data",), **kw)))
    return legs


def run_comm_ab(metric_prefix: str = "comm_ab") -> "list | None":
    """Emit every comm A/B leg (inline on a multi-device mesh, else via
    the CPU-mesh proxy subprocess).  Returns the leg records when run
    inline (bench.py --compare feeds them to the ledger); None when
    the subprocess emitted them."""
    import jax

    if jax.device_count() >= 2:
        return _run_legs_inline(metric_prefix)
    # single-device session: 8-virtual-device CPU proxy in a child
    # process (the XLA flag must precede backend init, hence the spawn)
    env = dict(os.environ)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8"
                        ).strip()
    env["JAX_PLATFORMS"] = "cpu"
    env["RLT_COMM_AB_METRIC"] = f"{metric_prefix}_cpu_proxy8"
    subprocess.run([sys.executable, "-m", "benchmarks.bench_comm"],
                   env=env, check=True)
    return None


#: warm-tail dispatches traced on the overlap legs for the measured
#: exposed-comm figure
TRACE_STEPS = 4

#: the legs whose measured-vs-proxy divergence the overlap comparison
#: reads (same codec/bytes; only the barrier differs)
OVERLAP_LEGS = ("int8_bucketed", "int8_barrier")


def _run_legs_inline(metric_prefix: str) -> list:
    import shutil

    import jax

    from benchmarks.harness import run_steps_per_sec
    from ray_lightning_tpu.models.gpt import GPTLightningModule
    from ray_lightning_tpu.telemetry import metrics as _metrics

    world = jax.device_count()
    multi = jax.process_count() > 1
    batch = max(8, world)
    steps = WARMUP + TIMED + 4 + TRACE_STEPS

    def leg(tag, policy, extra=None, trace_steps=0, strategy=None):
        module = GPTLightningModule("tiny", dataset_size=batch * steps,
                                    batch_size=batch)
        kwargs = {"comm_policy": policy} if policy is not None else {}
        res = run_steps_per_sec(
            module, f"{metric_prefix}_{tag}", warmup=WARMUP, timed=TIMED,
            strategy=strategy, trainer_kwargs=kwargs, telemetry=False,
            extra_fields=extra, trace_steps=trace_steps)
        if res.get("trace_dir"):
            shutil.rmtree(res.pop("trace_dir"), ignore_errors=True)
        return res

    # comm-off floor: the same model/mesh with the partitioner's
    # implicit fp32 sync — every leg's exposed seconds subtract it
    floor = leg("fp32", None)
    floor_s = 1.0 / floor["value"]

    def differential(res):
        step_s = 1.0 / res["value"]
        out = {"step_seconds": round(step_s, 6),
               "exposed_comm_seconds": round(step_s - floor_s, 6)}
        measured = (res.get("anatomy") or {}).get("exposed_s")
        if measured is not None:
            # trace-measured exposed comm next to the proxy: the
            # divergence is the quantize/dequantize + host share the
            # subtraction cannot separate from serialization
            out["measured_exposed_comm_seconds"] = round(measured, 6)
            out["exposed_divergence_seconds"] = round(
                (step_s - floor_s) - measured, 6)
        return out

    results = [floor]
    exposed, measured = {}, {}
    for tag, policy in _legs(world, multi):
        res = leg(tag, policy, extra=differential,
                  trace_steps=TRACE_STEPS if tag in OVERLAP_LEGS else 0)
        results.append(res)
        exposed[tag] = res["exposed_comm_seconds"]
        measured[tag] = res.get("measured_exposed_comm_seconds")
    if all(t in exposed for t in OVERLAP_LEGS):
        # the measured figure feeds the gauge when a trace parsed; the
        # proxy stays the fallback (gauge's source label says which)
        if measured["int8_bucketed"] is not None:
            _metrics.note_exposed_comm(max(measured["int8_bucketed"], 0.0),
                                       source="anatomy")
        else:
            _metrics.note_exposed_comm(max(exposed["int8_bucketed"], 0.0))
        summary = {
            "metric": f"{metric_prefix}_overlap_win",
            "barrier_exposed_s": round(exposed["int8_barrier"], 6),
            "bucketed_exposed_s": round(exposed["int8_bucketed"], 6),
            "overlap_wins": bool(exposed["int8_bucketed"]
                                 < exposed["int8_barrier"]),
            "barrier_measured_exposed_s": measured["int8_barrier"],
            "bucketed_measured_exposed_s": measured["int8_bucketed"],
            "note": "exposed_s = wall minus same-process fp32 floor; "
                    "measured_* = trace-interval overlap "
                    "(telemetry/anatomy.py).  Divergence between the "
                    "two is codec compute + host jitter the proxy "
                    "cannot separate; on the serial CPU proxy measured "
                    "exposed ≈ collective (no overlap possible — the "
                    "real-fabric leg is ROADMAP item 5)",
        }
        print(json.dumps(summary))
        results.append(summary)

    # ZeRO-1 updated-param gather pair (ops/flash_decode PR's train
    # leg): identical int8 reduction + explicit fp32 gather; the only
    # difference is WHEN the gathers may issue — consumption-ordered
    # buckets, each depending on its own leaves, vs one
    # optimization_barrier tying the COMPLETE updated tree before any
    # gather (the monolithic end-of-step construction).
    from ray_lightning_tpu.comm import CommPolicy

    z_floor = leg("zero1_int8", CommPolicy(compress="int8",
                                           axes=("data",)),
                  strategy="zero1")
    z_floor_s = 1.0 / z_floor["value"]

    def gather_differential(res):
        step_s = 1.0 / res["value"]
        out = {"step_seconds": round(step_s, 6),
               "exposed_comm_seconds": round(step_s - z_floor_s, 6)}
        m = (res.get("anatomy") or {}).get("exposed_s")
        if m is not None:
            out["measured_exposed_comm_seconds"] = round(m, 6)
            out["exposed_divergence_seconds"] = round(
                (step_s - z_floor_s) - m, 6)
        return out

    gather_pair = (
        ("zero1_gather_bucketed",
         CommPolicy(compress="int8", axes=("data",),
                    gather_bucket_bytes=1 << 20)),
        ("zero1_gather_barrier",
         CommPolicy(compress="int8", axes=("data",),
                    gather_bucket_bytes=1 << 20, barrier_sync=True)),
    )
    g_exposed, g_measured = {}, {}
    results.append(z_floor)
    for tag, policy in gather_pair:
        res = leg(tag, policy, extra=gather_differential,
                  trace_steps=TRACE_STEPS, strategy="zero1")
        results.append(res)
        g_exposed[tag] = res["exposed_comm_seconds"]
        g_measured[tag] = res.get("measured_exposed_comm_seconds")
    summary = {
        "metric": f"{metric_prefix}_gather_overlap",
        "barrier_exposed_s": round(
            g_exposed["zero1_gather_barrier"], 6),
        "bucketed_exposed_s": round(
            g_exposed["zero1_gather_bucketed"], 6),
        "barrier_measured_exposed_s":
            g_measured["zero1_gather_barrier"],
        "bucketed_measured_exposed_s":
            g_measured["zero1_gather_bucketed"],
        # judged on the TRACE-MEASURED exposure when a capture parsed
        # (the wall-minus-floor proxy is sub-noise at gather scale on
        # this model); wall proxy is the fallback
        "overlap_wins": bool(
            g_measured["zero1_gather_bucketed"]
            < g_measured["zero1_gather_barrier"]
            if None not in (g_measured["zero1_gather_bucketed"],
                            g_measured["zero1_gather_barrier"])
            else g_exposed["zero1_gather_bucketed"]
            < g_exposed["zero1_gather_barrier"]),
        "note": "exposed_s = wall minus same-process zero1+int8 floor "
                "(no explicit gather); measured_* = trace-interval "
                "overlap.  The same serial-executor caveat as the "
                "reduction pair applies on the CPU proxy — the "
                "scheduler freedom the buckets buy only pays on a "
                "fabric that can overlap (ROADMAP item 5)",
    }
    print(json.dumps(summary))
    results.append(summary)
    return results


def main() -> None:
    _run_legs_inline(os.environ.get("RLT_COMM_AB_METRIC", "comm_ab"))


if __name__ == "__main__":
    sys.exit(main())
