"""Serving-plane benchmark: continuous-batching throughput + latency.

Stands up a :class:`ray_lightning_tpu.serve.Server` fleet (CPU workers
by default; ``RLT_SERVE_WORKERS``/``RLT_SERVE_PLATFORM`` override),
drives a multi-tenant open-loop workload of mixed-length prompts, and
emits ONE ``serve`` JSON line with the acceptance numbers:

- ``tokens_per_sec``   — generated tokens / wall seconds
- ``ttft_p50_ms`` / ``ttft_p99_ms`` — time to first token percentiles
- ``tpot_p50_ms``      — steady decode time per output token
- ``batch_occupancy``  — mean live-slot fraction per decode step
- ``compile_cache``    — hit|miss|off (the compiled-once evidence)
- ``tracing``          — whether per-request tracing was live for the
  timed leg, plus ``per_tenant`` queue-wait p99 / decode attribution
  (trace plane, ISSUE 9) so the tracing overhead target (<2% tokens/s)
  is pinned in the bench trajectory
- ``RLT_SERVE_TRACE_AB=1`` adds a second timed leg with telemetry off
  and reports ``trace_overhead_pct`` directly

    python -m benchmarks.bench_serve [--requests N] [--slots S]
"""

from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np


def _percentile_ms(vals) -> "dict[str, float]":
    arr = np.asarray([v for v in vals if v is not None], dtype=float)
    if not len(arr):
        return {}
    return {"p50_ms": round(float(np.percentile(arr, 50)) * 1e3, 3),
            "p99_ms": round(float(np.percentile(arr, 99)) * 1e3, 3)}


def _run_leg(module, *, telemetry, requests, slots, max_new_tokens,
             buckets, num_workers, platform, vocab_size, root,
             spec=None):
    """One timed serve leg; returns (wall_s, reqs, stats)."""
    from ray_lightning_tpu.serve import Server
    server = Server(
        module,
        num_workers=num_workers, platform=platform,
        buckets=buckets, max_batch_slots=slots,
        max_new_tokens=max_new_tokens,
        default_root_dir=root,
        compile_cache=None,   # RLT_COMPILE_CACHE* env knobs apply
        telemetry=telemetry,
        spec=spec,
    ).start()
    rng = np.random.default_rng(0)
    tenants = ("alice", "bob", "carol")
    try:
        t0 = time.monotonic()
        reqs = []
        for i in range(requests):
            n = int(rng.integers(4, min(buckets[-1], 48)))
            prompt = rng.integers(1, vocab_size, size=n)
            reqs.append(server.submit(prompt,
                                      tenant=tenants[i % len(tenants)]))
        outs = [r.result(timeout=600) for r in reqs]
        wall = time.monotonic() - t0
    finally:
        stats = server.stats()
        server.shutdown()
    return wall, reqs, outs, stats


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--requests", type=int, default=24)
    parser.add_argument("--slots", type=int, default=8)
    parser.add_argument("--max-new-tokens", type=int, default=16)
    parser.add_argument("--config", default="tiny")
    args = parser.parse_args()

    from ray_lightning_tpu.compile import cache as compile_cache
    from ray_lightning_tpu.models.gpt import CONFIGS, GPTLightningModule

    cfg = CONFIGS[args.config]
    num_workers = int(os.environ.get("RLT_SERVE_WORKERS", "2"))
    platform = os.environ.get("RLT_SERVE_PLATFORM", "cpu")
    buckets = tuple(b for b in (16, 32, 64, 128, 256)
                    if b <= cfg.block_size) or (cfg.block_size,)
    root = os.environ.get("RLT_SERVE_DIR", "rlt_serve")
    leg = dict(requests=args.requests, slots=args.slots,
               max_new_tokens=args.max_new_tokens, buckets=buckets,
               num_workers=num_workers, platform=platform,
               vocab_size=cfg.vocab_size, root=root)

    wall, reqs, outs, stats = _run_leg(
        GPTLightningModule(args.config),
        telemetry={"metrics_port": 0}, **leg)

    total_tokens = sum(len(o) for o in outs)
    ttfts = np.asarray([r.ttft_s for r in reqs]) * 1e3
    tpots = np.asarray([r.tpot_s for r in reqs
                        if r.tpot_s is not None]) * 1e3
    sched = stats["scheduler"]
    workers = stats.get("workers", [])
    retraces = (max(sum(w["retraces"].values()) for w in workers)
                if workers else None)

    # per-tenant latency attribution (trace plane): queue-wait p99 and
    # the decode share of total request latency, from the request
    # handles' phase stamps — the same numbers /status serves live
    per_tenant: dict = {}
    for r in reqs:
        per_tenant.setdefault(r.tenant, []).append(r)
    tenant_rows = {}
    for tenant, rs in sorted(per_tenant.items()):
        queue = _percentile_ms(r.queue_wait_s for r in rs)
        decode = _percentile_ms(r.decode_s for r in rs)
        shares = [r.decode_s / (r.t_done - r.t_submit) for r in rs
                  if r.decode_s is not None and r.t_done > r.t_submit]
        tenant_rows[tenant] = {
            "requests": len(rs),
            "queue_wait_p99_ms": queue.get("p99_ms"),
            "decode_p50_ms": decode.get("p50_ms"),
            "decode_attribution": (round(sum(shares) / len(shares), 3)
                                   if shares else None),
        }

    serve = {
        "tokens_per_sec": round(total_tokens / wall, 2),
        "requests": len(reqs),
        "total_tokens": int(total_tokens),
        "wall_s": round(wall, 2),
        "ttft_p50_ms": round(float(np.percentile(ttfts, 50)), 2),
        "ttft_p99_ms": round(float(np.percentile(ttfts, 99)), 2),
        "tpot_p50_ms": (round(float(np.percentile(tpots, 50)), 2)
                        if len(tpots) else None),
        "decode_kernel": (workers[0].get("decode_kernel")
                          if workers else None),
        "batch_occupancy": round(sched["batch_occupancy"], 3),
        "tenants": len(tenant_rows),
        "workers": num_workers,
        "slots": args.slots,
        "buckets": list(buckets),
        "retraces_after_warmup": retraces,
        "compile_cache": compile_cache.status_word(),
        "tracing": True,
        "per_tenant": tenant_rows,
    }

    if os.environ.get("RLT_SPEC_BENCH", "1") != "0":
        # speculative-decoding leg: same workload with a layer-truncated
        # draft model drafting k tokens per round and ONE target forward
        # verifying them.  The CPU-proxy win metric is tokens per target
        # forward (> 1 means speculation amortized target compute —
        # CPU wall-clock is draft-dominated because every forward costs
        # the same here; on TPU the draft forwards are proportionally
        # cheap and the proxy converts into wall-clock tokens/s).
        spec_cfg = {
            "k": int(os.environ.get("RLT_SPEC_K", "4") or 4),
            "draft_layers": int(
                os.environ.get("RLT_SPEC_DRAFT_LAYERS", "0") or 0),
            "min_accept": float(
                os.environ.get("RLT_SPEC_MIN_ACCEPT", "0.1") or 0.1),
        }
        if os.environ.get("RLT_DRAFT_QUANT", "").strip():
            spec_cfg["draft_quant"] = os.environ["RLT_DRAFT_QUANT"].strip()
        wall_sp, reqs_sp, outs_sp, stats_sp = _run_leg(
            GPTLightningModule(args.config), telemetry=False,
            spec=spec_cfg, **leg)
        for o, o2 in zip(outs, outs_sp):
            assert list(o) == list(o2), "spec decode broke greedy parity"
        sp = stats_sp["scheduler"]["spec"]
        sp_workers = stats_sp.get("workers", [])
        sp_retraces = (max(sum(w["retraces"].values())
                           for w in sp_workers) if sp_workers else None)
        serve["spec"] = {
            "tokens_per_sec": round(
                sum(len(o) for o in outs_sp) / wall_sp, 2),
            "k": sp["k"],
            "acceptance_rate": sp["acceptance_rate"],
            "tokens_per_target_forward": sp["tokens_per_target_forward"],
            "drafted": sp["drafted"],
            "accepted": sp["accepted"],
            "fallbacks": sp["fallbacks"],
            "draft_quant": spec_cfg.get("draft_quant"),
            "retraces_after_warmup": sp_retraces,
        }
        if sp_workers and "spec" in sp_workers[0]:
            # draft-weight residency (int8 quant satellite): the HBM
            # delta vs a dedicated bf16 draft copy
            serve["spec"]["draft_hbm_delta_bytes"] = \
                sp_workers[0]["spec"].get("draft_hbm_delta_bytes")
        assert sp["tokens_per_target_forward"] > 1.0, sp
        if sp_retraces is not None:
            assert sp_retraces == 0, f"spec programs retraced: {sp_workers}"

    if os.environ.get("RLT_SERVE_TRACE_AB") == "1":
        # A/B leg with telemetry (and therefore per-request tracing)
        # fully off: pins the tracing overhead directly instead of
        # across bench rounds (target: <2% tokens/s)
        wall_off, _reqs2, outs2, _stats2 = _run_leg(
            GPTLightningModule(args.config), telemetry=False, **leg)
        tps_off = sum(len(o) for o in outs2) / wall_off
        serve["tokens_per_sec_tracing_off"] = round(tps_off, 2)
        serve["trace_overhead_pct"] = round(
            (tps_off - serve["tokens_per_sec"]) / tps_off * 100.0, 2)

    line = {
        "metric": "serve",
        "value": serve["tokens_per_sec"],
        "unit": "tokens/s",
        "serve": serve,
    }
    print(json.dumps(line), flush=True)
    assert sched["completed"] == len(reqs), sched
    if retraces is not None:
        assert retraces == 0, f"decode loop retraced: {workers}"


if __name__ == "__main__":
    main()
