"""Serving-plane benchmark: continuous-batching throughput + latency.

Stands up a :class:`ray_lightning_tpu.serve.Server` fleet (CPU workers
by default; ``RLT_SERVE_WORKERS``/``RLT_SERVE_PLATFORM`` override),
drives a multi-tenant open-loop workload of mixed-length prompts, and
emits ONE ``serve`` JSON line with the acceptance numbers:

- ``tokens_per_sec``   — generated tokens / wall seconds
- ``ttft_p50_ms`` / ``ttft_p99_ms`` — time to first token percentiles
- ``tpot_p50_ms``      — steady decode time per output token
- ``batch_occupancy``  — mean live-slot fraction per decode step
- ``compile_cache``    — hit|miss|off (the compiled-once evidence)

    python -m benchmarks.bench_serve [--requests N] [--slots S]
"""

from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--requests", type=int, default=24)
    parser.add_argument("--slots", type=int, default=8)
    parser.add_argument("--max-new-tokens", type=int, default=16)
    parser.add_argument("--config", default="tiny")
    args = parser.parse_args()

    from ray_lightning_tpu.compile import cache as compile_cache
    from ray_lightning_tpu.models.gpt import CONFIGS, GPTLightningModule
    from ray_lightning_tpu.serve import Server

    cfg = CONFIGS[args.config]
    num_workers = int(os.environ.get("RLT_SERVE_WORKERS", "2"))
    platform = os.environ.get("RLT_SERVE_PLATFORM", "cpu")
    buckets = tuple(b for b in (16, 32, 64, 128, 256)
                    if b <= cfg.block_size) or (cfg.block_size,)

    server = Server(
        GPTLightningModule(args.config),
        num_workers=num_workers, platform=platform,
        buckets=buckets, max_batch_slots=args.slots,
        max_new_tokens=args.max_new_tokens,
        default_root_dir=os.environ.get("RLT_SERVE_DIR", "rlt_serve"),
        compile_cache=None,   # RLT_COMPILE_CACHE* env knobs apply
        telemetry={"metrics_port": 0},
    ).start()

    rng = np.random.default_rng(0)
    tenants = ("alice", "bob", "carol")
    try:
        t0 = time.monotonic()
        reqs = []
        for i in range(args.requests):
            n = int(rng.integers(4, min(buckets[-1], 48)))
            prompt = rng.integers(1, cfg.vocab_size, size=n)
            reqs.append(server.submit(prompt,
                                      tenant=tenants[i % len(tenants)]))
        outs = [r.result(timeout=600) for r in reqs]
        wall = time.monotonic() - t0
    finally:
        stats = server.stats()
        server.shutdown()

    total_tokens = sum(len(o) for o in outs)
    ttfts = np.asarray([r.ttft_s for r in reqs]) * 1e3
    tpots = np.asarray([r.tpot_s for r in reqs
                        if r.tpot_s is not None]) * 1e3
    sched = stats["scheduler"]
    workers = stats.get("workers", [])
    retraces = (max(sum(w["retraces"].values()) for w in workers)
                if workers else None)
    line = {
        "metric": "serve",
        "value": round(total_tokens / wall, 2),
        "unit": "tokens/s",
        "serve": {
            "tokens_per_sec": round(total_tokens / wall, 2),
            "requests": len(reqs),
            "total_tokens": int(total_tokens),
            "wall_s": round(wall, 2),
            "ttft_p50_ms": round(float(np.percentile(ttfts, 50)), 2),
            "ttft_p99_ms": round(float(np.percentile(ttfts, 99)), 2),
            "tpot_p50_ms": (round(float(np.percentile(tpots, 50)), 2)
                            if len(tpots) else None),
            "batch_occupancy": round(sched["batch_occupancy"], 3),
            "tenants": len(tenants),
            "workers": num_workers,
            "slots": args.slots,
            "buckets": list(buckets),
            "retraces_after_warmup": retraces,
            "compile_cache": compile_cache.status_word(),
        },
    }
    print(json.dumps(line), flush=True)
    assert sched["completed"] == len(reqs), sched
    if retraces is not None:
        assert retraces == 0, f"decode loop retraced: {workers}"


if __name__ == "__main__":
    main()
