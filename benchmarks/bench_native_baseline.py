"""BASELINE.md's stated bar, measured: framework steps/sec must be
>= 90% of a hand-tuned raw-JAX training loop of the identical workload
(BASELINE.md "≥90% of native steps/sec"; VERDICT round-1 weak #1).

For each BASELINE workload (MNIST MLP #1, ResNet-50 #2, GPT-2 #5) this
script times

- **native**: a from-scratch loop a competent JAX user would write —
  ``jax.jit`` train step (value_and_grad + optax update) driven by a
  bare Python loop over pre-collected host batches, loss fetch as the
  only sync point.  The flax model definitions are shared with the
  framework (the bar measures loop/trainer machinery, not model code).
- **framework**: the full ``Trainer`` path via benchmarks/harness.py.

Each leg runs in its OWN subprocess: residual device buffers and jit
caches from one leg measurably depress the other on a shared chip
(measured: gpt2 framework 15.5 → 13.2 steps/s when run after the
native leg in-process), so in-process sequencing would understate
whichever leg runs second.

Output: the two absolute steps/sec lines (from the leg subprocesses),
then one ratio line per workload —
``{"metric": "<w>_framework_vs_native", "value": r, "unit": "ratio",
"vs_baseline": r/0.9}`` (vs_baseline >= 1.0 means the bar is met).

    python -m benchmarks.bench_native_baseline [mnist|resnet50|gpt2|
                                                bert_zero1|moe]

Each leg also emits a DEVICE-TIME line (median device ms/step of the
dominant XLA module from a warm-tail trace) and the parent a
``<w>_device_time_ratio`` — the tunnel-immune machinery measure: wall
ratios swing with the host link (resnet observed 0.54-1.19 across
windows), device ratios repeat to <1%.  BERT/MoE legs add an analytic
MFU estimate.  Measured round 5 (2 rounds, donated legs both sides):
wall / device — gpt2 1.00/1.003, resnet50 1.09/0.982,
bert_zero1 0.99/1.000, gpt2_medium 1.02/1.000 (matched `dots` at B=8),
moe 0.99/1.000 (at the `dots` default),
mnist 0.86-1.09/0.81 (the mnist device step is ~13-16 MICROseconds;
the residual gap is the per-step train-accuracy metric the module
logs — work the native loop doesn't do.  Deterministic modules declare
uses_rng=False so the step skips PRNG bookkeeping).  The load-bearing
claim: every transformer workload's device ratio is 1.000-1.003 and
resnet's 0.982, all >=0.97; mnist's BASELINE-specified wall bar
(>=0.9) holds within tunnel drift.

Round 5: the native steps donate their state (``donate_argnums=0`` —
standard raw-JAX practice the legs previously omitted).  That halves
native state residency, which is what let the profiler capture the
gpt2-medium/MoE native legs (round-4 RESOURCE_EXHAUSTED) and the
fp32-logits loop run `dots` at B=8 at all.
"""

from __future__ import annotations

import functools
import json
import os
import subprocess
import sys
import time

import jax
import numpy as np
import optax


def _collect_batches(loader, n):
    out = []
    while len(out) < n:
        for b in loader:
            out.append(b)
            if len(out) >= n:
                break
    return out


def _time_native(step, state, batches, fetch, warmup, timed,
                 trace_steps=None) -> float:
    for i in range(warmup):
        state = step(state, batches[i % len(batches)])
    fetch(state)
    t0 = time.monotonic()
    for i in range(timed):
        state = step(state, batches[(warmup + i) % len(batches)])
    fetch(state)
    rate = timed / (time.monotonic() - t0)
    _emit_device_ms(
        lambda st=state: _drive(step, st, batches, fetch, trace_steps),
        "native")
    return rate


def _drive(step, state, batches, fetch, steps=None):
    if steps is None:
        # big-model traces can exhaust the profiler's device buffer;
        # RLT_TRACE_STEPS shrinks the captured window
        steps = int(os.environ.get("RLT_TRACE_STEPS", "8"))
    for i in range(steps):
        state = step(state, batches[i % len(batches)])
    fetch(state)


_CURRENT_WORKLOAD = None  # set by --leg dispatch; names the device line


def _emit_device_ms(run, side: str) -> "float | None":
    """Trace ``run()`` (warm code) and emit the dominant XLA module's
    median device ms/step — the tunnel-immune counterpart of the wall
    steps/sec, captured AFTER the timed window so tracing overhead never
    contaminates the wall figure."""
    from benchmarks import trace_tools
    try:
        d = trace_tools.capture_trace(run)
    except Exception as e:  # profiler unavailable on some backends
        sys.stderr.write(f"device-time capture skipped: {e}\n")
        return None
    med = trace_tools.dominant_module_ms_or_none(d)
    if med is None:
        return None
    _emit(f"{_CURRENT_WORKLOAD}_{side}_device_ms", med, unit="ms/step")
    return med


def _emit(metric, value, unit="steps/sec", vs=None):
    line = {"metric": metric, "value": round(value, 3), "unit": unit}
    if vs is not None:
        line["vs_baseline"] = round(vs, 3)
    print(json.dumps(line), flush=True)
    return value


def _emit_framework_device(result: dict) -> "float | None":
    """Emit the framework device ms/step from a harness result that ran
    with ``trace_steps`` (the trace covers WARM steps of the same fit
    the wall clock measured — a fresh Trainer would recompile inside
    the trace window and the tunnel profiler would drop the events)."""
    from benchmarks import trace_tools
    med = trace_tools.dominant_module_ms_or_none(result.get("trace_dir"))
    if med is None:
        return None
    _emit(f"{_CURRENT_WORKLOAD}_framework_device_ms", med, unit="ms/step")
    return med


def _emit_mfu(module, device_ms: float, metric: str,
              peak_tflops: float = 197.0) -> None:
    """Analytic MFU from the module's own config: train FLOPs/step ≈
    3 × (2·N_active·tokens + 4·L·B·T²·C) against the v5e bf16 peak
    (embedding params counted — a PaLM-style estimate, not a bound).
    For MoE configs the expert parameters count at ``top_k/n_experts``
    (only the routed fraction does FLOPs per token).  Parameter sizes
    come from ``jax.eval_shape`` — no device memory or compile."""
    import jax as _jax

    model = module.configure_model()
    cfg = module.config
    B = module.batch_size
    T = cfg.block_size if hasattr(cfg, "block_size") else cfg.max_len
    x = np.zeros((B, T), np.int32)
    shapes = _jax.eval_shape(model.init, _jax.random.PRNGKey(0), x)
    params = shapes["params"]
    flat = {"/".join(str(getattr(k, "key", k)) for k in path):
            int(np.prod(v.shape))
            for path, v in
            _jax.tree_util.tree_flatten_with_path(params)[0]}
    total = sum(flat.values())
    moe = sum(v for k, v in flat.items() if "/moe/" in f"/{k}/")
    n_active = total - moe
    if moe and getattr(cfg, "n_experts", 0):
        n_active += moe * cfg.moe_top_k / cfg.n_experts
    tokens = B * T
    L = cfg.n_layer if hasattr(cfg, "n_layer") else cfg.num_layers
    C = cfg.n_embd if hasattr(cfg, "n_embd") else cfg.hidden
    flops = 3 * (2 * n_active * tokens + 4 * L * B * T * T * C)
    mfu = flops / (device_ms / 1e3) / (peak_tflops * 1e12)
    _emit(metric, mfu, unit="mfu")


def _init_like_framework(module, params, tx):
    """Mirror build_init_fn's precision recipe in the native legs: the
    optimizer snapshots full-precision masters BEFORE any residency
    downcast, then params adopt the module's param_dtype (bf16 for the
    GPT/BERT modules) — the native loop a competent user writes against
    these modules would do the same, and it keeps the comparison (and
    the HBM footprint) apples-to-apples."""
    import jax.numpy as jnp

    opt = tx.init(params)
    pd = getattr(module, "param_dtype", None)
    if pd is not None:
        params = jax.tree_util.tree_map(
            lambda x: x.astype(pd)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, params)
    return params, opt


# -- workload: MNIST MLP (BASELINE #1) --------------------------------------

MNIST_STEPS = (3, 100)   # warmup, timed


def _mnist_module():
    from ray_lightning_tpu.models.boring import LightningMNISTClassifier

    # dataset >= warmup+timed batches: ONE epoch covers the whole
    # measurement, so no epoch-boundary metric flush (a device_get sync)
    # stalls the pipeline mid-window — the same sizing bench.py uses
    warmup, timed = MNIST_STEPS
    return LightningMNISTClassifier(
        config={"batch_size": 128}, train_size=128 * (warmup + timed + 2))


def native_mnist(platform):
    from ray_lightning_tpu.models.boring import _MLP

    warmup, timed = MNIST_STEPS
    module = _mnist_module()
    batches = _collect_batches(module.train_dataloader(), warmup + timed)

    model = _MLP(module.hidden1, module.hidden2)
    tx = optax.adam(module.lr)
    params = model.init(jax.random.PRNGKey(0), batches[0][0])
    opt = tx.init(params)

    @functools.partial(jax.jit, donate_argnums=0)
    def step(state, batch):
        params, opt, _, _ = state
        x, y = batch

        def loss_fn(p):
            logits = model.apply(p, x)
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()
            # matched work: the framework module logs per-step train
            # accuracy in-graph (models/boring.py training_step); the
            # native leg computes the same metric so the mnist device
            # ratio compares equal programs — the round-5 README's
            # "remaining 3 µs is the accuracy metric" footnote is now a
            # measured comparison, not an explained residual
            import jax.numpy as jnp
            acc = jnp.mean((jnp.argmax(logits, -1) == y)
                           .astype(jnp.float32))
            return loss, acc

        (loss, acc), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt = tx.update(grads, opt, params)
        return optax.apply_updates(params, updates), opt, loss, acc

    native = _time_native(step, (params, opt, 0.0, 0.0), batches,
                          lambda s: float(np.asarray(s[2])), warmup, timed)
    _emit(f"mnist_native_steps_per_sec_{platform}", native)


def framework_mnist(platform):
    from benchmarks.harness import run_steps_per_sec

    warmup, timed = MNIST_STEPS
    res = run_steps_per_sec(_mnist_module(),
                            f"mnist_framework_steps_per_sec_{platform}",
                            warmup=warmup, timed=timed, trace_steps=8)
    _emit_framework_device(res)


# -- workload: ResNet-50 (BASELINE #2) --------------------------------------

RESNET_STEPS = (3, 30)


def _resnet_parts(platform):
    from ray_lightning_tpu.models.resnet import ResNetLightningModule

    cfg_name = "resnet50" if platform != "cpu" else "resnet18"
    batch = 128 if platform != "cpu" else 8
    warmup, timed = RESNET_STEPS
    module = ResNetLightningModule(
        cfg_name, batch_size=batch,
        train_size=batch * (warmup + timed + 2))
    return cfg_name, module


def native_resnet50(platform):
    from ray_lightning_tpu.models.resnet import CONFIGS, ResNet

    warmup, timed = RESNET_STEPS
    cfg_name, module = _resnet_parts(platform)
    batches = _collect_batches(module.train_dataloader(), warmup + timed)

    model = ResNet(CONFIGS[cfg_name])
    tx = module.configure_optimizers()
    variables = model.init(jax.random.PRNGKey(0), batches[0][0], True)
    params, batch_stats = variables["params"], variables["batch_stats"]
    opt = tx.init(params)

    @functools.partial(jax.jit, donate_argnums=0)
    def step(state, batch):
        params, batch_stats, opt, _ = state
        x, y = batch

        def loss_fn(p):
            logits, new = model.apply(
                {"params": p, "batch_stats": batch_stats}, x, True,
                mutable=["batch_stats"])
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()
            return loss, new["batch_stats"]

        (loss, new_bs), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt = tx.update(grads, opt, params)
        return (optax.apply_updates(params, updates), new_bs, opt, loss)

    native = _time_native(step, (params, batch_stats, opt, 0.0), batches,
                          lambda s: float(np.asarray(s[3])), warmup, timed)
    _emit(f"{cfg_name}_native_steps_per_sec_{platform}", native)


def framework_resnet50(platform):
    from benchmarks.harness import run_steps_per_sec

    warmup, timed = RESNET_STEPS
    cfg_name, module = _resnet_parts(platform)
    res = run_steps_per_sec(
        module, f"{cfg_name}_framework_steps_per_sec_{platform}",
        warmup=warmup, timed=timed, trace_steps=8)
    _emit_framework_device(res)


# -- workloads: GPT-2 small (BASELINE #5 headline) and medium (the remat
# regime, gateway to config #5's 1.3B) — one shared leg body -----------------

GPT_STEPS = (3, 30)
GPT_MEDIUM_STEPS = (3, 20)


def _gpt_module(platform, cfg_name, steps, batch=8):
    from ray_lightning_tpu.models.gpt import GPTLightningModule

    resolved = cfg_name if platform != "cpu" else "tiny"
    warmup, timed = steps
    return resolved, GPTLightningModule(
        resolved, dataset_size=batch * (warmup + timed + 2),
        batch_size=batch)


def _native_gpt_leg(platform, cfg_name, steps, remat_policy=None,
                    batch=8, trace_steps=None, label=None):
    """Raw-JAX loop over the named GPT config (optax full-logits CE —
    what a competent user writes, including ``donate_argnums=0``).
    ``remat_policy`` pins the native leg's policy independently of the
    config default for A/B sweeps.  Since round 5 the donated state
    fits the gpt2-medium loop's fp32 logits alongside "dots" even at
    B=8 (the round-4 runtime OOM was the un-donated state
    double-residency), so the default gpt2-medium comparison runs at
    matched policy; ``gpt2_medium_b4`` is the reduced-batch
    cross-check.  ``trace_steps`` shrinks the device-capture window
    (big-model traces exhaust the profiler's HBM buffer at the default
    8); ``label`` overrides the emitted metric name (the b4 variant
    must not collide with the B=8 lines)."""
    import dataclasses

    from ray_lightning_tpu.models.gpt import GPT

    warmup, timed = steps
    resolved, module = _gpt_module(platform, cfg_name, steps, batch=batch)
    label = label or cfg_name
    batches = _collect_batches(module.train_dataloader(), warmup + timed)

    config = module.config
    saved_policy = os.environ.get("RLT_REMAT_POLICY")
    try:
        if remat_policy is not None and config.remat:
            config = dataclasses.replace(config, remat_policy=remat_policy)
            # the sweep env knob (models/gpt._remat_policy) outranks the
            # config; pin it too, or a sweep run would drag the native leg
            # onto a policy it cannot execute (fp32-logits OOM at "dots")
            os.environ["RLT_REMAT_POLICY"] = remat_policy
        model = GPT(config)
        tx = module.configure_optimizers()
        params = model.init(jax.random.PRNGKey(0), batches[0][0])["params"]
        params, opt = _init_like_framework(module, params, tx)

        @functools.partial(jax.jit, donate_argnums=0)
        def step(state, batch):
            params, opt, _ = state
            x, y = batch

            def loss_fn(p):
                logits = model.apply({"params": p}, x, False)
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits, y).mean()

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt = tx.update(grads, opt, params)
            return optax.apply_updates(params, updates), opt, loss

        native = _time_native(step, (params, opt, 0.0), batches,
                              lambda s: float(np.asarray(s[2])),
                              warmup, timed, trace_steps=trace_steps)
        _emit(f"{label}_native_steps_per_sec_{platform}", native)
    finally:
        # the policy pin must not outlive the leg when legs share a
        # process (the subprocess-per-leg runner masks the leak)
        if saved_policy is None:
            os.environ.pop("RLT_REMAT_POLICY", None)
        else:
            os.environ["RLT_REMAT_POLICY"] = saved_policy


def _framework_gpt_leg(platform, cfg_name, steps, mfu: bool = False,
                       batch=8, trace_steps=8, label=None):
    from benchmarks.harness import run_steps_per_sec

    warmup, timed = steps
    _, module = _gpt_module(platform, cfg_name, steps, batch=batch)
    label = label or cfg_name
    res = run_steps_per_sec(
        module, f"{label}_framework_steps_per_sec_{platform}",
        warmup=warmup, timed=timed, trace_steps=trace_steps)
    med = _emit_framework_device(res)
    if med and mfu:
        # analytic MFU counts the MODEL's 3x fwd+bwd FLOPs only; remat
        # recompute is real extra device work on top, so this reads LOW
        # in the remat regime by construction
        _emit_mfu(module, med, f"{label}_model_mfu_{platform}")


def native_gpt2(platform):
    _native_gpt_leg(platform, "gpt2-small" if platform != "cpu"
                    else "tiny", GPT_STEPS)


def framework_gpt2(platform):
    _framework_gpt_leg(platform, "gpt2-small" if platform != "cpu"
                       else "tiny", GPT_STEPS)


def native_gpt2_medium(platform):
    # matched policy since round 5: with donate_argnums=0 on the native
    # step (standard raw-JAX practice the legs previously omitted) the
    # fp32-logits loop fits "dots" at B=8 — the round-4 runtime OOM was
    # the un-donated state double-residency, not the logits alone
    _native_gpt_leg(platform, "gpt2-medium", GPT_MEDIUM_STEPS,
                    remat_policy="dots", trace_steps=3)


def framework_gpt2_medium(platform):
    _framework_gpt_leg(platform, "gpt2-medium", GPT_MEDIUM_STEPS,
                       mfu=True)


def native_gpt2_medium_b4(platform):
    """Reduced-batch cross-check of the matched-policy comparison
    (VERDICT r4 next #1): both legs at ``dots`` and B=4 — a second
    point confirming the B=8 device ratio isn't a batch-size
    coincidence."""
    _native_gpt_leg(platform, "gpt2-medium", GPT_MEDIUM_STEPS,
                    remat_policy="dots", batch=4, trace_steps=3,
                    label="gpt2-medium-b4")


def framework_gpt2_medium_b4(platform):
    _framework_gpt_leg(platform, "gpt2-medium", GPT_MEDIUM_STEPS,
                       batch=4, trace_steps=3, label="gpt2-medium-b4")


# -- workload: BERT-base masked-LM, ZeRO-1 (BASELINE #4) ---------------------

BERT_STEPS = (3, 30)


def _bert_parts(platform):
    from ray_lightning_tpu.models.bert import BertMLMModule

    cfg_name = "bert-base" if platform != "cpu" else "tiny"
    batch = 32 if platform != "cpu" else 4
    warmup, timed = BERT_STEPS
    module = BertMLMModule(cfg_name, batch_size=batch,
                           train_size=batch * (warmup + timed + 2))
    return cfg_name, module


def native_bert_zero1(platform):
    """Raw-JAX loop of the identical MLM workload.  On one chip the
    zero1 annotations are identity, so the native equivalent is the
    plain loop — the ratio isolates the framework's sharded-path
    machinery cost at its single-chip degenerate point."""
    warmup, timed = BERT_STEPS
    cfg_name, module = _bert_parts(platform)
    batches = _collect_batches(module.train_dataloader(), warmup + timed)

    module.setup_model()
    model = module.model
    tx = module.configure_optimizers()
    rng = jax.random.PRNGKey(0)
    # the MLM loader passes (inputs, targets) through; the steps unpack
    # tokens from batch[0] — mirror that here
    batches = [b[0] if isinstance(b, (tuple, list)) else b
               for b in batches]
    params = model.init(rng, batches[0])["params"]
    params, opt = _init_like_framework(module, params, tx)

    @functools.partial(jax.jit, donate_argnums=0)
    def step(state, tokens):
        params, opt, loss_prev, rng = state
        rng, step_rng = jax.random.split(rng)

        def loss_fn(p):
            from ray_lightning_tpu.core.module import StepContext
            ctx = StepContext(module, p, {}, step_rng, training=True)
            return module._mlm_loss(ctx, tokens, step_rng)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt = tx.update(grads, opt, params)
        return (optax.apply_updates(params, updates), opt, loss, rng)

    native = _time_native(step, (params, opt, 0.0, rng), batches,
                          lambda s: float(np.asarray(s[2])), warmup, timed)
    _emit(f"bert_{cfg_name}_zero1_native_steps_per_sec_{platform}", native)


def framework_bert_zero1(platform):
    from benchmarks.harness import run_steps_per_sec

    warmup, timed = BERT_STEPS
    cfg_name, module = _bert_parts(platform)
    res = run_steps_per_sec(
        module, f"bert_{cfg_name}_zero1_framework_steps_per_sec_{platform}",
        warmup=warmup, timed=timed, strategy="zero1", trace_steps=8)
    med = _emit_framework_device(res)
    if med:
        _emit_mfu(module, med,
                  f"bert_{cfg_name}_zero1_mfu_{platform}")


# -- workload: MoE GPT, expert-parallel showcase -----------------------------

MOE_STEPS = (3, 20)


def _moe_parts(platform):
    from ray_lightning_tpu.models.gpt import GPTLightningModule

    cfg_name = "gpt2-moe-8e" if platform != "cpu" else "moe-tiny"
    batch = 8
    warmup, timed = MOE_STEPS
    module = GPTLightningModule(
        cfg_name, dataset_size=batch * (warmup + timed + 2),
        batch_size=batch)
    return cfg_name, module


def native_moe(platform):
    from ray_lightning_tpu.core.module import StepContext

    warmup, timed = MOE_STEPS
    cfg_name, module = _moe_parts(platform)
    batches = _collect_batches(module.train_dataloader(), warmup + timed)

    module.setup_model()
    tx = module.configure_optimizers()
    rng = jax.random.PRNGKey(0)
    variables = dict(module.init_params(rng, batches[0]))
    params = variables.pop("params")
    params, opt = _init_like_framework(module, params, tx)

    @functools.partial(jax.jit, donate_argnums=0)
    def step(state, batch):
        params, model_state, opt, _, rng = state
        rng, step_rng = jax.random.split(rng)

        def loss_fn(p):
            ctx = StepContext(module, p, model_state, step_rng,
                              training=True)
            loss = module.training_step(ctx, batch)
            return loss, ctx.model_state

        (loss, new_ms), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt = tx.update(grads, opt, params)
        return (optax.apply_updates(params, updates), new_ms, opt, loss,
                rng)

    # trace_steps=3: at the dots default the routed model's residents
    # leave too little HBM for the profiler's 8-step buffer (the round-4
    # RESOURCE_EXHAUSTED) — a 3-step window fits and device times repeat
    # to <1% between steps
    native = _time_native(step, (params, variables, opt, 0.0, rng),
                          batches, lambda s: float(np.asarray(s[3])),
                          warmup, timed, trace_steps=3)
    _emit(f"moe_{cfg_name}_native_steps_per_sec_{platform}", native)


def framework_moe(platform):
    from benchmarks.harness import run_steps_per_sec

    warmup, timed = MOE_STEPS
    cfg_name, module = _moe_parts(platform)
    res = run_steps_per_sec(
        module, f"moe_{cfg_name}_framework_steps_per_sec_{platform}",
        warmup=warmup, timed=timed, trace_steps=8)
    med = _emit_framework_device(res)
    if med:
        _emit_mfu(module, med,
                  f"moe_{cfg_name}_mfu_{platform}")


WORKLOADS = {
    "mnist": (native_mnist, framework_mnist),
    "resnet50": (native_resnet50, framework_resnet50),
    "gpt2": (native_gpt2, framework_gpt2),
    "gpt2_medium": (native_gpt2_medium, framework_gpt2_medium),
    "gpt2_medium_b4": (native_gpt2_medium_b4, framework_gpt2_medium_b4),
    "bert_zero1": (native_bert_zero1, framework_bert_zero1),
    "moe": (native_moe, framework_moe),
}


def _run_leg(leg: str) -> dict:
    """Spawn one leg as a fresh process; return {metric: value} for every
    JSON line it printed (steps/sec + device ms + mfu)."""
    proc = subprocess.run(
        [sys.executable, "-m", "benchmarks.bench_native_baseline",
         "--leg", leg],
        capture_output=True, text=True, env=os.environ.copy())
    out: dict = {}
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            print(line, flush=True)     # forward the absolute numbers
            rec = json.loads(line)
            out[rec["metric"]] = rec["value"]
    if proc.returncode != 0 or not out:
        sys.stderr.write(proc.stderr)
        raise RuntimeError(f"leg {leg} failed")
    return out


def _pick(metrics: dict, suffix: str) -> "float | None":
    for k, v in metrics.items():
        if suffix in k:
            return v
    return None


def main():
    global _CURRENT_WORKLOAD
    args = sys.argv[1:]
    if args[:1] == ["--leg"]:
        kind, name = args[1].split(":")
        _CURRENT_WORKLOAD = name
        platform = jax.devices()[0].platform
        WORKLOADS[name][0 if kind == "native" else 1](platform)
        return
    # alternate legs over several rounds and take each side's best: the
    # device link's throughput drifts minute-to-minute, so a single
    # native-then-framework pair confounds drift with overhead
    rounds = int(os.environ.get("RLT_BASELINE_ROUNDS", "2"))
    for name in args or list(WORKLOADS):
        native = framework = 0.0
        ndev = fdev = None
        for _ in range(rounds):
            nm = _run_leg(f"native:{name}")
            fm = _run_leg(f"framework:{name}")
            native = max(native, _pick(nm, "_native_steps_per_sec") or 0)
            framework = max(framework,
                            _pick(fm, "_framework_steps_per_sec") or 0)
            nd = _pick(nm, "_native_device_ms")
            fd = _pick(fm, "_framework_device_ms")
            ndev = min(ndev, nd) if (ndev and nd) else (nd or ndev)
            fdev = min(fdev, fd) if (fdev and fd) else (fd or fdev)
        ratio = framework / native
        _emit(f"{name}_framework_vs_native", ratio, unit="ratio",
              vs=ratio / 0.9)
        if ndev and fdev:
            # the tunnel-immune ratio: pure device time per step
            # (framework >= native means its compiled program is at
            # least as lean; the wall ratio adds host/tunnel luck)
            dratio = ndev / fdev
            _emit(f"{name}_device_time_ratio", dratio, unit="ratio",
                  vs=dratio / 0.9)


if __name__ == "__main__":
    main()
