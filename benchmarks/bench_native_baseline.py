"""BASELINE.md's stated bar, measured: framework steps/sec must be
>= 90% of a hand-tuned raw-JAX training loop of the identical workload
(BASELINE.md "≥90% of native steps/sec"; VERDICT round-1 weak #1).

For each BASELINE workload (MNIST MLP #1, ResNet-50 #2, GPT-2 #5) this
script times

- **native**: a from-scratch loop a competent JAX user would write —
  ``jax.jit`` train step (value_and_grad + optax update) driven by a
  bare Python loop over pre-collected host batches, loss fetch as the
  only sync point.  The flax model definitions are shared with the
  framework (the bar measures loop/trainer machinery, not model code).
- **framework**: the full ``Trainer`` path via benchmarks/harness.py.

Each leg runs in its OWN subprocess: residual device buffers and jit
caches from one leg measurably depress the other on a shared chip
(measured: gpt2 framework 15.5 → 13.2 steps/s when run after the
native leg in-process), so in-process sequencing would understate
whichever leg runs second.

Output: the two absolute steps/sec lines (from the leg subprocesses),
then one ratio line per workload —
``{"metric": "<w>_framework_vs_native", "value": r, "unit": "ratio",
"vs_baseline": r/0.9}`` (vs_baseline >= 1.0 means the bar is met).

    python -m benchmarks.bench_native_baseline [mnist|resnet50|gpt2]

Measured on one v5e chip (2026-07-30): gpt2 0.98, resnet50 1.19,
mnist 1.46 — the bar holds on every workload.  Ratios above 1.0 are
tunnel-bandwidth drift landing in the framework's favor (MNIST/ResNet
are transfer-bound on this link; the compiled step is identical either
way), not a real speedup; the load-bearing claim is the >=0.9 floor.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import jax
import numpy as np
import optax


def _collect_batches(loader, n):
    out = []
    while len(out) < n:
        for b in loader:
            out.append(b)
            if len(out) >= n:
                break
    return out


def _time_native(step, state, batches, fetch, warmup, timed) -> float:
    for i in range(warmup):
        state = step(state, batches[i % len(batches)])
    fetch(state)
    t0 = time.monotonic()
    for i in range(timed):
        state = step(state, batches[(warmup + i) % len(batches)])
    fetch(state)
    return timed / (time.monotonic() - t0)


def _emit(metric, value, unit="steps/sec", vs=None):
    line = {"metric": metric, "value": round(value, 3), "unit": unit}
    if vs is not None:
        line["vs_baseline"] = round(vs, 3)
    print(json.dumps(line), flush=True)
    return value


# -- workload: MNIST MLP (BASELINE #1) --------------------------------------

MNIST_STEPS = (3, 100)   # warmup, timed


def _mnist_module():
    from ray_lightning_tpu.models.boring import LightningMNISTClassifier

    # dataset >= warmup+timed batches: ONE epoch covers the whole
    # measurement, so no epoch-boundary metric flush (a device_get sync)
    # stalls the pipeline mid-window — the same sizing bench.py uses
    warmup, timed = MNIST_STEPS
    return LightningMNISTClassifier(
        config={"batch_size": 128}, train_size=128 * (warmup + timed + 2))


def native_mnist(platform):
    from ray_lightning_tpu.models.boring import _MLP

    warmup, timed = MNIST_STEPS
    module = _mnist_module()
    batches = _collect_batches(module.train_dataloader(), warmup + timed)

    model = _MLP(module.hidden1, module.hidden2)
    tx = optax.adam(module.lr)
    params = model.init(jax.random.PRNGKey(0), batches[0][0])
    opt = tx.init(params)

    @jax.jit
    def step(state, batch):
        params, opt, _ = state
        x, y = batch

        def loss_fn(p):
            logits = model.apply(p, x)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt = tx.update(grads, opt, params)
        return optax.apply_updates(params, updates), opt, loss

    native = _time_native(step, (params, opt, 0.0), batches,
                          lambda s: float(np.asarray(s[2])), warmup, timed)
    _emit(f"mnist_native_steps_per_sec_{platform}", native)


def framework_mnist(platform):
    from benchmarks.harness import run_steps_per_sec

    warmup, timed = MNIST_STEPS
    run_steps_per_sec(_mnist_module(),
                      f"mnist_framework_steps_per_sec_{platform}",
                      warmup=warmup, timed=timed)


# -- workload: ResNet-50 (BASELINE #2) --------------------------------------

RESNET_STEPS = (3, 30)


def _resnet_parts(platform):
    from ray_lightning_tpu.models.resnet import ResNetLightningModule

    cfg_name = "resnet50" if platform != "cpu" else "resnet18"
    batch = 128 if platform != "cpu" else 8
    warmup, timed = RESNET_STEPS
    module = ResNetLightningModule(
        cfg_name, batch_size=batch,
        train_size=batch * (warmup + timed + 2))
    return cfg_name, module


def native_resnet50(platform):
    from ray_lightning_tpu.models.resnet import CONFIGS, ResNet

    warmup, timed = RESNET_STEPS
    cfg_name, module = _resnet_parts(platform)
    batches = _collect_batches(module.train_dataloader(), warmup + timed)

    model = ResNet(CONFIGS[cfg_name])
    tx = module.configure_optimizers()
    variables = model.init(jax.random.PRNGKey(0), batches[0][0], True)
    params, batch_stats = variables["params"], variables["batch_stats"]
    opt = tx.init(params)

    @jax.jit
    def step(state, batch):
        params, batch_stats, opt, _ = state
        x, y = batch

        def loss_fn(p):
            logits, new = model.apply(
                {"params": p, "batch_stats": batch_stats}, x, True,
                mutable=["batch_stats"])
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()
            return loss, new["batch_stats"]

        (loss, new_bs), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt = tx.update(grads, opt, params)
        return (optax.apply_updates(params, updates), new_bs, opt, loss)

    native = _time_native(step, (params, batch_stats, opt, 0.0), batches,
                          lambda s: float(np.asarray(s[3])), warmup, timed)
    _emit(f"{cfg_name}_native_steps_per_sec_{platform}", native)


def framework_resnet50(platform):
    from benchmarks.harness import run_steps_per_sec

    warmup, timed = RESNET_STEPS
    cfg_name, module = _resnet_parts(platform)
    run_steps_per_sec(
        module, f"{cfg_name}_framework_steps_per_sec_{platform}",
        warmup=warmup, timed=timed)


# -- workload: GPT-2 (BASELINE #5 headline) ---------------------------------

GPT_STEPS = (3, 30)


def _gpt_parts(platform):
    from ray_lightning_tpu.models.gpt import GPTLightningModule

    cfg_name = "gpt2-small" if platform != "cpu" else "tiny"
    warmup, timed = GPT_STEPS
    module = GPTLightningModule(
        cfg_name, dataset_size=8 * (warmup + timed + 2), batch_size=8)
    return cfg_name, module


def native_gpt2(platform):
    from ray_lightning_tpu.models.gpt import GPT

    warmup, timed = GPT_STEPS
    cfg_name, module = _gpt_parts(platform)
    batches = _collect_batches(module.train_dataloader(), warmup + timed)

    model = GPT(module.config)
    tx = module.configure_optimizers()
    params = model.init(jax.random.PRNGKey(0), batches[0][0])["params"]
    opt = tx.init(params)

    @jax.jit
    def step(state, batch):
        params, opt, _ = state
        x, y = batch

        def loss_fn(p):
            logits = model.apply({"params": p}, x, False)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt = tx.update(grads, opt, params)
        return optax.apply_updates(params, updates), opt, loss

    native = _time_native(step, (params, opt, 0.0), batches,
                          lambda s: float(np.asarray(s[2])), warmup, timed)
    _emit(f"{cfg_name}_native_steps_per_sec_{platform}", native)


def framework_gpt2(platform):
    from benchmarks.harness import run_steps_per_sec

    warmup, timed = GPT_STEPS
    cfg_name, module = _gpt_parts(platform)
    run_steps_per_sec(
        module, f"{cfg_name}_framework_steps_per_sec_{platform}",
        warmup=warmup, timed=timed)


WORKLOADS = {
    "mnist": (native_mnist, framework_mnist),
    "resnet50": (native_resnet50, framework_resnet50),
    "gpt2": (native_gpt2, framework_gpt2),
}


def _run_leg(leg: str) -> float:
    """Spawn one leg as a fresh process; return its measured value."""
    proc = subprocess.run(
        [sys.executable, "-m", "benchmarks.bench_native_baseline",
         "--leg", leg],
        capture_output=True, text=True, env=os.environ.copy())
    value = None
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            print(line, flush=True)     # forward the absolute number
            value = json.loads(line)["value"]
    if proc.returncode != 0 or value is None:
        sys.stderr.write(proc.stderr)
        raise RuntimeError(f"leg {leg} failed")
    return value


def main():
    args = sys.argv[1:]
    if args[:1] == ["--leg"]:
        kind, name = args[1].split(":")
        platform = jax.devices()[0].platform
        WORKLOADS[name][0 if kind == "native" else 1](platform)
        return
    # alternate legs over several rounds and take each side's best: the
    # device link's throughput drifts minute-to-minute, so a single
    # native-then-framework pair confounds drift with overhead
    rounds = int(os.environ.get("RLT_BASELINE_ROUNDS", "2"))
    for name in args or list(WORKLOADS):
        native, framework = 0.0, 0.0
        for _ in range(rounds):
            native = max(native, _run_leg(f"native:{name}"))
            framework = max(framework, _run_leg(f"framework:{name}"))
        ratio = framework / native
        _emit(f"{name}_framework_vs_native", ratio, unit="ratio",
              vs=ratio / 0.9)


if __name__ == "__main__":
    main()
