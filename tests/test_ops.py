"""Pallas kernel tests (interpret mode on CPU, compiled on TPU).

Mirrors the reference's numeric-assertion style (weights-changed /
accuracy floors, reference: tests/utils.py:174-210) but at the kernel
level: flash output and gradients must match the naive attention to
tight fp32 tolerances.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_tpu.models.gpt import dot_product_attention
from ray_lightning_tpu.ops.flash_attention import flash_attention


def _rand_qkv(b=2, t=128, h=2, d=32, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (b, t, h, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("t", [64, 128, 256])
def test_flash_forward_matches_naive(causal, t):
    q, k, v = _rand_qkv(t=t)
    out = flash_attention(q, k, v, causal=causal, dtype=jnp.float32,
                          block_q=64, block_k=64)
    ref = dot_product_attention(q, k, v, causal=causal, dtype=jnp.float32)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_tri_decode_exact_for_all_indices():
    """The triangular-grid decode must be EXACT on every backend: the
    float sqrt is only an estimate (TPU's sqrt misrounds, e.g. i=6 →
    2.99999976) and the integer correction must land every index on the
    true (qi, kb) pair — a misdecode silently corrupts causal attention
    at T>=2048 where the tri path is default-on."""
    from ray_lightning_tpu.ops.flash_attention import (_tri_decode,
                                                       _tri_decode_rev)
    n = 64                                   # up to 64x64 block grids
    idx = jnp.arange(n * (n + 1) // 2)
    qi, kb = jax.jit(_tri_decode)(idx)
    expect = [(q, c) for q in range(n) for c in range(q + 1)]
    np.testing.assert_array_equal(np.asarray(qi), [e[0] for e in expect])
    np.testing.assert_array_equal(np.asarray(kb), [e[1] for e in expect])

    ki, qi2 = jax.jit(lambda i: _tri_decode_rev(i, n))(idx)
    # every (ki, qi2) pair covers the qi>=ki triangle exactly once,
    # contiguously per ki group, qi descending from n-1
    seen = list(zip(np.asarray(ki).tolist(), np.asarray(qi2).tolist()))
    assert sorted(seen) == sorted(
        (k, q) for k in range(n) for q in range(k, n))
    for a, b in zip(seen, seen[1:]):
        assert (b[0] == a[0] and b[1] == a[1] - 1) or \
            (b[0] == a[0] - 1 and b[1] == n - 1)


def test_flash_uneven_blocks():
    # T=96 forces the block picker to halve down to a divisor
    q, k, v = _rand_qkv(t=96)
    out = flash_attention(q, k, v, causal=True, dtype=jnp.float32)
    ref = dot_product_attention(q, k, v, causal=True, dtype=jnp.float32)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_grads_match_naive(causal):
    q, k, v = _rand_qkv(t=128)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, dtype=jnp.float32,
                            block_q=64, block_k=64)
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v):
        o = dot_product_attention(q, k, v, causal=causal, dtype=jnp.float32)
        return jnp.sum(jnp.sin(o))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-5,
                                   err_msg=f"d{name} mismatch")


def test_flash_under_jit_and_bf16():
    q, k, v = _rand_qkv(t=128, dtype=jnp.bfloat16)

    @jax.jit
    def f(q, k, v):
        return flash_attention(q, k, v, causal=True)

    out = f(q, k, v)
    assert out.dtype == jnp.bfloat16
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               atol=3e-2, rtol=3e-2)


def test_gpt_attention_impl_flash_trains():
    # end-to-end: tiny GPT with attention_impl="flash" takes a step
    from ray_lightning_tpu import Trainer
    from ray_lightning_tpu.models.gpt import GPTConfig, GPTLightningModule

    cfg = GPTConfig(vocab_size=128, block_size=64, n_layer=1, n_head=2,
                    n_embd=32, remat=False, attention_impl="flash")
    module = GPTLightningModule(cfg, dataset_size=16, batch_size=4)
    trainer = Trainer(max_steps=2, max_epochs=1, enable_checkpointing=False,
                      num_sanity_val_steps=0, limit_val_batches=0,
                      log_every_n_steps=1)
    trainer.fit(module)
    assert np.isfinite(float(trainer.callback_metrics["loss"]))


# -- head-packed single-block kernels (the production path at T<=1024) ------
#
# _head_pack engages when 128//d divides h; the default test shapes
# (h=2, d=32 → pack=4 ∤ 2) never hit it, so these cases pin the packed
# forward AND backward explicitly — a regression here would otherwise
# ship under a green suite while being the path the headline runs.

_PACKED_SHAPES = [
    (4, 32),    # pack=4 divides h=4
    (2, 64),    # pack=2 divides h=2 (the gpt2 head_dim)
    (2, 128),   # pack=1, d == lane width
]


@pytest.mark.parametrize("h,d", _PACKED_SHAPES)
@pytest.mark.parametrize("causal", [True, False])
def test_packed_forward_matches_naive(h, d, causal):
    from ray_lightning_tpu.ops.flash_attention import _head_pack
    assert _head_pack(d, h) > 0
    q, k, v = _rand_qkv(t=128, h=h, d=d)
    out = flash_attention(q, k, v, causal=causal, dtype=jnp.float32)
    ref = dot_product_attention(q, k, v, causal=causal, dtype=jnp.float32)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("h,d", _PACKED_SHAPES)
def test_packed_grads_match_naive(h, d):
    q, k, v = _rand_qkv(t=128, h=h, d=d)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=True, dtype=jnp.float32)
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v):
        o = dot_product_attention(q, k, v, causal=True, dtype=jnp.float32)
        return jnp.sum(jnp.sin(o))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-5,
                                   err_msg=f"d{name} mismatch")


def test_odd_head_count_falls_back_to_folded():
    """h=3 with d=64 (pack=2 ∤ 3) must take the folded path and still be
    correct — the dispatch seam between the two layouts."""
    from ray_lightning_tpu.ops.flash_attention import _head_pack
    assert _head_pack(64, 3) == 0
    q, k, v = _rand_qkv(t=128, h=3, d=64)
    out = flash_attention(q, k, v, causal=True, dtype=jnp.float32)
    ref = dot_product_attention(q, k, v, causal=True, dtype=jnp.float32)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("h,d", [(4, 32), (2, 64)])
def test_packed_triangular_multiblock(h, d):
    """Multi-block causal with square blocks engages the PACKED
    triangular-grid kernels (transpose-free [B,T,C] layout at T>=2048
    in production; forced here with small blocks) — forward and grads
    must match the XLA reference."""
    from ray_lightning_tpu.ops.flash_attention import _head_pack, _use_tri
    assert _head_pack(d, h) > 0
    assert _use_tri(True, 64, 64, 4)
    q, k, v = _rand_qkv(t=256, h=h, d=d)

    out = flash_attention(q, k, v, causal=True, dtype=jnp.float32,
                          block_q=64, block_k=64)
    ref = dot_product_attention(q, k, v, causal=True, dtype=jnp.float32)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=True, dtype=jnp.float32,
                            block_q=64, block_k=64)
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v):
        o = dot_product_attention(q, k, v, causal=True, dtype=jnp.float32)
        return jnp.sum(jnp.sin(o))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-5,
                                   err_msg=f"d{name} mismatch")


# -- causal staircase subtiling (the round-4 single-block fast path) --------
#
# _sub_block auto-engages at T>=512 (the production headline runs
# T=1024, sub=256); these tests force small sub sizes via RLT_FLASH_SUB
# so the staircase math is pinned at CI-friendly shapes, and one case
# pins the auto default at its threshold.


# (2,64)/(3,64): packed/folded with the sm_scale fold (1/8 is a power
# of two); (4,32): packed WITHOUT the fold (1/√32 has a non-trivial
# mantissa) so the `not fold` scaling branches are covered too.
@pytest.mark.parametrize("h,d", [(2, 64), (3, 64), (4, 32)])
def test_staircase_single_block_matches_full(h, d, monkeypatch):
    """Staircase on (sub=32 at T=128) must match staircase off bit-for-
    bit on dq/dv and to fp tolerance elsewhere, and match the XLA
    reference — for BOTH the head-packed and the folded fused kernels."""
    from ray_lightning_tpu.ops.flash_attention import _sub_block
    q, k, v = _rand_qkv(t=128, h=h, d=d)

    def loss(attn):
        def f(q, k, v):
            o = attn(q, k, v)
            return jnp.sum(jnp.sin(o))
        return f

    flash = loss(lambda q, k, v: flash_attention(
        q, k, v, causal=True, dtype=jnp.float32))
    ref = loss(lambda q, k, v: dot_product_attention(
        q, k, v, causal=True, dtype=jnp.float32))

    monkeypatch.setenv("RLT_FLASH_SUB", "0")
    assert _sub_block(128, True) == 0
    v_off = flash(q, k, v)
    g_off = jax.grad(flash, argnums=(0, 1, 2))(q, k, v)

    monkeypatch.setenv("RLT_FLASH_SUB", "32")
    assert _sub_block(128, True) == 32
    v_on = flash(q, k, v)
    g_on = jax.grad(flash, argnums=(0, 1, 2))(q, k, v)

    np.testing.assert_allclose(v_on, v_off, atol=1e-5, rtol=1e-5)
    for a, b, name in zip(g_on, g_off, "qkv"):
        np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5,
                                   err_msg=f"d{name} staircase vs full")
    g_ref = jax.grad(ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_on, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-5,
                                   err_msg=f"d{name} staircase vs ref")


def test_staircase_auto_threshold(monkeypatch):
    """The auto default: off below T=512, sub=256 at T in [512, 1024]
    (single-block territory), irrelevant past 1024 where the tiled tri
    grid takes over — and off for non-causal always."""
    from ray_lightning_tpu.ops.flash_attention import _sub_block
    monkeypatch.delenv("RLT_FLASH_SUB", raising=False)
    assert _sub_block(128, True) == 0
    assert _sub_block(256, True) == 0
    assert _sub_block(512, True) == 256
    assert _sub_block(1024, True) == 256
    assert _sub_block(1024, False) == 0


def test_staircase_env_malformed_warns_and_defaults(monkeypatch):
    """A typo'd opt-out like RLT_FLASH_SUB=off must warn and fall back
    to the auto default instead of crashing at trace time
    (ADVICE r4 #4)."""
    from ray_lightning_tpu.ops.flash_attention import _sub_block
    monkeypatch.setenv("RLT_FLASH_SUB", "off")
    with pytest.warns(UserWarning, match="RLT_FLASH_SUB"):
        assert _sub_block(512, True) == 256   # the auto default
    monkeypatch.setenv("RLT_FLASH_SUB", "")
    assert _sub_block(512, True) == 256       # empty: silent default


def test_rowres_gates_factor_head_width(monkeypatch):
    """The row-resident VMEM budgets were measured at w=128; wide heads
    (d >= 256 pack to w=d) must cap t·w, not t alone (ADVICE r4 #3)."""
    from ray_lightning_tpu.ops.flash_attention import (
        _use_row_resident, _use_row_resident_fwd)
    monkeypatch.delenv("RLT_FLASH_ROWRES", raising=False)
    assert _use_row_resident_fwd(8192, 128)        # the measured point
    assert not _use_row_resident_fwd(8192, 256)    # 2x resident k/v
    assert _use_row_resident_fwd(4096, 256)        # same t*w budget
    assert _use_row_resident(2048, 128)
    assert not _use_row_resident(2048, 256)
    assert _use_row_resident(1024, 256)
    monkeypatch.setenv("RLT_FLASH_ROWRES", "0")
    assert not _use_row_resident_fwd(1024, 128)


def test_staircase_non_causal_unaffected(monkeypatch):
    """Non-causal single block must ignore RLT_FLASH_SUB entirely."""
    monkeypatch.setenv("RLT_FLASH_SUB", "32")
    q, k, v = _rand_qkv(t=128, h=2, d=64)
    out = flash_attention(q, k, v, causal=False, dtype=jnp.float32)
    ref = dot_product_attention(q, k, v, causal=False, dtype=jnp.float32)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("sm_scale", [None, 0.1])  # fold and no-fold
@pytest.mark.parametrize("rowres", ["1", "0"])
def test_rowres_backward_matches_reference(rowres, sm_scale, monkeypatch):
    """The row-resident fused triangular backward (default at
    multi-block causal T<=2048) and the grid-tri pair it replaces must
    BOTH match the reference — the env A/B pins the dispatch seam and
    keeps the fallback path covered.  sm_scale=0.1 (not a power of
    two) exercises the no-fold scaling branches, checked against the
    full-precision einsum recipe directly (the XLA helper hardwires
    1/sqrt(d))."""
    from ray_lightning_tpu.ops.flash_attention import (_head_pack,
                                                       _use_row_resident)
    monkeypatch.setenv("RLT_FLASH_ROWRES", rowres)
    assert _use_row_resident(256) == (rowres == "1")
    assert _head_pack(64, 2) > 0
    q, k, v = _rand_qkv(t=256, h=2, d=64)
    scale = sm_scale if sm_scale is not None else 64 ** -0.5

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=True, dtype=jnp.float32,
                            sm_scale=sm_scale, block_q=64, block_k=64)
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        mask = np.tril(np.ones((256, 256), bool))
        s = jnp.where(mask, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
        return jnp.sum(jnp.sin(o))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-5,
                                   err_msg=f"d{name} rowres={rowres}")


def test_fwd_rowres_with_grid_tri_backward(monkeypatch):
    """The 2048 < T <= 8192 production combination: row-resident FORWARD
    (whose lse ships in the packed [B, H/pack, T, pack] layout) feeding
    the grid-tri backward.  Forced at small T by disabling only the
    backward gate — a layout drift between the two would break grads
    here."""
    import sys
    fa = sys.modules["ray_lightning_tpu.ops.flash_attention"]
    monkeypatch.setattr(fa, "_use_row_resident", lambda t, w=128: False)
    assert fa._use_row_resident_fwd(256)
    q, k, v = _rand_qkv(t=256, h=2, d=64)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=True, dtype=jnp.float32,
                            block_q=64, block_k=64)
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v):
        o = dot_product_attention(q, k, v, causal=True, dtype=jnp.float32)
        return jnp.sum(jnp.sin(o))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-5,
                                   err_msg=f"d{name} fwd-rowres+tri-bwd")


# -- decode kernel tier (ops/flash_decode.py) ------------------------------


def _rand_decode(s=4, L=256, h=2, d=32, seed=3, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (s, 1, h, d), dtype)
    kc = jax.random.normal(ks[1], (s, L, h, d), dtype)
    vc = jax.random.normal(ks[2], (s, L, h, d), dtype)
    return q, kc, vc


def _decode(impl, q, kc, vc, pos, dtype=jnp.float32, page_table=None):
    from ray_lightning_tpu.ops.attention import cached_attention
    return cached_attention(q, kc, vc, jnp.asarray(pos, jnp.int32),
                            dtype=dtype, impl=impl,
                            page_table=page_table)


def test_flash_decode_matches_dense_ragged():
    """Length-aware kernel vs the masked dense einsum across ragged
    per-slot positions — including position 0 (single valid index) and
    the last index of the cache."""
    q, kc, vc = _rand_decode()
    pos = [0, 17, 128, 255]
    ref = _decode("dense", q, kc, vc, pos)
    out = _decode("flash_decode", q, kc, vc, pos)
    assert out.shape == ref.shape == q.shape
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_flash_decode_single_slot():
    q, kc, vc = _rand_decode(s=1, L=128)
    ref = _decode("dense", q, kc, vc, [63])
    out = _decode("flash_decode", q, kc, vc, [63])
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_flash_decode_bf16_tolerance():
    """bf16 caches (the serve plane's storage dtype) stay within bf16
    rounding of the dense reference."""
    q, kc, vc = _rand_decode(dtype=jnp.bfloat16)
    pos = [5, 100, 200, 255]
    ref = _decode("dense", q, kc, vc, pos, dtype=jnp.bfloat16)
    out = _decode("flash_decode", q, kc, vc, pos, dtype=jnp.bfloat16)
    np.testing.assert_allclose(out.astype(jnp.float32),
                               ref.astype(jnp.float32),
                               atol=2e-2, rtol=2e-2)


def test_paged_decode_page_boundary_straddle():
    """The paged variant (identity page table — slot-contiguous cache)
    must agree with dense at positions ON and AROUND page boundaries,
    where an off-by-one in the table walk or the logical-position
    masking would surface, and agree bitwise with the slot-contiguous
    kernel at matching block size."""
    from ray_lightning_tpu.ops.flash_decode import flash_decode_attention
    from ray_lightning_tpu.serve.fleet.pages import identity_page_table
    page = 64
    q, kc, vc = _rand_decode(s=4, L=256)
    table = jnp.asarray(identity_page_table(4, 256, page))
    pos = [page - 1, page, 2 * page + 1, 255]
    ref = _decode("dense", q, kc, vc, pos)
    out = _decode("paged", q, kc, vc, pos, page_table=table)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
    flat = flash_decode_attention(
        q, kc, vc, jnp.asarray(pos, jnp.int32), dtype=jnp.float32,
        block_k=page)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(flat))


def test_dense_decode_fully_masked_no_nan():
    """satellite pin: the dense path masks with NEG_INF (-1e30), not
    finfo.min — a fully-masked row (position -1: nothing valid yet)
    softmaxes to finite uniform weights instead of NaN, and position 0
    reduces to exactly v[:, 0]."""
    q, kc, vc = _rand_decode(s=2, L=64)
    out = _decode("dense", q, kc, vc, [-1, 0])
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_allclose(out[1, 0], vc[1, 0], atol=2e-5, rtol=2e-5)
