"""Distributed-plugin tests over the built-in subprocess actor backend
(reference: tests/test_ddp.py — same pyramid, CPU workers standing in for
TPU hosts the way gloo stood in for NCCL).
"""

import os

import numpy as np
import pytest

from ray_lightning_tpu import (
    Callback,
    RayXlaPlugin,
    RayXlaShardedPlugin,
    Trainer,
)
from ray_lightning_tpu.core.data import DataLoader
from ray_lightning_tpu.models import BoringModel, LightningMNISTClassifier
from ray_lightning_tpu.models.boring import RandomDataset

from tests.utils import (
    cpu_plugin, get_trainer, load_test, predict_test, train_test)


# -- constructor / resource parsing (test_ddp.py:136-174 parity) ----------

def test_resources_per_worker_precedence():
    p = RayXlaPlugin(num_workers=2, num_cpus_per_worker=8,
                     resources_per_worker={"CPU": 3, "TPU": 4, "extra": 1})
    assert p.num_cpus_per_worker == 3
    assert p.use_tpu and p.devices_per_worker == 4
    assert p.additional_resources == {"extra": 1}
    res = p._worker_resources()
    assert res == {"CPU": 3, "extra": 1, "TPU": 4}


def test_invalid_num_workers():
    with pytest.raises(ValueError):
        RayXlaPlugin(num_workers=0)


def test_plugin_pickle_drops_handles():
    import pickle
    p = cpu_plugin()
    p._workers = ["sentinel"]
    q = pickle.loads(pickle.dumps(p))
    assert q._workers == []
    assert q.num_workers == 2


# -- rank topology (test_ddp.py:78-112 fake-node parity) ------------------

def test_assign_local_ranks_two_nodes():
    info = [{"ip": "1"}, {"ip": "2"}, {"ip": "1"}, {"ip": "2"}]
    ranks = RayXlaPlugin._assign_local_ranks(info)
    # node "1" gets global ranks 0,2; node "2" gets 1,3
    assert ranks[0] == (0, 0)
    assert ranks[2] == (0, 1)
    assert ranks[1] == (1, 0)
    assert ranks[3] == (1, 1)


# -- end-to-end train/load/predict × worker counts (test_ddp.py) ----------

@pytest.mark.parametrize("num_workers", [1, 2])
def test_train(tmp_path, seed, num_workers):
    trainer = get_trainer(str(tmp_path),
                          plugins=[cpu_plugin(num_workers)])
    train_test(trainer, BoringModel())


@pytest.mark.parametrize("num_workers", [2])
def test_load(tmp_path, seed, num_workers):
    trainer = get_trainer(str(tmp_path), plugins=[cpu_plugin(num_workers)])
    load_test(trainer, BoringModel())


def test_train_chunked_dispatch_across_actors(tmp_path, seed):
    """steps_per_execution under a multi-process mesh: the stacked batch
    rides make_array_from_process_local_data with leading-axis-replicated
    shardings inside each worker — the in_shardings path the local tests
    can't reach."""
    trainer = get_trainer(str(tmp_path), plugins=[cpu_plugin(2)],
                          max_epochs=1, limit_train_batches=8,
                          checkpoint=False, steps_per_execution=4)
    train_test(trainer, BoringModel(batch_size=8, dataset_length=128))
    assert trainer.global_step == 8


@pytest.mark.slow
def test_predict(tmp_path, seed):
    trainer = get_trainer(str(tmp_path), max_epochs=4,
                          limit_train_batches=16, limit_val_batches=2,
                          plugins=[cpu_plugin(2)])
    predict_test(trainer, LightningMNISTClassifier(
        config={"batch_size": 32}))


def test_metrics_and_progress_roundtrip(tmp_path, seed):
    """callback_metrics / epoch / global_step propagate driver-side after
    remote training (ray_ddp.py:366-370 analog)."""
    trainer = get_trainer(str(tmp_path), max_epochs=2, checkpoint=False,
                          plugins=[cpu_plugin(2)])
    trainer.fit(BoringModel())
    assert trainer.current_epoch == 2
    assert trainer.global_step == 20
    assert np.isfinite(trainer.callback_metrics["loss"])
    assert np.isfinite(trainer.callback_metrics["val_loss"])


def test_best_model_path_propagates(tmp_path, seed):
    trainer = get_trainer(str(tmp_path), plugins=[cpu_plugin(2)])
    trainer.fit(BoringModel())
    best = trainer.checkpoint_callback.best_model_path
    assert best and os.path.exists(best)


def test_init_hook_runs_on_workers(tmp_path, seed):
    """init_hook executes once per worker before training
    (examples/ray_ddp_tune.py:22-25 parity)."""
    marker_dir = str(tmp_path / "markers")
    os.makedirs(marker_dir, exist_ok=True)

    def hook():
        open(os.path.join(os.environ["RLT_MARKER_DIR"],
                          f"pid_{os.getpid()}"), "w").close()

    plugin = cpu_plugin(2, init_hook=hook,
                        worker_env={"RLT_MARKER_DIR": marker_dir})
    trainer = get_trainer(str(tmp_path), checkpoint=False,
                          plugins=[plugin])
    trainer.fit(BoringModel())
    assert len(os.listdir(marker_dir)) == 2


def test_worker_env_propagation(tmp_path, seed):
    """Env vars reach workers (set_env_vars parity, ray_ddp.py:206-219)
    asserted *inside* the remote worker via callback — the reference's
    assertion-via-callback idiom (test_ddp.py:184-204)."""

    class AssertEnv(Callback):
        def on_train_start(self, trainer, module):
            assert os.environ.get("RLT_CUSTOM") == "42"
            assert int(os.environ["RLT_NUM_PROCESSES"]) == 2

    trainer = get_trainer(str(tmp_path), checkpoint=False,
                          callbacks=[AssertEnv()],
                          plugins=[cpu_plugin(2, worker_env={
                              "RLT_CUSTOM": "42"})])
    trainer.fit(BoringModel())


def test_world_info_inside_workers(tmp_path, seed):
    """world_size/global_rank visible to remote code; failure inside the
    worker surfaces on the driver (util.py:61-63 error parity)."""

    class AssertWorld(Callback):
        def on_train_start(self, trainer, module):
            assert trainer.world_size == 2
            assert trainer.global_rank in (0, 1)

    trainer = get_trainer(str(tmp_path), checkpoint=False,
                          callbacks=[AssertWorld()],
                          plugins=[cpu_plugin(2)])
    trainer.fit(BoringModel())


def test_worker_failure_raises_on_driver(tmp_path, seed):
    class Boom(Callback):
        def on_train_start(self, trainer, module):
            raise RuntimeError("worker exploded")

    trainer = get_trainer(str(tmp_path), checkpoint=False,
                          callbacks=[Boom()], plugins=[cpu_plugin(2)])
    with pytest.raises(Exception, match="worker exploded"):
        trainer.fit(BoringModel())


def test_actors_torn_down(tmp_path, seed):
    plugin = cpu_plugin(2)
    trainer = get_trainer(str(tmp_path), checkpoint=False, plugins=[plugin])
    trainer.fit(BoringModel())
    assert plugin._workers == []   # ray.kill + clear parity (ray_ddp.py:383-386)


def test_evaluate_without_fit(tmp_path, seed):
    """trainer.test() without fit (test_ddp.py:230-237 parity)."""
    trainer = get_trainer(str(tmp_path), checkpoint=False,
                          plugins=[cpu_plugin(2)])
    out = trainer.test(BoringModel())
    assert "test_loss" in out[0]


# -- sharded plugin (test_ddp_sharded.py parity) --------------------------

def test_sharded_train(tmp_path, seed):
    trainer = get_trainer(str(tmp_path), checkpoint=False,
                          plugins=[RayXlaShardedPlugin(num_workers=2,
                                                       platform="cpu")])
    train_test(trainer, BoringModel())


def test_sharded_strategy_resolved():
    p = RayXlaShardedPlugin(num_workers=2, platform="cpu")
    assert p.strategy.name == "zero1"


@pytest.mark.slow
def test_sharded_resume_fewer_workers(tmp_path, seed):
    """Checkpoint from 2 sharded workers resumes on 1 worker
    (test_ddp_sharded.py:119-138 parity): checkpoints hold the full
    gathered state, so resharding is just re-distribution."""
    module = BoringModel()
    trainer = get_trainer(str(tmp_path), max_epochs=1,
                          plugins=[RayXlaShardedPlugin(num_workers=2,
                                                       platform="cpu")])
    trainer.fit(module)
    ckpt = trainer.checkpoint_callback.best_model_path
    assert ckpt and os.path.exists(ckpt)

    module2 = BoringModel()
    trainer2 = get_trainer(str(tmp_path / "resume"), max_epochs=2,
                           checkpoint=False,
                           plugins=[RayXlaShardedPlugin(num_workers=1,
                                                        platform="cpu")])
    trainer2.fit(module2, ckpt_path=ckpt)
    assert trainer2.current_epoch == 2


def test_checkpoint_equals_trained_weights(tmp_path, seed):
    """Saved checkpoint state equals the round-tripped weights
    (test_ddp_sharded.py:47-64 parity)."""
    module = BoringModel()
    trainer = get_trainer(str(tmp_path), plugins=[cpu_plugin(2)])
    trainer.fit(module)
    ckpt = Trainer.load_checkpoint_dict(
        trainer.checkpoint_callback.best_model_path)
    from flax import serialization
    trained = module._trained_variables["params"]
    saved = serialization.from_state_dict(trained, ckpt["state"]["params"])
    for a, b in zip(np.asarray(list(saved.values())[0]["kernel"]).ravel()[:3],
                    np.asarray(list(trained.values())[0]["kernel"]).ravel()[:3]):
        np.testing.assert_allclose(a, b, rtol=1e-6)


def test_cached_dataset_across_actors(tmp_path, seed):
    """cache_train_dataset under a multi-process mesh (VERDICT r2 #4):
    the flat cache is ONE global array (each worker materializes its
    devices' sample rows), the per-epoch repack is a global SPMD
    gather, and the cached step programs dispatch in lockstep.  The
    run must match the streamed actor run exactly — same steps, same
    final loss."""
    def run(cache):
        trainer = get_trainer(str(tmp_path / f"c{cache}"),
                              plugins=[cpu_plugin(2)], max_epochs=2,
                              limit_train_batches=8, checkpoint=False,
                              cache_train_dataset=cache, seed=0)
        trainer.fit(BoringModel(batch_size=8, dataset_length=128))
        assert trainer.global_step == 16
        return float(trainer.callback_metrics["loss"])

    streamed = run(False)
    cached = run(True)
    assert abs(cached - streamed) <= 1e-5 * max(1.0, abs(streamed)), \
        f"cached {cached} != streamed {streamed}"


def test_cached_chunked_across_actors(tmp_path, seed):
    """cache + steps_per_execution together across actors — the cached
    multi-step scan with a globally sharded device dataset."""
    trainer = get_trainer(str(tmp_path), plugins=[cpu_plugin(2)],
                          max_epochs=1, limit_train_batches=8,
                          checkpoint=False, steps_per_execution=4,
                          cache_train_dataset=True, seed=0)
    train_test(trainer, BoringModel(batch_size=8, dataset_length=128))
    assert trainer.global_step == 8


# -- the multi-process stream-prefetch seam (VERDICT r4 weak #4) -----------
#
# Round 4 lifted the process_count()==1 prefetch gate on the strength of
# the shared-loader contract ("every process prefetches in the same
# order").  These tests turn that comment into assertions: the env A/B
# pins that prefetch never changes math on a contract-respecting loader,
# and the canary proves a contract VIOLATION is now DETECTED — with
# RLT_DATA_CHECK=1 the workers relay per-step batch fingerprints and the
# driver raises naming the divergent rank (core/datacheck.py), instead
# of training on silently skewed batch pairings.


def _loss_traj_run(tmp_path, tag, module, prefetch, batches=8,
                   extra_env=None):
    """Actor-path run relaying rank-0's per-step loss sequence to the
    driver through a file (subprocess actors share the filesystem)."""
    import json
    out = str(tmp_path / f"{tag}.json")

    class DumpLosses(Callback):
        def __init__(self, path):
            self._path = path
            self._losses = []

        def on_train_batch_end(self, trainer, module, outputs, batch, idx):
            self._losses.append(
                float(np.asarray(outputs["loss"]).ravel()[-1]))

        def on_train_end(self, trainer, module):
            if trainer.global_rank == 0:
                with open(self._path, "w") as f:
                    json.dump(self._losses, f)

    plugin = cpu_plugin(2, worker_env={"RLT_STREAM_PREFETCH": prefetch,
                                       **(extra_env or {})})
    trainer = get_trainer(str(tmp_path / f"run_{tag}"), plugins=[plugin],
                          max_epochs=1, limit_train_batches=batches,
                          limit_val_batches=0, checkpoint=False,
                          callbacks=[DumpLosses(out)], seed=0)
    trainer.fit(module)
    assert trainer.global_step == batches
    with open(out) as f:
        traj = json.load(f)
    assert len(traj) == batches
    return traj


@pytest.fixture(scope="module")
def prefetch_on_traj(tmp_path_factory):
    """Rank-0 loss sequence of the contract-respecting prefetch=1 actor
    run — shared by the A/B and the canary test (one fewer 2-actor
    fit per suite run)."""
    from ray_lightning_tpu.utils.seed import seed_everything
    seed_everything(0)
    return _loss_traj_run(tmp_path_factory.mktemp("pf_on"), "pf_on",
                          BoringModel(batch_size=8, dataset_length=128),
                          "1")


def test_stream_prefetch_ab_across_actors(tmp_path, seed,
                                          prefetch_on_traj):
    """RLT_STREAM_PREFETCH=0 vs 1 across the actor path must be
    loss-sequence IDENTICAL: prefetch moves the host->device transfer
    under the previous step's compute, never the data it carries."""
    off = _loss_traj_run(tmp_path, "pf_off",
                         BoringModel(batch_size=8, dataset_length=128), "0")
    np.testing.assert_allclose(prefetch_on_traj, off, rtol=0, atol=0,
                               err_msg="prefetch changed training math")


def test_data_check_is_silent_on_honest_loader(tmp_path, seed,
                                               prefetch_on_traj):
    """RLT_DATA_CHECK=1 on a contract-respecting loader: the fit
    completes with the IDENTICAL loss sequence (the fingerprint relay
    observes, never perturbs)."""
    checked = _loss_traj_run(
        tmp_path, "dc_honest",
        BoringModel(batch_size=8, dataset_length=128), "1",
        extra_env={"RLT_DATA_CHECK": "1"})
    np.testing.assert_allclose(prefetch_on_traj, checked, rtol=0, atol=0,
                               err_msg="data check changed training math")


def test_divergent_loader_order_is_detected(tmp_path, seed):
    """A loader whose per-process order diverges beyond the shard stride
    used to train on SKEWED batch pairings silently (process A's step k
    met process B's step n-1-k); under RLT_DATA_CHECK=1 the workers
    relay per-step batch fingerprints over the queue and the DRIVER
    raises, naming the divergent rank (core/datacheck.py) — the canary
    flipped from documenting skew to detecting it.

    The canary classes live inside the test so cloudpickle ships them by
    value (module-level test classes serialize by reference, which the
    worker subprocess cannot import)."""

    class DivergentLoader(DataLoader):
        """Canary: rank-odd shards iterate their samples in REVERSED
        order — a violation of the shared-loader contract (every process
        must derive its order from the same loader state; only the shard
        stride may differ, core/data.py DataLoader.shard)."""

        def shard(self, num_shards, shard_index):
            clone = DivergentLoader(
                self.dataset, batch_size=self.batch_size,
                shuffle=self.shuffle, drop_last=self.drop_last,
                seed=self.seed, num_shards=num_shards,
                shard_index=shard_index)
            clone._epoch = self._epoch
            return clone

        def _indices(self):
            idx = super()._indices()
            return idx[::-1].copy() if self.shard_index % 2 else idx

    class DivergentBoring(BoringModel):
        def train_dataloader(self):
            return DivergentLoader(
                RandomDataset(32, self.dataset_length, 0),
                batch_size=self.batch_size)

    with pytest.raises(Exception, match="divergent data order"):
        _loss_traj_run(
            tmp_path, "dc_skew",
            DivergentBoring(batch_size=8, dataset_length=128), "1",
            extra_env={"RLT_DATA_CHECK": "1"})
