"""fp32_master / bf16-resident-param tests (ops/optim.py).

The mixed-precision recipe the gpt2 headline rides: resident params in
bf16 (no per-step fp32->bf16 kernel casts), fp32 master copy in the
optimizer state (FairScale-OSS-style full-precision ownership,
reference: ray_ddp_sharded.py:17-34).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ray_lightning_tpu.ops.optim import FP32MasterState, fp32_master


def _tree_bf16(tree):
    return jax.tree_util.tree_map(lambda x: x.astype(jnp.bfloat16), tree)


def test_resident_params_track_master_exactly():
    """After every step, resident params == cast(master) bit-for-bit."""
    tx = fp32_master(optax.adamw(1e-2))
    params32 = {"w": jnp.linspace(-1.0, 1.0, 64).reshape(8, 8),
                "b": jnp.ones((8,))}
    opt_state = tx.init(params32)
    params = _tree_bf16(params32)

    def loss_fn(p):
        return jnp.sum(p["w"] ** 2) + jnp.sum(jnp.abs(p["b"]))

    for _ in range(5):
        grads = jax.grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        expect = _tree_bf16(opt_state.master)
        for k in params:
            np.testing.assert_array_equal(np.asarray(params[k]),
                                          np.asarray(expect[k]))
            assert params[k].dtype == jnp.bfloat16


def test_master_initialized_from_full_precision():
    tx = fp32_master(optax.sgd(0.1))
    p32 = {"w": jnp.float32(0.3333333)}
    st = tx.init(p32)
    assert isinstance(st, FP32MasterState)
    assert st.master["w"].dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(st.master["w"]), 0.3333333)


def test_small_updates_accumulate_in_master_not_lost_to_bf16():
    """Updates below bf16 resolution still accumulate in the master —
    the reason the master exists.  1000 steps of 1e-4 on a param at 1.0
    moves a plain-bf16 path nowhere useful but the master path by ~0.1."""
    tx = fp32_master(optax.sgd(1e-4))
    params32 = {"w": jnp.ones(())}
    opt_state = tx.init(params32)
    params = _tree_bf16(params32)
    grads = {"w": jnp.ones((), jnp.bfloat16)}
    for _ in range(1000):
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
    np.testing.assert_allclose(np.asarray(opt_state.master["w"]), 0.9,
                               rtol=1e-3)
    np.testing.assert_allclose(np.asarray(params["w"], np.float32), 0.9,
                               rtol=1e-2)


def test_non_float_leaves_pass_through():
    tx = fp32_master(optax.sgd(0.1))
    params = {"w": jnp.ones((2,)), "steps": jnp.zeros((), jnp.int32)}
    st = tx.init(params)
    grads = {"w": jnp.ones((2,)), "steps": jnp.zeros((), jnp.int32)}
    updates, st = tx.update(grads, st, params)
    assert updates["steps"].dtype == jnp.int32
    np.testing.assert_array_equal(np.asarray(updates["steps"]), 0)


def test_update_without_params_raises():
    tx = fp32_master(optax.sgd(0.1))
    st = tx.init({"w": jnp.ones(())})
    with pytest.raises(ValueError, match="params"):
        tx.update({"w": jnp.ones(())}, st)


def test_gpt_bf16_resident_matches_fp32_trajectory(monkeypatch):
    """Tiny-GPT fit with bf16-resident params tracks the fp32 run: same
    data, same seed, losses within bf16 tolerance and both decreasing."""
    from ray_lightning_tpu.core.trainer import Trainer
    from ray_lightning_tpu.models.gpt import GPTLightningModule

    def run(bf16: bool):
        monkeypatch.setenv("RLT_BF16_PARAMS", "1" if bf16 else "0")
        model = GPTLightningModule("tiny", dataset_size=64, batch_size=8,
                                   lr=1e-3)
        trainer = Trainer(max_epochs=2, logger=False,
                          enable_checkpointing=False,
                          enable_progress_bar=False)
        trainer.fit(model)
        if bf16:
            p = trainer.state.params
            leaf = jax.tree_util.tree_leaves(p)[0]
            assert leaf.dtype == jnp.bfloat16
        return float(trainer.callback_metrics["loss"])

    final32 = run(False)
    final16 = run(True)
    assert np.isfinite(final16)
    # same objective, same data: the trajectories agree to bf16 noise
    assert abs(final16 - final32) < 0.15 * max(1.0, abs(final32))


def test_master_copy_shards_under_zero1(seed):
    """The fp32 master inside FP32MasterState must shard across the
    data axis under Zero1Strategy — the FairScale-OSS move (each rank
    owns a slice of the full-precision weights) expressed as a sharding
    annotation.  Its pytree path embeds the param path, so the
    strategies' opt-state rules apply to it like any optax state."""
    from ray_lightning_tpu.core.trainer import Trainer
    from ray_lightning_tpu.models.gpt import GPTLightningModule

    model = GPTLightningModule("tiny", dataset_size=32, batch_size=8)
    trainer = Trainer(max_steps=1, max_epochs=1, strategy="zero1",
                      enable_checkpointing=False, num_sanity_val_steps=0,
                      limit_val_batches=0, seed=0)
    trainer.fit(model)

    masters = trainer.state.opt_state.master
    leaves = jax.tree_util.tree_leaves(masters)
    assert leaves, "no master copy in optimizer state"
    sharded = [x for x in leaves
               if x.ndim > 0 and x.size > 1
               and any(s is not None for s in x.sharding.spec)]
    assert sharded, (
        "zero1 left every fp32 master replicated: "
        + str({tuple(x.shape): str(x.sharding) for x in leaves[:4]}))
    for x in leaves:
        assert x.dtype == jnp.float32
    # and the resident params stayed replicated low-precision (ZeRO-1
    # shards OPTIMIZER state, not params)
    p_leaves = jax.tree_util.tree_leaves(trainer.state.params)
    assert all(pl.dtype == jnp.bfloat16 for pl in p_leaves)
    assert all(not any(s is not None for s in pl.sharding.spec)
               for pl in p_leaves if pl.ndim > 0)
