"""Fleet serving plane (ray_lightning_tpu/serve/fleet/): paged-KV
prefix reuse, router policy, fleet-wide quotas, failover, and
signal-driven autoscaling.

Three tiers:

- host-only: PagePool/PrefixIndex/FleetConfig units and the paged
  Scheduler driven against fabricated fleet results (no jax work);
- engine-level: prefix reuse through the REAL copy/suffix programs with
  the token-parity-vs-cold-prefill bar (reused pages asserted > 0);
- router-level: a FleetServer over in-process fake replicas (real
  Scheduler + real routing/failover/autoscale machinery, fabricated
  step results) — deterministic and fast — plus the real-fleet e2e on
  the local backend (marked slow).
"""

import threading
import time

import numpy as np
import pytest

from ray_lightning_tpu.serve.fleet.config import FleetConfig
from ray_lightning_tpu.serve.fleet.pages import (
    PageConfig,
    PagedKV,
    PagePool,
    PrefixIndex,
)
from ray_lightning_tpu.serve.fleet.router import (
    FleetReplicaLost,
    FleetServer,
    pick_replica,
)
from ray_lightning_tpu.serve.scheduler import Scheduler


def test_pick_replica_least_loaded_sticky_slack():
    rows = [{"rid": 0, "active": 2, "queued": 1, "slots": 4},
            {"rid": 1, "active": 1, "queued": 0, "slots": 4},
            {"rid": 2, "active": 1, "queued": 1, "slots": 4}]
    assert pick_replica(rows) == 1                       # least loaded
    assert pick_replica(rows, sticky_rid=2) == 2         # within slack
    assert pick_replica(rows, sticky_rid=0,
                        sticky_slack=0) == 1             # past slack
    assert pick_replica([]) is None

def test_pick_replica_pool_routing():
    rows = [{"rid": 0, "active": 2, "queued": 0, "slots": 4,
             "role": "prefill"},
            {"rid": 1, "active": 0, "queued": 0, "slots": 4,
             "role": "decode"},
            {"rid": 2, "active": 1, "queued": 0, "slots": 4,
             "role": "prefill"}]
    assert pick_replica(rows, pool="prefill") == 2   # least loaded in pool
    assert pick_replica(rows, pool="decode") == 1
    # a pool that emptied (shrink/failover) degrades to pooled routing
    assert pick_replica([rows[0], rows[2]], pool="decode") == 2
    # bare rows carry no role: the filter matches nothing, falls back
    bare = [{"rid": 5, "active": 0, "queued": 0, "slots": 2}]
    assert pick_replica(bare, pool="prefill") == 5


def test_fleet_roles_config_validation_and_env_roundtrip(monkeypatch):
    cfg = FleetConfig(roles=("prefill", "decode"), kvship_codec="int8")
    for k, v in cfg.worker_env().items():
        monkeypatch.setenv(k, v)
    assert FleetConfig.resolve(None) == cfg
    assert [cfg.role_for(i) for i in range(4)] == \
        ["prefill", "decode", "prefill", "decode"]
    assert FleetConfig().role_for(3) == "pooled"     # no roles: pooled
    with pytest.raises(ValueError, match="role"):
        FleetConfig(roles=("prefill", "verify"))
    with pytest.raises(ValueError, match="kvship_codec"):
        FleetConfig(kvship_codec="zstd")


PAGED = PageConfig(enabled=True, page_size=8)


# -- pages: pool + index ---------------------------------------------------

def test_page_pool_accounting():
    pool = PagePool(slots=4, max_seq_len=32, page_size=8)
    assert pool.total_pages == 16
    pool.note_written(0, 1)
    pool.note_written(0, 17)                 # 3 pages, high-water
    assert pool.held(0) == 3 and pool.free == 13
    assert pool.shrink_to(0, 16) == 1        # donor keeps 2 prefix pages
    pool.check()
    assert pool.release(0) == 2 and pool.free == 16
    pool.check()
    with pytest.raises(ValueError):
        PagePool(slots=2, max_seq_len=8, page_size=16)


def test_prefix_index_longest_match_and_verification():
    idx = PrefixIndex(page_size=4)
    tokens = np.arange(50, 68, dtype=np.int32)      # 18 tokens
    assert idx.register(1, tokens, limit=31) == 16  # 4 whole pages
    # longest page-aligned match wins; exact tokens verified
    probe = np.concatenate([tokens[:12], [1, 2, 3, 4]])
    assert idx.lookup(probe) == (1, 12)
    assert idx.lookup(tokens[:3]) is None           # under a page
    diverged = tokens.copy()
    diverged[0] = 9
    assert idx.lookup(diverged) is None
    idx.drop(1)
    assert idx.lookup(tokens) is None


def test_paged_kv_retention_and_lru_eviction():
    kv = PagedKV(PageConfig(enabled=True, page_size=4), slots=2,
                 max_seq_len=16)
    a = np.arange(1, 9)
    kv.on_admit(0, a, computed=len(a))
    assert kv.retain(0) is True and kv.donor_count == 1
    b = np.arange(21, 29)
    kv.on_admit(1, b, computed=len(b))
    assert kv.retain(1) is True and kv.donor_count == 2
    # a lookup refreshes donor 0's LRU stamp, so 1 is evicted first
    assert kv.match(np.concatenate([a, [3, 3]])) == (0, 8)
    assert kv.evict_lru_donor() == 1
    kv.pool.check()
    assert kv.match(np.concatenate([b, [3]])) is None


def test_fleet_and_page_config_env_roundtrip(monkeypatch):
    cfg = FleetConfig(min_replicas=2, max_replicas=4,
                      grow_queue_depth=1.5, grow_ttft_p99_ms=100.0,
                      cooldown_s=3.0, tick_interval_s=0.2)
    for k, v in cfg.worker_env().items():
        monkeypatch.setenv(k, v)
    assert FleetConfig.resolve(None) == cfg
    pc = PageConfig(enabled=True, page_size=64)
    for k, v in pc.worker_env().items():
        monkeypatch.setenv(k, v)
    assert PageConfig.resolve(None) == pc
    monkeypatch.delenv("RLT_SERVE_PAGED")
    monkeypatch.delenv("RLT_SERVE_PAGE_SIZE")
    assert PageConfig.resolve(None) == PageConfig(enabled=False)
    # sugar forms
    assert PageConfig.resolve(True).enabled
    assert PageConfig.resolve(32).page_size == 32
    assert not PageConfig.resolve(False).enabled


def test_ledger_covers_serve_and_fleet_figures():
    """Satellite: the perf ledger gates serve-side fields (tokens/s,
    TTFT p99) from `serve`/`fleet` records, not just fit-side steps."""
    from benchmarks import ledger
    prev = [{"metric": "m", "unit": "tokens/s", "value": 1,
             "fleet": {"tokens_per_sec": 1000.0, "ttft_p99_ms": 50.0},
             "serve": {"tokens_per_sec": 500.0, "ttft_p99_ms": 20.0}}]
    ok = ledger.compare(prev, [{
        "metric": "m", "unit": "tokens/s", "value": 1,
        "fleet": {"tokens_per_sec": 950.0, "ttft_p99_ms": 55.0},
        "serve": {"tokens_per_sec": 480.0, "ttft_p99_ms": 21.0}}])
    assert ok["ok"] and ok["compared"] == 4, ok
    bad = ledger.compare(prev, [{
        "metric": "m", "unit": "tokens/s", "value": 1,
        "fleet": {"tokens_per_sec": 700.0, "ttft_p99_ms": 90.0},
        "serve": {"tokens_per_sec": 480.0, "ttft_p99_ms": 21.0}}])
    assert not bad["ok"]
    assert {x["figure"] for x in bad["regressions"]} \
        == {"fleet.tokens_per_sec", "fleet.ttft_p99_ms"}
    # sub-floor TTFT jitter is noise, not a regression
    floor = ledger.compare(
        [{"metric": "m", "serve": {"ttft_p99_ms": 1.0}}],
        [{"metric": "m", "serve": {"ttft_p99_ms": 2.4}}])
    assert floor["ok"], floor
    # federated prefix reuse: a collapse regresses; a sub-floor dip
    # (under 2 points of fraction) is replay noise
    fed = ledger.compare(
        [{"metric": "m", "fleet": {"federated_reuse_ratio": 0.5}}],
        [{"metric": "m", "fleet": {"federated_reuse_ratio": 0.1}}])
    assert not fed["ok"], fed
    assert fed["regressions"][0]["figure"] \
        == "fleet.federated_reuse_ratio", fed
    fed_ok = ledger.compare(
        [{"metric": "m", "fleet": {"federated_reuse_ratio": 0.05}}],
        [{"metric": "m", "fleet": {"federated_reuse_ratio": 0.04}}])
    assert fed_ok["ok"], fed_ok


# -- paged scheduler against a fabricated fleet ----------------------------

def _fake_step(sched):
    plan = sched.plan()
    if plan is None:
        return None
    result = {"prefill": {p["slot"]: 7 for p in plan["prefills"]},
              "decode": {}}
    if plan["decode"] is not None:
        result["decode"] = {s: 9 for s in plan["decode"]["slots"]}
    sched.apply(plan, result)
    return plan


def test_paged_scheduler_emits_reuse_and_retains_donors():
    sched = Scheduler(buckets=(16, 32), slots=2, max_seq_len=32,
                      max_prefills_per_step=1,
                      default_max_new_tokens=2, paged=PAGED)
    shared = np.arange(1, 17)                  # 2 whole pages
    r1 = sched.submit(np.concatenate([shared, [40]]))
    plans = [p for p in iter(lambda: _fake_step(sched), None)]
    assert r1.done() and all("reuse" not in p
                             for plan in plans
                             for p in plan["prefills"])
    assert sched.pages.donor_count == 1        # retained after finish
    # a later request with the same system prompt reuses the donor
    r2 = sched.submit(np.concatenate([shared, [50, 51]]))
    plan = sched.plan()
    entry = plan["prefills"][0]
    assert entry["reuse"]["matched"] == 16
    st = sched.pages.stats()
    assert st["prefill_tokens_requested"] > st["prefill_tokens_computed"]
    assert st["prefix_reuse_ratio"] > 0
    # idle-slot dummy decode writes aim at the LAST row under paging
    result = {"prefill": {entry["slot"]: 7}, "decode": {}}
    sched.apply(plan, result)
    plan2 = sched.plan()
    assert plan2["decode"] is not None
    dummies = [s for s in range(2) if s not in plan2["decode"]["slots"]]
    for s in dummies:
        assert plan2["decode"]["positions"][s] == 31
    sched.apply(plan2, {"prefill": {},
                        "decode": {s: 9 for s
                                   in plan2["decode"]["slots"]}})
    while not sched.idle():
        _fake_step(sched)
    assert r2.done()
    sched.pages.pool.check()


def test_paged_scheduler_evicts_donors_under_slot_pressure():
    sched = Scheduler(buckets=(16,), slots=2, max_seq_len=32,
                      max_prefills_per_step=2,
                      default_max_new_tokens=2, paged=PAGED)
    for i in range(2):
        sched.submit(np.arange(1, 10) + 20 * i)
    while not sched.idle():
        _fake_step(sched)
    assert sched.pages.donor_count == 2        # both slots retained
    assert sched.allocator.free_count == 0
    # new admissions must evict donors for slots — and succeed
    r = sched.submit(np.arange(100, 110))
    while not sched.idle():
        _fake_step(sched)
    assert r.done()
    sched.pages.pool.check()


def test_withdraw_queued_leaves_active_untouched():
    sched = Scheduler(buckets=(8,), slots=1, max_seq_len=16,
                      default_max_new_tokens=4)
    active = sched.submit([1, 2, 3])
    queued = [sched.submit([4, 5]) for _ in range(3)]
    _fake_step(sched)                          # admit the first
    out = sched.withdraw_queued()
    assert [r.id for r in out] == [r.id for r in queued]
    assert all(r.state == "withdrawn" and not r.done() for r in out)
    assert sched.queued_count == 0 and sched.active_count == 1
    while not sched.idle():
        _fake_step(sched)
    assert active.done()


# -- fake replicas: the router harness -------------------------------------

class _FakeServer:
    """Server-surface double: the REAL Scheduler under the router, with
    fabricated step results instead of an engine.  ``auto=False`` gives
    the test manual control over admission timing (failover tests need
    requests pinned in the queued-but-unprefilled state)."""

    def __init__(self, slots=2, step_delay=0.0, auto=True, paged=None):
        self.scheduler = Scheduler(buckets=(32,), slots=slots,
                                   max_seq_len=64,
                                   max_prefills_per_step=slots,
                                   default_max_new_tokens=3,
                                   paged=paged)
        self.max_batch_slots = slots
        self.step_delay = step_delay
        self.auto = auto
        self._error = None
        self.failure_report = None
        self.started = False
        self.shut_down = False
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        self.started = True
        if self.auto:
            self._thread = threading.Thread(target=self._pump,
                                            daemon=True)
            self._thread.start()
        return self

    def _pump(self):
        while not self._stop.is_set():
            if self._error is not None:
                return
            if self.step() is None:
                time.sleep(0.002)
            elif self.step_delay:
                time.sleep(self.step_delay)

    def step(self):
        plan = self.scheduler.plan()
        if plan is None:
            return None
        result = {"prefill": {p["slot"]: 7 for p in plan["prefills"]},
                  "decode": {}}
        if plan["decode"] is not None:
            result["decode"] = {s: 9 for s
                                in plan["decode"]["slots"]}
        self.scheduler.apply(plan, result)
        return plan

    def submit(self, prompt, tenant="default", max_new_tokens=None):
        if self._error is not None:
            raise RuntimeError("replica failed") from self._error
        return self.scheduler.submit(prompt, tenant=tenant,
                                     max_new_tokens=max_new_tokens)

    # -- KV-ship surface (federation pulls need both ends) -------------

    def can_ship_kv(self):
        return self.started and self.scheduler.pages is not None

    def can_adopt_kv(self):
        sched = self.scheduler
        if sched.pages is None:
            return False
        with sched._lock:
            return (sched.allocator.free_count > 0
                    or sched.pages.donor_count > 0)

    def export_kv(self, prompt_tokens, req_id=None):
        """Server.export_kv double: same match+pin-under-lock donor
        lookup, fabricated non-zero rows (arange, never zeros — an fp8
        quantize of all-zeros would divide by a zero scale)."""
        sched = self.scheduler
        if sched.pages is None or not self.started:
            return None
        if req_id is not None:
            boxed = sched.pop_kv_export(int(req_id))
            if boxed is not None:
                return boxed
        prompt_tokens = np.asarray(prompt_tokens,
                                   dtype=np.int32).reshape(-1)
        with sched._lock:
            hit = sched.pages.match(prompt_tokens)
            if hit is None:
                return None
            _, matched = hit
        rows = (np.arange(2 * int(matched) * 4, dtype=np.float32)
                .reshape(2, int(matched), 4) + 1.0)
        return rows, rows.copy(), int(matched)

    def import_kv(self, prompt_tokens, k_rows, v_rows):
        prompt_tokens = np.asarray(prompt_tokens,
                                   dtype=np.int32).reshape(-1)
        slot = self.scheduler.adopt_imported(prompt_tokens)
        if slot is None:
            return False
        self.scheduler.adopt_commit(slot, prompt_tokens)
        return True

    def die(self, error):
        """Simulate a mid-serve fleet failure: the pump's failure path
        (flight dumps + fail_all)."""
        self._error = error
        self.failure_report = {
            "cause": repr(error),
            "flight_paths": {0: "/tmp/flight_0.json"}}
        self.scheduler.fail_all(error)

    def goodput(self):
        """Synthetic finalized serve partition (telemetry/goodput.py)
        so router-level tests exercise fleet goodput aggregation —
        including the retired-replica fold — without an engine."""
        from ray_lightning_tpu.telemetry.goodput import GoodputLedger
        led = GoodputLedger("serve")
        led.note_step(1.0, k=4)
        led.add("prefill", 0.25)
        return led.finalize(2.0)

    def drain(self, timeout=None):
        deadline = time.monotonic() + (timeout or 10)
        while not self.scheduler.idle():
            if time.monotonic() > deadline:
                raise TimeoutError
            time.sleep(0.002)

    def shutdown(self, graceful=True):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5)
        self.shut_down = True


def _mk_fleet(n=2, factory=None, paged=False, autoscale=False,
              fleet=None, **kw):
    return FleetServer(
        object(), replicas=n, autoscale=autoscale, fleet=fleet,
        paged=paged, telemetry=False,
        replica_factory=factory or (lambda rid: _FakeServer()), **kw)


def _wait(predicate, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while not predicate():
        assert time.monotonic() < deadline, f"timed out on: {msg}"
        time.sleep(0.005)


def test_router_routes_and_completes_mixed_load():
    fleet = _mk_fleet(2).start()
    try:
        reqs = [fleet.submit(np.arange(1, 6), tenant=t)
                for t in ("a", "b", "a", "b", "a", "b", "c", "c")]
        outs = [r.result(timeout=10) for r in reqs]
        assert all(len(o) == 3 for o in outs)
        assert fleet.completed == 8 and fleet.failed == 0
        # stickiness recorded per tenant, and every tenant has a home
        with fleet._lock:
            assert set(fleet._sticky) == {"a", "b", "c"}
        # both replicas exist and served without failovers
        assert not fleet.failovers
        sig = fleet.signals()
        assert sig["replicas"] == 2 and sig["queued"] == 0
    finally:
        fleet.shutdown()


def test_router_fleet_wide_quota_holds_under_load():
    """A quota-1 tenant never holds more than one in-flight slot ACROSS
    replicas, while an unquoted tenant proceeds unimpeded (no
    head-of-line blocking)."""
    fleet = _mk_fleet(
        2, factory=lambda rid: _FakeServer(step_delay=0.01),
        tenant_quotas={"greedy": 1}).start()
    try:
        reqs = [fleet.submit(np.arange(1, 4), tenant="greedy")
                for _ in range(5)]
        quiet = [fleet.submit(np.arange(1, 4), tenant="quiet")
                 for _ in range(3)]
        peak = 0
        deadline = time.monotonic() + 10
        while not all(r.done() for r in reqs + quiet):
            assert time.monotonic() < deadline
            with fleet._lock:
                greedy_inflight = sum(
                    1 for fr in fleet._inflight.values()
                    if fr.tenant == "greedy")
            peak = max(peak, greedy_inflight)
            assert greedy_inflight <= 1, "fleet-wide quota violated"
            time.sleep(0.001)
        assert peak == 1            # the quota actually bound
        assert fleet.completed == 8
    finally:
        fleet.shutdown()


def test_router_failover_requeues_queued_fails_inflight():
    """The dying replica's queued-but-unprefilled requests complete on
    the survivor; its admitted in-flight request fails with the cause
    and the flight-recorder links."""
    servers = {}

    def factory(rid):
        servers[rid] = _FakeServer(slots=1, auto=False)
        return servers[rid]

    fleet = _mk_fleet(2, factory=factory,
                      fleet={"sticky_slack": 5}).start()
    try:
        # pin every dispatch onto replica 0 via stickiness (both idle,
        # rid 0 wins first; a wide sticky_slack keeps the tenant there
        # even as its queue grows)
        reqs = [fleet.submit(np.arange(1, 5), tenant="t")
                for _ in range(3)]
        _wait(lambda: all(r.inner is not None for r in reqs),
              msg="dispatch")
        assert {r.replica for r in reqs} == {0}
        servers[0].step()           # admit exactly one (slots=1)
        admitted = [r for r in reqs if r.inner.t_admit is not None]
        queued = [r for r in reqs if r.inner.t_admit is None]
        assert len(admitted) == 1 and len(queued) == 2
        servers[0].die(RuntimeError("chaos: replica 0 lost"))
        # router: requeue the queued two onto replica 1, fail the
        # admitted one with the flight-linked error
        _wait(lambda: admitted[0].done(), msg="in-flight failed")
        with pytest.raises(FleetReplicaLost, match="flight"):
            admitted[0].result(1)
        assert admitted[0].error.flight_paths == {
            0: "/tmp/flight_0.json"}
        _wait(lambda: all(r.replica == 1 for r in queued),
              msg="requeue to survivor")
        while not all(r.done() for r in queued):
            servers[1].step()
            time.sleep(0.002)
        assert all(len(r.result(1)) == 3 for r in queued)
        assert fleet.failovers and fleet.failovers[0]["requeued"] == 2 \
            and fleet.failovers[0]["failed"] == 1
        assert fleet.failovers[0]["flight_paths"]
        # replacement grow back toward min_replicas
        _wait(lambda: len([r for r in fleet._replicas.values()
                           if r.state == "serving"]) >= 2,
              msg="failover replacement")
    finally:
        fleet.shutdown(graceful=False)


def test_autoscaler_grow_and_shrink_through_router():
    """Queue pressure grows the fleet 1→2; the idle tail shrinks it
    back; no request is lost and the drained replica's requests
    complete elsewhere."""
    fleet = _mk_fleet(
        1, factory=lambda rid: _FakeServer(slots=1, step_delay=0.02),
        autoscale=True,
        fleet={"min_replicas": 1, "max_replicas": 2,
               "grow_queue_depth": 1.0, "patience_ticks": 1,
               "cooldown_s": 0.05, "tick_interval_s": 0.02}).start()
    try:
        reqs = [fleet.submit(np.arange(1, 6)) for _ in range(10)]
        _wait(lambda: fleet.autoscaler.stats()["grows"] >= 1,
              msg="grow event")
        outs = [r.result(timeout=20) for r in reqs]
        assert all(len(o) == 3 for o in outs)
        _wait(lambda: fleet.autoscaler.stats()["shrinks"] >= 1,
              timeout=20, msg="shrink event")
        _wait(lambda: len(fleet._replicas) == 1, timeout=20,
              msg="replica reaped")
        st = fleet.autoscaler.stats()
        assert st["events"][0]["action"] == "grow"
        assert st["events"][0]["seconds"] is not None
        assert fleet.failed == 0 and fleet.completed == 10
        # late requests still served after the shrink
        assert len(fleet.generate(np.arange(1, 4), timeout=10)) == 3

        # fleet goodput (telemetry/goodput.py): the reaped replica's
        # finalized doc is preserved next to the survivor's live peek,
        # and the autoscaler's actuation seconds extend the wall as
        # their own bucket — the identity holds on the aggregate by
        # construction
        from ray_lightning_tpu.telemetry.goodput import check_identity
        gp = fleet.goodput_stats()
        assert gp["kind"] == "serve" and gp["ranks"] >= 2
        assert check_identity(gp), gp
        assert gp["buckets"]["decode"] == pytest.approx(1.0 * gp["ranks"])
        # actuation seconds land in their own bucket (fake replicas
        # actuate in sub-ms, so the rounded event sum may be 0.0 —
        # equality, not >0, is the contract here)
        actuation = sum(e["seconds"] or 0.0
                        for e in fleet.autoscaler.stats()["events"])
        assert gp["buckets"]["autoscale"] == pytest.approx(
            actuation, abs=1e-6)
        assert fleet.status()["fleet"]["goodput"]["ranks"] == gp["ranks"]
    finally:
        fleet.shutdown()


def test_fleet_drain_rejects_new_and_settles():
    fleet = _mk_fleet(1).start()
    try:
        reqs = [fleet.submit(np.arange(1, 4)) for _ in range(4)]
        fleet.drain(timeout=10)
        assert all(r.done() for r in reqs)
        with pytest.raises(RuntimeError, match="draining"):
            fleet.submit([1, 2])
    finally:
        fleet.shutdown()


# -- prefix federation: the fleet-wide directory + pull-driven kvship ------


def test_prefix_directory_lifecycle_and_liveness():
    """register → lookup → invalidate round-trip, exclusion, ttl
    expiry under an injected clock, and the size bound (re-registration
    replaces — the directory can never outgrow retained pages)."""
    from ray_lightning_tpu.serve.fleet.federation import PrefixDirectory

    clock = [0.0]
    d = PrefixDirectory(page_size=8, ttl_s=5.0, clock=lambda: clock[0])
    base = np.arange(1, 25, dtype=np.int32)
    assert d.register(0, 2, base[:17]) == 16       # whole pages only
    assert d.register(1, 0, base) == 24
    assert d.lookup(base) == (1, 0, 24)            # longest wins
    assert d.lookup(base, exclude_rid=1) == (0, 2, 16)
    assert d.lookup(np.arange(100, 107)) is None   # sub-page: miss
    # re-registration REPLACES the donor's entry
    d.register(1, 0, base[:8])
    assert d.entries() == 2 and d.pages() == 2 + 1
    assert d.lookup(base) == (0, 2, 16)
    d.invalidate(0, 2)
    assert d.lookup(base) == (1, 0, 8)
    d.invalidate_replica(1)
    assert d.lookup(base) is None and d.entries() == 0
    # liveness: a wedged replica's advertisement ages out
    d.register(3, 1, base[:8])
    clock[0] = 4.0
    assert d.lookup(base) == (3, 1, 8)
    clock[0] = 6.0
    assert d.lookup(base) is None
    assert d.entries() == 0, "expired entry not pruned"
    assert d.stats()["invalidations"] == 2


def test_pick_replica_prefix_affinity_within_slack():
    rows = [{"rid": 0, "active": 2, "queued": 0, "slots": 4},
            {"rid": 1, "active": 0, "queued": 2, "slots": 4},
            {"rid": 2, "active": 0, "queued": 0, "slots": 4}]
    # the replica measured to hold the prefix wins inside the slack,
    # over least-loaded AND over stickiness; longest prefix wins ties
    assert pick_replica(rows, sticky_slack=2, affinity={1: 16}) == 1
    assert pick_replica(rows, sticky_rid=2, sticky_slack=2,
                        affinity={1: 16}) == 1
    assert pick_replica(rows, sticky_slack=2,
                        affinity={1: 8, 2: 16}) == 2
    # past the slack the pages get FETCHED instead of routed-to
    assert pick_replica(rows, sticky_slack=1, affinity={0: 16}) == 2
    assert pick_replica(rows, sticky_slack=0, affinity={1: 16}) == 2


def _mk_fed_fleet(fleet_extra=None, **fake_kw):
    """Two fake paged replicas under a federation-enabled router with
    manual stepping (auto=False): tests control exactly when each
    replica admits and completes."""
    servers = {}

    def factory(rid):
        servers[rid] = _FakeServer(slots=2, auto=False, paged=PAGED,
                                   **fake_kw)
        return servers[rid]

    cfg = {"sticky_slack": 0, "prefix_fed": True}
    cfg.update(fleet_extra or {})
    fleet = _mk_fleet(2, factory=factory, paged=PAGED, fleet=cfg)
    return fleet, servers


def _run_to_done(server, fr, timeout=10.0):
    """Step one fake replica until the fleet request completes (the
    router's poll loop finishes it off-thread)."""
    deadline = time.monotonic() + timeout
    while not fr.done():
        assert time.monotonic() < deadline, "request never completed"
        server.step()
        time.sleep(0.005)


def _seed_donor(fleet, servers, prompt, tenant="alice"):
    """Complete one request on replica 0 so its pages retain as a
    donor and advertise to the fleet directory."""
    r = fleet.submit(prompt, tenant=tenant)
    _wait(lambda: servers[0].scheduler.queued_count
          + servers[0].scheduler.active_count > 0,
          msg="seed request admitted on replica 0")
    _run_to_done(servers[0], r)
    _wait(lambda: fleet.directory.entries() >= 1,
          msg="donor advertised to the directory")
    return r


def test_router_federated_fetch_installs_remote_prefix():
    """The tentpole path end-to-end at the router tier: a prefix
    prefilled on replica 0 is PULLED by replica 1 over the kvship
    plane on a directory hit — the admission computes only the suffix
    (federated_tokens_reused), the wire bytes land in the federation
    counters, and the fetch seconds land in the kv_fed goodput
    bucket, distinct from prefill."""
    fleet, servers = _mk_fed_fleet()
    fleet.start()
    try:
        shared = np.arange(1, 17)               # 2 whole pages
        _seed_donor(fleet, servers, shared)
        # occupy replica 0 so slack-0 routing sends the next request
        # to replica 1 (which holds nothing)
        filler = fleet.submit(np.arange(40, 52), tenant="carol")
        _wait(lambda: servers[0].scheduler.queued_count > 0,
              msg="filler queued on replica 0")
        servers[0].step()                        # admit, don't finish
        target = fleet.submit(np.concatenate([shared, [99]]),
                              tenant="bob")
        _wait(lambda: servers[1].scheduler.queued_count
              + servers[1].scheduler.active_count > 0,
              msg="target submitted on replica 1 after the fetch")
        _run_to_done(servers[1], target)
        assert list(target.result(0)) == [7, 9, 9]
        fed = fleet.federation
        assert fed["hits"] == 1 and fed["fetches"] == 1 \
            and fed["ships"] == 1, fed
        assert fed["bytes_wire"] > 0 \
            and fed["bytes_raw"] > fed["bytes_wire"], fed
        st1 = servers[1].scheduler.pages.stats()
        assert st1["remote_imports"] == 1, st1
        # prompt is 17 tokens, 16 arrived over the wire: only the
        # suffix token was computed locally
        assert st1["federated_tokens_reused"] == 16, st1
        pages = fleet.pages_stats()
        assert pages["federated_tokens_reused"] == 16 \
            and pages["federated_reuse_ratio"] > 0, pages
        doc = fleet.status()["fleet"]
        assert doc["federation"]["compression_ratio"] > 1, doc
        assert doc["federation"]["directory"]["entries"] >= 1
        gp = fleet.goodput_stats()
        assert gp["buckets"].get("kv_fed", 0) > 0, \
            "federated wire seconds must land in their own bucket"
        _run_to_done(servers[0], filler)
    finally:
        fleet.shutdown(graceful=False)


def test_router_federated_fetch_stale_donor_heals_and_prefills():
    """The lookup→fetch race (satellite 2): the donor evicts between
    the directory hit and the export — the fetch comes back empty,
    the stale entry is healed, and the request falls over to a LOCAL
    prefill with exact tokens (counted, never wedged)."""
    fleet, servers = _mk_fed_fleet()
    fleet.start()
    try:
        shared = np.arange(1, 17)
        _seed_donor(fleet, servers, shared)
        # evict the donor BEHIND the directory's back (hooks bypassed)
        # so the directory entry goes stale exactly like a donor dying
        # between lookup and fetch
        pages = servers[0].scheduler.pages
        with servers[0].scheduler._lock:
            slot = next(iter(pages._donors))
            pages._donors.pop(slot)
            pages.index.drop(slot)
        assert fleet.directory.entries() == 1    # stale on purpose
        filler = fleet.submit(np.arange(40, 52), tenant="carol")
        _wait(lambda: servers[0].scheduler.queued_count > 0,
              msg="filler queued")
        servers[0].step()
        target = fleet.submit(np.concatenate([shared, [99]]),
                              tenant="bob")
        _wait(lambda: servers[1].scheduler.queued_count
              + servers[1].scheduler.active_count > 0,
              msg="target fell over to local prefill on replica 1")
        _run_to_done(servers[1], target)
        assert list(target.result(0)) == [7, 9, 9]   # token-exact
        fed = fleet.federation
        assert fed["fetches"] == 1 and fed["ships"] == 0 \
            and fed["skipped"] >= 1, fed
        # the stale advertisement was healed by the failed fetch:
        # replica 0 no longer claims the prefix (entries() may be >0
        # again — the target's own completion re-advertises on r1)
        assert fleet.directory.stats()["invalidations"] == 1
        assert 0 not in fleet.directory.affinity(shared), \
            "stale entry must be healed by the failed fetch"
        st1 = servers[1].scheduler.pages.stats()
        assert st1["remote_imports"] == 0 \
            and st1["federated_tokens_reused"] == 0, st1
        _run_to_done(servers[0], filler)
    finally:
        fleet.shutdown(graceful=False)


def test_router_federated_fetch_chaos_peerdrop_failover(monkeypatch):
    """Chaos leg over the existing RLT_FAULT peerdrop machinery: a
    dropped federated pull exhausts its bounded retries
    (RLT_PEER_RETRIES), fails over to local prefill token-exactly,
    and does NOT invalidate the directory (the donor is alive — only
    the wire lost)."""
    monkeypatch.setenv("RLT_FAULT", "peerdrop:rank=0,step=1,count=1")
    monkeypatch.setenv("RLT_PEER_RETRIES", "2")
    monkeypatch.setenv("RLT_PEER_BACKOFF_S", "0.01")
    monkeypatch.setenv("RLT_KVSHIP_TIMEOUT_S", "0.05")
    fleet, servers = _mk_fed_fleet()
    assert fleet._kvship_drop == 1, \
        "RLT_FAULT peerdrop must arm the router's kvship chaos"
    fleet.start()
    try:
        shared = np.arange(1, 17)
        _seed_donor(fleet, servers, shared)
        filler = fleet.submit(np.arange(40, 52), tenant="carol")
        _wait(lambda: servers[0].scheduler.queued_count > 0,
              msg="filler queued")
        servers[0].step()
        target = fleet.submit(np.concatenate([shared, [99]]),
                              tenant="bob")
        _wait(lambda: servers[1].scheduler.queued_count
              + servers[1].scheduler.active_count > 0,
              msg="target fell over after the chaos drop")
        _run_to_done(servers[1], target)
        assert list(target.result(0)) == [7, 9, 9]
        fed = fleet.federation
        assert fed["retries"] == 2 and fed["failovers"] == 1 \
            and fed["ships"] == 0, fed
        # the donor is alive — only the wire lost: its advertisement
        # must survive for the next fetch
        assert fleet.directory.stats()["invalidations"] == 0
        assert fleet.directory.affinity(shared).get(0) == 16, \
            "a wire timeout must NOT invalidate a live donor"
        _run_to_done(servers[0], filler)
    finally:
        fleet.shutdown(graceful=False)


# -- engine tier: prefix reuse through the real copy/suffix programs -------

TINY = None


def _tiny():
    global TINY
    if TINY is None:
        from ray_lightning_tpu.models.gpt import GPTConfig
        TINY = GPTConfig(vocab_size=128, block_size=32, n_layer=2,
                         n_head=2, n_embd=32, remat=False)
    return TINY


@pytest.fixture(scope="module")
def paged_engine():
    from ray_lightning_tpu.models.gpt import GPTLightningModule
    from ray_lightning_tpu.parallel.strategy import DataParallelStrategy
    from ray_lightning_tpu.serve.engine import ServeEngine
    module = GPTLightningModule(_tiny())
    return ServeEngine(module, DataParallelStrategy(), buckets=(16, 32),
                       slots=4, max_seq_len=32, seed=0,
                       paged=PAGED).setup()


def _assert_greedy_parity(eng, prompt, got, atol=2e-2):
    """tests/test_serve.py's teacher-forced parity bar: every generated
    token is the whole-sequence reference argmax, or within the bf16
    near-tie tolerance of it — corrupted K/V fails hard."""
    import jax
    model = eng.module.configure_decode_model()
    params = jax.device_get(eng.params)
    seq = [int(t) for t in np.asarray(prompt)]
    for i, tok in enumerate(got):
        logits = np.asarray(model.apply(
            {"params": params}, np.asarray([seq], np.int32), True))[0, -1]
        best = int(np.argmax(logits))
        assert tok == best or logits[tok] >= logits[best] - atol, \
            (i, seq, tok, best, float(logits[tok]), float(logits[best]))
        seq.append(int(tok))


@pytest.mark.slow
def test_prefix_reuse_token_parity_vs_cold_prefill(paged_engine):
    """The acceptance bar for the paged path: requests admitted through
    a prefix-cache hit (page copy + suffix-only compute) generate
    token-for-token what the cold whole-sequence reference generates,
    and reused pages are asserted > 0 — while concurrent decodes,
    donor retention, and idle-slot dummy writes all churn the cache."""
    from ray_lightning_tpu.serve.worker import ServeWorker
    eng = paged_engine
    sched = Scheduler(buckets=(16, 32), slots=4, max_seq_len=32,
                      max_prefills_per_step=1,
                      default_max_new_tokens=5, paged=PAGED)
    worker = ServeWorker()
    worker._engine = eng
    worker._rank = 0
    shared = np.arange(1, 17)            # 2-page shared system prompt
    prompts = [np.concatenate([shared, np.array([30 + i, 40 + i])])
               for i in range(5)]
    prompts.append(np.arange(100, 107))  # cold-path control
    reqs = [sched.submit(p, tenant=("alice", "bob")[i % 2])
            for i, p in enumerate(prompts)]
    reused = 0
    for _ in range(300):
        plan = sched.plan()
        if plan is None:
            if sched.idle():
                break
            continue
        reused += sum(1 for p in plan["prefills"] if "reuse" in p)
        sched.apply(plan, worker.serve_step(plan))
    assert all(r.done() for r in reqs)
    assert reused >= 3, "prefix cache never hit"
    st = sched.pages.stats()
    assert st["reused_prefills"] == reused
    assert st["prefill_tokens_computed"] \
        < st["prefill_tokens_requested"]
    assert st["prefix_reuse_ratio"] > 0.3, st
    sched.pages.pool.check()
    for r in reqs:
        _assert_greedy_parity(eng, r.tokens, r.result(1).tolist())
    # the paged programs traced once each; serving never re-traced
    warm = eng.trace_counts_at_warmup
    assert eng.trace_counts == warm \
        and warm.get("kv_copy") == 1 and warm.get("suffix") == 1


@pytest.mark.slow
def test_retained_donor_survives_dummy_write_traffic(paged_engine):
    """Cross-wave reuse: a donor retained after its request finished
    keeps donating CORRECT pages even after many decode steps of
    idle-slot dummy writes (aimed at the never-registered last row)."""
    from ray_lightning_tpu.serve.worker import ServeWorker
    eng = paged_engine
    sched = Scheduler(buckets=(32,), slots=4, max_seq_len=32,
                      max_prefills_per_step=1,
                      default_max_new_tokens=4, paged=PAGED)
    worker = ServeWorker()
    worker._engine = eng
    worker._rank = 0
    shared = np.arange(3, 19)

    def drive():
        for _ in range(300):
            plan = sched.plan()
            if plan is None:
                if sched.idle():
                    return
                continue
            sched.apply(plan, worker.serve_step(plan))

    r1 = sched.submit(np.concatenate([shared, [77]]))
    drive()
    assert sched.pages.donor_count == 1
    # a full wave of unrelated traffic (dummy writes every decode step)
    other = [sched.submit(np.arange(50, 60) + i) for i in range(3)]
    drive()
    hits0 = sched.pages.stats()["prefix_hits"]
    r2 = sched.submit(np.concatenate([shared, [88, 89]]))
    drive()
    assert sched.pages.stats()["prefix_hits"] > hits0, \
        "retained donor was not reused"
    for r in [r1, *other, r2]:
        _assert_greedy_parity(eng, r.tokens, r.result(1).tolist())


# -- real fleet e2e on the local backend -----------------------------------

def _real_server_kwargs(tmp_path):
    return dict(num_workers=1, platform="cpu", buckets=(16, 32),
                max_batch_slots=4, max_new_tokens=6,
                compile_cache=str(tmp_path / "compile_cache"),
                telemetry=False)


@pytest.mark.slow
def test_fleet_e2e_autoscale_grow_shrink_local_backend(tmp_path, seed):
    """The real thing on the builtin local backend: a FleetServer of
    real Servers (subprocess worker actors) grows 1→2 under a burst,
    serves every request greedy-parity-correct through paged prefix
    reuse, shrinks back to 1 on the idle tail — the drained replica's
    requests complete elsewhere — and loses nothing."""
    from ray_lightning_tpu.models.gpt import GPTLightningModule
    from ray_lightning_tpu.parallel.strategy import DataParallelStrategy
    from ray_lightning_tpu.serve.engine import ServeEngine
    from ray_lightning_tpu.serve.fleet import FleetServer

    module = GPTLightningModule(_tiny())
    fleet = FleetServer(
        module, replicas=1,
        fleet={"min_replicas": 1, "max_replicas": 2,
               "grow_queue_depth": 1.0, "patience_ticks": 1,
               "cooldown_s": 0.2, "tick_interval_s": 0.05},
        paged={"page_size": 8},
        default_root_dir=str(tmp_path / "fleet"),
        **_real_server_kwargs(tmp_path)).start()
    try:
        shared = np.arange(1, 17)
        reqs = [fleet.submit(
            np.concatenate([shared, [20 + i]]),
            tenant=("alice", "bob")[i % 2]) for i in range(12)]
        outs = [r.result(timeout=180) for r in reqs]
        assert all(len(o) == 6 for o in outs)
        # the burst grew the fleet; the idle tail shrinks it
        _wait(lambda: fleet.autoscaler.stats()["grows"] >= 1,
              timeout=120, msg="grow event")
        _wait(lambda: fleet.autoscaler.stats()["shrinks"] >= 1,
              timeout=120, msg="shrink event")
        _wait(lambda: len(fleet._replicas) == 1, timeout=60,
              msg="drained replica reaped")
        st = fleet.autoscaler.stats()
        assert all(e["seconds"] is not None for e in st["events"])
        assert fleet.failed == 0 and not fleet.failovers
        # requests routed across the scale events still parity-check
        pages = fleet.pages_stats()
        assert pages["prefix_reuse_ratio"] > 0, pages
        # a late request lands on the survivor
        late = fleet.generate(np.concatenate([shared, [99]]),
                              tenant="alice", timeout=120)
        assert len(late) == 6
        status = fleet.status()["fleet"]
        assert status["completed"] == 13 and status["failed"] == 0
    finally:
        fleet.shutdown()
    # greedy parity vs the cold whole-sequence reference (the fixture
    # engine shares the fleet's params: same config/seed/strategy)
    eng = ServeEngine(module, DataParallelStrategy(), buckets=(16, 32),
                      slots=4, max_seq_len=32, seed=0).setup()
    for r, out in zip(reqs, outs):
        _assert_greedy_parity(eng, r.prompt, out.tolist())


@pytest.mark.slow
def test_disagg_roles_ship_resume_parity_and_chaos_failover(
        tmp_path, seed, monkeypatch):
    """Disaggregated decode e2e on the local backend: a 1-prefill +
    1-decode fleet serves every request with tokens IDENTICAL to a
    pooled fleet's (ship -> resume parity; raw ships fp32 and is
    bit-exact, fp8 rides the wire >= 3x smaller under the same bar),
    sub-page prompts stay pooled, and a chaos-dropped ship exhausts
    its bounded retries (RLT_PEER_RETRIES) then fails over PER-REQUEST
    to a local prefill — same tokens, counted failover."""
    from ray_lightning_tpu.models.gpt import GPTLightningModule
    from ray_lightning_tpu.serve.fleet import FleetServer

    monkeypatch.setenv("RLT_PEER_RETRIES", "2")
    monkeypatch.setenv("RLT_PEER_BACKOFF_S", "0.01")
    monkeypatch.setenv("RLT_KVSHIP_TIMEOUT_S", "0.05")
    module = GPTLightningModule(_tiny())
    kw = _real_server_kwargs(tmp_path)
    shared = np.arange(1, 17)                  # 2 whole pages
    prompts = [np.concatenate([shared, [20 + i]]) for i in range(3)]
    prompts.append(np.arange(1, 7))            # sub-page: stays pooled

    def serve(tag, fleet_cfg):
        fleet = FleetServer(
            module, replicas=2, autoscale=False, fleet=fleet_cfg,
            paged={"page_size": 8},
            default_root_dir=str(tmp_path / tag), **kw).start()
        outs, kv = [], None
        try:
            # sequential: each ship sees its own fresh donor pages
            outs = [fleet.generate(p, timeout=180).tolist()
                    for p in prompts]
            if fleet_cfg:
                fleet.arm_kvship_drop(1)
                outs.append(fleet.generate(prompts[0],
                                           timeout=180).tolist())
            kv = fleet.status()["fleet"].get("kvship")
        finally:
            fleet.shutdown()
        return outs, kv

    want, kv = serve("pooled", None)
    assert kv is None                  # pooled fleets carry no kvship
    for codec in ("raw", "fp8"):
        outs, kv = serve(codec, {"roles": ("prefill", "decode"),
                                 "kvship_codec": codec})
        # clean legs: exact ship->resume token parity vs pooled
        assert outs[:len(prompts)] == want, codec
        # chaos leg replays prompt 0: identical tokens via failover
        assert outs[-1] == want[0], codec
        assert kv["ships"] == 3 and kv["failovers"] == 1, kv
        assert kv["retries"] == 2, kv      # bounded: RLT_PEER_RETRIES
        if codec == "fp8":
            assert kv["compression_ratio"] >= 3.0, kv
        else:
            assert kv["compression_ratio"] == 1.0, kv


@pytest.mark.slow
def test_serve_pump_flight_dump_on_worker_death(tmp_path, seed):
    """Satellite: a replica classified dead MID-SERVE dumps
    flight_<rank>.json with the serve cause, and the server's
    failure_report links the paths (the router's failover report
    surface)."""
    import os

    from ray_lightning_tpu.models.gpt import GPTLightningModule
    from ray_lightning_tpu.serve import Server

    kwargs = _real_server_kwargs(tmp_path)
    kwargs["telemetry"] = {"metrics": False, "heartbeat_interval": 0.2}
    server = Server(
        GPTLightningModule(_tiny()),
        default_root_dir=str(tmp_path / "serve"), **kwargs).start()
    try:
        # kill the worker process out from under the pump, then submit:
        # the next serve_step dispatch dies mid-serve — the death-
        # classification path, deterministically
        server._workers[0].kill()
        req = server.submit(np.arange(1, 12))
        with pytest.raises(BaseException):
            req.result(timeout=120)
        report = server.failure_report
        assert report is not None and "cause" in report
        assert report["flight_paths"], report
        for rank, path in report["flight_paths"].items():
            assert os.path.exists(path), path
            import json
            doc = json.load(open(path))
            assert doc["cause"].startswith("serve fleet failure"), \
                doc["cause"]
        assert "failure" in server.stats()
    finally:
        server.shutdown(graceful=False)
