"""Compiled-collective audit: the sharding claims that matter on a pod,
asserted over the ACTUAL lowered programs on the 8-virtual-device mesh
(VERDICT r3 next #3).

Round 3 asserted these in docstrings; this file asserts them against
``jit(...).lower(...).compile()`` — op kinds, element types, and
per-device argument bytes — so a strategy that silently degrades to the
wrong collective, loses its sharding, or widens a buffer to fp32 fails
CI instead of shipping a pod-scale regression no single-chip bench can
see.

Audited facts (current XLA CPU lowering; shapes/bytes are
backend-independent sharding truth, op *formation* can vary by backend
pass pipeline — reduce-scatter creation is such a pass, which is why
the ZeRO-1 assertion accepts all-reduce + dynamic-slice as the summed
grads' spelling):

- DDP: grads cross-replica summed (all-reduce), params NEVER gathered
  (they are replicated), full-size optimizer buffers.
- ZeRO-1: optimizer buffers 1/N per device, each rank slices its grad
  shard, updated params re-assembled by all-gather.
- FSDP: params also 1/N; all-gathers at use sites (strictly more than
  ZeRO-1's single post-update gather).
- Gradient collectives ride at f32 — the partitioner resolves partial
  sums at the f32-accumulating grad dots, before the bf16 cotangent
  cast (a bf16 all-reduce here would be a silent numerics change; a
  f64 one a silent widening — both fail this audit).

Reference anchor: SURVEY.md §2.2 FairScale row (reduce-scatter /
all-gather is the stated parity mechanism, ray_ddp_sharded.py:17-34).
"""

from __future__ import annotations

import re

import jax
import numpy as np
import pytest

from ray_lightning_tpu.comm import CommPolicy
from ray_lightning_tpu.comm.audit import (collective_defs,
                                          collective_wire_bytes)
from ray_lightning_tpu.core.steps import build_init_fn, build_train_step
from ray_lightning_tpu.models.gpt import GPTLightningModule
from ray_lightning_tpu.parallel.strategy import resolve_strategy

BATCH = 16


def _compiled(strategy, comm_policy=None, module=None, **module_kw):
    """Compile the real train step under ``strategy`` (optionally with
    an active comm policy, replicating the trainer's wiring: resolved
    GradSync, wrapped tx, residual shardings fixup)."""
    strat = resolve_strategy(strategy) if isinstance(strategy, str) \
        else strategy
    if module is None:
        module = GPTLightningModule("tiny", dataset_size=4 * BATCH,
                                    batch_size=BATCH, **module_kw)
    module.setup_model()
    tx = module.configure_optimizers()
    mesh = strat.build_mesh(batch_hint=BATCH)
    comm = strat.grad_transform(mesh, comm_policy) \
        if comm_policy is not None else None
    if comm is not None:
        tx = comm.wrap_tx(tx)
    batch = jax.tree_util.tree_map(
        np.asarray, next(iter(module.train_dataloader())))
    abstract = jax.eval_shape(build_init_fn(module, tx),
                              jax.random.PRNGKey(0), batch)
    shardings = strat.state_shardings(mesh, abstract)
    if comm is not None:
        shardings = shardings.replace(
            opt_state=comm.fix_opt_shardings(shardings.opt_state,
                                             abstract.opt_state))
    jitted = jax.jit(build_train_step(module, tx, grad_sync=comm),
                     donate_argnums=0,
                     in_shardings=(shardings,
                                   strat.batch_shardings(mesh, batch)),
                     out_shardings=(shardings, None))
    return mesh, jitted.lower(abstract, batch).compile()


@pytest.fixture(scope="module")
def programs():
    """One compile per strategy, shared by every assertion below."""
    out = {}
    for name in ("ddp", "zero1", "fsdp"):
        mesh, comp = _compiled(name)
        assert dict(mesh.shape)["data"] == 8, "audit needs the full mesh"
        out[name] = {
            "text": comp.as_text(),
            "args": comp.memory_analysis().argument_size_in_bytes,
        }
    return out


def _count(text: str, op: str) -> int:
    """Occurrences of collective-op DEFINITIONS (async start variants
    count once; `-done` and get-tuple-element references do not)."""
    return len(re.findall(rf"= \(?[a-z0-9]+\[[^)]*?\]\S* {op}(?:-start)?\(",
                          text))


def _def_dtypes(text: str, op: str) -> set:
    """Element types produced by ``op`` definitions (tuple or scalar)."""
    out = set()
    for m in re.finditer(rf"= (\(?)([a-z0-9]+)\[[^)]*?\]\S* {op}", text):
        if m.group(1):   # tuple type: collect every element type inside
            span = text[m.start():text.index(")", m.start())]
            out.update(re.findall(r"([a-z0-9]+)\[", span))
        else:
            out.add(m.group(2))
    return out


def test_ddp_allreduces_grads_and_never_gathers_params(programs):
    t = programs["ddp"]["text"]
    assert _count(t, "all-reduce") > 0, "DDP lost its gradient psum"
    assert _count(t, "all-gather") == 0, (
        "DDP program gathers something — params/opt must be replicated")
    assert _count(t, "reduce-scatter") == 0


def test_zero1_shards_update_and_gathers_params(programs):
    t = programs["zero1"]["text"]
    # summed grads: either a literal reduce-scatter or the partitioner's
    # all-reduce + per-rank dynamic-slice spelling
    rs = _count(t, "reduce-scatter")
    assert rs > 0 or (_count(t, "all-reduce") > 0
                      and t.count("dynamic-slice") > 0), (
        "ZeRO-1 lost the sharded-update pattern entirely")
    assert _count(t, "all-gather") > 0, (
        "ZeRO-1 must re-assemble updated params with an all-gather")


def test_fsdp_gathers_params_at_use_sites(programs):
    ag_fsdp = _count(programs["fsdp"]["text"], "all-gather")
    ag_zero1 = _count(programs["zero1"]["text"], "all-gather")
    assert ag_fsdp > ag_zero1 > 0, (
        f"FSDP should gather params at use sites (fwd+bwd): "
        f"{ag_fsdp} vs zero1's {ag_zero1}")


def test_grad_allreduce_rides_f32(programs):
    """The cross-replica grad sum must stay f32: bf16 would silently
    change numerics (summing rounded partials), f64 silently widen the
    dominant collective (module docstring, ops/optim.py)."""
    for name in ("ddp", "zero1", "fsdp"):
        types = _def_dtypes(programs[name]["text"], "all-reduce")
        assert types and types <= {"f32"}, (
            f"{name}: gradient all-reduce element types {types} != f32")


def test_per_device_state_bytes_order(programs):
    """The memory story IS the point of the sharded strategies: per
    device, fsdp (params+opt sharded) < zero1 (opt sharded) < ddp
    (everything replicated).  A lost sharding annotation collapses one
    of these gaps."""
    ddp = programs["ddp"]["args"]
    zero1 = programs["zero1"]["args"]
    fsdp = programs["fsdp"]["args"]
    assert fsdp < zero1 < ddp, (ddp, zero1, fsdp)
    # opt state (f32 master + bf16 mu + f32 nu ≈ 5 bytes/param) dwarfs
    # bf16 params; sharding it 8-way should reclaim well over half
    assert zero1 < 0.45 * ddp, (zero1, ddp)
    # fsdp shards the bf16 params too
    assert fsdp < 0.75 * zero1, (fsdp, zero1)


def test_tensor_parallel_psums_forward(programs):
    """Megatron-style tensor parallelism: row-parallel matmuls produce
    partial activations that MUST be psum'd in the forward pass — a
    tensor-sharded program with no all-reduce is silently computing
    garbage.  Params shard on the tensor axis, so per-device state
    bytes drop vs DDP."""
    from ray_lightning_tpu.models.gpt import gpt_partition_rules
    from ray_lightning_tpu.parallel.strategy import SpmdStrategy

    strat = SpmdStrategy(rules=gpt_partition_rules(),
                         axis_names=("data", "tensor"),
                         axis_sizes={"tensor": 2})
    mesh, comp = _compiled(strat)
    assert dict(mesh.shape) == {"data": 4, "tensor": 2}
    assert _count(comp.as_text(), "all-reduce") > 0
    assert comp.memory_analysis().argument_size_in_bytes \
        < 0.8 * programs["ddp"]["args"]


# ---------------------------------------------------------------------------
# compressed collectives (comm/): dtype + wire-byte audit
# ---------------------------------------------------------------------------

INT8_POLICY = CommPolicy(compress="int8", axes=("data",))


@pytest.fixture(scope="module")
def compressed(programs):
    """The int8-compressed ddp/zero1 programs (one compile each)."""
    out = {}
    for name in ("ddp", "zero1"):
        _mesh, comp = _compiled(name, comm_policy=INT8_POLICY)
        out[name] = {"text": comp.as_text()}
    return out


def _wire(text):
    return collective_wire_bytes(text, axis_size=8)


def test_compressed_ddp_reduction_bytes(programs, compressed):
    """With comm=int8 on the data axis, the DDP grad reduction rides
    s8 all-to-all + all-gather and the program's total collective wire
    bytes drop >= 3.5x vs the fp32 all-reduce (the acceptance bar; the
    residue above 4x is the fp32 per-block scales)."""
    fp = _wire(programs["ddp"]["text"])
    q = _wire(compressed["ddp"]["text"])
    assert ("all-to-all", "s8") in q and ("all-gather", "s8") in q, q
    # the fp32 gradient all-reduce is gone (only epsilon-sized scalar
    # psums remain: loss/logged means)
    assert q.get(("all-reduce", "f32"), 0) < 1024
    ratio = sum(fp.values()) / sum(q.values())
    assert ratio >= 3.5, (ratio, fp, q)


def test_compressed_zero1_grad_phase_bytes(programs, compressed):
    """ZeRO-1's grad reduce-scatter (+ its all-gather leg) carries >=
    3.5x fewer bytes compressed.  The updated-param all-gather is
    unchanged between legs (param_gather="none"), so subtracting the
    fp32 leg's f32 all-gather isolates the grad phases."""
    fp = _wire(programs["zero1"]["text"])
    q = _wire(compressed["zero1"]["text"])
    assert ("all-to-all", "s8") in q and ("all-gather", "s8") in q, q
    param_gather_f32 = fp.get(("all-gather", "f32"), 0) \
        + fp.get(("all-gather", "bf16"), 0)
    grad_fp = fp[("all-reduce", "f32")]
    grad_q = sum(q.values()) - param_gather_f32 \
        - q.get(("all-reduce", "f32"), 0)
    assert grad_fp / grad_q >= 3.5, (grad_fp, grad_q, fp, q)


HIER_POLICY = CommPolicy(compress="int8", axes=("data",), hierarchy=4)


@pytest.fixture(scope="module")
def hierarchical(programs):
    """The two-level (ici4 x dcn2) int8 ddp/zero1 programs."""
    out = {}
    for name in ("ddp", "zero1"):
        _mesh, comp = _compiled(name, comm_policy=HIER_POLICY)
        out[name] = {"text": comp.as_text()}
    return out


@pytest.mark.parametrize("name", ["ddp", "zero1"])
def test_hierarchical_dcn_bytes_vs_flat_int8(compressed, hierarchical,
                                             name):
    """THE tentpole pin: on a 2-level (ici4 x dcn2) split of the 8-way
    mesh, the hierarchical program's DCN-crossing compressed payload is
    >= 2x below the flat-int8 path's (the flat collectives span all 8
    ranks, so every compressed byte crosses hosts; the hierarchical
    level-2 phases move a 1/ici shard).  Audited over the lowered HLO's
    replica groups — a lost ``axis_index_groups`` (everything suddenly
    full-span) fails here, not on a pod."""
    from ray_lightning_tpu.comm.audit import wire_bytes_by_link

    qdt = ("s8", "u8")
    flat = wire_bytes_by_link(compressed[name]["text"], ici_size=4,
                              axis_size=8, dtypes=qdt)
    hier = wire_bytes_by_link(hierarchical[name]["text"], ici_size=4,
                              axis_size=8, dtypes=qdt)
    assert flat["dcn"] > 0 and hier["dcn"] > 0, (flat, hier)
    assert flat["ici"] == 0, flat    # flat program: all spans cross
    assert 2 * hier["dcn"] <= flat["dcn"], (hier, flat)


def test_hierarchical_ici_phases_stay_fp32(hierarchical):
    """The EQuARX trade in the lowered program: the hierarchical ddp
    step moves fp32 INSIDE the ICI groups (levels 1/3 — the fast link
    carries full precision) while the compressed dtype appears only on
    host-crossing groups."""
    from ray_lightning_tpu.comm.audit import wire_bytes_by_link

    t = hierarchical["ddp"]["text"]
    f32 = wire_bytes_by_link(t, ici_size=4, axis_size=8, dtypes=("f32",),
                             ops=("all-to-all", "all-gather"))
    assert f32["ici"] > 0, f32
    q = wire_bytes_by_link(t, ici_size=4, axis_size=8, dtypes=("s8", "u8"))
    assert q["ici"] == 0, q          # codec never rides the fast tier


def test_fp8_program_rides_one_byte_wire():
    """The fp8 codec's collectives must move a 1-byte element type (the
    u8 bitcast) — an f16-widened wire (what a raw f8 collective lowers
    to on CPU) would silently double the DCN bytes."""
    _mesh, comp = _compiled(
        "ddp", comm_policy=CommPolicy(compress="fp8", axes=("data",)))
    wire = collective_wire_bytes(comp.as_text(), axis_size=8)
    assert ("all-to-all", "u8") in wire and ("all-gather", "u8") in wire, \
        wire
    assert not any(dt == "f16" for _op, dt in wire), wire


def test_int4_program_halves_the_payload():
    """int4's packed wire: the all-to-all payload is half the element
    count, so total compressed bytes land >= 1.6x under the int8 leg's
    (scales are the fixed overhead)."""
    _mesh, comp8 = _compiled("ddp", comm_policy=INT8_POLICY)
    _mesh, comp4 = _compiled(
        "ddp", comm_policy=CommPolicy(compress="int4", axes=("data",)))
    qdt = ("s8", "u8")
    b8 = sum(b for (op, dt), b in
             collective_wire_bytes(comp8.as_text(), axis_size=8).items()
             if dt in qdt)
    b4 = sum(b for (op, dt), b in
             collective_wire_bytes(comp4.as_text(), axis_size=8).items()
             if dt in qdt)
    assert b4 * 1.6 <= b8, (b4, b8)


def test_comm_policy_off_is_bit_identical(programs):
    """The resolved-but-off policy (compress="none") routes through the
    comm-aware wiring and must produce the IDENTICAL program text —
    default behavior is today's build, byte for byte."""
    _mesh, comp = _compiled("ddp", comm_policy=CommPolicy())
    assert comp.as_text() == programs["ddp"]["text"]


def test_zero1_param_gather_compresses():
    """param_gather="int8" re-routes the updated-param all-gather
    through the quantize→replicate sandwich: the s8 all-gather appears
    and the full-precision param-sized gather disappears (boring model:
    one [32, 2] dense layer, cheap compile)."""
    from ray_lightning_tpu.models import BoringModel

    def boring():
        return BoringModel(batch_size=BATCH)

    _m, comp_fp = _compiled("zero1", module=boring())
    _m, comp_q = _compiled(
        "zero1", module=boring(),
        comm_policy=CommPolicy(compress="int8", axes=("data",),
                               param_gather="int8"))
    fp = _wire(comp_fp.as_text())
    q = _wire(comp_q.as_text())
    assert all(dt != "s8" for _op, dt in fp), fp
    assert ("all-gather", "s8") in q
    # full-precision gather traffic is reduced to scale-sized f32 rows —
    # strictly smaller than the s8 payload it describes
    assert q.get(("all-gather", "f32"), 0) < q[("all-gather", "s8")]


# ---------------------------------------------------------------------------
# ring attention + pipeline (VERDICT #5): the other compiled collectives
# ---------------------------------------------------------------------------


def test_ring_attention_collective_permute_bytes():
    """Ring attention rotates K/V with collective-permute — per hop one
    LOCAL block of O(T/N · D) bytes, never an all-gather of the full
    sequence — and its traced byte note matches the schedule model
    (ring-1 rotations x global K+V)."""
    from ray_lightning_tpu.parallel.mesh import build_device_mesh
    from ray_lightning_tpu.parallel.ring import ring_attention
    from ray_lightning_tpu.telemetry.metrics import (disable_metrics,
                                                     enable_metrics)

    mesh = build_device_mesh(("data", "sequence"),
                             {"data": 1, "sequence": 8})
    ring = 8
    b, t, h, d = 2, 64, 2, 8
    aval = jax.ShapeDtypeStruct((b, t, h, d), np.float32)
    reg = enable_metrics(rank=0, sink=None, pump=False)
    try:
        comp = jax.jit(
            lambda q, k, v: ring_attention(q, k, v, mesh=mesh)).lower(
                aval, aval, aval).compile()
        traced = reg.traced_bytes.get("ring")
    finally:
        disable_metrics()
    text = comp.as_text()
    hop_bytes = b * (t // ring) * h * d * 4      # one f32 K or V block
    cps = [x for x in collective_defs(text)
           if x[0] == "collective-permute"]
    assert len(cps) == 2 * (ring - 1), len(cps)  # K and V per rotation
    assert all(nbytes == hop_bytes for _op, _dt, nbytes in cps), cps
    assert _count(text, "all-gather") == 0, (
        "ring must rotate blocks, not gather the sequence")
    # schedule model: (ring-1) rotations move the global K+V once each
    kv_bytes = 2 * (b * t * h * d * 4)
    assert traced == (ring - 1) * kv_bytes


def test_pipeline_collective_permute_matches_microbatch_schedule():
    """The pipeline's cross-stage transfer is one collective-permute of
    exactly one microbatch activation block (B_local/M rows), and its
    traced byte note matches the GPipe schedule: S stages x (M+S-1)
    time steps x (x_bytes/M) per hop + the final psum broadcast."""
    import jax.numpy as jnp

    from ray_lightning_tpu.parallel.mesh import build_device_mesh
    from ray_lightning_tpu.parallel.pipeline import pipeline_forward
    from ray_lightning_tpu.telemetry.metrics import (disable_metrics,
                                                     enable_metrics)

    mesh = build_device_mesh(("data", "stage"), {"data": 2, "stage": 4})
    S, M, L, F, B = 4, 2, 4, 8, 16

    def stage_fn(p, h):
        return jnp.tanh(h @ p)

    reg = enable_metrics(rank=0, sink=None, pump=False)
    try:
        comp = jax.jit(
            lambda params, x: pipeline_forward(
                stage_fn, params, x, n_microbatches=M, mesh=mesh)).lower(
            jax.ShapeDtypeStruct((L, F, F), np.float32),
            jax.ShapeDtypeStruct((B, F), np.float32)).compile()
        traced = reg.traced_bytes.get("pipeline")
    finally:
        disable_metrics()
    text = comp.as_text()
    mb_bytes = (B // 2 // M) * F * 4     # per-data-shard microbatch, f32
    cps = [x for x in collective_defs(text)
           if x[0] == "collective-permute"]
    assert cps, "pipeline lost its cross-stage ppermute"
    assert all(nbytes == mb_bytes for _op, _dt, nbytes in cps), cps
    # the last stage's outputs broadcast with a psum (not a ppermute
    # chain); its payload is the stacked microbatch outputs
    assert _count(text, "all-reduce") > 0
    x_bytes = B * F * 4
    assert traced == S * (M + S - 1) * x_bytes // M + x_bytes
