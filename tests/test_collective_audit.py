"""Compiled-collective audit: the sharding claims that matter on a pod,
asserted over the ACTUAL lowered programs on the 8-virtual-device mesh
(VERDICT r3 next #3).

Round 3 asserted these in docstrings; this file asserts them against
``jit(...).lower(...).compile()`` — op kinds, element types, and
per-device argument bytes — so a strategy that silently degrades to the
wrong collective, loses its sharding, or widens a buffer to fp32 fails
CI instead of shipping a pod-scale regression no single-chip bench can
see.

Audited facts (current XLA CPU lowering; shapes/bytes are
backend-independent sharding truth, op *formation* can vary by backend
pass pipeline — reduce-scatter creation is such a pass, which is why
the ZeRO-1 assertion accepts all-reduce + dynamic-slice as the summed
grads' spelling):

- DDP: grads cross-replica summed (all-reduce), params NEVER gathered
  (they are replicated), full-size optimizer buffers.
- ZeRO-1: optimizer buffers 1/N per device, each rank slices its grad
  shard, updated params re-assembled by all-gather.
- FSDP: params also 1/N; all-gathers at use sites (strictly more than
  ZeRO-1's single post-update gather).
- Gradient collectives ride at f32 — the partitioner resolves partial
  sums at the f32-accumulating grad dots, before the bf16 cotangent
  cast (a bf16 all-reduce here would be a silent numerics change; a
  f64 one a silent widening — both fail this audit).

Reference anchor: SURVEY.md §2.2 FairScale row (reduce-scatter /
all-gather is the stated parity mechanism, ray_ddp_sharded.py:17-34).
"""

from __future__ import annotations

import re

import jax
import numpy as np
import pytest

from ray_lightning_tpu.core.steps import build_init_fn, build_train_step
from ray_lightning_tpu.models.gpt import GPTLightningModule
from ray_lightning_tpu.parallel.strategy import resolve_strategy

BATCH = 16


def _compiled(strategy, **module_kw):
    strat = resolve_strategy(strategy) if isinstance(strategy, str) \
        else strategy
    module = GPTLightningModule("tiny", dataset_size=4 * BATCH,
                                batch_size=BATCH, **module_kw)
    module.setup_model()
    tx = module.configure_optimizers()
    mesh = strat.build_mesh(batch_hint=BATCH)
    batch = jax.tree_util.tree_map(
        np.asarray, next(iter(module.train_dataloader())))
    abstract = jax.eval_shape(build_init_fn(module, tx),
                              jax.random.PRNGKey(0), batch)
    shardings = strat.state_shardings(mesh, abstract)
    jitted = jax.jit(build_train_step(module, tx), donate_argnums=0,
                     in_shardings=(shardings,
                                   strat.batch_shardings(mesh, batch)),
                     out_shardings=(shardings, None))
    return mesh, jitted.lower(abstract, batch).compile()


@pytest.fixture(scope="module")
def programs():
    """One compile per strategy, shared by every assertion below."""
    out = {}
    for name in ("ddp", "zero1", "fsdp"):
        mesh, comp = _compiled(name)
        assert dict(mesh.shape)["data"] == 8, "audit needs the full mesh"
        out[name] = {
            "text": comp.as_text(),
            "args": comp.memory_analysis().argument_size_in_bytes,
        }
    return out


def _count(text: str, op: str) -> int:
    """Occurrences of collective-op DEFINITIONS (async start variants
    count once; `-done` and get-tuple-element references do not)."""
    return len(re.findall(rf"= \(?[a-z0-9]+\[[^)]*?\]\S* {op}(?:-start)?\(",
                          text))


def _def_dtypes(text: str, op: str) -> set:
    """Element types produced by ``op`` definitions (tuple or scalar)."""
    out = set()
    for m in re.finditer(rf"= (\(?)([a-z0-9]+)\[[^)]*?\]\S* {op}", text):
        if m.group(1):   # tuple type: collect every element type inside
            span = text[m.start():text.index(")", m.start())]
            out.update(re.findall(r"([a-z0-9]+)\[", span))
        else:
            out.add(m.group(2))
    return out


def test_ddp_allreduces_grads_and_never_gathers_params(programs):
    t = programs["ddp"]["text"]
    assert _count(t, "all-reduce") > 0, "DDP lost its gradient psum"
    assert _count(t, "all-gather") == 0, (
        "DDP program gathers something — params/opt must be replicated")
    assert _count(t, "reduce-scatter") == 0


def test_zero1_shards_update_and_gathers_params(programs):
    t = programs["zero1"]["text"]
    # summed grads: either a literal reduce-scatter or the partitioner's
    # all-reduce + per-rank dynamic-slice spelling
    rs = _count(t, "reduce-scatter")
    assert rs > 0 or (_count(t, "all-reduce") > 0
                      and t.count("dynamic-slice") > 0), (
        "ZeRO-1 lost the sharded-update pattern entirely")
    assert _count(t, "all-gather") > 0, (
        "ZeRO-1 must re-assemble updated params with an all-gather")


def test_fsdp_gathers_params_at_use_sites(programs):
    ag_fsdp = _count(programs["fsdp"]["text"], "all-gather")
    ag_zero1 = _count(programs["zero1"]["text"], "all-gather")
    assert ag_fsdp > ag_zero1 > 0, (
        f"FSDP should gather params at use sites (fwd+bwd): "
        f"{ag_fsdp} vs zero1's {ag_zero1}")


def test_grad_allreduce_rides_f32(programs):
    """The cross-replica grad sum must stay f32: bf16 would silently
    change numerics (summing rounded partials), f64 silently widen the
    dominant collective (module docstring, ops/optim.py)."""
    for name in ("ddp", "zero1", "fsdp"):
        types = _def_dtypes(programs[name]["text"], "all-reduce")
        assert types and types <= {"f32"}, (
            f"{name}: gradient all-reduce element types {types} != f32")


def test_per_device_state_bytes_order(programs):
    """The memory story IS the point of the sharded strategies: per
    device, fsdp (params+opt sharded) < zero1 (opt sharded) < ddp
    (everything replicated).  A lost sharding annotation collapses one
    of these gaps."""
    ddp = programs["ddp"]["args"]
    zero1 = programs["zero1"]["args"]
    fsdp = programs["fsdp"]["args"]
    assert fsdp < zero1 < ddp, (ddp, zero1, fsdp)
    # opt state (f32 master + bf16 mu + f32 nu ≈ 5 bytes/param) dwarfs
    # bf16 params; sharding it 8-way should reclaim well over half
    assert zero1 < 0.45 * ddp, (zero1, ddp)
    # fsdp shards the bf16 params too
    assert fsdp < 0.75 * zero1, (fsdp, zero1)


def test_tensor_parallel_psums_forward(programs):
    """Megatron-style tensor parallelism: row-parallel matmuls produce
    partial activations that MUST be psum'd in the forward pass — a
    tensor-sharded program with no all-reduce is silently computing
    garbage.  Params shard on the tensor axis, so per-device state
    bytes drop vs DDP."""
    from ray_lightning_tpu.models.gpt import gpt_partition_rules
    from ray_lightning_tpu.parallel.strategy import SpmdStrategy

    strat = SpmdStrategy(rules=gpt_partition_rules(),
                         axis_names=("data", "tensor"),
                         axis_sizes={"tensor": 2})
    mesh, comp = _compiled(strat)
    assert dict(mesh.shape) == {"data": 4, "tensor": 2}
    assert _count(comp.as_text(), "all-reduce") > 0
    assert comp.memory_analysis().argument_size_in_bytes \
        < 0.8 * programs["ddp"]["args"]
