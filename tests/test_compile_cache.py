"""Compile-plane tests: persistent XLA compilation cache + AOT
precompile (ray_lightning_tpu/compile/).

The load-bearing assertion is the cold→warm A/B across real process
boundaries: two subprocess fits sharing one cache dir, where the warm
one records cache hits, spends a fraction of the cold one's
backend-compile seconds, and reaches its first step faster — the
multiplied-by-trial-count cost the compile plane exists to remove.
"""

import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from ray_lightning_tpu import Trainer
from ray_lightning_tpu import tune
from ray_lightning_tpu.compile import cache as cc
from ray_lightning_tpu.compile import shipping
from ray_lightning_tpu.compile.aot import (
    AotPrecompiler,
    global_batch_abstract,
    stack_abstract,
)
from ray_lightning_tpu.core.trainer import _cache_bytes_estimate
from ray_lightning_tpu.models import BoringModel


@pytest.fixture(autouse=True)
def _isolate_cache_state():
    """Each test starts from a clean compile-plane state and leaves no
    active cache dir behind for unrelated tests."""
    cc.reset_stats()
    yield
    cc.deactivate()


# ---------------------------------------------------------------------------
# config / env resolution
# ---------------------------------------------------------------------------

def _clear_env(monkeypatch):
    for k in cc.ENV_KNOBS:
        monkeypatch.delenv(k, raising=False)


def test_config_default_disabled(monkeypatch):
    _clear_env(monkeypatch)
    assert not cc.CompileCacheConfig.resolve(None).enabled


def test_config_env_enable_forms(monkeypatch, tmp_path):
    _clear_env(monkeypatch)
    monkeypatch.setenv(cc.ENV_ENABLE, "1")
    cfg = cc.CompileCacheConfig.resolve(None)
    assert cfg.enabled and cfg.root == cc.DEFAULT_ROOT

    monkeypatch.setenv(cc.ENV_ENABLE, str(tmp_path / "root"))
    cfg = cc.CompileCacheConfig.resolve(None)
    assert cfg.enabled and cfg.root == str(tmp_path / "root")

    monkeypatch.setenv(cc.ENV_ENABLE, "0")
    monkeypatch.setenv(cc.ENV_DIR, str(tmp_path))
    assert not cc.CompileCacheConfig.resolve(None).enabled  # 0 kills all


def test_config_env_dir_and_knobs(monkeypatch, tmp_path):
    _clear_env(monkeypatch)
    monkeypatch.setenv(cc.ENV_DIR, str(tmp_path))
    monkeypatch.setenv(cc.ENV_MIN_ENTRY, "2048")
    monkeypatch.setenv(cc.ENV_MIN_COMPILE, "0.5")
    cfg = cc.CompileCacheConfig.resolve(None)
    assert cfg.enabled and cfg.root == str(tmp_path)
    assert cfg.min_entry_bytes == 2048
    assert cfg.min_compile_secs == 0.5


def test_config_explicit_arg_forms(monkeypatch, tmp_path):
    _clear_env(monkeypatch)
    assert not cc.CompileCacheConfig.resolve(False).enabled
    assert cc.CompileCacheConfig.resolve(True).enabled
    cfg = cc.CompileCacheConfig.resolve(str(tmp_path))
    assert cfg.enabled and cfg.root == str(tmp_path)
    cfg = cc.CompileCacheConfig.resolve(
        {"dir": str(tmp_path), "min_entry_bytes": 7})
    assert cfg.enabled and cfg.min_entry_bytes == 7
    with pytest.raises(TypeError):
        cc.CompileCacheConfig.resolve(3.14)


def test_worker_env_round_trip(monkeypatch, tmp_path):
    _clear_env(monkeypatch)
    cfg = cc.CompileCacheConfig(enabled=True, dir=str(tmp_path),
                                min_entry_bytes=64, min_compile_secs=0.1)
    for k, v in cfg.worker_env().items():
        monkeypatch.setenv(k, v)
    assert cc.CompileCacheConfig.resolve(None) == cfg
    assert cc.CompileCacheConfig(enabled=False).worker_env() == {}


def test_namespace_dir_components(tmp_path):
    ns = cc.namespace_dir(str(tmp_path))
    base = os.path.basename(ns)
    assert os.path.dirname(ns) == str(tmp_path)
    assert jax.__version__ in base
    assert f"-d{jax.device_count()}-p{jax.process_count()}" in base
    # path-safe: nothing but the sanctioned characters
    assert "/" not in base and " " not in base


# ---------------------------------------------------------------------------
# cache seeding (shipping)
# ---------------------------------------------------------------------------

def test_pack_unpack_round_trip(tmp_path):
    src = tmp_path / "src"
    (src / "sub").mkdir(parents=True)
    (src / "a").write_bytes(b"alpha")
    (src / "sub" / "b").write_bytes(b"beta" * 100)
    blob = shipping.pack_cache_dir(str(src))
    assert blob is not None
    dst = tmp_path / "dst"
    assert shipping.unpack_cache_dir(blob, str(dst)) == 2
    assert (dst / "a").read_bytes() == b"alpha"
    assert (dst / "sub" / "b").read_bytes() == b"beta" * 100
    # additive: an existing (newer) entry is never overwritten
    (dst / "a").write_bytes(b"newer")
    assert shipping.unpack_cache_dir(blob, str(dst)) == 0
    assert (dst / "a").read_bytes() == b"newer"


def test_pack_empty_and_missing(tmp_path):
    assert shipping.pack_cache_dir(str(tmp_path / "nope")) is None
    empty = tmp_path / "empty"
    empty.mkdir()
    assert shipping.pack_cache_dir(str(empty)) is None


def test_pack_cap_keeps_newest(tmp_path):
    src = tmp_path / "src"
    src.mkdir()
    (src / "old").write_bytes(b"x" * 600)
    os.utime(src / "old", (1, 1))
    (src / "new").write_bytes(b"y" * 600)
    blob = shipping.pack_cache_dir(str(src), max_bytes=1000)
    dst = tmp_path / "dst"
    shipping.unpack_cache_dir(blob, str(dst))
    assert (dst / "new").exists() and not (dst / "old").exists()


# ---------------------------------------------------------------------------
# AOT precompiler
# ---------------------------------------------------------------------------

def test_aot_precompile_and_dispatch():
    jitted = jax.jit(lambda x: x * 2 + 1)
    pre = AotPrecompiler()
    pre.submit("double", jitted,
               (jax.ShapeDtypeStruct((4,), np.float32),))
    results = pre.barrier(timeout=60)
    assert pre.succeeded("double"), results
    out = jitted(np.ones((4,), np.float32))
    np.testing.assert_allclose(np.asarray(out), np.full((4,), 3.0))


def test_aot_failure_is_soft():
    pre = AotPrecompiler()
    pre.submit("bad", jax.jit(lambda x: x), ("not-an-aval",))
    results = pre.barrier(timeout=60)
    assert not pre.succeeded("bad")
    assert isinstance(results["bad"], Exception)


def test_aot_disabled_noop(monkeypatch):
    monkeypatch.setenv("RLT_AOT_PRECOMPILE", "0")
    pre = AotPrecompiler.resolve()
    assert not pre.enabled
    pre.submit("x", None, ())        # must not touch the dead jitted
    assert pre.barrier(timeout=1) == {}


def test_abstract_helpers():
    batch = {"x": np.zeros((4, 3), np.float32),
             "n": np.int32(7)}
    ab = global_batch_abstract(batch, process_count=1)
    assert ab["x"].shape == (4, 3) and ab["n"].shape == ()
    ab2 = global_batch_abstract(batch, process_count=4)
    assert ab2["x"].shape == (16, 3)      # dim 0 scales; scalars don't
    assert ab2["n"].shape == ()
    st = stack_abstract(ab, 5)
    assert st["x"].shape == (5, 4, 3) and st["x"].dtype == np.float32


# ---------------------------------------------------------------------------
# trainer integration (in-process)
# ---------------------------------------------------------------------------

def _fit(tmp_path, cache_dir, **kw):
    trainer = Trainer(max_steps=3, enable_checkpointing=False,
                      num_sanity_val_steps=0, limit_val_batches=0,
                      default_root_dir=str(tmp_path),
                      compile_cache=str(cache_dir), **kw)
    trainer.fit(BoringModel())
    return trainer


def test_fit_records_first_step_and_precompiles(tmp_path):
    trainer = _fit(tmp_path, tmp_path / "cache")
    assert trainer.time_to_first_step is not None
    assert trainer.time_to_first_step > 0
    assert trainer._precompiler.succeeded("train_step"), \
        trainer._precompiler.results
    ns = cc.active_dir()
    assert ns and ns.startswith(str(tmp_path / "cache"))
    assert os.listdir(ns)           # entries persisted
    assert cc.stats().requests > 0


def test_second_fit_hits_cache_in_process(tmp_path):
    _fit(tmp_path, tmp_path / "cache")
    before = cc.stats()
    t2 = _fit(tmp_path, tmp_path / "cache")
    after = cc.stats()
    # a fresh Trainer builds fresh jit objects: same programs, new
    # requests — served from the persistent cache, not recompiled
    assert after.hits > before.hits
    assert t2.time_to_first_step is not None


def test_chunked_fit_precompiles_multi_step(tmp_path):
    trainer = Trainer(max_steps=4, steps_per_execution=2,
                      enable_checkpointing=False, num_sanity_val_steps=0,
                      limit_val_batches=0, default_root_dir=str(tmp_path),
                      compile_cache=str(tmp_path / "cache"))
    trainer.fit(BoringModel(batch_size=8))
    assert trainer.global_step == 4
    assert trainer._precompiler.succeeded("multi_step"), \
        trainer._precompiler.results


def test_cached_dataset_fit_precompiles_cached_steps(tmp_path):
    trainer = Trainer(max_steps=4, steps_per_execution=2,
                      cache_train_dataset=True,
                      enable_checkpointing=False, num_sanity_val_steps=0,
                      limit_val_batches=0, default_root_dir=str(tmp_path),
                      compile_cache=str(tmp_path / "cache"))
    trainer.fit(BoringModel(batch_size=8))
    assert trainer.global_step == 4
    res = trainer._precompiler.results
    assert trainer._precompiler.succeeded("cached_single"), res
    assert trainer._precompiler.succeeded("cached_multi"), res


def test_metrics_plane_exports_compile_counters(tmp_path):
    from ray_lightning_tpu.telemetry import metrics as tmetrics
    reg = tmetrics.enable_metrics(pump=False)
    try:
        _fit(tmp_path, tmp_path / "cache")
        names = {m["name"] for m in reg.snapshot()}
    finally:
        tmetrics.disable_metrics()
    assert {"rlt_compile_cache_hits_total",
            "rlt_compile_cache_misses_total",
            "rlt_compile_seconds_total"} <= names


# ---------------------------------------------------------------------------
# cold → warm across process boundaries (the acceptance A/B)
# ---------------------------------------------------------------------------

_CHILD = """\
import json, sys
from ray_lightning_tpu import Trainer
from ray_lightning_tpu.compile import cache as cc
from ray_lightning_tpu.models import BoringModel

batch = int(sys.argv[1])
trainer = Trainer(max_steps=3, enable_checkpointing=False,
                  num_sanity_val_steps=0, limit_val_batches=0)
trainer.fit(BoringModel(dataset_length=32, batch_size=batch))
s = cc.stats()
print(json.dumps({"ttfs": trainer.time_to_first_step, "hits": s.hits,
                  "misses": s.misses,
                  "compile_secs": s.backend_compile_secs}))
"""


def _run_child(tmp_path, cache_dir, batch=2):
    script = tmp_path / "child.py"
    script.write_text(_CHILD)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ,
           "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
           "RLT_COMPILE_CACHE_DIR": str(cache_dir),
           "PYTHONPATH": repo_root + os.pathsep
           + os.environ.get("PYTHONPATH", "")}
    out = subprocess.run(
        [sys.executable, str(script), str(batch)],
        capture_output=True, text=True, cwd=str(tmp_path), timeout=300,
        env=env)
    assert out.returncode == 0, f"child failed:\n{out.stdout}\n{out.stderr}"
    line = [ln for ln in out.stdout.splitlines() if ln.startswith("{")][-1]
    return json.loads(line)


def test_cold_then_warm_across_processes(tmp_path):
    """Same process tree torn down between fits, cache dir retained:
    the warm process must record cache hits, spend a fraction of the
    cold one's XLA compile seconds, and start stepping sooner; a shape
    change must miss (fresh programs compile, namespacing untouched)."""
    cache_dir = tmp_path / "cache"
    cold = _run_child(tmp_path, cache_dir)
    # fresh dir: every program misses (a stray in-process hit can come
    # from byte-identical duplicate programs within the cold run itself)
    assert cold["misses"] > 0, cold

    warm = _run_child(tmp_path, cache_dir)
    assert warm["hits"] > cold["hits"], (cold, warm)
    assert warm["compile_secs"] < cold["compile_secs"] * 0.5, (cold, warm)
    assert warm["ttfs"] < cold["ttfs"], (cold, warm)

    reshaped = _run_child(tmp_path, cache_dir, batch=4)
    assert reshaped["misses"] > 0, reshaped


# ---------------------------------------------------------------------------
# tune: shared cache across trials and restarts
# ---------------------------------------------------------------------------

def _tune_trainable(config, checkpoint_dir=None):
    trainer = Trainer(max_steps=2, enable_checkpointing=False,
                      num_sanity_val_steps=0, limit_val_batches=0,
                      default_root_dir=tune.get_trial_dir())
    trainer.fit(BoringModel())
    tune.report(loss=float(trainer.callback_metrics.get("loss", 0.0)))


def test_tune_trials_share_compile_cache(tmp_path, seed):
    before = cc.stats()
    analysis = tune.run(_tune_trainable, config={}, num_samples=2,
                        metric="loss", mode="min",
                        local_dir=str(tmp_path), name="cc_exp")
    assert all(t.status == "TERMINATED" for t in analysis.trials)
    after = cc.stats()
    # trial 1 rebuilt every jit object; its programs came off trial 0's
    # persistent cache instead of recompiling
    assert after.hits > before.hits
    assert os.path.isdir(os.path.join(str(tmp_path), "cc_exp",
                                      "compile_cache"))


def test_tune_restart_resumes_warm(tmp_path, seed):
    attempts = []

    def flaky(config, checkpoint_dir=None):
        _tune_trainable(config, checkpoint_dir)
        attempts.append(cc.stats().hits)
        if len(attempts) == 1:
            raise RuntimeError("boom after first fit")

    analysis = tune.run(flaky, config={}, num_samples=1, max_failures=1,
                        metric="loss", mode="min",
                        local_dir=str(tmp_path), name="cc_restart")
    assert analysis.trials[0].status == "TERMINATED"
    assert len(attempts) == 2
    # the retry's fit hit the cache the crashed attempt populated
    assert attempts[1] > attempts[0]


def test_tune_cache_optout(tmp_path, monkeypatch, seed):
    monkeypatch.setenv("RLT_COMPILE_CACHE", "0")
    tune.run(_tune_trainable, config={}, num_samples=1,
             metric="loss", mode="min",
             local_dir=str(tmp_path), name="cc_off")
    assert not os.path.isdir(os.path.join(str(tmp_path), "cc_off",
                                          "compile_cache"))


# ---------------------------------------------------------------------------
# satellites: advisor r5 fixes
# ---------------------------------------------------------------------------

class _Loader:
    def __init__(self, n, shuffle=False):
        self._n = n
        self.shuffle = shuffle

    def __len__(self):
        return self._n


def test_cache_bytes_estimate_ignores_limit_and_doubles_shuffle():
    batch = {"x": np.zeros((4, 8), np.float32)}     # 128 bytes
    # the flat upload covers the FULL dataset: limit_train_batches must
    # not shrink the debit (the old signature took and applied a limit)
    assert _cache_bytes_estimate(_Loader(10), batch) == 10 * 128
    # shuffling keeps flat + repacked resident: double
    assert _cache_bytes_estimate(_Loader(10, shuffle=True), batch) \
        == 2 * 10 * 128
    # length-less loaders stay un-estimable (caller donates)
    assert _cache_bytes_estimate(iter(()), batch) is None


def test_slots_callback_batch_hook_plan():
    """A callback instance without a __dict__ (all-slots hierarchy)
    must not crash the hook plan (advisor r5 low: ``vars(cb)`` raised
    TypeError for it; ``Callback`` subclasses always inherit a __dict__,
    so the duck-typed case is exactly where this bites)."""

    class SlotsCb:
        __slots__ = ()

        def on_train_batch_end(self, trainer, module, metrics, batch,
                               batch_idx):
            pass

    cb = SlotsCb()
    with pytest.raises(TypeError):
        vars(cb)                     # the shape the old probe choked on
    trainer = Trainer(enable_checkpointing=False)
    trainer.callbacks = [cb]
    invoke, materialize = trainer._batch_hook_plan()
    assert invoke                    # override detected
    assert materialize               # conservative default: batch needed


def test_slots_callback_respects_class_needs_batch_flag():
    """The slots-safe probe still honors a class-level needs_batch=False
    declared alongside the overriding hook."""

    class SlotsCb:
        __slots__ = ()
        needs_batch = False

        def on_train_batch_end(self, trainer, module, metrics, batch,
                               batch_idx):
            pass

    trainer = Trainer(enable_checkpointing=False)
    trainer.callbacks = [SlotsCb()]
    invoke, materialize = trainer._batch_hook_plan()
    assert invoke and not materialize
