"""Tune subsystem tests (reference: tests/test_tune.py).

The reference's load-bearing assertions: per-trial isolation
(``training_iteration == max_epochs``, test_tune.py:42-57) and
``best_checkpoint`` existence (test_tune.py:66-90).  Plus native-runner
coverage the reference gets from Ray Tune itself: search-space expansion,
ASHA early stopping, PBT exploit.
"""

import os

import pytest

from ray_lightning_tpu import Trainer
from ray_lightning_tpu import tune
from ray_lightning_tpu.models import BoringModel, LightningMNISTClassifier


def train_fn(config, checkpoint_dir=None, max_epochs=2, model_cls=BoringModel):
    module = model_cls()
    trainer = Trainer(
        max_epochs=max_epochs,
        limit_train_batches=4,
        limit_val_batches=2,
        num_sanity_val_steps=0,
        enable_checkpointing=False,
        callbacks=[tune.TuneReportCallback(on="validation_end")],
        default_root_dir=tune.get_trial_dir(),
    )
    trainer.fit(module)


def test_tune_iteration_counts(tmp_path, seed):
    """Each trial reports exactly max_epochs iterations (per-trial
    isolation, test_tune.py:42-57 analog)."""
    analysis = tune.run(
        train_fn,
        config={"lr": tune.loguniform(1e-4, 1e-1)},
        num_samples=2,
        metric="val_loss",
        mode="min",
        local_dir=str(tmp_path),
    )
    assert len(analysis.trials) == 2
    for t in analysis.trials:
        assert t.status == "TERMINATED"
        assert t.last_result["training_iteration"] == 2


def test_tune_grid_and_samples(tmp_path, seed):
    reported = []

    def fn(config):
        reported.append(config["a"])
        tune.report(loss=float(config["a"]))

    analysis = tune.run(
        fn, config={"a": tune.grid_search([1, 2, 3])}, num_samples=2,
        metric="loss", mode="min", local_dir=str(tmp_path))
    assert sorted(reported) == [1, 1, 2, 2, 3, 3]
    assert analysis.best_trial.config["a"] == 1


def test_tune_checkpointing(tmp_path, seed):
    """best_checkpoint exists and reloads (test_tune.py:66-90 analog)."""

    def fn(config):
        module = BoringModel(lr=config["lr"])
        trainer = Trainer(
            max_epochs=2, limit_train_batches=4, limit_val_batches=2,
            num_sanity_val_steps=0, enable_checkpointing=False,
            callbacks=[tune.TuneReportCheckpointCallback(
                on="validation_end")],
        )
        trainer.fit(module)

    analysis = tune.run(
        fn, config={"lr": tune.choice([0.05, 0.1])}, num_samples=2,
        metric="val_loss", mode="min", local_dir=str(tmp_path))
    best = analysis.best_checkpoint
    assert best is not None and os.path.isdir(best)
    ckpt_file = os.path.join(best, "checkpoint")
    assert os.path.isfile(ckpt_file)
    ckpt = Trainer.load_checkpoint_dict(ckpt_file)
    assert ckpt["global_step"] > 0
    assert "state" in ckpt


def test_tune_asha_stops_bad_trials(tmp_path):
    iters = {}

    def fn(config):
        for i in range(16):
            iters[config["level"]] = i + 1
            tune.report(loss=float(config["level"]))

    tune.run(
        fn, config={"level": tune.grid_search([0.0, 1.0, 2.0, 3.0])},
        num_samples=1,
        scheduler=tune.ASHAScheduler(metric="loss", mode="min", max_t=16,
                                     grace_period=2, reduction_factor=2),
        local_dir=str(tmp_path))
    # the best trial (level 0) must outlive the worst (level 3)
    assert iters[0.0] == 16
    assert iters[3.0] < 16


def test_tune_pbt_exploits(tmp_path):
    """Bottom-quantile trials must restart from a donor checkpoint."""
    restores = []

    import threading
    barrier = threading.Barrier(2, timeout=30)

    def fn(config, checkpoint_dir=None):
        import time
        start = 0.0
        if checkpoint_dir:
            restores.append(checkpoint_dir)
            with open(os.path.join(checkpoint_dir, "v.txt")) as f:
                start = float(f.read())
        else:
            # both population members must coexist before racing ahead,
            # else the fast trial can finish before the slow one reports
            try:
                barrier.wait()
            except threading.BrokenBarrierError:
                pass
        score = start
        for step in range(1, 9):
            time.sleep(0.02)   # keep the population interleaved
            score += config["rate"]
            with tune.checkpoint_dir(step) as d:
                with open(os.path.join(d, "v.txt"), "w") as f:
                    f.write(str(score))
            tune.report(score=score)

    analysis = tune.run(
        fn,
        config={"rate": tune.grid_search([0.01, 1.0])},
        num_samples=1,
        scheduler=tune.PopulationBasedTraining(
            metric="score", mode="max", perturbation_interval=2,
            hyperparam_mutations={"rate": [0.01, 1.0]}),
        local_dir=str(tmp_path))
    assert restores, "no exploit happened"
    best = analysis.get_best_trial("score", "max")
    assert best.last_result["score"] > 1.0


def test_get_tune_resources_bundles():
    res = tune.get_tune_resources(num_workers=4, num_cpus_per_worker=2,
                                  use_tpu=True, tpus_per_worker=4)
    assert len(res.bundles) == 5          # head + 4 workers
    assert res.bundles[0] == {"CPU": 1}   # trial-driver head (tune.py:50-53)
    assert res.bundles[1] == {"CPU": 2, "TPU": 4}
    assert res.strategy == "PACK"


def test_get_tune_resources_override_precedence():
    """resources_per_worker overrides the convenience args
    (test_ddp.py:136-174 precedence parity)."""
    res = tune.get_tune_resources(
        num_workers=2, num_cpus_per_worker=8,
        resources_per_worker={"CPU": 3, "TPU": 2, "extra": 1})
    assert res.bundles[1] == {"CPU": 3, "extra": 1, "TPU": 2}


def test_get_tune_resources_deprecated_shim():
    with pytest.warns(DeprecationWarning):
        res = tune.get_tune_resources(num_workers=1, cpus_per_worker=5)
    assert res.bundles[1]["CPU"] == 5


def test_concurrent_trials_get_disjoint_devices(tmp_path, seed):
    """Two trials running AT THE SAME TIME (barrier-proven) must train
    on disjoint halves of the 8-device mesh when resources_per_trial
    declares 4 chips (VERDICT weak #4: placement-group-style isolation,
    reference tune.py:50-56)."""
    import threading

    barrier = threading.Barrier(2, timeout=60)
    seen = {}

    def fn(config):
        module = BoringModel()
        trainer = Trainer(
            max_epochs=1, limit_train_batches=2, limit_val_batches=1,
            num_sanity_val_steps=0, enable_checkpointing=False,
            callbacks=[tune.TuneReportCallback(on="validation_end")],
        )
        trainer.fit(module)
        seen[config["tag"]] = [d.id for d in trainer._mesh.devices.flat]
        barrier.wait()  # both trials must hold their lease simultaneously

    tune.run(
        fn, config={"tag": tune.grid_search([0, 1])},
        resources_per_trial=tune.get_tune_resources(
            num_workers=1, use_tpu=True, tpus_per_worker=4),
        max_concurrent_trials=2,
        metric="val_loss", mode="min", local_dir=str(tmp_path))
    # each trial's mesh sits entirely inside its own 4-chip lease (the
    # tiny batch may use fewer than 4 of them), and the leases differ
    halves = ({0, 1, 2, 3}, {4, 5, 6, 7})
    half_of = {tag: next(h for h in halves if set(ids) <= h)
               for tag, ids in seen.items()}
    assert half_of[0] != half_of[1]
    assert set(seen[0]).isdisjoint(seen[1])


def test_full_mesh_trials_serialize(tmp_path, seed):
    """In-process trials each demanding all 8 chips cannot overlap: the
    single lease serializes them even at max_concurrent_trials=2.  The
    lease is held from the first device ask to trial end, so the
    measured intervals span each trial's whole fit."""
    import time

    from ray_lightning_tpu.core.callbacks import Callback

    intervals = {}

    class MarkStart(Callback):
        """Clock starts once training begins — i.e. after the mesh was
        built and therefore after the device lease was acquired."""

        def __init__(self):
            self.t0 = None

        def on_train_start(self, trainer, module):
            self.t0 = time.monotonic()

    def fn(config):
        module = BoringModel(batch_size=8)
        mark = MarkStart()
        trainer = Trainer(
            max_epochs=1, limit_train_batches=4, limit_val_batches=0,
            num_sanity_val_steps=0, enable_checkpointing=False,
            callbacks=[mark, tune.TuneReportCallback(on="train_epoch_end")],
        )
        trainer.fit(module)
        time.sleep(0.1)  # widen the window an overlap would show in
        intervals[config["tag"]] = (mark.t0, time.monotonic())

    tune.run(
        fn, config={"tag": tune.grid_search([0, 1])},
        resources_per_trial={"TPU": 8},
        max_concurrent_trials=2,
        metric="loss", mode="min", local_dir=str(tmp_path))
    (a0, a1), (b0, b1) = intervals[0], intervals[1]
    assert a1 <= b0 or b1 <= a0, "full-mesh trials overlapped"


def test_trial_demand_exceeding_devices_errors(tmp_path, seed):
    """An in-process trial whose declared demand cannot fit the visible
    devices fails with a clear error (surfaced at lease time, in the
    trial — the driver itself never touches JAX)."""

    def fn(config):
        trainer = Trainer(
            max_epochs=1, limit_train_batches=2, limit_val_batches=0,
            num_sanity_val_steps=0, enable_checkpointing=False,
        )
        trainer.fit(BoringModel(batch_size=8))

    analysis = tune.run(
        fn, config={}, resources_per_trial={"TPU": 16},
        metric="loss", mode="min", local_dir=str(tmp_path),
        raise_on_failed_trial=False)
    (trial,) = analysis.trials
    assert trial.status == "ERROR"
    assert "only 8 are visible" in trial.error


@pytest.mark.slow
def test_pbt_population_with_device_leases(tmp_path, seed):
    """BASELINE config #3 shape on the virtual mesh: a PBT population
    of 4 concurrent MNIST trials, each training on its own disjoint
    2-chip lease of the 8-device mesh."""
    import threading

    leases = {}
    barrier = threading.Barrier(4, timeout=120)

    def fn(config, checkpoint_dir=None):
        module = LightningMNISTClassifier(
            config={"batch_size": 16, "lr": config["lr"]}, train_size=64)
        trainer = Trainer(
            max_epochs=2, limit_train_batches=2, limit_val_batches=1,
            num_sanity_val_steps=0, enable_checkpointing=False,
            callbacks=[tune.TuneReportCallback(on="validation_end")],
        )
        trainer.fit(module)
        leases[config["lr"]] = frozenset(
            d.id for d in trainer._mesh.devices.flat)
        barrier.wait()   # the whole population held leases concurrently

    analysis = tune.run(
        fn,
        config={"lr": tune.grid_search([0.05, 0.02, 0.01, 0.005])},
        resources_per_trial=tune.get_tune_resources(
            num_workers=1, use_tpu=True, tpus_per_worker=2),
        scheduler=tune.PopulationBasedTraining(
            metric="ptl/val_accuracy", mode="max",
            perturbation_interval=10**6,   # population runs, no exploit
            hyperparam_mutations={"lr": [0.05, 0.01]}),
        local_dir=str(tmp_path))
    assert len(leases) == 4
    assert all(t.status == "TERMINATED" for t in analysis.trials)
    sets = list(leases.values())
    for i in range(4):
        for j in range(i + 1, 4):
            assert sets[i].isdisjoint(sets[j])


def test_trial_retry_on_failure(tmp_path, seed):
    """max_failures retries a crashed trial (the reference's recovery
    story: Tune trial retries, SURVEY.md §5)."""
    attempts = {"n": 0}

    def fn(config):
        attempts["n"] += 1
        if attempts["n"] == 1:
            raise RuntimeError("flaky init")
        tune.report(loss=1.0)

    analysis = tune.run(fn, config={}, max_failures=1,
                        metric="loss", mode="min",
                        local_dir=str(tmp_path))
    (trial,) = analysis.trials
    assert trial.status == "TERMINATED"
    assert attempts["n"] == 2
    assert trial.last_result["loss"] == 1.0


def test_trial_retry_resumes_from_checkpoint(tmp_path, seed):
    """A retried checkpoint-taking trainable resumes from the trial's
    latest checkpoint instead of restarting cold."""
    attempts = {"n": 0}

    def fn(config, checkpoint_dir=None):
        attempts["n"] += 1
        start = 0
        if checkpoint_dir:
            with open(os.path.join(checkpoint_dir, "v.txt")) as f:
                start = int(f.read())
        for step in range(start + 1, 7):
            with tune.checkpoint_dir(step) as d:
                with open(os.path.join(d, "v.txt"), "w") as f:
                    f.write(str(step))
            tune.report(progress=step)
            if attempts["n"] == 1 and step == 3:
                raise RuntimeError("mid-training crash")

    analysis = tune.run(fn, config={}, max_failures=2,
                        metric="progress", mode="max",
                        local_dir=str(tmp_path))
    (trial,) = analysis.trials
    assert trial.status == "TERMINATED"
    assert attempts["n"] == 2
    # resumed at 3, not 0: steps 4..6 ran exactly once
    assert trial.last_result["progress"] == 6


def test_trial_retry_skips_deliberate_exits(tmp_path, seed):
    """SystemExit is a deliberate bail-out, not a retryable crash
    (ray.tune parity): one attempt, trial ERROR."""
    attempts = {"n": 0}

    def fn(config):
        attempts["n"] += 1
        raise SystemExit(1)

    analysis = tune.run(fn, config={}, max_failures=3,
                        metric="loss", mode="min",
                        local_dir=str(tmp_path),
                        raise_on_failed_trial=False)
    assert analysis.trials[0].status == "ERROR"
    assert attempts["n"] == 1


def test_trial_retries_exhausted(tmp_path, seed):
    attempts = {"n": 0}

    def fn(config):
        attempts["n"] += 1
        raise RuntimeError("always broken")

    analysis = tune.run(fn, config={}, max_failures=2,
                        metric="loss", mode="min",
                        local_dir=str(tmp_path),
                        raise_on_failed_trial=False)
    (trial,) = analysis.trials
    assert trial.status == "ERROR"
    assert attempts["n"] == 3          # initial + 2 retries
    assert "always broken" in trial.error


def test_report_outside_trial_raises():
    with pytest.raises(RuntimeError):
        tune.report(loss=1.0)


@pytest.mark.slow
def test_tune_report_through_actor_queue(tmp_path, seed):
    """The §3.3 grandchild relay: training runs in actor subprocesses,
    TuneReportCallback fires on the remote rank 0, the report callable
    rides the worker→driver queue, and executes in the trial thread where
    the tune session lives (reference: tune.py:130-134 + util.py:47-52)."""
    from ray_lightning_tpu import RayXlaPlugin

    def fn(config):
        module = BoringModel(lr=config["lr"])
        trainer = Trainer(
            max_epochs=2, limit_train_batches=2, limit_val_batches=1,
            num_sanity_val_steps=0, enable_checkpointing=False,
            callbacks=[tune.TuneReportCallback(on="validation_end")],
            plugins=[RayXlaPlugin(num_workers=2, platform="cpu")],
        )
        trainer.fit(module)

    analysis = tune.run(
        fn, config={"lr": 0.05}, num_samples=1,
        metric="val_loss", mode="min", local_dir=str(tmp_path))
    t = analysis.trials[0]
    assert t.status == "TERMINATED"
    assert t.last_result["training_iteration"] == 2
    assert "val_loss" in t.last_result


def test_tune_mnist_learns(tmp_path, seed):
    """End-to-end: a short MNIST sweep finds a config with decent
    accuracy (examples/ray_ddp_example.py tune_mnist analog)."""

    def fn(config):
        module = LightningMNISTClassifier(config)
        trainer = Trainer(
            max_epochs=2, limit_train_batches=8, limit_val_batches=4,
            num_sanity_val_steps=0, enable_checkpointing=False,
            callbacks=[tune.TuneReportCallback(
                {"acc": "ptl/val_accuracy"}, on="validation_end")],
        )
        trainer.fit(module)

    analysis = tune.run(
        fn,
        config={"lr": tune.choice([1e-2, 1e-3]),
                "batch_size": 32},
        num_samples=2, metric="acc", mode="max", local_dir=str(tmp_path))
    assert analysis.best_result["acc"] > 0.3
